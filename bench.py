#!/usr/bin/env python
"""Benchmark harness for the BASELINE.md acceptance matrix.

Default (no args) = the headline metric: ResNet-50 ImageNet-shaped
images/sec per chip under amp-O2 bf16 (BASELINE.md; target 4000 img/s/chip
on v5e).  Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``--config`` selects the other acceptance-matrix rows (BASELINE.md:17-30):
  c1        ResNet-18 / CIFAR-shaped fp32 O0, single device   (img/s/chip)
  c2        ResNet-50 / ImageNet-shaped amp-O2 bf16 (default) (img/s/chip)
  c3        ResNet-50 DDP + SyncBatchNorm over all local devices
            (img/s/chip; on the 1-chip rig this measures the sharded-step
            path; semantics are covered by the 8-CPU-device tests)
  c4        BERT-base MLM + FusedLAMB amp-O2                  (tokens/s/chip)
  c5        Transformer-XL + FusedLayerNorm + grad clip       (tokens/s/chip)
  hostpipe  c2 step fed by the native C++ double-buffered prefetcher
            instead of on-device synthesis (quantifies the host pipeline;
            stderr carries the on-device comparison)

Data is generated on-device once and reused across steps (c1-c5) so the
number isolates device throughput (this host has 1 CPU core; a host-side
input pipeline would bottleneck the measurement — the reference isolates
the same way with its CUDA-stream prefetcher, SURVEY.md §3.5).

``vs_baseline`` is reported against the only normative target (4000
img/s/chip, ResNet-50 O2) for c2/c3; other rows have no published baseline
(BASELINE.md:3) and report ``vs_baseline: null``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp

from apex_example_tpu.obs import (FlightRecorder, JsonlSink, StallWatchdog,
                                  rank_print, span)
from apex_example_tpu.obs import costmodel as obs_costmodel
from apex_example_tpu.obs import metrics as obs_metrics
from apex_example_tpu.utils.flops import (model_train_flops_per_token,
                                          mfu_pct,
                                          resnet_train_flops_per_image)

BASELINE_IMG_PER_SEC_PER_CHIP = 4000.0

# Optional JSONL sink (--metrics-jsonl): every _emit line also lands as a
# schema-valid "bench" record (obs/schema.py) for the tools/ thin clients.
_SINK: JsonlSink | None = None
# Optional stall watchdog (--stall-timeout): each emitted measurement is
# its heartbeat — a bench config that hangs mid-measurement leaves a
# 'stall' record with thread stacks instead of silence.
_WATCHDOG: StallWatchdog | None = None
_EMITS = 0


def _emit(metric: str, value: float, unit: str, vs_baseline,
          flops_per_item: float = None):
    """One JSON line.  ``flops_per_item`` (analytic model FLOPs per image/
    token, utils/flops.py) adds ``mfu_pct`` — the fraction of the v5e bf16
    peak this throughput represents.  MFU counts MODEL FLOPs by convention:
    rematerialization recompute does not inflate it."""
    rec = {
        "metric": metric,
        "value": round(value, 1),
        "unit": unit,
        "vs_baseline": (round(vs_baseline, 4)
                        if vs_baseline is not None else None),
    }
    if flops_per_item is not None:
        rec["mfu_pct"] = round(mfu_pct(value, flops_per_item), 2)
    rank_print(json.dumps(rec))
    if _SINK is not None:
        sunk = {"record": "bench", "time": obs_metrics.now(), **rec}
        if sunk["vs_baseline"] is None:
            del sunk["vs_baseline"]     # schema: omitted, never null
        _SINK.write(sunk)
    if _WATCHDOG is not None:
        global _EMITS
        _EMITS += 1
        _WATCHDOG.notify_step(_EMITS)


def chain_rate(step, state, batch, steps: int, items_per_step: int,
               fetch) -> float:
    """Two-point measurement: a scalar *value fetch* is the only reliable
    execution barrier through the remote-TPU tunnel (block_until_ready
    returns at enqueue there), and differencing two chain lengths cancels
    the fetch round-trip so the rate reflects device throughput.

    NOTE: consumes ``state`` (steps donate their input state); callers must
    not reuse the pytree they passed in.
    """
    steps = max(steps, 2)           # two chains must differ in length
    def run_chain(n, state):
        with span("bench_chain") as sp:
            for _ in range(n):
                state, metrics = step(state, batch)
            fetch(metrics)
        return sp.dur_s, state

    n1 = max(steps // 5, 1)
    if n1 >= steps:
        n1 = steps - 1
    t1, state = run_chain(n1, state)
    t2, state = run_chain(steps, state)
    return (steps - n1) * items_per_step / max(t2 - t1, 1e-9)


def _image_setup(policy, scaler, *, arch: str, batch_size: int,
                 image_size: int, num_classes: int,
                 syncbn: bool = False, remat: str = "none"):
    from apex_example_tpu.data import image_batch
    from apex_example_tpu.engine import create_train_state
    from apex_example_tpu.models import ARCHS
    from apex_example_tpu.optim import FusedSGD

    model = ARCHS[arch](
        num_classes=num_classes, dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype, bn_dtype=policy.bn_dtype,
        bn_axis_name="data" if syncbn else None, remat=remat)
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    batch = image_batch(jnp.asarray(0), batch_size=batch_size,
                        image_size=image_size, channels=3,
                        num_classes=num_classes, seed=0)
    state = create_train_state(jax.random.PRNGKey(0), model, opt,
                               batch[0][:1], policy, scaler)
    return model, opt, batch, state


def bench_image_single(args, *, arch: str, opt_level: str, image_size: int,
                       num_classes: int, metric: str, vs_target: bool):
    from apex_example_tpu import amp
    from apex_example_tpu.engine import make_train_step

    policy, scaler = amp.initialize(opt_level)
    model, opt, batch, state = _image_setup(
        policy, scaler, arch=arch, batch_size=args.batch_size,
        image_size=image_size, num_classes=num_classes,
        remat=getattr(args, "remat", "none"))
    batch = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, jax.devices()[0]), batch)
    step = obs_costmodel.instrument(f"bench_{args.config}_step",
                       jax.jit(make_train_step(model, opt, policy),
                               donate_argnums=(0,)))

    for _ in range(max(args.warmup, 1)):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    rate = chain_rate(step, state, batch, args.steps, args.batch_size,
                      lambda m: float(m["loss"]))
    _emit(metric, rate, "images/sec/chip",
          rate / BASELINE_IMG_PER_SEC_PER_CHIP if vs_target else None,
          flops_per_item=resnet_train_flops_per_image(
              arch, image_size, num_classes))


def bench_c3(args):
    """ResNet-50 DDP + SyncBN over every local device (BASELINE.md row 3)."""
    from apex_example_tpu import amp
    from apex_example_tpu.engine import make_sharded_train_step
    from apex_example_tpu.parallel.mesh import make_data_mesh

    devices = jax.devices()
    n = len(devices)
    mesh = make_data_mesh(devices=devices)
    policy, scaler = amp.initialize("O2")
    global_bs = args.batch_size * n
    model, opt, batch, state = _image_setup(
        policy, scaler, arch="resnet50", batch_size=global_bs,
        image_size=args.image_size, num_classes=1000, syncbn=True)
    step = obs_costmodel.instrument("bench_c3_step",
                       make_sharded_train_step(mesh, model, opt, policy))

    for _ in range(max(args.warmup, 1)):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    rate = chain_rate(step, state, batch, args.steps, global_bs,
                      lambda m: float(m["loss"]))
    _emit(f"resnet50_ddp_syncbn_{n}dev_ampO2_images_per_sec_per_chip",
          rate / n, "images/sec/chip",
          rate / n / BASELINE_IMG_PER_SEC_PER_CHIP,
          flops_per_item=resnet_train_flops_per_image(
              "resnet50", args.image_size, 1000))


def bench_c4(args):
    """BERT-base MLM + FusedLAMB under amp-O2 (BASELINE.md row 4)."""
    from apex_example_tpu import amp
    from apex_example_tpu.data import mlm_batch
    from apex_example_tpu.engine import create_train_state, make_train_step
    from apex_example_tpu.models.bert import bert_base
    from apex_example_tpu.optim import FusedLAMB
    from apex_example_tpu.workloads import mlm_loss

    policy, scaler = amp.initialize("O2")
    md = amp.module_dtypes(policy)
    # flag set => force the kernel; absent => "auto" (kernel at seq >= the
    # measured ~2k crossover, XLA path below — models/bert.py)
    model = bert_base(dtype=md.compute, param_dtype=md.param,
                      ln_dtype=md.ln_io, softmax_dtype=md.softmax,
                      fused_attention=args.fused_attention or "auto")
    opt = FusedLAMB(lr=1e-3, weight_decay=0.01)
    bs, seq = args.batch_size, args.seq_len
    V = model.vocab_size
    ids, labels, w = mlm_batch(jnp.asarray(0), batch_size=bs, seq_len=seq,
                               vocab_size=V, mask_token_id=V - 1, seed=0)
    batch = (ids, (labels, w))
    state = create_train_state(jax.random.PRNGKey(0), model, opt, ids[:1],
                               policy, scaler, train_kwargs={})
    step = obs_costmodel.instrument("bench_c4_step",
                       jax.jit(make_train_step(model, opt, policy,
                                               loss_fn=mlm_loss,
                                               compute_accuracy=False),
                               donate_argnums=(0,)))

    for _ in range(max(args.warmup, 1)):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    rate = chain_rate(step, state, batch, args.steps, bs * seq,
                      lambda m: float(m["loss"]))
    _emit("bert_base_mlm_fusedlamb_ampO2_tokens_per_sec_per_chip",
          rate, "tokens/sec/chip", None,
          flops_per_item=model_train_flops_per_token(model, seq))


def bench_gpt(args):
    """GPT-base causal LM + FusedAdam under amp-O2 (beyond-reference model
    family, models/gpt.py; same measurement contract as c4 — tokens/sec/
    chip, the "auto" flash crossover engages at --seq-len >= 2048)."""
    from apex_example_tpu import amp
    from apex_example_tpu.data import lm_batch
    from apex_example_tpu.engine import create_train_state, make_train_step
    from apex_example_tpu.models.gpt import gpt_base
    from apex_example_tpu.optim import FusedAdam
    from apex_example_tpu.workloads import lm_loss

    policy, scaler = amp.initialize("O2")
    md = amp.module_dtypes(policy)
    kw = {}
    if args.seq_len > 1024:
        kw["max_position"] = args.seq_len
    model = gpt_base(dtype=md.compute, param_dtype=md.param,
                     ln_dtype=md.ln_io, softmax_dtype=md.softmax,
                     fused_attention=args.fused_attention or "auto", **kw)
    opt = FusedAdam(lr=1e-4, weight_decay=0.01)
    bs, seq = args.batch_size, args.seq_len
    toks = lm_batch(jnp.asarray(0), batch_size=bs, seq_len=seq,
                    vocab_size=model.vocab_size, seed=0)
    batch = (toks[:, :-1], toks[:, 1:])
    state = create_train_state(jax.random.PRNGKey(0), model, opt,
                               batch[0][:1], policy, scaler,
                               train_kwargs={})
    step = obs_costmodel.instrument("bench_gpt_step",
                       jax.jit(make_train_step(model, opt, policy,
                                               loss_fn=lm_loss,
                                               compute_accuracy=False),
                               donate_argnums=(0,)))

    for _ in range(max(args.warmup, 1)):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    rate = chain_rate(step, state, batch, args.steps, bs * seq,
                      lambda m: float(m["loss"]))
    _emit("gpt_base_causal_lm_fusedadam_ampO2_tokens_per_sec_per_chip",
          rate, "tokens/sec/chip", None,
          flops_per_item=model_train_flops_per_token(model, seq))


def bench_c5(args):
    """Transformer-XL + FusedLayerNorm + grad clip (BASELINE.md row 5)."""
    from apex_example_tpu import amp
    from apex_example_tpu.data import lm_batch
    from apex_example_tpu.engine import create_train_state
    from apex_example_tpu.models.transformer_xl import transformer_xl_base
    from apex_example_tpu.optim import FusedAdam
    from apex_example_tpu.workloads import make_txl_train_step

    policy, scaler = amp.initialize("O2")
    md = amp.module_dtypes(policy)
    model = transformer_xl_base(dtype=md.compute, param_dtype=md.param,
                                ln_dtype=md.ln_io, softmax_dtype=md.softmax)
    opt = FusedAdam(lr=2.5e-4)
    bs, seq = args.batch_size, args.seq_len
    V = model.vocab_size
    toks = lm_batch(jnp.asarray(0), batch_size=bs, seq_len=seq + 1,
                    vocab_size=V, seed=0)
    batch = (toks[:, :-1], toks[:, 1:])
    state = create_train_state(jax.random.PRNGKey(0), model, opt,
                               batch[0][:1], policy, scaler,
                               train_kwargs={})
    mems = model.init_mems(bs)
    raw = obs_costmodel.instrument("bench_c5_step",
                      jax.jit(make_txl_train_step(model, opt, policy),
                              donate_argnums=(0, 1)))
    # adapt (state, mems) into the chain_rate (state, batch) shape
    def step(carry, batch):
        state, mems = carry
        state, mems, metrics = raw(state, mems, batch)
        return (state, mems), metrics

    carry = (state, mems)
    for _ in range(max(args.warmup, 1)):
        carry, metrics = step(carry, batch)
    float(metrics["loss"])

    rate = chain_rate(step, carry, batch, args.steps, bs * seq,
                      lambda m: float(m["loss"]))
    _emit("transformer_xl_fusedln_clip_tokens_per_sec_per_chip",
          rate, "tokens/sec/chip", None,
          flops_per_item=model_train_flops_per_token(model, seq))


def bench_hostpipe(args):
    """C2 step fed by the native host prefetcher vs on-device synthesis.

    Quantifies the C++ double-buffered pipeline (csrc/apex_tpu_host.cpp):
    the JSON line is the host-fed rate; stderr carries the on-device rate
    so the comparison lands in one run.
    """
    from apex_example_tpu import amp
    from apex_example_tpu.engine import make_train_step
    from apex_example_tpu.host_runtime import NativePrefetcher, available
    if not available():
        rank_print("hostpipe: native runtime not buildable", file=sys.stderr)
        return

    policy, scaler = amp.initialize("O2")
    model, opt, batch, state = _image_setup(
        policy, scaler, arch="resnet50", batch_size=args.batch_size,
        image_size=args.image_size, num_classes=1000)
    step = obs_costmodel.instrument("bench_hostpipe_step",
                       jax.jit(make_train_step(model, opt, policy),
                               donate_argnums=(0,)))

    dev_batch = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, jax.devices()[0]), batch)
    for _ in range(max(args.warmup, 1)):
        state, metrics = step(state, dev_batch)
    float(metrics["loss"])

    on_device = chain_rate(step, state, dev_batch, args.steps,
                           args.batch_size, lambda m: float(m["loss"]))

    pf = NativePrefetcher(batch=args.batch_size,
                          image_size=args.image_size,
                          num_classes=1000, seed=0)
    it = iter(pf)

    def host_step(state, _):
        img, lab = next(it)
        b = (jnp.asarray(img), jnp.asarray(lab))
        return step(state, b)

    # chain_rate consumed the donated state above (including the scaler
    # arrays) — build a fresh state from a fresh scaler for this phase.
    policy, scaler = amp.initialize("O2")
    _, _, _, state = _image_setup(
        policy, scaler, arch="resnet50", batch_size=args.batch_size,
        image_size=args.image_size, num_classes=1000)
    for _ in range(2):
        state, metrics = host_step(state, None)
    float(metrics["loss"])
    host_rate = chain_rate(host_step, state, None, args.steps,
                           args.batch_size, lambda m: float(m["loss"]))
    rank_print(f"hostpipe: on-device {on_device:.1f} img/s, "
          f"host-fed {host_rate:.1f} img/s "
          f"({host_rate / on_device:.2%})", file=sys.stderr)
    _emit("resnet50_ampO2_hostpipe_images_per_sec_per_chip", host_rate,
          "images/sec/chip", host_rate / BASELINE_IMG_PER_SEC_PER_CHIP,
          flops_per_item=resnet_train_flops_per_image(
              "resnet50", args.image_size, 1000))


def _tunnel_watchdog(timeout_s: float = 600.0):
    """Fail fast with a diagnosis if the device never answers.

    The axon tunnel can wedge (observed 2026-07-30: a killed long remote
    compile left EVERY subsequent client blocked before its first op, ~0%
    CPU).  A silent hang would surface only as an empty driver timeout; this
    arms a timer that is disarmed after the first successful scalar
    round-trip, and otherwise exits with a diagnostic on stderr.  The probe
    is a trivial scalar add — its compile is negligible, so the timer never
    races a legitimately long *workload* compile (those happen after the
    watchdog is already disarmed).  The default 600 s is ~4x the worst cold
    ResNet-50 compile on this rig; ``--watchdog-timeout`` overrides it and
    0 disables the watchdog entirely (e.g. slower rigs, cold remote-compile
    caches).
    """
    if timeout_s <= 0:
        return
    import os
    import threading

    def blow():
        print("BENCH ABORT: no device round-trip within "
              f"{timeout_s:.0f}s — the TPU tunnel is wedged or unreachable "
              "(see PERF.md 'rig pathology'); rerun when the backend "
              "recovers", file=sys.stderr, flush=True)
        os._exit(3)

    timer = threading.Timer(timeout_s, blow)
    timer.daemon = True
    timer.start()
    float(jnp.ones(()) + 1.0)          # scalar fetch = real tunnel barrier
    timer.cancel()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="c2",
                    choices=["c1", "c2", "c3", "c4", "c5", "gpt",
                             "hostpipe"])
    ap.add_argument("--batch-size", type=int, default=None)
    ap.add_argument("--image-size", type=int, default=None)
    ap.add_argument("--seq-len", type=int, default=None)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--fused-attention", action="store_true",
                    help="c4: flash-attention kernel (ops/attention.py)")
    ap.add_argument("--watchdog-timeout", type=float, default=600.0,
                    help="seconds before the first-device-round-trip "
                         "watchdog aborts (0 disables)")
    ap.add_argument("--remat", default="none",
                    choices=["none", "conv", "block"],
                    help="c1/c2 rematerialization variant (PERF.md HBM "
                         "traffic experiments)")
    ap.add_argument("--metrics-jsonl", default="", metavar="PATH",
                    help="also write each measurement as a schema-valid "
                         "'bench' JSONL record (obs/schema.py; "
                         "tools/metrics_lint.py validates)")
    ap.add_argument("--cost-model", action="store_true",
                    help="with --metrics-jsonl: AOT-compile the "
                         "measurement step and emit schema-v6 "
                         "compile_event + cost_model records (XLA flops/"
                         "HBM bytes + roofline verdict — the analytic "
                         "twin of the measured MFU; obs/costmodel.py)")
    ap.add_argument("--flight-recorder", action="store_true",
                    help="with --metrics-jsonl: emit a 'crash_dump' "
                         "record on crash/SIGTERM (obs/flight.py)")
    ap.add_argument("--stall-timeout", type=float, default=0.0,
                    metavar="S",
                    help="with --metrics-jsonl: emit a 'stall' record "
                         "with thread stacks if no measurement lands for "
                         "S seconds (0 disables; covers compile time)")
    args = ap.parse_args()
    global _SINK, _WATCHDOG
    recorder = None
    if (args.flight_recorder or args.stall_timeout > 0
            or args.cost_model) and not args.metrics_jsonl:
        raise SystemExit("--flight-recorder/--stall-timeout/--cost-model "
                         "write to the telemetry sink; add "
                         "--metrics-jsonl PATH")
    # Clear any instance a previous in-process run leaked before the
    # measurement bodies instrument their steps (train.make_telemetry
    # hygiene).
    obs_costmodel.set_default(None)
    if args.metrics_jsonl:
        _SINK = JsonlSink(args.metrics_jsonl)
        if args.flight_recorder:
            recorder = FlightRecorder(sink=_SINK, config=vars(args))
            recorder.install()
        if args.stall_timeout > 0:
            _WATCHDOG = StallWatchdog(_SINK,
                                      deadline_s=args.stall_timeout)
            _WATCHDOG.start()
        if args.cost_model:
            obs_costmodel.set_default(
                obs_costmodel.CostModel(sink=_SINK))
    _tunnel_watchdog(args.watchdog_timeout)

    defaults = {          # (batch_size, image_size, seq_len)
        "c1": (256, 32, None), "c2": (256, 224, None),
        "c3": (256, 224, None), "c4": (64, None, 128),
        "c5": (32, None, 192), "gpt": (64, None, 128),
        "hostpipe": (256, 224, None),
    }
    db, di, ds = defaults[args.config]
    if args.batch_size is None:
        args.batch_size = db
    if args.image_size is None:
        args.image_size = di
    if args.seq_len is None:
        args.seq_len = ds

    try:
        if args.config == "c1":
            bench_image_single(
                args, arch="resnet18", opt_level="O0",
                image_size=args.image_size, num_classes=10,
                metric="resnet18_cifar_fp32_images_per_sec_per_chip",
                vs_target=False)
        elif args.config == "c2":
            bench_image_single(
                args, arch="resnet50", opt_level="O2",
                image_size=args.image_size, num_classes=1000,
                metric="resnet50_imagenet_ampO2_bf16_train_images_per_sec"
                       "_per_chip",
                vs_target=True)
        elif args.config == "c3":
            bench_c3(args)
        elif args.config == "c4":
            bench_c4(args)
        elif args.config == "c5":
            bench_c5(args)
        elif args.config == "gpt":
            bench_gpt(args)
        elif args.config == "hostpipe":
            bench_hostpipe(args)
    finally:
        # Crash-aware teardown (sys.exc_info is live inside a finally):
        # an unwinding exception leaves a crash_dump, not a silent stream.
        if _WATCHDOG is not None:
            _WATCHDOG.close()
        exc = sys.exc_info()
        if recorder is not None:
            if exc[0] is not None and not issubclass(exc[0], SystemExit):
                recorder.crash_dump(f"exception:{exc[0].__name__}",
                                    exc_info=exc)
            recorder.close()
        obs_costmodel.set_default(None)
        if _SINK is not None:
            _SINK.close()


if __name__ == "__main__":
    main()
