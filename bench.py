#!/usr/bin/env python
"""Benchmark harness: headline metric = ResNet-50 ImageNet-shaped images/sec
per chip under amp-O2 bf16 (BASELINE.md; target 4000 img/s/chip on v5e).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Data is generated on-device once and reused across steps so the number
isolates device throughput (this host has 1 CPU core; a host-side input
pipeline would bottleneck the measurement — the reference isolates the same
way with its CUDA-stream prefetcher, SURVEY.md §3.5).
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp

from apex_example_tpu import amp
from apex_example_tpu.data import image_batch
from apex_example_tpu.engine import create_train_state, make_train_step
from apex_example_tpu.models import resnet50
from apex_example_tpu.optim import FusedSGD

BASELINE_IMG_PER_SEC_PER_CHIP = 4000.0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    args = ap.parse_args()

    policy, scaler = amp.initialize("O2")
    model = resnet50(num_classes=1000, dtype=policy.compute_dtype,
                     param_dtype=policy.param_dtype, bn_dtype=policy.bn_dtype)
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)

    batch = image_batch(jnp.asarray(0), batch_size=args.batch_size,
                        image_size=args.image_size, channels=3,
                        num_classes=1000, seed=0)
    batch = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, jax.devices()[0]), batch)

    state = create_train_state(jax.random.PRNGKey(0), model, opt,
                               batch[0][:1], policy, scaler)
    step = jax.jit(make_train_step(model, opt, policy), donate_argnums=(0,))

    for _ in range(max(args.warmup, 1)):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    # Two-point measurement: a scalar *value fetch* is the only reliable
    # execution barrier through the remote-TPU tunnel (block_until_ready
    # returns at enqueue there), and differencing two chain lengths cancels
    # the fetch round-trip so the rate reflects device throughput.
    def run_chain(n, state):
        t0 = time.perf_counter()
        for _ in range(n):
            state, metrics = step(state, batch)
        float(metrics["loss"])
        return time.perf_counter() - t0, state

    n1 = max(args.steps // 5, 1)
    t1, state = run_chain(n1, state)
    t2, state = run_chain(args.steps, state)
    rate = (args.steps - n1) * args.batch_size / max(t2 - t1, 1e-9)
    print(json.dumps({
        "metric": "resnet50_imagenet_ampO2_bf16_train_images_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(rate / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
    }))


if __name__ == "__main__":
    main()
