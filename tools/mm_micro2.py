#!/usr/bin/env python
"""Matmul tuning round: block_m sweep, NHWC conv reference, multi-step grid.

Question: what's the real ceiling for the 1x1-conv shape (802816,256)->(.,64)
on this chip, and can Pallas reach it?
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import jax.experimental.pallas as pl
from jax.experimental.pallas import tpu as pltpu


def loop_time(fn, init, iters=30):
    @jax.jit
    def run(carry):
        return jax.lax.fori_loop(0, iters, lambda i, c: fn(c), carry)
    out = run(init)
    float(jax.tree_util.tree_leaves(out)[-1].ravel()[0])
    t0 = time.perf_counter()
    out = run(init)
    float(jax.tree_util.tree_leaves(out)[-1].ravel()[0])
    return (time.perf_counter() - t0) / iters


M, K, N = 802816, 256, 64
NB, HH, WW = 256, 56, 56


def main():
    x = jax.random.normal(jax.random.PRNGKey(0), (M, K), jnp.bfloat16)
    x4 = x.reshape(NB, HH, WW, K)
    w = jax.random.normal(jax.random.PRNGKey(1), (K, N), jnp.bfloat16) * 0.05
    w4 = w.reshape(1, 1, K, N)
    bytes_min = (M * K + M * N) * 2

    # reference: XLA 1x1 conv in NHWC
    def conv(c):
        xx, ww, acc = c
        y = jax.lax.conv_general_dilated(
            xx, ww, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32).astype(jnp.bfloat16)
        return xx, ww, acc + y[0, 0, 0, 0].astype(jnp.float32)
    t = loop_time(conv, (x4, w4, jnp.zeros((), jnp.float32)))
    print(f"xla conv1x1 NHWC:   {t*1e3:7.3f} ms  {bytes_min/t/1e9:6.0f} GB/s")

    # XLA conv fused with a relu producer and consumer (in-model-like)
    def conv_ctx(c):
        xx, ww, acc = c
        a = jnp.maximum(xx, 0)
        y = jax.lax.conv_general_dilated(
            a, ww, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32).astype(jnp.bfloat16)
        return xx, ww, acc + y[0, 0, 0, 0].astype(jnp.float32)
    t = loop_time(conv_ctx, (x4, w4, jnp.zeros((), jnp.float32)))
    print(f"xla relu+conv1x1:   {t*1e3:7.3f} ms  {bytes_min/t/1e9:6.0f} GB/s")

    # pallas blocked matmul, block_m sweep
    for blk_m in (2048, 4096, 8192):
        def kernel(x_ref, w_ref, o_ref):
            o_ref[...] = jax.lax.dot_general(
                x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(jnp.bfloat16)
        mm = pl.pallas_call(
            kernel, grid=(M // blk_m,),
            in_specs=[pl.BlockSpec((blk_m, K), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((K, N), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((blk_m, N), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((M, N), jnp.bfloat16))

        def pl_mm(c):
            xx, ww, acc = c
            y = mm(xx, ww)
            return xx, ww, acc + y[0, 0].astype(jnp.float32)
        t = loop_time(pl_mm, (x, w, jnp.zeros((), jnp.float32)))
        print(f"pl mm blk_m={blk_m:5d}:  {t*1e3:7.3f} ms  {bytes_min/t/1e9:6.0f} GB/s")

    # pallas with wider N via K-padding? try fp32 accum output stats-only read
    # pure read benchmark: how fast can pallas stream x at all?
    blk_m = 4096
    def rd_kernel(x_ref, s_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            s_ref[...] = jnp.zeros_like(s_ref)
        s_ref[...] += jnp.sum(x_ref[...].astype(jnp.float32), axis=0)

    rd = pl.pallas_call(
        rd_kernel, grid=(M // blk_m,),
        in_specs=[pl.BlockSpec((blk_m, K), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((K,), lambda i: (0,),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((K,), jnp.float32))

    def pl_rd(c):
        xx, acc = c
        s = rd(xx)
        return xx, acc + s[0]
    t = loop_time(pl_rd, (x, jnp.zeros((), jnp.float32)))
    print(f"pl stream-read sum: {t*1e3:7.3f} ms  {M*K*2/t/1e9:6.0f} GB/s")

    # MXU-reduce read: s = ones @ x
    def rd2_kernel(x_ref, s_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            s_ref[...] = jnp.zeros_like(s_ref)
        ones = jnp.ones((8, blk_m), jnp.bfloat16)
        s_ref[...] += jax.lax.dot_general(
            ones, x_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    rd2 = pl.pallas_call(
        rd2_kernel, grid=(M // blk_m,),
        in_specs=[pl.BlockSpec((blk_m, K), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((8, K), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, K), jnp.float32))

    def pl_rd2(c):
        xx, acc = c
        s = rd2(xx)
        return xx, acc + s[0, 0]
    t = loop_time(pl_rd2, (x, jnp.zeros((), jnp.float32)))
    print(f"pl mxu-reduce read: {t*1e3:7.3f} ms  {M*K*2/t/1e9:6.0f} GB/s")


if __name__ == "__main__":
    main()
