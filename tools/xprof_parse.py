#!/usr/bin/env python
"""Parse an already-captured xplane.pb and print top ops by self time.

Usage: PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \
       python tools/xprof_parse.py /tmp/xprof_c2 [--top 40]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("logdir")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--tool", default="framework_op_stats")
    args = ap.parse_args()

    from tensorboard_plugin_profile.convert import raw_to_tool_data as rtd
    xplanes = glob.glob(os.path.join(args.logdir, "**", "*.xplane.pb"),
                        recursive=True)
    assert xplanes, f"no xplane under {args.logdir}"
    xp = max(xplanes, key=os.path.getmtime)
    data, _ = rtd.xspace_to_tool_data([xp], args.tool, {})
    if isinstance(data, bytes):
        try:
            data = data.decode()
        except UnicodeDecodeError:
            out = os.path.join(args.logdir, args.tool + ".bin")
            with open(out, "wb") as f:
                f.write(data)
            print("binary output ->", out)
            return
    try:
        j = json.loads(data)
    except Exception:
        print(data[:8000])
        return

    # gviz table format: [{cols, rows}, ...] or dict
    tables = j if isinstance(j, list) else [j]
    for t in tables:
        if not isinstance(t, dict) or "cols" not in t:
            continue
        cols = [c.get("label") or c.get("id") for c in t["cols"]]
        print("\t".join(str(c) for c in cols))
        for row in t["rows"][:args.top]:
            vals = [c.get("v") for c in row["c"]]
            print("\t".join(str(v) for v in vals))
        print("---")


if __name__ == "__main__":
    main()
