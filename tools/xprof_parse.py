#!/usr/bin/env python
"""Parse an already-captured xplane.pb and print top ops by self time.

Usage: PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \
       python tools/xprof_parse.py /tmp/xprof_c2 [--top 40]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from xprof_common import latest_xplane, tool_data  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("logdir")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--tool", default="framework_op_stats")
    args = ap.parse_args()

    data = tool_data(latest_xplane(args.logdir), args.tool)
    if isinstance(data, bytes):
        try:
            data = data.decode()
        except UnicodeDecodeError:
            out = os.path.join(args.logdir, args.tool + ".bin")
            with open(out, "wb") as f:
                f.write(data)
            print("binary output ->", out)
            return
    try:
        j = json.loads(data)
    except Exception:
        print(data[:8000])
        return

    # gviz table format: [{cols, rows}, ...] or dict
    tables = j if isinstance(j, list) else [j]
    for t in tables:
        if not isinstance(t, dict) or "cols" not in t:
            continue
        cols = [c.get("label") or c.get("id") for c in t["cols"]]
        print("\t".join(str(c) for c in cols))
        for row in t["rows"][:args.top]:
            vals = [c.get("v") for c in row["c"]]
            print("\t".join(str(v) for v in vals))
        print("---")


if __name__ == "__main__":
    main()
