#!/usr/bin/env python
"""Validate a telemetry JSONL file against the obs schema.

Thin client of apex_example_tpu.obs.schema — no jax import needed, so it
runs anywhere the repo is checked out:

    python tools/metrics_lint.py out.jsonl
    python tools/metrics_lint.py out.jsonl --require grad_norm --steps 10

Exit status: 0 when every line parses and validates (and the --require /
--steps demands hold), 1 otherwise.  The tier-1 smoke test
(tests/test_obs.py) runs this over a 10-step C1 run.
"""

from __future__ import annotations

import argparse
import collections
import importlib.util
import json
import os
import sys


def _load_schema():
    """Load obs/schema.py directly by path: importing the package would
    pull in jax via apex_example_tpu/__init__, and a lint tool must run
    on hosts that only have the file."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "apex_example_tpu", "obs", "schema.py")
    spec = importlib.util.spec_from_file_location("apex_obs_schema", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


validate_stream = _load_schema().validate_stream


def lint(path: str, require=(), steps: int = None) -> tuple[int, list]:
    """(exit_code, errors).  ``require``: fields every step record must
    carry beyond the schema's required set.  ``steps``: exact expected
    step-record count."""
    errors = []
    records = []
    with open(path) as fh:
        for n, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                errors.append(f"line {n + 1}: not JSON ({e})")
    errors.extend(validate_stream(records))

    kinds = collections.Counter(
        r.get("record") for r in records if isinstance(r, dict))
    for i, rec in enumerate(records):
        if isinstance(rec, dict) and rec.get("record") == "step":
            for field in require:
                if field not in rec:
                    errors.append(f"line {i + 1}: step record missing "
                                  f"required-by-caller field {field!r}")
    if steps is not None and kinds.get("step", 0) != steps:
        errors.append(f"expected {steps} step records, found "
                      f"{kinds.get('step', 0)}")
    return (1 if errors else 0), errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("path", help="JSONL file a JsonlSink wrote")
    ap.add_argument("--require", default="",
                    help="comma list of fields every step record must "
                         "carry (e.g. grad_norm,items_per_sec)")
    ap.add_argument("--steps", type=int, default=None,
                    help="exact expected number of step records")
    args = ap.parse_args(argv)
    require = [f for f in args.require.split(",") if f]
    code, errors = lint(args.path, require=require, steps=args.steps)
    for e in errors:
        print(f"{args.path}: {e}", file=sys.stderr)
    if code == 0:
        with open(args.path) as fh:
            n = sum(1 for line in fh if line.strip())
        print(f"{args.path}: {n} records OK")
    return code


if __name__ == "__main__":
    sys.exit(main())
