#!/usr/bin/env python
"""Validate a telemetry JSONL file against the obs schema.

Thin client of apex_example_tpu.obs.schema — no jax import needed, so it
runs anywhere the repo is checked out:

    python tools/metrics_lint.py out.jsonl
    python tools/metrics_lint.py out.jsonl --require grad_norm --steps 10
    python tools/metrics_lint.py out.jsonl --require-summary

Schema v2 streams (the diagnostics records: crash_dump / stall /
overflow_event, aborted run summaries), v3 streams (the serving
records), v4 streams (the resilience records: preemption / restart /
resume, run summaries with restart_count), v5 streams (the serving-
resilience records: request_failed / shed / serve_drain, serve
summaries with per-status counts + availability), v6 streams (the
cost records: compile_event / cost_model from --cost-model runs, run
summaries with measured compile totals, serve summaries with the
KV-occupancy gauges) and v7 streams (the block-paged KV stratum:
serve summaries with block_size / blocks_total / blocks_live /
kv_bytes_committed / prefix_hit_rate / cow_copies / rejected, the
block-accurate kv_waste_pct, request_failed status "rejected"), v8
streams (the static-analysis stratum: compile_event gains
``recompile_cause``, the graftlint HLO diff naming the first divergent
op behind a recompile), v9 streams (the trace stratum from --trace
runs: ``trace_event`` timeline records — ph B/E/X/i, perf_counter
``ts``/``dur``, span_id/parent_id trees, a stream-grouping trace_id —
plus the one-per-stream ``clock_sync`` wall-clock anchor
tools/trace_export.py exports against) and v10 streams (the fleet
stratum from fleet.py / apex_example_tpu/fleet/: ``route`` dispatch
records, ``replica_state`` health/lifecycle records — serve.py
replica-mode heartbeats and router transitions alike — the closing
``fleet_summary`` with per-replica breakdown + availability + the
zero-lost counter, and the supervisor's ``restart`` records gaining
the exit ``classification``), v11 streams (the quantization stratum:
``quant_event`` records announcing applied weight/KV quantization,
serve summaries with ``kv_dtype``/``weight_dtype`` and the actual-vs-
bf16-equivalent per-token bytes) and v12 streams (the sharded/
disaggregated-serving stratum: ``kv_handoff`` records — one per side
of a prefill-worker -> decode-worker KV-block transfer, with payload
byte/block/fill accounting and the decode side's transit latency —
plus ``role``/``mesh``/``dp``/``tp`` and the handoff counters on
``serve_summary``, and the dtype-accurate ``kv_bytes_live`` gauge on
``replica_state`` heartbeats) and v13 streams (the crash-safe handoff
stratum: ``kv_handoff`` gains the lease/redelivery provenance —
direction "quarantine" for corrupt payloads parked at ``*.bad``,
``redelivered`` for deliveries from a reclaimed/adopted lease,
``duplicate`` for idempotent re-admissions acked without a second
scatter — serve summaries gain ``handoff_duplicates`` /
``handoff_redelivered`` / ``handoff_quarantined``, replica heartbeats
gain ``role``, and ``fleet_summary`` gains the disagg topology +
spool accounting: ``prefill_replicas`` / ``decode_replicas`` /
``handoffs`` / ``handoff_redelivered`` / ``in_spool``) and v14
streams (the streaming-SLO stratum from --slo runs: ``slo_window``
tumbling-window scoreboards with good/bad counts, the error-budget
``burn_rate`` and mergeable log-bucket latency sketches, ``slo_breach``
records the moment a window burns past 1.0, ``fleet_rollup`` records
merging the replicas' heartbeat sketches — ``replica_state`` gains
``slo_sketch``, ``serve_summary`` gains the ``slo`` verdict dict, and
``fleet_summary`` gains the flat ``slo_verdict``/``slo_windows``/
``slo_breaches``/``slo_worst_burn`` fields) and v15 streams (the
hot-path overhead stratum from --tick-profile runs: sampled
``tick_profile`` records carrying the per-tick phase decomposition —
serve ticks into admit / dispatch_enqueue / device_wait / harvest /
spool_io / telemetry, train steps into data_wait / dispatch / device /
checkpoint / telemetry — plus the closing ``overhead_summary`` with
per-phase sketch summaries, ``host_gap_ms`` and the
``host_overhead_frac`` perf_ledger gates on; ``serve_summary`` gains
the idle-spin counters ``idle_ticks``/``idle_wait_ms`` and
``host_overhead_frac``, and ``replica_state`` heartbeats gain
``host_overhead_frac``) and v16 streams (the speculative-decoding
stratum from --speculate runs: ``serve_summary`` gains the armed
geometry ``speculate_k``/``draft_kind``, the conservation counters
``tokens_drafted``/``tokens_accepted``/``tokens_sampled`` — every
output token is an accepted draft token or a sampled one — and the
derived ``acceptance_rate``/``tokens_per_tick`` throughput verdicts)
and v17 streams (the multi-tenant scheduling stratum from --tenants
runs: ``request_complete``/``request_failed``/``shed`` gain the
``tenant`` lane stamp, ``serve_summary``/``fleet_summary`` gain the
per-tenant ``tenants`` block — counts, availability, weight/class/
budget, admitted tokens, per-tenant SLO verdicts — ``replica_state``
heartbeats gain the prefix-affinity advertisement ``prefix_keys``/
``prefix_shared_tokens``/``prefix_prompt_tokens`` and the
``tenant_admitted`` ledger, and ``fleet_summary`` gains the fleet
``prefix_hit_rate``) and v18 streams (the live-migration + elastic-
pool stratum from migration-armed runs: ``kv_migration`` records —
one per side of a mid-flight extract_live -> admit_migrated transfer,
with the committed-KV fill/block/byte accounting, the generated-token
count riding the payload, the destination's ``migration_ms`` transit
and the same ``redelivered``/``duplicate``/``requeued`` leased-spool
provenance ``kv_handoff`` carries — ``serve_summary`` gains the
``migrations_out``/``migrations_in``/``migration_requeued``/
``migration_duplicates``/``migration_redelivered``/``migration_bytes``
ledger plus ``migration_ms`` percentiles, a migrating ``serve_drain``
gains its ``migrated`` count, and ``fleet_summary`` gains
``migrations``/``migration_completed``/``migration_redelivered``/
``rebalance_migrations`` and the autoscaler's ``scale_up_events``/
``scale_down_events``)
all validate alongside v1
streams — each version's tables are a strict superset of the last.
A gracefully preempted run (train.py --preempt-grace) DOES close with a
run_summary, so --require-summary passes on it; only an actual abort
exits 2.

Exit status (the contract CI scripts key on):
  0   every line parses and validates, and the --require / --steps /
      --require-summary demands hold;
  1   parse or schema-validation errors (or a --require/--steps miss);
  2   the stream validated but carries no run_summary and
      --require-summary was demanded (i.e. an aborted/killed run whose
      flight recorder never fired).
The tier-1 smoke tests (tests/test_obs.py, tests/test_diag.py) run this
over 10-step C1 runs, clean and SIGTERM'd.
"""

from __future__ import annotations

import argparse
import collections
import importlib.util
import json
import math
import os
import sys


def _load_schema():
    """Load obs/schema.py directly by path: importing the package would
    pull in jax via apex_example_tpu/__init__, and a lint tool must run
    on hosts that only have the file."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "apex_example_tpu", "obs", "schema.py")
    spec = importlib.util.spec_from_file_location("apex_obs_schema", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


validate_stream = _load_schema().validate_stream


def pct(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list: the
    ceil(q/100 * n)-th value (1-based), clamped.  Shared by the report
    tools (telemetry_report, fleet_report); obs/metrics.Histogram applies
    the same formula on the jax side of the fence."""
    if not sorted_vals:
        return 0.0
    idx = math.ceil(q / 100.0 * len(sorted_vals)) - 1
    return sorted_vals[min(max(idx, 0), len(sorted_vals) - 1)]


def lint(path: str, require=(), steps: int = None,
         require_summary: bool = False) -> tuple[int, list]:
    """(exit_code, errors).  ``require``: fields every step record must
    carry beyond the schema's required set.  ``steps``: exact expected
    step-record count.  ``require_summary``: demand a run_summary record
    — an otherwise-valid stream without one exits 2 (see module
    docstring), distinguishing "invalid" from "aborted"."""
    errors = []
    records = []
    with open(path) as fh:
        for n, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                errors.append(f"line {n + 1}: not JSON ({e})")
    errors.extend(validate_stream(records))

    kinds = collections.Counter(
        r.get("record") for r in records if isinstance(r, dict))
    for i, rec in enumerate(records):
        if isinstance(rec, dict) and rec.get("record") == "step":
            for field in require:
                if field not in rec:
                    errors.append(f"line {i + 1}: step record missing "
                                  f"required-by-caller field {field!r}")
    if steps is not None and kinds.get("step", 0) != steps:
        errors.append(f"expected {steps} step records, found "
                      f"{kinds.get('step', 0)}")
    if errors:
        return 1, errors
    if require_summary and not kinds.get("run_summary"):
        return 2, ["stream ends without a run_summary (aborted run?)"]
    return 0, []


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("path", help="JSONL file a JsonlSink wrote")
    ap.add_argument("--require", default="",
                    help="comma list of fields every step record must "
                         "carry (e.g. grad_norm,items_per_sec)")
    ap.add_argument("--steps", type=int, default=None,
                    help="exact expected number of step records")
    ap.add_argument("--require-summary", action="store_true",
                    help="demand a run_summary record; a valid stream "
                         "without one exits 2 (aborted run)")
    args = ap.parse_args(argv)
    require = [f for f in args.require.split(",") if f]
    code, errors = lint(args.path, require=require, steps=args.steps,
                        require_summary=args.require_summary)
    for e in errors:
        print(f"{args.path}: {e}", file=sys.stderr)
    if code == 0:
        with open(args.path) as fh:
            n = sum(1 for line in fh if line.strip())
        print(f"{args.path}: {n} records OK")
    return code


if __name__ == "__main__":
    sys.exit(main())
