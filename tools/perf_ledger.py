#!/usr/bin/env python
"""perf_ledger: normalized perf snapshots + the regression baseline.

    python tools/perf_ledger.py serve.jsonl
    python tools/perf_ledger.py serve.jsonl train.jsonl --json
    python tools/perf_ledger.py serve.jsonl train.jsonl \
        --write-baseline PERF_BASELINE.json
    python tools/perf_ledger.py serve.jsonl train.jsonl \
        --compare PERF_BASELINE.json

The BENCH trajectory's missing ledger (ISSUE 17): ingest any serve /
train / fleet telemetry stream into a flat, normalized perf snapshot —
tokens/tick, throughput, TPOT, the per-phase tick decomposition from
``--tick-profile`` runs (ms/tick per phase: what each tick-millisecond
was spent on) and the ``host_overhead_frac`` ROADMAP item 5 will be
judged on — then diff it against a checked-in ``PERF_BASELINE.json``
with per-metric noise bands.  ``ci_gate --perf-stream`` wires this
into CI, so a perf claim is a regression-tested number instead of a
README sentence.

Consistency checks (always on, independent of any baseline): every
``tick_profile`` record's phase components must sum to its wall time
within 1%, and every ``overhead_summary`` must be self-consistent —
``host_gap_ms == wall_ms - device_ms``, ``host_overhead_frac ==
host_gap_ms / wall_ms``, the device phase's cumulative total must
match ``device_ms``, and the per-phase totals must sum to ``wall_ms``
within 1%.  An edited host fraction (the tamper fixture) fails here
no matter how wide the noise bands are.

Baseline shape::

    {"schema": 16,
     "streams": {"serve": {"source": "serve_perf.jsonl",
                           "metrics": {"tokens_per_tick":
                                       {"value": 3.2, "noise_pct": 5.0},
                                       ...}}}}

``--write-baseline`` derives one from the given streams with default
noise bands (exact for counters, tight for structural ratios, wide for
wall-clock-derived numbers); ``--compare`` re-snapshots the streams
and demands every baseline metric within its band.  Millisecond-scale
metrics additionally get a 0.1 ms absolute floor — a relative band on
a sub-0.1ms phase flags scheduler jitter, not regressions.  Comparing the
checked-in fixtures against the baseline derived from them is exact,
so the gate is deterministic at HEAD.

Exit status: 0 clean; 1 consistency violation or baseline regression;
2 unusable input (missing/corrupt stream or baseline).

Thin-client contract: NO jax import, direct or transitive — the phase
vocabulary comes from obs/tickprof.py loaded by FILE PATH (the
metrics_lint pattern), so this runs on the bare CI host.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import Any, Dict, List, Optional


def _load_tickprof():
    """obs/tickprof.py by file path: the phase vocabulary's single
    source of truth, without the jax-carrying package __init__."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "apex_example_tpu", "obs", "tickprof.py")
    spec = importlib.util.spec_from_file_location("_ledger_tickprof",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_tickprof = _load_tickprof()
DEVICE_PHASE = _tickprof.DEVICE_PHASE

# Components must sum to wall within this relative tolerance (the
# ISSUE 17 acceptance bound), with a small absolute slack for
# sub-millisecond ticks where float noise dominates.
SUM_TOL_REL = 0.01
SUM_TOL_ABS_MS = 1e-6
FRAC_TOL = 1e-3


def load_records(path: str) -> List[Dict[str, Any]]:
    """Parsed records, or raises ValueError naming the bad line."""
    records = []
    with open(path) as fh:
        for n, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"line {n + 1}: not JSON ({e})")
    return records


# ------------------------------------------------------- consistency

def consistency_errors(records: List[Dict[str, Any]]) -> List[str]:
    """The tamper gate: internal agreement of every tick_profile and
    overhead_summary record (empty list == consistent)."""
    errors: List[str] = []
    for i, r in enumerate(records):
        if not isinstance(r, dict):
            continue
        kind = r.get("record")
        if kind == "tick_profile":
            errors.extend(_tick_errors(i, r))
        elif kind == "overhead_summary":
            errors.extend(_summary_errors(i, r))
    return errors


def _tol(wall_ms: float) -> float:
    return max(SUM_TOL_REL * abs(wall_ms), SUM_TOL_ABS_MS)


def _tick_errors(i: int, r: Dict[str, Any]) -> List[str]:
    out: List[str] = []
    wall = r.get("wall_ms", 0.0)
    phases = r.get("phases")
    if not isinstance(phases, dict):
        return [f"record {i + 1}: tick_profile without a phases dict"]
    total = sum(v for v in phases.values()
                if isinstance(v, (int, float)))
    if abs(total - wall) > _tol(wall):
        out.append(f"record {i + 1}: tick_profile tick "
                   f"{r.get('tick')}: phases sum {total:.4f} ms vs "
                   f"wall {wall:.4f} ms — components must sum to wall "
                   f"within {SUM_TOL_REL:.0%}")
    dev = phases.get(DEVICE_PHASE.get(r.get("kind", ""), ""), 0.0)
    if not isinstance(dev, (int, float)) or isinstance(dev, bool):
        return out + [f"record {i + 1}: tick_profile device phase is "
                      "not a number (malformed phases dict)"]
    gap = r.get("host_gap_ms", 0.0)
    if abs(gap - (wall - dev)) > _tol(wall):
        out.append(f"record {i + 1}: tick_profile tick "
                   f"{r.get('tick')}: host_gap_ms {gap:.4f} != wall "
                   f"{wall:.4f} - device {dev:.4f}")
    return out


def _summary_errors(i: int, r: Dict[str, Any]) -> List[str]:
    out: List[str] = []
    wall = r.get("wall_ms", 0.0)
    device = r.get("device_ms", 0.0)
    gap = r.get("host_gap_ms", 0.0)
    frac = r.get("host_overhead_frac", 0.0)
    phases = r.get("phases")
    if abs(gap - (wall - device)) > _tol(wall):
        out.append(f"record {i + 1}: overhead_summary host_gap_ms "
                   f"{gap:.4f} != wall_ms {wall:.4f} - device_ms "
                   f"{device:.4f}")
    if wall > 0 and abs(frac - gap / wall) > FRAC_TOL:
        out.append(f"record {i + 1}: overhead_summary "
                   f"host_overhead_frac {frac:.6f} != host_gap_ms / "
                   f"wall_ms = {gap / wall:.6f} — tampered or "
                   "mis-folded")
    if isinstance(phases, dict):
        total = sum(p.get("total_ms", 0.0) for p in phases.values()
                    if isinstance(p, dict))
        if abs(total - wall) > _tol(wall):
            out.append(f"record {i + 1}: overhead_summary phase "
                       f"totals sum {total:.4f} ms vs wall_ms "
                       f"{wall:.4f} — components must sum to wall "
                       f"within {SUM_TOL_REL:.0%}")
        devp = phases.get(DEVICE_PHASE.get(r.get("kind", ""), ""))
        if isinstance(devp, dict) \
                and abs(devp.get("total_ms", 0.0) - device) > _tol(wall):
            out.append(f"record {i + 1}: overhead_summary device_ms "
                       f"{device:.4f} != device phase total "
                       f"{devp.get('total_ms', 0.0):.4f}")
    return out


# ---------------------------------------------------------- snapshot

def _find(records, kind) -> Optional[Dict[str, Any]]:
    found = [r for r in records if isinstance(r, dict)
             and r.get("record") == kind]
    return found[-1] if found else None


def snapshot(records: List[Dict[str, Any]],
             source: str) -> Optional[Dict[str, Any]]:
    """One stream -> {"kind", "source", "metrics": {flat scalars}};
    None when the stream carries no recognizable summary."""
    fleet = _find(records, "fleet_summary")
    serve = _find(records, "serve_summary")
    train = _find(records, "run_summary")
    overhead = _find(records, "overhead_summary")
    metrics: Dict[str, float] = {}
    if fleet is not None:
        kind = "fleet"
        metrics["replicas"] = fleet.get("replicas", 0)
        metrics["requests"] = fleet.get("requests", 0)
        metrics["availability"] = fleet.get("availability", 0.0)
        worst = worst_overhead_replica(records)
        if worst is not None:
            metrics["worst_host_overhead_frac"] = worst[1]
    elif serve is not None:
        kind = "serve"
        metrics["requests"] = serve.get("requests", 0)
        metrics["output_tokens"] = serve.get("output_tokens", 0)
        metrics["compute_steps"] = serve.get("compute_steps", 0)
        metrics["tokens_per_sec"] = serve.get("tokens_per_sec", 0.0)
        if serve.get("compute_steps"):
            metrics["tokens_per_tick"] = round(
                serve["output_tokens"] / serve["compute_steps"], 4)
        if isinstance(serve.get("tpot_ms"), dict):
            metrics["tpot_p50_ms"] = serve["tpot_ms"].get("p50", 0.0)
        if isinstance(serve.get("ttft_ms"), dict):
            metrics["ttft_p50_ms"] = serve["ttft_ms"].get("p50", 0.0)
        if "availability" in serve:
            metrics["availability"] = serve["availability"]
        if "idle_ticks" in serve:
            metrics["idle_ticks"] = serve["idle_ticks"]
        if "idle_wait_ms" in serve:
            metrics["idle_wait_ms"] = serve["idle_wait_ms"]
        # v16 (ISSUE 18): the speculation ledger — acceptance_rate is
        # the drafting-quality headline a proposer regression moves
        # first, ahead of the tokens_per_tick it produces.
        if "acceptance_rate" in serve:
            metrics["acceptance_rate"] = serve["acceptance_rate"]
    elif train is not None or (overhead is not None
                               and overhead.get("kind") == "train"):
        kind = "train"
        if train is not None:
            metrics["steps"] = train.get("steps", 0)
            if "items_per_sec" in train:
                metrics["items_per_sec"] = train["items_per_sec"]
            if "steady_step_ms" in train:
                metrics["steady_step_ms"] = train["steady_step_ms"]
    else:
        return None
    if overhead is not None:
        metrics["ticks"] = overhead.get("ticks", 0)
        metrics["host_overhead_frac"] = overhead.get(
            "host_overhead_frac", 0.0)
        ticks = overhead.get("ticks") or 0
        if ticks:
            # The TPOT decomposition: mean milliseconds each phase
            # contributes to one tick — what each tick-ms was spent on.
            metrics["wall_ms_per_tick"] = round(
                overhead.get("wall_ms", 0.0) / ticks, 4)
            metrics["host_gap_ms_per_tick"] = round(
                overhead.get("host_gap_ms", 0.0) / ticks, 4)
            phases = overhead.get("phases")
            if isinstance(phases, dict):
                for name, p in sorted(phases.items()):
                    if isinstance(p, dict):
                        metrics[f"phase_{name}_ms_per_tick"] = round(
                            p.get("total_ms", 0.0) / ticks, 4)
    return {"kind": kind, "source": os.path.basename(source),
            "metrics": metrics}


def worst_overhead_replica(records) -> Optional[tuple]:
    """(replica, frac) with the highest advertised host_overhead_frac
    across replica_state heartbeats; None when no heartbeat carries
    one.  Shared with fleet_report."""
    best: Optional[tuple] = None
    for r in records:
        if not isinstance(r, dict) \
                or r.get("record") != "replica_state":
            continue
        frac = r.get("host_overhead_frac")
        if isinstance(frac, (int, float)) and not isinstance(frac, bool):
            if best is None or frac > best[1]:
                best = (r.get("replica", "?"), float(frac))
    return best


# ---------------------------------------------------------- baseline

def default_noise_pct(name: str) -> float:
    """Per-metric noise band: counters are exact, structural ratios
    tight, wall-clock-derived numbers wide (a CI host's clock is not a
    benchmark rig)."""
    if name in ("requests", "output_tokens", "compute_steps", "steps",
                "ticks", "replicas", "idle_ticks"):
        return 0.0
    if name.endswith("_frac") or name == "availability":
        return 10.0
    if name in ("tokens_per_tick", "acceptance_rate"):
        # With --speculate armed both are workload-shaped rather than
        # structural: deterministic per seed, but a legitimate drafting
        # change moves them a few percent.  5% is the EXPLICIT band a
        # speculation-armed baseline rides; a real acceptance collapse
        # (draft path broken, tokens/tick back near 1.0) blows well
        # through it.
        return 5.0
    return 50.0


# Absolute noise floor for millisecond-scale metrics.  A relative band
# alone is meaningless on a sub-0.1ms phase (device_wait on the CPU rig
# sits at ~0.03 ms/tick): doubling it is pure scheduler jitter on a
# loaded host, not a regression.  Counters, fracs and rates keep the
# purely relative band.
ABS_FLOOR_MS = 0.1


def _abs_floor(name: str) -> float:
    return ABS_FLOOR_MS if name.endswith("_ms_per_tick") or \
        name.endswith("_ms") else 0.0


def make_baseline(snapshots: List[Dict[str, Any]]) -> Dict[str, Any]:
    streams: Dict[str, Any] = {}
    for snap in snapshots:
        streams[snap["kind"]] = {
            "source": snap["source"],
            "metrics": {
                name: {"value": value,
                       "noise_pct": default_noise_pct(name)}
                for name, value in sorted(snap["metrics"].items())
            },
        }
    return {"schema": 16, "streams": streams}


def compare(snapshots: List[Dict[str, Any]],
            baseline: Dict[str, Any]) -> List[str]:
    """Regressions of ``snapshots`` against ``baseline`` (empty list ==
    within every band).  Every baseline stream kind must be present and
    every baseline metric within value +- noise_pct%."""
    failures: List[str] = []
    by_kind = {s["kind"]: s for s in snapshots}
    for kind, spec in sorted(baseline.get("streams", {}).items()):
        snap = by_kind.get(kind)
        if snap is None:
            failures.append(f"{kind}: baseline stream kind missing "
                            "from the given streams")
            continue
        for name, m in sorted(spec.get("metrics", {}).items()):
            base, band = m.get("value"), m.get("noise_pct", 0.0)
            got = snap["metrics"].get(name)
            if got is None:
                failures.append(f"{kind}: metric {name!r} missing "
                                f"(baseline {base})")
                continue
            tol = abs(base) * band / 100.0 + _abs_floor(name) + 1e-9
            if abs(got - base) > tol:
                failures.append(
                    f"{kind}: {name} = {got} vs baseline {base} "
                    f"(noise band {band}%) — regression")
    return failures


# --------------------------------------------------------------- cli

def _print_snapshot(snap: Dict[str, Any]) -> None:
    m = snap["metrics"]
    head = f"perf_ledger: {snap['kind']} {snap['source']}:"
    parts = []
    for key in ("tokens_per_tick", "tokens_per_sec", "items_per_sec",
                "tpot_p50_ms", "steady_step_ms", "availability",
                "host_overhead_frac", "worst_host_overhead_frac"):
        if key in m:
            parts.append(f"{key}={m[key]}")
    print(head + " " + "  ".join(parts) if parts else head)
    decomp = {k: v for k, v in sorted(m.items())
              if k.startswith("phase_")}
    if decomp:
        inner = "  ".join(
            f"{k[len('phase_'):-len('_ms_per_tick')]}={v}"
            for k, v in decomp.items())
        print(f"  decomposition (ms/tick): {inner}  "
              f"wall={m.get('wall_ms_per_tick')}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="normalized perf snapshots + regression baseline")
    ap.add_argument("streams", nargs="+", metavar="JSONL",
                    help="serve/train/fleet telemetry stream(s)")
    ap.add_argument("--compare", default=None, metavar="BASELINE",
                    help="diff the snapshots against this "
                         "PERF_BASELINE.json (exit 1 outside any "
                         "noise band)")
    ap.add_argument("--write-baseline", default=None, metavar="PATH",
                    help="write a baseline derived from the given "
                         "streams (default noise bands)")
    ap.add_argument("--json", action="store_true",
                    help="print the snapshots as JSON instead of the "
                         "report lines")
    args = ap.parse_args(argv)

    snapshots = []
    rc = 0
    for path in args.streams:
        if not os.path.isfile(path):
            print(f"perf_ledger: no such stream: {path}",
                  file=sys.stderr)
            return 2
        try:
            records = load_records(path)
        except ValueError as e:
            print(f"perf_ledger: {path}: {e}", file=sys.stderr)
            return 2
        for e in consistency_errors(records):
            print(f"perf_ledger: {path}: {e}", file=sys.stderr)
            rc = 1
        snap = snapshot(records, path)
        if snap is None:
            print(f"perf_ledger: {path}: no serve_summary/run_summary/"
                  "fleet_summary — not a perf stream", file=sys.stderr)
            return 2
        snapshots.append(snap)

    if args.json:
        print(json.dumps(snapshots, indent=2, sort_keys=True))
    else:
        for snap in snapshots:
            _print_snapshot(snap)

    if args.write_baseline:
        with open(args.write_baseline, "w") as fh:
            json.dump(make_baseline(snapshots), fh, indent=2,
                      sort_keys=True)
            fh.write("\n")
        print(f"perf_ledger: baseline written to "
              f"{args.write_baseline}")

    if args.compare:
        if not os.path.isfile(args.compare):
            print(f"perf_ledger: no such baseline: {args.compare}",
                  file=sys.stderr)
            return 2
        try:
            with open(args.compare) as fh:
                baseline = json.load(fh)
        except (json.JSONDecodeError, OSError) as e:
            print(f"perf_ledger: {args.compare}: {e}", file=sys.stderr)
            return 2
        failures = compare(snapshots, baseline)
        for f in failures:
            print(f"perf_ledger: {f}", file=sys.stderr)
        if failures:
            rc = 1
        print(f"perf_ledger: compare vs {args.compare}: "
              f"{'PASS' if not failures else 'FAIL'}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
