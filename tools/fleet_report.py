#!/usr/bin/env python
"""Fleet report: training ranks OR a serving fleet, one tool.

TRAIN-RANK MODE (the original): merge per-rank --metrics-jsonl files
and find the rank that is ruining everyone's day.

Multi-host runs with ``--metrics-all-ranks`` write one JSONL file per
process (``out.jsonl`` for rank 0, ``out.jsonl.rankK`` for K > 0); every
rank's step dispatch is gated on the same collectives, so ONE slow or
sick host drags the whole fleet — production TPU practice says stragglers
and silent per-host faults dominate debugging time.  This tool
cross-compares the files no other tool reads together:

    python tools/fleet_report.py out.jsonl            # auto-discovers
                                                      # out.jsonl.rank*
    python tools/fleet_report.py r0.jsonl r1.jsonl    # explicit files

SERVE-FLEET MODE (ISSUE 12): point it at a fleet-router stream
(fleet.py --metrics-jsonl; schema-v10 ``route`` / ``replica_state`` /
``fleet_summary`` records) and it renders the serving-fleet story
instead — detected automatically by the records present:

    python tools/fleet_report.py fleet.jsonl
    #   serve fleet: 2 replica(s), policy round_robin,
    #       scenario rolling_restart
    #   replica  dispatched  ok  drained  lost  avail  state
    #   ...
    #   routing balance: skew 1.11x
    #   scenario verdict: PASS (availability 1.0, lost 0)

Per-replica availability, routing-balance skew (max dispatches over the
mean — ``--skew-factor`` flags imbalance), replica lifecycle anomalies
(crashes/stalls, with the supervisor's v10 exit classification), the
scenario verdict line, and — on a v13 disaggregated fleet — the DISAGG
line (prefill/decode topology, handoff count, redelivered admissions,
uids stuck in the spool at close: a spool leak is flagged as its own
anomaly).  On a v18 migration-armed fleet (ISSUE 20) the MIGRATION
line reports mid-flight transfers (shipped vs completed, peer
redeliveries, rebalance asks, transit percentiles recomputed from the
teed ``kv_migration`` records) — a migrated uid that never completed
is flagged — and the AUTOSCALE line reports the elastic-pool
scale-up/scale-down events.  On a v17 multi-tenant fleet (ISSUE 19)
the TENANT lines
name the starved tenant (lowest availability) and the noisiest one
(most admitted tokens), flag failing per-tenant SLO verdicts outside
chaos scenarios, and report the fleet prefix-affinity hit rate when
the replicas advertised prefix keys.  Still jax-free — same
thin-client contract, proved by graftlint's import rule.

Train-rank checks:
- per-rank status: aborted (crash_dump / aborted summary / no summary),
  stalls, step-record counts that diverge across ranks;
- straggler: a rank whose steady-state p50 step time exceeds
  ``--straggler-factor`` x the fleet median of p50s;
- overflow divergence: ranks disagreeing on WHICH steps overflowed
  (data-parallel overflow skips are a collective decision — divergence
  means replicated state has forked);
- loss spikes (step loss > ``--spike-factor`` x the rank's median) and
  step-time regression (second-half p50 > ``--regress-factor`` x
  first-half p50, compile step excluded).

No jax import; works on any host with the files.  Exit codes: 0 = no
anomalies, 1 = anomalies flagged, 2 = unusable input (no readable files /
no step records anywhere).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Same no-jax file-path load as tools/telemetry_report.py.
from metrics_lint import pct as _pct  # noqa: E402  (sibling import)
from metrics_lint import validate_stream  # noqa: E402


def _median(vals: List[float]) -> float:
    return _pct(sorted(vals), 50)


def discover(paths: List[str]) -> Dict[int, str]:
    """Map rank -> file.  A single path expands to itself + its
    ``.rankK`` siblings; explicit lists take ranks from the suffix (or
    positionally when none carries one)."""
    if len(paths) == 1 and not re.search(r"\.rank\d+$", paths[0]):
        base = paths[0]
        # Filter before sorting: a stale sibling like out.jsonl.rank1.bak
        # matches the glob but not the rank shape — skip it, don't crash.
        siblings = [p for p in glob.glob(glob.escape(base) + ".rank*")
                    if re.search(r"\.rank\d+$", p)]
        paths = [base] + sorted(
            siblings, key=lambda p: int(p.rsplit("rank", 1)[1]))
    out: Dict[int, str] = {}
    for i, path in enumerate(paths):
        m = re.search(r"\.rank(\d+)$", path)
        out[int(m.group(1)) if m else i] = path
    return out


def load_rank(path: str) -> Optional[dict]:
    """Parse + summarize one rank's stream (None when unreadable)."""
    records = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    pass                    # killed runs truncate the tail
    except OSError as e:
        print(f"WARNING: {path}: {e}", file=sys.stderr)
        return None
    steps = [r for r in records if isinstance(r, dict)
             and r.get("record") == "step" and "step_time_ms" in r]
    summary = next((r for r in records
                    if r.get("record") == "run_summary"), None)
    crash = next((r for r in records
                  if r.get("record") == "crash_dump"), None)
    stalls = [r for r in records if r.get("record") == "stall"]
    overflow_steps = sorted(r["step"] for r in steps
                            if r.get("grads_finite", 1) < 1)
    times = [r["step_time_ms"] for r in steps]
    return {
        "path": path,
        "records": records,
        "schema_errors": validate_stream(records),
        "steps": steps,
        "n_steps": len(steps),
        "times_ms": times,
        # steady state: the first step is trace+compile+execute
        "steady_ms": times[1:] if len(times) > 1 else times,
        "losses": [r["loss"] for r in steps if "loss" in r],
        "overflow_steps": overflow_steps,
        "summary": summary,
        "crash": crash,
        "stalls": stalls,
        "aborted": (crash is not None or summary is None
                    or bool(summary.get("aborted"))),
        "abort_reason": (crash or {}).get(
            "reason", (summary or {}).get(
                "abort_reason",
                None if summary is not None else "no run_summary")),
    }


def analyze(ranks: Dict[int, dict], straggler_factor: float,
            spike_factor: float, regress_factor: float,
            out=sys.stdout) -> int:
    """Print the report; returns the anomaly count."""
    anomalies = 0
    ids = sorted(ranks)

    # ---- fleet table -------------------------------------------------
    counts = sorted({ranks[i]["n_steps"] for i in ids})
    print(f"fleet: {len(ids)} rank(s), "
          + (f"{counts[0]} steps each" if len(counts) == 1 else
             f"step counts DIVERGE {counts}"), file=out)
    print("rank  steps  p50_ms    p95_ms    overflows  status", file=out)
    p50s = {}
    for i in ids:
        r = ranks[i]
        steady = sorted(r["steady_ms"])
        p50s[i] = _pct(steady, 50)
        status = "ok"
        if r["aborted"]:
            status = f"ABORTED ({r['abort_reason']})"
        elif r["stalls"]:
            status = f"stalled x{len(r['stalls'])}"
        print(f"{i:<5} {r['n_steps']:<6} {p50s[i]:<9.1f} "
              f"{_pct(steady, 95):<9.1f} {len(r['overflow_steps']):<10} "
              f"{status}", file=out)

    # ---- cross-rank checks ------------------------------------------
    if len(counts) > 1:
        anomalies += 1
        print(f"DIVERGENT STEP COUNTS: {counts} — a rank fell out of the "
              "run early", file=out)
    for i in ids:
        if ranks[i]["aborted"]:
            anomalies += 1
            print(f"ABORTED: rank {i} ({ranks[i]['abort_reason']})",
                  file=out)
        for s in ranks[i]["stalls"]:
            anomalies += 1
            print(f"STALL: rank {i} at step {s.get('step', '?')} — "
                  f"{s.get('seconds_since_step', 0):.0f}s without a step",
                  file=out)

    fleet_median = _median([p50s[i] for i in ids]) if ids else 0.0
    if fleet_median > 0:
        for i in ids:
            if p50s[i] > straggler_factor * fleet_median:
                anomalies += 1
                print(f"STRAGGLER: rank {i} p50 {p50s[i]:.1f} ms = "
                      f"{p50s[i] / fleet_median:.2f}x the fleet median "
                      f"{fleet_median:.1f} ms", file=out)

    overflow_sets = {i: set(ranks[i]["overflow_steps"]) for i in ids}
    union = set().union(*overflow_sets.values()) if ids else set()
    if union and any(overflow_sets[i] != union for i in ids):
        anomalies += 1
        detail = ", ".join(
            f"rank {i}: {sorted(overflow_sets[i])}" for i in ids)
        print("OVERFLOW DIVERGENCE: ranks disagree on which steps "
              f"overflowed ({detail}) — the overflow-skip decision must "
              "be collective; replicated state has likely forked",
              file=out)

    # ---- per-rank anomaly rules -------------------------------------
    for i in ids:
        r = ranks[i]
        if len(r["losses"]) >= 4:
            med = _median(r["losses"])
            spikes = [(rec["step"], rec["loss"]) for rec in r["steps"]
                      if "loss" in rec and med > 0
                      and rec["loss"] > spike_factor * med]
            if spikes:
                anomalies += 1
                step, loss = spikes[0]
                print(f"LOSS SPIKE: rank {i} step {step} loss {loss:.4g} "
                      f"> {spike_factor:.1f}x median {med:.4g} "
                      f"({len(spikes)} step(s))", file=out)
        steady = r["steady_ms"]
        if len(steady) >= 8:
            half = len(steady) // 2
            first, second = (_median(steady[:half]), _median(steady[half:]))
            if first > 0 and second > regress_factor * first:
                anomalies += 1
                print(f"STEP-TIME REGRESSION: rank {i} second-half p50 "
                      f"{second:.1f} ms = {second / first:.2f}x first-half "
                      f"{first:.1f} ms", file=out)
        for e in r["schema_errors"]:
            print(f"WARNING: rank {i}: {e}", file=sys.stderr)

    print(f"anomalies: {anomalies}", file=out)
    return anomalies


# ------------------------------------------------- serve-fleet mode

def load_fleet_records(path: str) -> Optional[List[dict]]:
    """Parse one file; return its records when it is a fleet-router
    stream (carries fleet records), else None."""
    records = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    pass                # killed runs truncate the tail
    except OSError as e:
        print(f"WARNING: {path}: {e}", file=sys.stderr)
        return None
    kinds = {r.get("record") for r in records if isinstance(r, dict)}
    # Router-EXCLUSIVE markers only: a serve.py replica child's own
    # stream also carries replica_state heartbeats, and it must fall
    # through to the rank path (serve_report is its real tool), not be
    # misread as a truncated router stream.  A router stream killed
    # before its first dispatch still self-identifies via its header
    # platform, so the truncation diagnostic stays reachable.
    if kinds & {"fleet_summary", "route"}:
        return records
    header = next((r for r in records
                   if isinstance(r, dict)
                   and r.get("record") == "run_header"), None)
    if header is not None and header.get("platform") == "fleet-router":
        return records
    return None


def analyze_fleet(records: List[dict], skew_factor: float,
                  out=sys.stdout) -> int:
    """The serve-fleet report; returns the anomaly count (exit-code
    semantics match the rank mode: 0 clean, 1 anomalies, 2 unusable —
    the caller maps a missing fleet_summary to 2)."""
    anomalies = 0
    summary = next((r for r in records
                    if r.get("record") == "fleet_summary"), None)
    routes = [r for r in records if r.get("record") == "route"]
    states = [r for r in records if r.get("record") == "replica_state"]
    if summary is None:
        print("no fleet_summary record (was the router stream "
              "truncated?)", file=sys.stderr)
        return -1
    scenario = summary.get("scenario", "none")
    print(f"serve fleet: {summary['replicas']} replica(s), policy "
          f"{summary.get('policy', '?')}, scenario {scenario}, "
          f"{summary['requests']} request(s) in "
          f"{summary.get('duration_s', 0.0):.1f}s", file=out)

    per = summary.get("per_replica", {})
    print("replica  dispatched  ok    drained  lost  avail  state",
          file=out)
    for name in sorted(per):
        stats = per[name]
        avail = stats.get("availability", 1.0)
        print(f"{name:<8} {stats.get('dispatches', 0):<11} "
              f"{stats.get('ok', 0):<5} {stats.get('drained', 0):<8} "
              f"{stats.get('lost', 0):<5} {avail:<6} "
              f"{stats.get('state', '?')}", file=out)
        if avail < 1.0:
            anomalies += 1
            print(f"REPLICA AVAILABILITY: {name} = {avail} < 1.0 "
                  "(non-ok terminal statuses on this replica)",
                  file=out)

    routing = summary.get("routing", {})
    skew = routing.get("balance_skew", 0.0)
    print(f"routing balance: {len(routes)} route record(s), "
          f"skew {skew}x", file=out)
    if skew > skew_factor:
        anomalies += 1
        print(f"ROUTING IMBALANCE: max dispatches = {skew}x the mean "
              f"(> {skew_factor}x) — one replica is soaking the "
              "fleet", file=out)

    # v17 multi-tenant scheduling (ISSUE 19): a --tenants-armed router
    # folds one verdict block per scheduling lane into fleet_summary.
    # Name the starved tenant (lowest availability) and the noisiest
    # one (most admitted tokens) — the two ends of the fairness story.
    # A failing per-tenant verdict is an anomaly outside chaos
    # scenarios (which EXPECT a victim to breach), same rule as the
    # DOWN transitions below.  Pre-v17 streams skip the block.
    tenants = summary.get("tenants")
    if isinstance(tenants, dict) and tenants:
        rows = []
        for name, blk in tenants.items():
            blk = blk or {}
            owned = sum((blk.get("counts") or {}).values())
            rows.append((name, owned, blk.get("availability", 1.0),
                         blk.get("slo_verdict"),
                         blk.get("admitted_tokens", 0)))
            if blk.get("slo_verdict") == "fail" \
                    and scenario in ("none", None):
                anomalies += 1
                print(f"TENANT SLO: {name} failed its per-tenant "
                      "windows", file=out)
        starved = min(rows, key=lambda r: (r[2], r[0]))
        noisiest = max(rows, key=lambda r: (r[4], r[1], r[0]))
        detail = "  ".join(
            f"{name} x{owned} avail={avail}"
            + (f" slo={verdict}" if verdict else "")
            for name, owned, avail, verdict, _ in rows)
        print(f"TENANT: {detail}", file=out)
        print(f"TENANT: starved={starved[0]} "
              f"(availability={starved[2]})  noisiest={noisiest[0]} "
              f"(admitted_tokens={noisiest[4]})", file=out)
    if "prefix_hit_rate" in summary:
        print(f"prefix affinity: fleet hit_rate "
              f"{summary['prefix_hit_rate']}", file=out)

    # v15 hot-path attribution (ISSUE 17): replicas armed with
    # --tick-profile advertise their host-overhead fraction on every
    # heartbeat; name the worst one so a fleet-wide perf question
    # ("who is burning host time?") has a one-line answer.  Pre-v15
    # streams carry no fraction and skip the line.
    fracs: Dict[str, float] = {}
    for rec in states:
        f = rec.get("host_overhead_frac")
        if isinstance(f, (int, float)) and not isinstance(f, bool):
            name = rec.get("replica", "?")
            if name not in fracs or f > fracs[name]:
                fracs[name] = float(f)
    if fracs:
        worst = max(fracs, key=lambda n: fracs[n])
        print(f"host overhead: worst replica {worst} at "
              f"{fracs[worst]:.4f} "
              f"({len(fracs)} replica(s) reporting)", file=out)

    # Lifecycle anomalies the router recorded (crash/stall transitions
    # carry the supervisor's v10 exit classification when known).
    for rec in states:
        if rec.get("state") in ("crashed", "stalled"):
            cls = rec.get("classification")
            print(f"DOWN: replica {rec['replica']} went "
                  f"{rec['state']}"
                  + (f" (classification {cls})" if cls else ""),
                  file=out)
            if scenario in ("none", None):
                anomalies += 1          # chaos scenarios EXPECT these

    if summary.get("lost", 0):
        anomalies += 1
        print(f"LOST REQUESTS: {summary['lost']} uid(s) never reached "
              "a terminal status", file=out)
    retries = summary.get("retries", 0)
    requeued = summary.get("drained_requeued", 0)
    if retries or requeued:
        print(f"recovery: {requeued} drain-requeue(s), {retries} "
              f"crash-retry(s), {summary.get('duplicates', 0)} "
              "duplicate report(s) ignored", file=out)

    # v13 disagg topology (ISSUE 15): a fleet split into prefill and
    # decode roles over a leased KV spool reports its handoff story —
    # uids still IN the spool at close never got decoded, which the
    # lost counter also caught, but naming the spool points at the
    # right subsystem.
    if "prefill_replicas" in summary or "decode_replicas" in summary:
        print(f"DISAGG: {summary.get('prefill_replicas', 0)} prefill + "
              f"{summary.get('decode_replicas', 0)} decode replica(s)  "
              f"{summary.get('handoffs', 0)} handoff(s)  "
              f"{summary.get('handoff_redelivered', 0)} redelivered  "
              f"{summary.get('in_spool', 0)} in spool at close",
              file=out)
        if summary.get("in_spool", 0):
            anomalies += 1
            print(f"SPOOL LEAK: {summary['in_spool']} uid(s) still on "
                  "the KV spool at close — no decode worker finished "
                  "them", file=out)

    # v18 live migration (ISSUE 20): a migration-armed fleet reports
    # its mid-flight transfer story — uids shipped with their KV vs
    # uids that reached a terminal afterwards (a gap is a lost
    # request, flagged even though the lost counter caught it too:
    # naming migration points at the right subsystem), peer
    # redeliveries (the leased ack-crash protocol firing), rebalance
    # asks, and transit percentiles recomputed from the teed
    # kv_migration records.  Pre-v18 streams carry none of these
    # fields and skip the block silently.
    if "migrations" in summary:
        migs = summary.get("migrations", 0)
        done = summary.get("migration_completed", 0)
        line = (f"MIGRATION: {migs} uid(s) shipped mid-flight  "
                f"{done} completed after migration  "
                f"{summary.get('migration_redelivered', 0)} "
                f"peer-redelivered")
        if summary.get("rebalance_migrations"):
            line += (f"  {summary['rebalance_migrations']} "
                     "rebalance ask(s)")
        lats = sorted(r["migration_ms"] for r in records
                      if r.get("record") == "kv_migration"
                      and "migration_ms" in r)
        if lats:
            line += (f"  transit p50 {_pct(lats, 50):.1f} "
                     f"p99 {_pct(lats, 99):.1f} (ms)")
        print(line, file=out)
        if done < migs:
            anomalies += 1
            print(f"MIGRATION LOSS: {migs - done} migrated uid(s) "
                  "never reached a terminal status", file=out)
    if "scale_up_events" in summary or "scale_down_events" in summary:
        print(f"AUTOSCALE: {summary.get('scale_up_events', 0)} "
              f"scale-up(s), {summary.get('scale_down_events', 0)} "
              "scale-down(s)", file=out)

    avail = summary["availability"]
    verdict = summary.get("verdict")
    if verdict is not None:
        print(f"scenario verdict: {verdict.upper()} (availability "
              f"{avail}, lost {summary.get('lost', 0)})", file=out)
        if verdict != "pass":
            anomalies += 1
    elif avail < 1.0:
        anomalies += 1
        print(f"FLEET AVAILABILITY: {avail} < 1.0", file=out)

    print(f"anomalies: {anomalies}", file=out)
    return anomalies


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="cross-host straggler/anomaly report over per-rank "
                    "--metrics-jsonl files, or a serve-fleet report "
                    "over a fleet-router stream (auto-detected)")
    ap.add_argument("paths", nargs="+",
                    help="rank-0 file (siblings .rankK auto-discovered),"
                         " an explicit list of per-rank files, or a "
                         "fleet.py router stream")
    ap.add_argument("--straggler-factor", type=float, default=1.25,
                    help="flag ranks whose steady p50 exceeds this factor "
                         "x the fleet median (default 1.25)")
    ap.add_argument("--spike-factor", type=float, default=3.0,
                    help="flag steps whose loss exceeds this factor x the "
                         "rank's median loss (default 3)")
    ap.add_argument("--regress-factor", type=float, default=1.3,
                    help="flag ranks whose second-half p50 step time "
                         "exceeds this factor x the first half "
                         "(default 1.3)")
    ap.add_argument("--skew-factor", type=float, default=2.0,
                    help="serve-fleet mode: flag routing imbalance when "
                         "max dispatches exceed this factor x the mean "
                         "(default 2.0)")
    args = ap.parse_args(argv)

    # Serve-fleet streams are self-identifying (schema-v10 records);
    # a single path that carries them switches modes.
    if len(args.paths) == 1:
        fleet_records = load_fleet_records(args.paths[0])
        if fleet_records is not None:
            anomalies = analyze_fleet(fleet_records, args.skew_factor)
            if anomalies < 0:
                return 2
            return 1 if anomalies else 0

    files = discover(args.paths)
    ranks = {i: r for i, r in
             ((i, load_rank(p)) for i, p in sorted(files.items()))
             if r is not None}
    if not ranks or not any(r["n_steps"] for r in ranks.values()):
        print("no step records in any input", file=sys.stderr)
        return 2
    anomalies = analyze(ranks, args.straggler_factor, args.spike_factor,
                        args.regress_factor)
    return 1 if anomalies else 0


if __name__ == "__main__":
    sys.exit(main())
