#!/usr/bin/env python
"""Round-4 TPU measurement campaign (VERDICT r3 item 1) — one command that
drains the queued-behind-the-outage measurements the moment the tunnel is
healthy, maximizing whatever window appears.

Design for a flaky single-tenant tunnel (PERF.md methodology):
- A cheap matmul PROBE runs before every item; the first failed probe
  aborts the whole batch (a wedged tunnel hangs every client — better to
  stop and keep the partial results than to stack doomed processes).
- Each item is its own subprocess with a hard timeout, so one bad compile
  cannot wedge the driver process itself; bench.py's own first-op
  watchdog also runs inside.
- Results append to MEASURE_R4.jsonl as they land; items already present
  are skipped, so re-running after a mid-batch wedge resumes where it
  stopped.

Items (priority order — the headline first so even a short window lands
the contract number, then every other cheap-compile config, and ONLY
then the long-compile experiments): c2 headline, c1, c4 (BERT+LAMB),
c5 (TXL), gpt, hostpipe, the steploop dispatch-bubble probe, the
per-seed on-chip accuracy reruns (~15-20 min each); then remat
conv/block and c4 @ seq 8192 (the flash kernel's must-win point) last —
see the ITEMS comment for why that order is load-bearing.  CP
throughput is NOT here: context parallelism needs >1 real chip and this
rig has exactly one (the 8-device mesh evidence is the driver's CPU
dryrun).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "MEASURE_R4.jsonl")

PROBE = ("import jax, jax.numpy as jnp, time\n"
         "t0 = time.time()\n"
         "x = jnp.ones((256, 256), jnp.bfloat16)\n"
         "y = (x @ x).block_until_ready()\n"
         "print('PROBE OK %.1fs' % (time.time() - t0), float(y[0, 0]))\n")

# (key, script + argv, subprocess timeout seconds) — scripts other than
# bench.py join the same resumable queue: tools/steploop_probe.py (the
# dispatch-bubble arbitration, PERF.md) and the on-chip accuracy rerun
# (VERDICT r3 item 9) drain in the same window.
#
# ORDER MATTERS (learned 2026-07-31 03:55–04:12): all known-cheap-compile
# items run FIRST, every long-compile experiment LAST.  The first campaign
# attempt put remat right after c2; c2 landed (2566.8 img/s, 322 s) but
# c2_remat_conv's rematerialized-backward XLA compile exceeded the 900 s
# item timeout (plain c2's compile is ~60-90 s), the subprocess kill hit
# mid-remote-compile, and the tunnel wedged for every subsequent client —
# the same pathology as the 07-30 day-long outage.  bench.py's watchdog
# cannot guard this window: it disarms after the first trivial scalar op,
# which precedes the workload compile.  So the defense is ordering + a
# timeout that outlasts the worst plausible compile.
ITEMS = [
    ("c2",            ["bench.py", "--config", "c2"], 900),
    ("c1",            ["bench.py", "--config", "c1"], 900),
    ("c4",            ["bench.py", "--config", "c4"], 900),
    ("c5",            ["bench.py", "--config", "c5"], 900),
    ("gpt",           ["bench.py", "--config", "gpt"], 900),
    ("hostpipe",      ["bench.py", "--config", "hostpipe"], 900),
    ("steploop",      ["tools/steploop_probe.py"], 1200),
    # on-chip accuracy reruns (non-saturated label-noise design at full
    # ResNet-50 scale; replaces the CPU artifact's platform caveat).
    # One item PER SEED so a mid-campaign wedge preserves completed
    # seeds — each writes its own artifact; the cross-seed gap summary
    # is the mean over the three gap fields.
    ("accuracy_full_s0", ["accuracy.py", "--preset", "full",
                          "--label-noise", "0.3", "--seeds", "0",
                          "--eval-batches", "32",
                          "--out", "ACCURACY_FULL_seed0.json"], 1800),
    ("accuracy_full_s1", ["accuracy.py", "--preset", "full",
                          "--label-noise", "0.3", "--seeds", "1",
                          "--eval-batches", "32",
                          "--out", "ACCURACY_FULL_seed1.json"], 1800),
    ("accuracy_full_s2", ["accuracy.py", "--preset", "full",
                          "--label-noise", "0.3", "--seeds", "2",
                          "--eval-batches", "32",
                          "--out", "ACCURACY_FULL_seed2.json"], 1800),
    # ---- long-compile experiments: nothing queues behind these ----
    ("c2_remat_conv", ["bench.py", "--config", "c2", "--remat", "conv"],
     2700),
    ("c2_remat_block", ["bench.py", "--config", "c2", "--remat", "block"],
     2700),
    # seq-8192 compiles a big Pallas grid through the remote-compile path:
    # this is the item whose mid-compile kill wedged the tunnel for a day
    # (PERF.md outage record) — the ITEM timeout must outlast the worst
    # compile.  bench.py's own watchdog stays at its default: it only
    # guards the pre-compile first-op round-trip (wedged-at-entry), not
    # the workload compile, so widening it would just slow that detection.
    ("c4_seq8192",    ["bench.py", "--config", "c4", "--seq-len", "8192",
                       "--batch-size", "2"], 2700),
]


def have() -> dict:
    done = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            for line in f:
                try:
                    r = json.loads(line)
                    done[r["key"]] = r
                except (json.JSONDecodeError, KeyError):
                    pass
    return done


def log(rec: dict) -> None:
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")


def probe(timeout: float = 150.0) -> bool:
    try:
        p = subprocess.run([sys.executable, "-c", PROBE], timeout=timeout,
                           capture_output=True, text=True, cwd=REPO)
    except subprocess.TimeoutExpired:
        print(f"probe TIMEOUT after {timeout:.0f}s — tunnel wedged")
        return False
    ok = p.returncode == 0 and "PROBE OK" in p.stdout
    print(p.stdout.strip() if ok else
          f"probe rc={p.returncode}\nstdout: {p.stdout[-250:]}\n"
          f"stderr: {p.stderr[-250:]}")
    return ok


# Items with no JSON stdout line — rc 0 alone marks them done on resume.
# accuracy writes its artifact file; steploop's numbers live ONLY in the
# stdout_tail logged below (it writes no file), so that field is the
# record of the dispatch-bubble arbitration.
NO_JSON_ITEMS = {"steploop", "accuracy_full_s0", "accuracy_full_s1",
                 "accuracy_full_s2"}


def main() -> int:
    done = have()
    for key, argv, timeout in ITEMS:
        # A number from a crashed run (rc != 0) is not a measurement —
        # only a clean parse (or, for the no-JSON scripts, a clean exit)
        # counts as done.
        if key in done and done[key].get("rc") == 0 \
                and (done[key].get("parsed") or key in NO_JSON_ITEMS):
            print(f"[{key}] already measured — skip")
            continue
        if not probe():
            log({"key": "__abort__", "at": key,
                 "reason": "probe failed (tunnel wedged)",
                 "utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime())})
            return 3
        print(f"[{key}] python {' '.join(argv)}  (timeout {timeout}s)")
        t0 = time.time()
        try:
            p = subprocess.run([sys.executable] + argv,
                               timeout=timeout, capture_output=True,
                               text=True, cwd=REPO)
        except subprocess.TimeoutExpired as e:
            # the captured tails show WHERE the kill landed (mid-compile
            # = the tunnel-wedging case) without having to rerun
            tail = lambda b: (b.decode() if isinstance(b, bytes) else
                              (b or ""))[-400:]
            log({"key": key, "parsed": None, "rc": "timeout",
                 "seconds": timeout,
                 "stdout_tail": tail(e.stdout), "stderr_tail": tail(e.stderr),
                 "utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime())})
            print(f"[{key}] TIMEOUT after {timeout}s — stopping the batch "
                  "(the tunnel is likely wedged behind the killed compile)")
            return 4
        parsed = None
        for line in reversed(p.stdout.strip().splitlines()):
            try:
                parsed = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
        log({"key": key, "parsed": parsed, "rc": p.returncode,
             "seconds": round(time.time() - t0, 1),
             "stdout_tail": p.stdout[-600:],
             "stderr_tail": p.stderr[-300:],
             "utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime())})
        print(f"[{key}] rc={p.returncode} {json.dumps(parsed)}")
    print("measurement batch complete")
    return 0


def _accuracy_artifacts():
    """The per-seed --out names, derived from ITEMS (single source of
    truth — adding/renaming a seed item keeps the merge in sync)."""
    outs = []
    for key, argv, _t in ITEMS:
        if key.startswith("accuracy_full_"):
            outs.append(argv[argv.index("--out") + 1])
    return outs


def _merge_accuracy() -> None:
    """When every per-seed on-chip artifact exists, synthesize the
    canonical ACCURACY_FULL.json under the name the acceptance contract
    keys on — every cross-seed field is RECOMPUTED from the per-seed
    data (a wholesale copy of seed 0 would present one seed's top-1
    means as the aggregate)."""
    outs = _accuracy_artifacts()
    arts = []
    for name in outs:
        f = os.path.join(REPO, name)
        if not os.path.exists(f):
            return
        with open(f) as fh:
            arts.append(json.load(fh))
    gaps = [a["gap"] for a in arts]
    per_seed = {}
    for a in arts:
        per_seed.update(a["per_seed"])
    seeds = sorted(int(s) for s in per_seed)
    mean = lambda xs: sum(xs) / len(xs)
    merged = {k: arts[0][k] for k in
              ("preset", "arch", "steps", "batch_size", "eval_batches",
               "top1_quantum_pct", "label_noise") if k in arts[0]}
    if "top1_ceiling" in arts[0]:
        merged["top1_ceiling"] = arts[0]["top1_ceiling"]
    merged.update({
        "seeds": seeds,
        "top1_fp32": mean([per_seed[str(s)]["O0"]["top1"] for s in seeds]),
        "top1_o2": mean([per_seed[str(s)]["O2"]["top1"] for s in seeds]),
        "per_seed": per_seed,
        "gap": mean(gaps),
        "gap_per_seed": gaps,
        "gap_spread": max(gaps) - min(gaps),
        "merged_from": outs,
    })
    out = os.path.join(REPO, "ACCURACY_FULL.json")
    with open(out, "w") as fh:
        json.dump(merged, fh, indent=1)
    print(f"merged per-seed accuracy artifacts -> {out}")


if __name__ == "__main__":
    try:
        rc = main()
    finally:
        # the merge runs on EVERY exit path: the likeliest real-world run
        # lands all accuracy seeds and then times out on a long-compile
        # experiment — the canonical artifact must still appear.
        _merge_accuracy()
    raise SystemExit(rc)
