#!/usr/bin/env python
"""BN reduce microbenchmark, take 2: time inside one jit via lax.fori_loop
with forced data dependence, so dispatch/tunnel effects cancel.

Also benchmarks the fused one-pass BN-backward (sums + dx in one kernel
read) and the 4-D NHWC-blocked variants.
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import jax.experimental.pallas as pl
from jax.experimental.pallas import tpu as pltpu


def loop_time(make_step, init, iters=50):
    """Time `iters` dependent applications inside one jit."""
    @jax.jit
    def run(carry):
        return jax.lax.fori_loop(0, iters, lambda i, c: make_step(c), carry)
    out = run(init)
    float(jax.tree_util.tree_leaves(out)[0].ravel()[0])  # warm
    t0 = time.perf_counter()
    out = run(init)
    float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    return (time.perf_counter() - t0) / iters


def main():
    N, H, W, C = 256, 56, 56, 256
    x = jax.random.normal(jax.random.PRNGKey(0), (N, H, W, C), jnp.bfloat16)
    dy = jax.random.normal(jax.random.PRNGKey(1), (N, H, W, C), jnp.bfloat16)
    nbytes = x.size * 2
    R = N * H * W
    x2, dy2 = x.reshape(R, C), dy.reshape(R, C)
    mean = jnp.zeros((C,), jnp.float32)
    inv = jnp.ones((C,), jnp.float32)

    blk = 4096

    def stat_kernel(x_ref, s_ref, ss_ref):
        i = pl.program_id(0)
        xf = x_ref[...].astype(jnp.float32)

        @pl.when(i == 0)
        def _():
            s_ref[...] = jnp.zeros_like(s_ref)
            ss_ref[...] = jnp.zeros_like(ss_ref)
        s_ref[...] += jnp.sum(xf, axis=0)
        ss_ref[...] += jnp.sum(xf * xf, axis=0)

    def pl_bnstat(x2):
        return pl.pallas_call(
            stat_kernel,
            grid=(R // blk,),
            in_specs=[pl.BlockSpec((blk, C), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=[pl.BlockSpec((C,), lambda i: (0,),
                                    memory_space=pltpu.VMEM),
                       pl.BlockSpec((C,), lambda i: (0,),
                                    memory_space=pltpu.VMEM)],
            out_shape=[jax.ShapeDtypeStruct((C,), jnp.float32),
                       jax.ShapeDtypeStruct((C,), jnp.float32)])(x2)

    # chain: feed the (tiny) sums back so iterations depend on each other
    def step_stat(carry):
        xx, acc = carry
        s, ss = pl_bnstat(xx)
        return xx, acc + s[0] + ss[0]

    t = loop_time(step_stat, (x2, jnp.zeros((), jnp.float32)))
    print(f"pl_bnstat(2d):    {t*1e3:7.3f} ms  {nbytes/t/1e9:7.1f} GB/s")

    # XLA versions under the same harness
    def step_xla_stat(carry):
        xx, acc = carry
        xf = xx.astype(jnp.float32)
        s = jnp.sum(xf, (0,))
        ss = jnp.sum(xf * xf, (0,))
        return xx, acc + s[0] + ss[0]

    t = loop_time(step_xla_stat, (x2, jnp.zeros((), jnp.float32)))
    print(f"xla_bnstat(2d):   {t*1e3:7.3f} ms  {nbytes/t/1e9:7.1f} GB/s")

    def step_xla_stat4(carry):
        xx, acc = carry
        xf = xx.astype(jnp.float32)
        s = jnp.sum(xf, (0, 1, 2))
        ss = jnp.sum(xf * xf, (0, 1, 2))
        return xx, acc + s[0] + ss[0]

    t = loop_time(step_xla_stat4, (x, jnp.zeros((), jnp.float32)))
    print(f"xla_bnstat(4d):   {t*1e3:7.3f} ms  {nbytes/t/1e9:7.1f} GB/s")

    # ---- backward: sums only ----
    def bwd_kernel(x_ref, dy_ref, m_ref, i_ref, s_ref, sx_ref):
        i = pl.program_id(0)
        xf = x_ref[...].astype(jnp.float32)
        dyf = dy_ref[...].astype(jnp.float32)
        xhat = (xf - m_ref[...]) * i_ref[...]

        @pl.when(i == 0)
        def _():
            s_ref[...] = jnp.zeros_like(s_ref)
            sx_ref[...] = jnp.zeros_like(sx_ref)
        s_ref[...] += jnp.sum(dyf, axis=0)
        sx_ref[...] += jnp.sum(dyf * xhat, axis=0)

    def pl_bnbwd(x2, dy2):
        return pl.pallas_call(
            bwd_kernel,
            grid=(R // blk,),
            in_specs=[pl.BlockSpec((blk, C), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((blk, C), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((C,), lambda i: (0,),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((C,), lambda i: (0,),
                                   memory_space=pltpu.VMEM)],
            out_specs=[pl.BlockSpec((C,), lambda i: (0,),
                                    memory_space=pltpu.VMEM),
                       pl.BlockSpec((C,), lambda i: (0,),
                                    memory_space=pltpu.VMEM)],
            out_shape=[jax.ShapeDtypeStruct((C,), jnp.float32),
                       jax.ShapeDtypeStruct((C,), jnp.float32)])(x2, dy2, mean, inv)

    def step_bwd(carry):
        xx, dd, acc = carry
        s, sx = pl_bnbwd(xx, dd)
        return xx, dd, acc + s[0] + sx[0]

    t = loop_time(step_bwd, (x2, dy2, jnp.zeros((), jnp.float32)))
    print(f"pl_bnbwd(2d):     {t*1e3:7.3f} ms  {2*nbytes/t/1e9:7.1f} GB/s")

    # ---- full BN backward: sums pass + dx pass, both Pallas ----
    def dx_kernel(x_ref, dy_ref, m_ref, i_ref, g_ref, s_ref, sx_ref, dx_ref):
        xf = x_ref[...].astype(jnp.float32)
        dyf = dy_ref[...].astype(jnp.float32)
        xhat = (xf - m_ref[...]) * i_ref[...]
        dx = g_ref[...] * i_ref[...] * (dyf - s_ref[...] - xhat * sx_ref[...])
        dx_ref[...] = dx.astype(dx_ref.dtype)

    def pl_bndx(x2, dy2, s, sx):
        return pl.pallas_call(
            dx_kernel,
            grid=(R // blk,),
            in_specs=[pl.BlockSpec((blk, C), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((blk, C), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)] +
                     [pl.BlockSpec((C,), lambda i: (0,),
                                   memory_space=pltpu.VMEM)] * 5,
            out_specs=[pl.BlockSpec((blk, C), lambda i: (i, 0),
                                    memory_space=pltpu.VMEM)],
            out_shape=[jax.ShapeDtypeStruct((R, C), jnp.bfloat16)],
        )(x2, dy2, mean, inv, jnp.ones((C,), jnp.float32), s, sx)

    def step_full_bwd(carry):
        xx, dd, acc = carry
        s, sx = pl_bnbwd(xx, dd)
        dx, = pl_bndx(xx, dd, s / R, sx / R)
        return xx, dd, acc + dx[0, 0].astype(jnp.float32)

    t = loop_time(step_full_bwd, (x2, dy2, jnp.zeros((), jnp.float32)))
    print(f"pl_bn_full_bwd:   {t*1e3:7.3f} ms  {5*nbytes/t/1e9:7.1f} GB/s "
          f"(sums+dx, 4r+1w)")

    # XLA full backward under same harness
    def step_xla_full_bwd(carry):
        xx, dd, acc = carry
        xf = xx.astype(jnp.float32)
        dyf = dd.astype(jnp.float32)
        xhat = (xf - mean) * inv
        s = jnp.sum(dyf, 0) / R
        sx = jnp.sum(dyf * xhat, 0) / R
        dx = (inv * (dyf - s - xhat * sx)).astype(jnp.bfloat16)
        return xx, dd, acc + dx[0, 0].astype(jnp.float32)

    t = loop_time(step_xla_full_bwd, (x2, dy2, jnp.zeros((), jnp.float32)))
    print(f"xla_bn_full_bwd:  {t*1e3:7.3f} ms  {5*nbytes/t/1e9:7.1f} GB/s")


if __name__ == "__main__":
    main()
