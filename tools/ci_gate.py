#!/usr/bin/env python
"""ci_gate: the repo's static gates behind ONE command.

    python tools/ci_gate.py                         # graftlint only
    python tools/ci_gate.py --stream run.jsonl      # + recompile gate
    python tools/ci_gate.py --stream a.jsonl --stream b.jsonl

    python tools/ci_gate.py --trace-stream traced.jsonl  # + trace lint

    python tools/ci_gate.py --fleet-stream fleet.jsonl   # + fleet gate

Gates:

1. **graftlint --fail-on-new** (tools/graftlint): the two-stratum
   static analysis — jax-free import contracts, host-sync-in-step,
   lock discipline, schema-emission consistency — against the checked-
   in baseline (empty at HEAD).
2. **cost_report --fail-on-recompile** (per ``--stream``): the compile-
   once contract over recorded ``--cost-model`` telemetry, with the
   schema-v8 ``recompile_cause`` diagnosis printed when a stream
   carries one.
3. **trace_export --check** (per ``--trace-stream``): the structural
   trace lint over recorded ``--trace`` telemetry — balanced B/E spans
   per thread row, monotonic timestamps, orphan parent_ids, span
   containment, exactly one clock_sync per stream (schema v9).
4. **fleet availability** (per ``--fleet-stream``): the scenario
   contract over a recorded fleet-router stream (schema v10) — every
   record validates, exactly one ``fleet_summary``, ZERO lost requests
   and ``availability >= --fleet-availability-min`` (default 1.0); a
   scenario verdict other than "pass" fails the gate.  Run over the
   checked-in scenario stream, this turns "handles a rolling restart"
   into a regression-tested number.

Exit 0 only when every gate passes; 1 when any gate fails; 2 on usage
errors (unreadable stream, bad baseline).  Thin-client contract: NO
jax import, direct or transitive — this must run on the bare CI host
(graftlint's own jax-free rule checks this file too).
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)                     # sibling tools imports
sys.path.insert(0, os.path.dirname(_HERE))    # `tools.graftlint` package

from tools.graftlint.cli import main as graftlint_main  # noqa: E402


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_HERE, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fleet_gate(stream: str, availability_min: float) -> int:
    """The fleet-scenario gate: schema-v10 validation + zero lost +
    availability threshold + a passing verdict over one recorded
    fleet-router stream.  Returns 0/1 (2 is the caller's unreadable-
    stream path)."""
    import json

    metrics_lint = _load_tool("metrics_lint")
    records = []
    with open(stream) as fh:
        for n, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"{stream}: line {n + 1}: not JSON",
                      file=sys.stderr)
                return 1
    errors = metrics_lint.validate_stream(records)
    for e in errors:
        print(f"{stream}: {e}", file=sys.stderr)
    summaries = [r for r in records
                 if r.get("record") == "fleet_summary"]
    if len(summaries) != 1:
        print(f"{stream}: {len(summaries)} fleet_summary records "
              "(expected exactly 1)", file=sys.stderr)
        return 1
    if errors:
        return 1
    summ = summaries[0]
    rc = 0
    if summ.get("lost", 0) != 0:
        print(f"{stream}: {summ['lost']} request(s) LOST (uids with no "
              "terminal status)", file=sys.stderr)
        rc = 1
    if summ["availability"] < availability_min:
        print(f"{stream}: fleet availability {summ['availability']} < "
              f"required {availability_min}", file=sys.stderr)
        rc = 1
    if "verdict" in summ and summ["verdict"] != "pass":
        print(f"{stream}: scenario {summ.get('scenario', '?')} verdict "
              f"is {summ['verdict']!r}", file=sys.stderr)
        rc = 1
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="one command for every static CI gate")
    ap.add_argument("--stream", action="append", default=[],
                    metavar="JSONL",
                    help="a --cost-model telemetry stream to run the "
                         "recompile gate over (repeatable)")
    ap.add_argument("--trace-stream", action="append", default=[],
                    metavar="JSONL",
                    help="a --trace telemetry stream to run the "
                         "trace_export --check structural lint over "
                         "(repeatable)")
    ap.add_argument("--fleet-stream", action="append", default=[],
                    metavar="JSONL",
                    help="a fleet-router stream to run the scenario "
                         "gate over: schema-v10 validation, zero lost "
                         "requests, availability threshold, passing "
                         "verdict (repeatable)")
    ap.add_argument("--fleet-availability-min", type=float, default=1.0,
                    metavar="X",
                    help="fleet availability the --fleet-stream gate "
                         "requires (default 1.0)")
    ap.add_argument("--baseline", default=None,
                    help="graftlint baseline override")
    ap.add_argument("paths", nargs="*",
                    help="restrict graftlint's reported findings")
    args = ap.parse_args(argv)

    worst = 0
    lint_argv = ["--fail-on-new"] + args.paths
    if args.baseline:
        lint_argv += ["--baseline", args.baseline]
    rc = graftlint_main(lint_argv)
    print(f"ci_gate: graftlint --fail-on-new: "
          f"{'PASS' if rc == 0 else 'FAIL'}")
    worst = max(worst, rc)

    if args.stream:
        cost_report = _load_tool("cost_report")
        for stream in args.stream:
            if not os.path.isfile(stream):
                print(f"ci_gate: no such stream: {stream}",
                      file=sys.stderr)
                return 2
            rc = cost_report.main([stream, "--fail-on-recompile"])
            print(f"ci_gate: cost_report --fail-on-recompile "
                  f"{stream}: {'PASS' if rc == 0 else 'FAIL'}")
            worst = max(worst, rc)

    if args.trace_stream:
        trace_export = _load_tool("trace_export")
        for stream in args.trace_stream:
            if not os.path.isfile(stream):
                print(f"ci_gate: no such stream: {stream}",
                      file=sys.stderr)
                return 2
            rc = trace_export.main(["--check", stream])
            print(f"ci_gate: trace_export --check "
                  f"{stream}: {'PASS' if rc == 0 else 'FAIL'}")
            worst = max(worst, rc)

    for stream in args.fleet_stream:
        if not os.path.isfile(stream):
            print(f"ci_gate: no such stream: {stream}",
                  file=sys.stderr)
            return 2
        rc = _fleet_gate(stream, args.fleet_availability_min)
        print(f"ci_gate: fleet gate {stream}: "
              f"{'PASS' if rc == 0 else 'FAIL'}")
        worst = max(worst, rc)

    print(f"ci_gate: {'PASS' if worst == 0 else 'FAIL'}")
    return worst                 # 1 = gate failure, 2 = usage error


if __name__ == "__main__":
    sys.exit(main())
