#!/usr/bin/env python
"""ci_gate: the repo's static gates behind ONE command.

    python tools/ci_gate.py                         # graftlint only
    python tools/ci_gate.py --stream run.jsonl      # + recompile gate
    python tools/ci_gate.py --stream a.jsonl --stream b.jsonl

    python tools/ci_gate.py --trace-stream traced.jsonl  # + trace lint

    python tools/ci_gate.py --fleet-stream fleet.jsonl   # + fleet gate

    python tools/ci_gate.py --slo-stream slo.jsonl       # + SLO gate

    python tools/ci_gate.py --perf-stream perf.jsonl \\
        --perf-baseline PERF_BASELINE.json               # + perf gate

Gates:

1. **graftlint --fail-on-new** (tools/graftlint): the two-stratum
   static analysis — jax-free import contracts, host-sync-in-step,
   lock discipline, schema-emission consistency — against the checked-
   in baseline (empty at HEAD).
2. **cost_report --fail-on-recompile** (per ``--stream``): the compile-
   once contract over recorded ``--cost-model`` telemetry, with the
   schema-v8 ``recompile_cause`` diagnosis printed when a stream
   carries one.
3. **trace_export --check** (per ``--trace-stream``): the structural
   trace lint over recorded ``--trace`` telemetry — balanced B/E spans
   per thread row, monotonic timestamps, orphan parent_ids, span
   containment, exactly one clock_sync per stream (schema v9).
4. **fleet availability** (per ``--fleet-stream``): the scenario
   contract over a recorded fleet-router stream (schema v10) — every
   record validates, exactly one ``fleet_summary``, ZERO lost requests
   and ``availability >= --fleet-availability-min`` (default 1.0); a
   scenario verdict other than "pass" fails the gate.  Run over the
   checked-in scenario stream, this turns "handles a rolling restart"
   into a regression-tested number.
5. **quant compression** (per ``--quant-stream``): the quantized-
   serving contract over a recorded ``--kv-quant`` stream (schema
   v11) — every record validates, exactly one ``serve_summary``, an
   int8 ``kv_dtype`` announced by a ``quant_event``, and
   ``kv_bytes_committed`` at or below its bf16-equivalent /
   ``--quant-compression-min`` (default 1.9).  Run over the checked-in
   quantized-smoke stream (tests/fixtures/quant/), this turns "the KV
   cache got smaller" into a regression-tested number.
6. **disagg conservation** (over the ``--disagg-stream`` group): the
   disaggregated-serving contract over ONE deployment's recorded role
   streams (schema v13: a prefill stream plus one per decode worker)
   — every record validates, one ``serve_summary`` per stream (the
   prefill role claimed once; decode workers pool), and every
   ``kv_handoff`` shipped out was quarantined or admitted with
   EXACTLY one terminal request record.  Redelivery episodes (the
   leased-spool crash-safety protocol, ISSUE 15) are tolerated, but
   at most one admission per uid may lack redelivered/duplicate
   provenance — anything else is a silent double-serve.  Run over the
   checked-in redelivery pair (tests/fixtures/disagg/), this turns
   "a decode worker can die between poll and ack and lose nothing"
   into a regression-tested contract.
7. **slo gate** (per ``--slo-stream``): the streaming-SLO contract
   over one recorded ``--slo`` stream (schema v14) — every record
   validates, exactly one run_header announces the spec, the
   ``slo_window`` / ``slo_breach`` records agree with each other
   (every breach is burn > 1.0 and mirrors its window; every window
   past 1.0 has a breach record) and with the summary's windows /
   breaches / verdict; on a serve stream the summary's latency
   sketches are additionally checked against the EXACT nearest-rank
   percentiles recomputed from the raw ``request_complete`` records
   (within the sketch's declared relative-error bound alpha); on a
   fleet-router stream at least one ``fleet_rollup`` must have merged
   the replicas' sketches with a conserved sample count.  Run over the
   checked-in SLO streams (tests/fixtures/slo/), this turns "the
   online percentiles are honest" into a regression-tested bound.
8. **perf gate** (per ``--perf-stream``): the hot-path overhead
   contract over one recorded ``--tick-profile`` stream (schema v15)
   — every record validates, an ``overhead_summary`` is present (the
   run was armed), and perf_ledger's consistency checks hold: every
   ``tick_profile``'s phase components sum to its wall time within
   1%, and the summary's ``host_gap_ms`` / ``host_overhead_frac`` /
   per-phase totals agree with each other (an edited host fraction —
   the tamper fixture — fails here).  With ``--perf-baseline``, the
   stream's normalized snapshot is additionally diffed against the
   checked-in ``PERF_BASELINE.json`` within its per-metric noise
   bands.  Run over the checked-in perf fixtures (tests/fixtures/
   perf/), this turns "host overhead stayed put" into a regression-
   tested number.
9. **spec conservation** (per ``--spec-stream``): the speculative-
   decoding contract over one recorded ``--speculate`` stream (schema
   v16) — every record validates, exactly one ``serve_summary``, the
   summary is armed (``speculate_k`` >= 1 with the drafted/accepted/
   sampled counter triple), and tokens are CONSERVED: every output
   token is an accepted draft token or a sampled one
   (``output_tokens == tokens_accepted + tokens_sampled``), no token
   was accepted that was never drafted, and ``acceptance_rate``
   equals accepted/drafted.  Run over the checked-in spec-smoke
   stream (tests/fixtures/spec/), this turns "speculation is
   lossless" into a regression-tested identity.

10. **tenant conservation** (per ``--tenant-stream``): the multi-
    tenant fairness contract over one recorded tenancy-armed fleet
    stream (schema v17) — every record validates, exactly one
    ``fleet_summary`` with a per-tenant verdict block, every routed
    request reaches EXACTLY one terminal record (a parked over-budget
    request may wait, never vanish), the summary's per-tenant status
    counts equal the counts recomputed from the stream's terminal
    records, and per-tenant admitted tokens respect the announced
    budget (every heartbeat ledger <= budget; fleet total <= budget x
    replicas).  Run over the checked-in noisy-neighbor stream
    (tests/fixtures/sched/), this turns "the DWRR scheduler is fair
    and lossless" into a regression-tested ledger.

11. **migration conservation** (per ``--migrate-stream``): the live-
    migration contract over one recorded migration-armed fleet stream
    (schema v18: the router's records with the engines' kv_migration
    / serve_drain / terminal records teed in) — every record
    validates, exactly one ``fleet_summary`` from an armed run
    (``migrations`` >= 1), zero lost requests, an empty migration
    spool at exit, every migrating ``serve_drain`` evicted EXACTLY
    zero slots (drain-without-eviction), and the per-uid ledger
    conserved across any number of hops: every ``kv_migration`` out
    leg was admitted or quarantined, extra admissions carry
    redelivered/duplicate provenance (the leased ack-crash window),
    every migrated uid reaches exactly one terminal record, and the
    summary's ``migration_completed`` matches the recomputed count.
    Run over the checked-in rolling-drain stream (tests/fixtures/
    migrate/), this turns "a restart never kills a request" into a
    regression-tested ledger.

Exit 0 only when every gate passes; 1 when any gate fails; 2 on usage
errors (unreadable stream, bad baseline).  Thin-client contract: NO
jax import, direct or transitive — this must run on the bare CI host
(graftlint's own jax-free rule checks this file too).
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)                     # sibling tools imports
sys.path.insert(0, os.path.dirname(_HERE))    # `tools.graftlint` package

from tools.graftlint.cli import main as graftlint_main  # noqa: E402


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_HERE, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_gated_stream(stream: str, summary_record: str):
    """Shared preamble of the stream gates: parse the JSONL, validate
    every record against the schema, require EXACTLY one summary of
    ``summary_record``.  Returns ``(summary, records)`` on success,
    ``(None, records)`` after printing the failure (the caller exits
    1)."""
    import json

    metrics_lint = _load_tool("metrics_lint")
    records = []
    with open(stream) as fh:
        for n, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"{stream}: line {n + 1}: not JSON",
                      file=sys.stderr)
                return None, records
    errors = metrics_lint.validate_stream(records)
    for e in errors:
        print(f"{stream}: {e}", file=sys.stderr)
    summaries = [r for r in records
                 if r.get("record") == summary_record]
    if len(summaries) != 1:
        print(f"{stream}: {len(summaries)} {summary_record} records "
              "(expected exactly 1)", file=sys.stderr)
        return None, records
    if errors:
        return None, records
    return summaries[0], records


def _fleet_gate(stream: str, availability_min: float) -> int:
    """The fleet-scenario gate: schema-v10 validation + zero lost +
    availability threshold + a passing verdict over one recorded
    fleet-router stream.  Returns 0/1 (2 is the caller's unreadable-
    stream path)."""
    summ, _ = _load_gated_stream(stream, "fleet_summary")
    if summ is None:
        return 1
    rc = 0
    if summ.get("lost", 0) != 0:
        print(f"{stream}: {summ['lost']} request(s) LOST (uids with no "
              "terminal status)", file=sys.stderr)
        rc = 1
    if summ["availability"] < availability_min:
        print(f"{stream}: fleet availability {summ['availability']} < "
              f"required {availability_min}", file=sys.stderr)
        rc = 1
    if "verdict" in summ and summ["verdict"] != "pass":
        print(f"{stream}: scenario {summ.get('scenario', '?')} verdict "
              f"is {summ['verdict']!r}", file=sys.stderr)
        rc = 1
    return rc


def _quant_gate(stream: str, min_ratio: float) -> int:
    """The quantized-serving gate (ISSUE 13): schema-v11 validation,
    exactly one serve_summary, an armed int8 KV arena, and the
    compression floor — ``kv_bytes_committed`` must sit at or below
    its bf16-equivalent divided by ``min_ratio`` (default 1.9: int8
    payload + bf16 block scales beats a scale-free bf16 arena by at
    least that much at every supported geometry).  Returns 0/1 (2 is
    the caller's unreadable-stream path)."""
    summ, records = _load_gated_stream(stream, "serve_summary")
    if summ is None:
        return 1
    rc = 0
    if summ.get("kv_dtype") != "int8":
        print(f"{stream}: kv_dtype is {summ.get('kv_dtype')!r} "
              "(quant stream must serve an int8 KV arena)",
              file=sys.stderr)
        rc = 1
    if not any(r.get("record") == "quant_event" for r in records):
        print(f"{stream}: no quant_event record (the applied "
              "quantization must announce itself)", file=sys.stderr)
        rc = 1
    per = summ.get("kv_bytes_per_token")
    bf16 = summ.get("kv_bytes_per_token_bf16")
    committed = (summ.get("kv_bytes_committed") or {}).get("max")
    if per is None or bf16 is None or committed is None:
        print(f"{stream}: serve_summary lacks the v11 per-token/"
              "committed byte fields", file=sys.stderr)
        return 1
    if per <= 0 or bf16 <= 0:
        print(f"{stream}: degenerate per-token bytes "
              f"(kv_bytes_per_token={per}, bf16-eq={bf16})",
              file=sys.stderr)
        return 1
    # committed <= (committed / per * bf16) / min_ratio is algebraically
    # per * min_ratio <= bf16 — checked in that form so an empty run
    # (committed max 0, which would make 0 > 0 vacuously pass) cannot
    # sneak a regressed geometry through the floor.
    bf16_equiv = committed / per * bf16
    if per * min_ratio > bf16:
        print(f"{stream}: kv_bytes_committed max {committed:.0f} > "
              f"bf16-equivalent {bf16_equiv:.0f} / {min_ratio} — "
              f"compression {bf16 / per:.2f}x under the floor "
              f"({per} B/token vs bf16-eq {bf16})",
              file=sys.stderr)
        rc = 1
    return rc


def _spec_gate(stream: str) -> int:
    """The speculative-decoding gate (ISSUE 18): schema-v16
    validation, exactly one serve_summary, an ARMED summary
    (``speculate_k`` >= 1 with the full drafted/accepted/sampled
    counter triple), and token CONSERVATION — every output token was
    either an accepted draft token or a sampled one
    (``output_tokens == tokens_accepted + tokens_sampled``), no draft
    was accepted that was never proposed
    (``tokens_accepted <= tokens_drafted``), and the summary's
    ``acceptance_rate`` is the ratio it claims to be.  Returns 0/1
    (2 is the caller's unreadable-stream path)."""
    summ, records = _load_gated_stream(stream, "serve_summary")
    if summ is None:
        return 1
    rc = 0
    k = summ.get("speculate_k")
    if not isinstance(k, int) or k < 1:
        print(f"{stream}: speculate_k is {k!r} (spec stream must come "
              "from a --speculate-armed run)", file=sys.stderr)
        return 1
    missing = [f for f in ("tokens_drafted", "tokens_accepted",
                           "tokens_sampled", "acceptance_rate",
                           "tokens_per_tick")
               if f not in summ]
    if missing:
        print(f"{stream}: serve_summary lacks the v16 speculation "
              f"field(s) {missing}", file=sys.stderr)
        return 1
    drafted = summ["tokens_drafted"]
    accepted = summ["tokens_accepted"]
    sampled = summ["tokens_sampled"]
    out = summ.get("output_tokens")
    if accepted > drafted:
        print(f"{stream}: tokens_accepted {accepted} > tokens_drafted "
              f"{drafted} — accepted a token nobody proposed",
              file=sys.stderr)
        rc = 1
    if out != accepted + sampled:
        print(f"{stream}: output_tokens {out} != tokens_accepted "
              f"{accepted} + tokens_sampled {sampled} — a token left "
              "the engine with no provenance", file=sys.stderr)
        rc = 1
    claimed = summ["acceptance_rate"]
    actual = (accepted / drafted) if drafted else 0.0
    if abs(claimed - actual) > 5e-4:
        print(f"{stream}: acceptance_rate {claimed} != "
              f"{accepted}/{drafted} = {actual:.4f}", file=sys.stderr)
        rc = 1
    if not 0.0 <= claimed <= 1.0:
        print(f"{stream}: acceptance_rate {claimed} outside [0, 1]",
              file=sys.stderr)
        rc = 1
    return rc


def _disagg_gate(streams) -> int:
    """The disaggregated-serving gate (ISSUE 14, crash-safe since
    ISSUE 15) over ONE deployment's role streams (a prefill stream
    plus one stream per decode worker): every record validates (schema
    v13), each stream closes with exactly one ``serve_summary``
    carrying a ``role`` (multiple DECODE streams are one spool's
    worker pool; a duplicated prefill role is still an error), and
    handoffs are CONSERVED under the leased redelivery protocol —
    every ``kv_handoff`` shipped out was either quarantined (a
    recorded disposition) or admitted and finished with EXACTLY one
    terminal request record; redelivery episodes are tolerated, but
    per uid at most one admission may be a plain first delivery
    (every extra must carry ``redelivered``/``duplicate`` provenance,
    else two workers silently double-served it).  Returns 0/1 (2 is
    the caller's unreadable-stream path)."""
    rc = 0
    roles = []
    out_uids = {}                        # uid -> source stream
    in_events = {}                       # uid -> [in records]
    terminal = {}                        # uid -> terminal-record count
    quarantined = set()
    for stream in streams:
        summ, records = _load_gated_stream(stream, "serve_summary")
        if summ is None:
            return 1
        role = summ.get("role")
        if role not in ("prefill", "decode", "both"):
            print(f"{stream}: serve_summary carries no role (a disagg "
                  "stream is a v12+ role stream)", file=sys.stderr)
            rc = 1
        roles.append(role)
        for r in records:
            if r.get("record") == "kv_handoff":
                uid = r.get("request_id", "?")
                if r.get("direction") == "out":
                    out_uids[uid] = stream
                elif r.get("direction") == "quarantine":
                    quarantined.add(uid)
                else:
                    in_events.setdefault(uid, []).append(r)
            elif r.get("record") in ("request_complete",
                                     "request_failed"):
                uid = r.get("request_id", "?")
                terminal[uid] = terminal.get(uid, 0) + 1
    dup = [r for r in set(roles)
           if r in ("prefill", "both") and roles.count(r) > 1]
    if dup:
        print(f"disagg gate: role(s) {sorted(dup)} claimed by more "
              "than one stream (one producer per spool; only decode "
              "workers pool)", file=sys.stderr)
        rc = 1
    never_admitted = sorted(u for u in out_uids
                            if u not in in_events
                            and u not in quarantined)
    never_terminal = sorted(u for u in out_uids
                            if terminal.get(u, 0) == 0
                            and u not in quarantined)
    multi_terminal = sorted(u for u in out_uids
                            if terminal.get(u, 0) > 1)
    double_served = []
    for uid, evs in sorted(in_events.items()):
        fresh = [r for r in evs
                 if not r.get("duplicate") and not r.get("redelivered")]
        if len(fresh) > 1:
            double_served.append(uid)
    for uid in never_admitted[:10]:
        print(f"disagg gate: handoff {uid} (from {out_uids[uid]}) was "
              "never admitted by a decode stream", file=sys.stderr)
    for uid in never_terminal[:10]:
        print(f"disagg gate: handoff {uid} never reached a terminal "
              "request record — LOST", file=sys.stderr)
    for uid in multi_terminal[:10]:
        print(f"disagg gate: handoff {uid} reached "
              f"{terminal[uid]} terminal records — exactly-once "
              "admission violated (double-served)", file=sys.stderr)
    for uid in double_served[:10]:
        print(f"disagg gate: handoff {uid} admitted more than once "
              "with no redelivered/duplicate provenance — two workers "
              "double-claimed it", file=sys.stderr)
    if never_admitted or never_terminal or multi_terminal \
            or double_served:
        rc = 1
    if not out_uids:
        print("disagg gate: no kv_handoff records across the given "
              "streams (nothing was disaggregated)", file=sys.stderr)
        rc = 1
    return rc


def _tenant_gate(stream: str) -> int:
    """The multi-tenant fairness gate (ISSUE 19) over one recorded
    tenancy-armed fleet stream (the router's records interleaved with
    the replica engines' terminal records): schema-v17 validation,
    exactly one ``fleet_summary`` carrying the per-tenant verdict
    block, and CONSERVATION of the fair scheduler's ledger —

    - every routed request reaches EXACTLY one terminal record
      (``request_complete`` / ``request_failed`` / ``shed``): a parked
      over-budget request may wait, but it may not vanish, and it may
      not finish twice;
    - the per-tenant status counts in ``fleet_summary.tenants`` equal
      the counts recomputed from the stream's terminal records (an
      edited summary — the tamper fixture — fails here);
    - per-tenant admitted tokens respect the announced budget: every
      ``replica_state`` heartbeat's ledger stays at or below it, and
      the fleet total stays below budget x replicas.

    Returns 0/1 (2 is the caller's unreadable-stream path)."""
    summ, records = _load_gated_stream(stream, "fleet_summary")
    if summ is None:
        return 1
    rc = 0
    tenants = summ.get("tenants")
    if not isinstance(tenants, dict) or not tenants:
        print(f"{stream}: fleet_summary carries no tenants block "
              "(tenant stream must come from a --tenants-armed run)",
              file=sys.stderr)
        return 1
    budgets = {}
    for r in records:
        if r.get("record") == "run_header" \
                and isinstance(r.get("config"), dict) \
                and isinstance(r["config"].get("tenants"), dict):
            for name, spec in r["config"]["tenants"].items():
                if isinstance(spec, dict) \
                        and spec.get("budget") is not None:
                    budgets[name] = spec["budget"]
    for name, block in tenants.items():
        if budgets.get(name) is None \
                and isinstance(block, dict) \
                and block.get("budget") is not None:
            budgets[name] = block["budget"]

    # Exactly-once terminal conservation over the routed uid set.
    _STATUS = {"request_complete": "ok"}
    routed = set()
    terminal = {}               # uid -> [(tenant, status)]
    replicas = set()
    for r in records:
        rec = r.get("record")
        if rec == "route":
            routed.add(r.get("request_id", "?"))
        elif rec in ("request_complete", "request_failed", "shed"):
            uid = r.get("request_id", "?")
            status = _STATUS.get(rec) or r.get("status") or rec
            terminal.setdefault(uid, []).append(
                (r.get("tenant", "default"), status))
        elif rec == "replica_state":
            replicas.add(r.get("replica", "?"))
            admitted = r.get("tenant_admitted")
            if isinstance(admitted, dict):
                for name, tok in admitted.items():
                    cap = budgets.get(name)
                    if cap is not None and tok > cap:
                        print(f"{stream}: replica "
                              f"{r.get('replica', '?')} admitted {tok} "
                              f"token(s) for tenant {name!r} over its "
                              f"budget {cap}", file=sys.stderr)
                        rc = 1
    never = sorted(u for u in routed if u not in terminal)
    multi = sorted(u for u, evs in terminal.items() if len(evs) > 1)
    orphans = sorted(u for u in terminal if u not in routed)
    for uid in never[:10]:
        print(f"{stream}: request {uid} was routed but never reached "
              "a terminal record — a parked request vanished",
              file=sys.stderr)
    for uid in multi[:10]:
        print(f"{stream}: request {uid} reached "
              f"{len(terminal[uid])} terminal records — exactly-once "
              "violated", file=sys.stderr)
    for uid in orphans[:10]:
        print(f"{stream}: terminal record for {uid} with no route "
              "record — the router never dispatched it",
              file=sys.stderr)
    if never or multi or orphans:
        rc = 1

    # Per-tenant summary counts vs the stream's own terminal records.
    recounted = {}
    for evs in terminal.values():
        for name, status in evs:
            recounted.setdefault(name, {})
            recounted[name][status] = \
                recounted[name].get(status, 0) + 1
    for name, block in tenants.items():
        claimed = (block or {}).get("counts", {})
        actual = recounted.get(name, {})
        if claimed != actual:
            print(f"{stream}: fleet_summary tenant {name!r} counts "
                  f"{claimed} != {actual} recomputed from the "
                  "stream's terminal records", file=sys.stderr)
            rc = 1
    extra = sorted(n for n in recounted if n not in tenants)
    for name in extra[:10]:
        print(f"{stream}: tenant {name!r} has terminal records but no "
              "fleet_summary entry", file=sys.stderr)
    if extra:
        rc = 1

    # Fleet-total budget: each engine debits its own ledger, so the
    # fleet-wide ceiling is budget x participating replicas.
    n_rep = max(1, len(replicas))
    for name, cap in sorted(budgets.items()):
        got = (tenants.get(name) or {}).get("admitted_tokens", 0)
        if got > cap * n_rep:
            print(f"{stream}: tenant {name!r} admitted {got} token(s) "
                  f"fleet-wide over budget {cap} x {n_rep} "
                  "replica(s)", file=sys.stderr)
            rc = 1
    return rc


def _migrate_gate(stream: str) -> int:
    """The live-migration gate (ISSUE 20) over one migration-armed
    fleet stream (the router's records with the engines' kv_migration
    / serve_drain / terminal records teed in): schema-v18 validation,
    exactly one ``fleet_summary`` from an ARMED run (``migrations`` >=
    1, zero lost, empty spool at exit), drain-WITHOUT-eviction (every
    migrating ``serve_drain`` evicted exactly 0 — a drain that killed
    what it was asked to preserve fails here), and the migration
    ledger CONSERVED per uid across any number of hops —

    - every ``kv_migration`` "out" leg was admitted ("in") or
      quarantined: at least as many non-duplicate admissions as out
      legs (a leased ack-crash redelivery adds admissions, never
      subtracts);
    - no admission from nowhere: at most one FIRST-delivery admission
      (no ``redelivered``/``duplicate`` provenance) per out leg —
      anything beyond that is two workers silently double-claiming;
    - every migrated uid reaches EXACTLY one terminal request record:
      it finished once, somewhere, after every hop (zero is a lost
      request, two is a double-serve);
    - the summary's ``migration_completed`` equals the count of
      migrated uids with a terminal record recomputed from the stream
      (an edited summary fails here).

    Returns 0/1 (2 is the caller's unreadable-stream path)."""
    summ, records = _load_gated_stream(stream, "fleet_summary")
    if summ is None:
        return 1
    rc = 0
    migs = summ.get("migrations")
    if not isinstance(migs, int) or migs < 1:
        print(f"{stream}: migrations is {migs!r} (migrate stream must "
              "come from a migration-armed run)", file=sys.stderr)
        return 1
    if summ.get("lost", 0) != 0:
        print(f"{stream}: {summ['lost']} request(s) LOST",
              file=sys.stderr)
        rc = 1
    if summ.get("in_spool", 0) != 0:
        print(f"{stream}: {summ['in_spool']} migration payload(s) "
              "still parked in the spool at exit", file=sys.stderr)
        rc = 1
    outs = {}                    # uid -> out-leg count
    in_events = {}               # uid -> [in records]
    quarantined = set()
    terminal = {}                # uid -> terminal-record count
    for r in records:
        rec = r.get("record")
        if rec == "kv_migration":
            uid = r.get("request_id", "?")
            d = r.get("direction")
            if d == "out":
                outs[uid] = outs.get(uid, 0) + 1
            elif d == "quarantine":
                quarantined.add(uid)
            else:
                in_events.setdefault(uid, []).append(r)
        elif rec in ("request_complete", "request_failed"):
            uid = r.get("request_id", "?")
            terminal[uid] = terminal.get(uid, 0) + 1
        elif rec == "serve_drain" and "migrated" in r:
            if r.get("evicted", 0) != 0:
                print(f"{stream}: migrating serve_drain evicted "
                      f"{r['evicted']} slot(s) — drain-without-"
                      "eviction violated", file=sys.stderr)
                rc = 1
    if not outs:
        print(f"{stream}: no kv_migration records (nothing migrated)",
              file=sys.stderr)
        return 1
    lost_legs = []               # uid shipped, never landed anywhere
    over_fresh = []              # admissions with no provenance > legs
    for uid, n_out in sorted(outs.items()):
        if uid in quarantined:
            continue
        evs = in_events.get(uid, [])
        non_dup = [e for e in evs if not e.get("duplicate")]
        fresh = [e for e in non_dup if not e.get("redelivered")]
        if len(non_dup) < n_out:
            lost_legs.append((uid, n_out, len(non_dup)))
        if len(fresh) > n_out:
            over_fresh.append((uid, n_out, len(fresh)))
    never_terminal = sorted(u for u in outs
                            if terminal.get(u, 0) == 0
                            and u not in quarantined)
    multi_terminal = sorted(u for u in outs
                            if terminal.get(u, 0) > 1)
    for uid, n_out, n_in in lost_legs[:10]:
        print(f"{stream}: uid {uid} migrated out {n_out} time(s) but "
              f"was admitted only {n_in} — a payload vanished in "
              "transit", file=sys.stderr)
    for uid, n_out, n_fresh in over_fresh[:10]:
        print(f"{stream}: uid {uid} has {n_fresh} first-delivery "
              f"admission(s) for {n_out} out leg(s) with no "
              "redelivered/duplicate provenance — double-claimed",
              file=sys.stderr)
    for uid in never_terminal[:10]:
        print(f"{stream}: migrated uid {uid} never reached a terminal "
              "request record — LOST", file=sys.stderr)
    for uid in multi_terminal[:10]:
        print(f"{stream}: migrated uid {uid} reached "
              f"{terminal[uid]} terminal records — exactly-once "
              "violated (double-served)", file=sys.stderr)
    if lost_legs or over_fresh or never_terminal or multi_terminal:
        rc = 1
    done = len([u for u in outs if terminal.get(u, 0) > 0])
    if "migration_completed" in summ \
            and summ["migration_completed"] != done:
        print(f"{stream}: fleet_summary migration_completed "
              f"{summ['migration_completed']} != {done} migrated "
              "uid(s) with a terminal record recomputed from the "
              "stream", file=sys.stderr)
        rc = 1
    return rc


def _slo_gate(stream: str) -> int:
    """The streaming-SLO gate (ISSUE 16) over one recorded ``--slo``
    stream — a serve.py replica stream (``serve_summary`` with its
    ``slo`` dict) or a fleet.py router stream (``fleet_summary`` with
    the flat ``slo_*`` fields).  Schema-v14 validation, exactly one
    announced spec, window/breach/summary agreement, and (serve
    streams) the sketch-vs-exact honesty bound: the summary's online
    percentiles must sit within the declared relative error alpha of
    the exact nearest-rank percentiles recomputed from the raw
    ``request_complete`` records.  Returns 0/1 (2 is the caller's
    unreadable-stream path)."""
    kind = "serve_summary"
    with open(stream) as fh:
        for line in fh:
            if '"fleet_summary"' in line:
                kind = "fleet_summary"
                break
    summ, records = _load_gated_stream(stream, kind)
    if summ is None:
        return 1
    rc = 0
    announced = [r for r in records
                 if r.get("record") == "run_header"
                 and isinstance(r.get("config"), dict)
                 and r["config"].get("slo")]
    if len(announced) != 1:
        print(f"{stream}: {len(announced)} run_header(s) announce an "
              "SLO spec (expected exactly 1 — an --slo stream declares "
              "its targets up front)", file=sys.stderr)
        rc = 1
    windows = [r for r in records if r.get("record") == "slo_window"]
    breaches = [r for r in records if r.get("record") == "slo_breach"]
    if not windows:
        print(f"{stream}: no slo_window records (nothing was scored — "
              "was the run armed with --slo?)", file=sys.stderr)
        return 1
    wmap = {w["window"]: w for w in windows}
    for b in breaches:
        w = wmap.get(b.get("window"))
        if w is None:
            print(f"{stream}: slo_breach for window {b.get('window')} "
                  "has no matching slo_window record", file=sys.stderr)
            rc = 1
        elif b["burn_rate"] <= 1.0 or b["burn_rate"] != w["burn_rate"]:
            print(f"{stream}: slo_breach window {b['window']} burn "
                  f"{b['burn_rate']} inconsistent with its window "
                  f"record (window says {w['burn_rate']}; a breach is "
                  "burn > 1.0)", file=sys.stderr)
            rc = 1
    breached = {b.get("window") for b in breaches}
    silent = [w["window"] for w in windows
              if w["burn_rate"] > 1.0 and w["window"] not in breached]
    for wi in silent[:10]:
        print(f"{stream}: window {wi} burned past 1.0 with no "
              "slo_breach record", file=sys.stderr)
    if silent:
        rc = 1

    if kind == "serve_summary":
        slo = summ.get("slo")
        if not isinstance(slo, dict):
            print(f"{stream}: serve_summary carries no slo dict "
                  "(the armed engine must fold its verdict into the "
                  "summary)", file=sys.stderr)
            return 1
        if slo.get("windows") != len(windows):
            print(f"{stream}: summary says {slo.get('windows')} "
                  f"window(s), stream carries {len(windows)} "
                  "slo_window record(s)", file=sys.stderr)
            rc = 1
        if slo.get("breaches") != len(breaches):
            print(f"{stream}: summary says {slo.get('breaches')} "
                  f"breach(es), stream carries {len(breaches)} "
                  "slo_breach record(s)", file=sys.stderr)
            rc = 1
        if (slo.get("verdict") == "fail") != bool(breaches):
            print(f"{stream}: verdict {slo.get('verdict')!r} "
                  f"contradicts {len(breaches)} breach record(s)",
                  file=sys.stderr)
            rc = 1
        # The honesty bound: the summary's ONLINE percentiles vs the
        # exact nearest-rank percentiles over the raw completion
        # records (same rank convention — metrics_lint.pct).  The
        # record values are rounded to 3 decimals, hence the small
        # absolute slack on top of the relative bound.
        metrics_lint = _load_tool("metrics_lint")
        alpha = slo.get("alpha", 0.01)
        for key in ("ttft_ms", "tpot_ms"):
            sk = slo.get(key)
            if not isinstance(sk, dict) or not sk.get("count"):
                continue
            exact = sorted(r[key] for r in records
                           if r.get("record") == "request_complete"
                           and isinstance(r.get(key), (int, float)))
            if sk["count"] != len(exact):
                print(f"{stream}: {key} sketch folded {sk['count']} "
                      f"sample(s) but the stream carries {len(exact)} "
                      "ok request_complete record(s)", file=sys.stderr)
                rc = 1
                continue
            for q in (50, 90, 99):
                ex = metrics_lint.pct(exact, q)
                est = sk.get(f"p{q}", 0.0)
                if abs(est - ex) > alpha * abs(ex) + 0.01:
                    print(f"{stream}: {key} p{q} sketch {est:.3f} vs "
                          f"exact {ex:.3f} — outside the declared "
                          f"relative-error bound alpha={alpha}",
                          file=sys.stderr)
                    rc = 1
    else:
        if "slo_verdict" not in summ:
            print(f"{stream}: fleet_summary carries no slo_verdict "
                  "(the armed router must fold its verdict into the "
                  "summary)", file=sys.stderr)
            return 1
        if summ.get("slo_windows") != len(windows):
            print(f"{stream}: summary says {summ.get('slo_windows')} "
                  f"window(s), stream carries {len(windows)} "
                  "slo_window record(s)", file=sys.stderr)
            rc = 1
        if summ.get("slo_breaches") != len(breaches):
            print(f"{stream}: summary says {summ.get('slo_breaches')} "
                  f"breach(es), stream carries {len(breaches)} "
                  "slo_breach record(s)", file=sys.stderr)
            rc = 1
        if (summ["slo_verdict"] == "fail") != bool(breaches):
            print(f"{stream}: slo_verdict {summ['slo_verdict']!r} "
                  f"contradicts {len(breaches)} breach record(s)",
                  file=sys.stderr)
            rc = 1
        rollups = [r for r in records
                   if r.get("record") == "fleet_rollup"]
        if not rollups:
            print(f"{stream}: no fleet_rollup record (the replicas' "
                  "sketches never merged — rollup cadence longer than "
                  "the run?)", file=sys.stderr)
            rc = 1
        for r in rollups:
            per = r.get("per_replica")
            if isinstance(per, dict) and per:
                total = sum(v.get("count", 0) for v in per.values())
                if total != r.get("count"):
                    print(f"{stream}: fleet_rollup count "
                          f"{r.get('count')} != {total} summed over "
                          "per_replica — merge lost samples",
                          file=sys.stderr)
                    rc = 1
    return rc


def _perf_gate(stream: str, baseline_path) -> int:
    """The hot-path overhead gate (ISSUE 17) over one recorded
    ``--tick-profile`` stream: schema-v15 validation, an armed run
    (``overhead_summary`` present), perf_ledger's internal-consistency
    checks (phase components sum to wall within 1%; the summary's
    gap / fraction / phase totals agree — the tamper gate), and, when
    ``baseline_path`` is given, the normalized snapshot within the
    baseline's per-metric noise bands.  Returns 0/1 (2 is the caller's
    unreadable-stream path)."""
    import json

    perf_ledger = _load_tool("perf_ledger")
    metrics_lint = _load_tool("metrics_lint")
    try:
        records = perf_ledger.load_records(stream)
    except ValueError as e:
        print(f"{stream}: {e}", file=sys.stderr)
        return 1
    rc = 0
    for e in metrics_lint.validate_stream(records):
        print(f"{stream}: {e}", file=sys.stderr)
        rc = 1
    if not any(isinstance(r, dict)
               and r.get("record") == "overhead_summary"
               for r in records):
        print(f"{stream}: no overhead_summary record (was the run "
              "armed with --tick-profile?)", file=sys.stderr)
        rc = 1
    for e in perf_ledger.consistency_errors(records):
        print(f"{stream}: {e}", file=sys.stderr)
        rc = 1
    snap = perf_ledger.snapshot(records, stream)
    if snap is None:
        print(f"{stream}: no serve_summary/run_summary/fleet_summary "
              "— not a perf stream", file=sys.stderr)
        return 1
    if baseline_path:
        with open(baseline_path) as fh:
            baseline = json.load(fh)
        # One stream per gate call: hold it only to ITS kind's slice
        # of the baseline (the other kinds are other --perf-stream
        # invocations).
        sub = {"streams": {k: v
                           for k, v in baseline.get("streams",
                                                    {}).items()
                           if k == snap["kind"]}}
        for f in perf_ledger.compare([snap], sub):
            print(f"{stream}: {f}", file=sys.stderr)
            rc = 1
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="one command for every static CI gate")
    ap.add_argument("--stream", action="append", default=[],
                    metavar="JSONL",
                    help="a --cost-model telemetry stream to run the "
                         "recompile gate over (repeatable)")
    ap.add_argument("--trace-stream", action="append", default=[],
                    metavar="JSONL",
                    help="a --trace telemetry stream to run the "
                         "trace_export --check structural lint over "
                         "(repeatable)")
    ap.add_argument("--fleet-stream", action="append", default=[],
                    metavar="JSONL",
                    help="a fleet-router stream to run the scenario "
                         "gate over: schema-v10 validation, zero lost "
                         "requests, availability threshold, passing "
                         "verdict (repeatable)")
    ap.add_argument("--fleet-availability-min", type=float, default=1.0,
                    metavar="X",
                    help="fleet availability the --fleet-stream gate "
                         "requires (default 1.0)")
    ap.add_argument("--disagg-stream", action="append", default=[],
                    metavar="JSONL",
                    help="a disaggregated-serving role stream (repeat "
                         "for the prefill + decode pair of ONE "
                         "deployment): schema-v12 validation, exactly "
                         "one serve_summary per role, zero lost "
                         "handoffs across the group")
    ap.add_argument("--quant-stream", action="append", default=[],
                    metavar="JSONL",
                    help="a quantized-serving stream to run the quant "
                         "gate over: schema-v11 validation, exactly one "
                         "serve_summary, int8 kv_dtype + quant_event, "
                         "and kv_bytes_committed <= bf16-equivalent / "
                         "--quant-compression-min (repeatable)")
    ap.add_argument("--slo-stream", action="append", default=[],
                    metavar="JSONL",
                    help="an --slo-armed stream (serve.py replica or "
                         "fleet.py router) to run the SLO gate over: "
                         "schema-v14 validation, one announced spec, "
                         "window/breach/summary agreement, and the "
                         "sketch-vs-exact relative-error bound "
                         "(repeatable)")
    ap.add_argument("--perf-stream", action="append", default=[],
                    metavar="JSONL",
                    help="a --tick-profile-armed telemetry stream to "
                         "run the perf gate over: schema-v15 "
                         "validation, an overhead_summary present, "
                         "and perf_ledger's consistency checks — "
                         "phase components sum to wall within 1%%, "
                         "gap/fraction/totals agree (repeatable)")
    ap.add_argument("--spec-stream", action="append", default=[],
                    metavar="JSONL",
                    help="a --speculate-armed stream to run the spec "
                         "gate over: schema-v16 validation, exactly "
                         "one armed serve_summary, accepted <= "
                         "drafted, and output_tokens == accepted + "
                         "sampled (repeatable)")
    ap.add_argument("--tenant-stream", action="append", default=[],
                    metavar="JSONL",
                    help="a tenancy-armed fleet stream to run the "
                         "tenant gate over: schema-v17 validation, "
                         "exactly one fleet_summary with a tenants "
                         "block, exactly-once terminal conservation, "
                         "summary counts == recomputed counts, and "
                         "admitted tokens within budget (repeatable)")
    ap.add_argument("--migrate-stream", action="append", default=[],
                    metavar="JSONL",
                    help="a migration-armed fleet stream to run the "
                         "migrate gate over: schema-v18 validation, "
                         "exactly one armed fleet_summary, zero lost, "
                         "empty spool, zero drain evictions, and the "
                         "per-uid out/in/terminal conservation ledger "
                         "(repeatable)")
    ap.add_argument("--perf-baseline", default=None, metavar="JSON",
                    help="PERF_BASELINE.json to additionally diff "
                         "every --perf-stream snapshot against "
                         "(per-metric noise bands)")
    ap.add_argument("--quant-compression-min", type=float, default=1.9,
                    metavar="X",
                    help="KV compression ratio the --quant-stream gate "
                         "requires vs the bf16-equivalent arena "
                         "(default 1.9)")
    ap.add_argument("--baseline", default=None,
                    help="graftlint baseline override")
    ap.add_argument("paths", nargs="*",
                    help="restrict graftlint's reported findings")
    args = ap.parse_args(argv)

    worst = 0
    lint_argv = ["--fail-on-new"] + args.paths
    if args.baseline:
        lint_argv += ["--baseline", args.baseline]
    rc = graftlint_main(lint_argv)
    print(f"ci_gate: graftlint --fail-on-new: "
          f"{'PASS' if rc == 0 else 'FAIL'}")
    worst = max(worst, rc)

    if args.stream:
        cost_report = _load_tool("cost_report")
        for stream in args.stream:
            if not os.path.isfile(stream):
                print(f"ci_gate: no such stream: {stream}",
                      file=sys.stderr)
                return 2
            rc = cost_report.main([stream, "--fail-on-recompile"])
            print(f"ci_gate: cost_report --fail-on-recompile "
                  f"{stream}: {'PASS' if rc == 0 else 'FAIL'}")
            worst = max(worst, rc)

    if args.trace_stream:
        trace_export = _load_tool("trace_export")
        for stream in args.trace_stream:
            if not os.path.isfile(stream):
                print(f"ci_gate: no such stream: {stream}",
                      file=sys.stderr)
                return 2
            rc = trace_export.main(["--check", stream])
            print(f"ci_gate: trace_export --check "
                  f"{stream}: {'PASS' if rc == 0 else 'FAIL'}")
            worst = max(worst, rc)

    for stream in args.fleet_stream:
        if not os.path.isfile(stream):
            print(f"ci_gate: no such stream: {stream}",
                  file=sys.stderr)
            return 2
        rc = _fleet_gate(stream, args.fleet_availability_min)
        print(f"ci_gate: fleet gate {stream}: "
              f"{'PASS' if rc == 0 else 'FAIL'}")
        worst = max(worst, rc)

    for stream in args.slo_stream:
        if not os.path.isfile(stream):
            print(f"ci_gate: no such stream: {stream}",
                  file=sys.stderr)
            return 2
        rc = _slo_gate(stream)
        print(f"ci_gate: slo gate {stream}: "
              f"{'PASS' if rc == 0 else 'FAIL'}")
        worst = max(worst, rc)

    if args.perf_stream and args.perf_baseline \
            and not os.path.isfile(args.perf_baseline):
        print(f"ci_gate: no such baseline: {args.perf_baseline}",
              file=sys.stderr)
        return 2
    for stream in args.perf_stream:
        if not os.path.isfile(stream):
            print(f"ci_gate: no such stream: {stream}",
                  file=sys.stderr)
            return 2
        rc = _perf_gate(stream, args.perf_baseline)
        print(f"ci_gate: perf gate {stream}: "
              f"{'PASS' if rc == 0 else 'FAIL'}")
        worst = max(worst, rc)

    for stream in args.tenant_stream:
        if not os.path.isfile(stream):
            print(f"ci_gate: no such stream: {stream}",
                  file=sys.stderr)
            return 2
        rc = _tenant_gate(stream)
        print(f"ci_gate: tenant gate {stream}: "
              f"{'PASS' if rc == 0 else 'FAIL'}")
        worst = max(worst, rc)

    for stream in args.migrate_stream:
        if not os.path.isfile(stream):
            print(f"ci_gate: no such stream: {stream}",
                  file=sys.stderr)
            return 2
        rc = _migrate_gate(stream)
        print(f"ci_gate: migrate gate {stream}: "
              f"{'PASS' if rc == 0 else 'FAIL'}")
        worst = max(worst, rc)

    for stream in args.spec_stream:
        if not os.path.isfile(stream):
            print(f"ci_gate: no such stream: {stream}",
                  file=sys.stderr)
            return 2
        rc = _spec_gate(stream)
        print(f"ci_gate: spec gate {stream}: "
              f"{'PASS' if rc == 0 else 'FAIL'}")
        worst = max(worst, rc)

    for stream in args.quant_stream:
        if not os.path.isfile(stream):
            print(f"ci_gate: no such stream: {stream}",
                  file=sys.stderr)
            return 2
        rc = _quant_gate(stream, args.quant_compression_min)
        print(f"ci_gate: quant gate {stream}: "
              f"{'PASS' if rc == 0 else 'FAIL'}")
        worst = max(worst, rc)

    if args.disagg_stream:
        for stream in args.disagg_stream:
            if not os.path.isfile(stream):
                print(f"ci_gate: no such stream: {stream}",
                      file=sys.stderr)
                return 2
        rc = _disagg_gate(args.disagg_stream)
        print(f"ci_gate: disagg gate "
              f"{' '.join(args.disagg_stream)}: "
              f"{'PASS' if rc == 0 else 'FAIL'}")
        worst = max(worst, rc)

    print(f"ci_gate: {'PASS' if worst == 0 else 'FAIL'}")
    return worst                 # 1 = gate failure, 2 = usage error


if __name__ == "__main__":
    sys.exit(main())
