#!/usr/bin/env python
"""Capture a device trace of the C2 train step and print the top ops by
self-time (tensorboard_plugin_profile's framework_op_stats over a
jax.profiler trace).

Usage: python tools/xprof_dump.py [--batch-size 256] [--steps 5] [--top 40]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from xprof_common import latest_xplane, tool_data


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--logdir", default="/tmp/xprof_c2")
    args = ap.parse_args()

    from apex_example_tpu import amp
    from apex_example_tpu.data import image_batch
    from apex_example_tpu.engine import create_train_state, make_train_step
    from apex_example_tpu.models import resnet50
    from apex_example_tpu.optim import FusedSGD

    policy, scaler = amp.initialize("O2")
    model = resnet50(num_classes=1000, dtype=policy.compute_dtype,
                     param_dtype=policy.param_dtype, bn_dtype=policy.bn_dtype)
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    batch = image_batch(jnp.asarray(0), batch_size=args.batch_size,
                        image_size=224, channels=3, num_classes=1000, seed=0)
    batch = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, jax.devices()[0]), batch)
    state = create_train_state(jax.random.PRNGKey(0), model, opt,
                               batch[0][:1], policy, scaler)
    step = jax.jit(make_train_step(model, opt, policy), donate_argnums=(0,))

    for _ in range(3):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    with jax.profiler.trace(args.logdir):
        for _ in range(args.steps):
            state, metrics = step(state, batch)
        float(metrics["loss"])

    # ---- parse the xplane with the tensorboard profile plugin ----
    xp = latest_xplane(args.logdir)
    for tool in ("framework_op_stats", "op_profile"):
        try:
            data = tool_data(xp, tool)
        except Exception as e:
            print(f"[{tool}] failed: {type(e).__name__}: {e}")
            continue
        out = os.path.join(args.logdir, f"{tool}.out")
        mode = "wb" if isinstance(data, bytes) else "w"
        with open(out, mode) as f:
            f.write(data)
        print(f"[{tool}] -> {out} ({len(data)} bytes)")

    # framework_op_stats is CSV-ish JSON; try to print a quick top-N
    import json
    fos = os.path.join(args.logdir, "framework_op_stats.out")
    if os.path.exists(fos):
        try:
            with open(fos) as f:
                j = json.load(f)
            print(json.dumps(j, indent=1)[:4000])
        except Exception:
            with open(fos, errors="replace") as f:
                print(f.read()[:4000])


if __name__ == "__main__":
    main()
