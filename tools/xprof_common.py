#!/usr/bin/env python
"""Shared xprof trace-path handling for tools/xprof_dump.py and
tools/xprof_parse.py (both used to re-implement the glob + mtime pick +
plugin conversion inline)."""

from __future__ import annotations

import glob
import os


def latest_xplane(logdir: str) -> str:
    """Newest ``*.xplane.pb`` under ``logdir`` (jax.profiler nests them
    under plugins/profile/<timestamp>/); raises FileNotFoundError when the
    trace never materialized."""
    xplanes = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                        recursive=True)
    if not xplanes:
        raise FileNotFoundError(f"no xplane under {logdir}")
    return max(xplanes, key=os.path.getmtime)


def tool_data(xplane_path: str, tool: str):
    """Convert one xplane through the tensorboard profile plugin; returns
    the tool payload (str or bytes, tool-dependent)."""
    from tensorboard_plugin_profile.convert import raw_to_tool_data as rtd
    data, _ = rtd.xspace_to_tool_data([xplane_path], tool, {})
    return data
