#!/usr/bin/env python
"""Summarize a --metrics-jsonl telemetry file: step-time distribution,
throughput, compile estimate, overflow accounting, span histograms — and
the failure path: aborted runs (a stream that ends without a
run_summary, or one marked ``aborted: true``), overflow step indices,
``crash_dump`` / ``stall`` diagnostics records when present — and the
recover path (schema v4): graceful preemptions are reported as
PREEMPTED (resumable), distinct from ABORTED (broken); supervisor
streams surface their ``restart``/``resume`` records and the summary's
``restart_count`` — and the cost stratum (schema v6): COMPILE lines per
``compile_event`` (recompiles flagged), COST lines per ``cost_model``
record, and measured compile totals replacing the first-vs-steady
estimate when a ``--cost-model`` run recorded them
(tools/cost_report.py renders the full roofline join) — and the trace
stratum (schema v9): a TRACE summary line (event count, trace_id,
clock_sync presence) when a ``--trace`` run recorded a timeline
(tools/trace_export.py renders the actual Perfetto export) — and the
fleet stratum (schema v10): a FLEET line (replica/request totals,
availability, lost count, route count, crash/stall transitions,
scenario verdict) when the stream is a fleet-router's
(tools/fleet_report.py renders the per-replica breakdown) — and the
disaggregated-serving stratum (schema v12): a HANDOFF line (out/in
counts, KV bytes moved) when the stream took part in a prefill/decode
split (tools/serve_report.py renders the latency percentiles) — with
the v13 crash-safety counters appended (redelivered admissions,
duplicates acked without a second scatter, quarantined payloads) when
the leased-spool protocol had to recover anything — and the streaming-
SLO stratum (schema v14): an SLO line (windows scored, breaches, burn
verdict) when the run was armed with ``--slo``; a stream that ENDS on
a breaching ``slo_window`` without a summary is flagged as BREACHED,
never read as healthy (tools/slo_report.py renders the window
timeline and burn trajectory) — and the hot-path stratum (schema
v15): an OVERHEAD line (host-overhead fraction, per-phase p50/p99
tick decomposition) when the run was armed with ``--tick-profile``
(tools/perf_ledger.py turns it into the regression snapshot) — and
the speculation stratum (schema v16): the SERVE line carries the
acceptance rate and tokens/tick when the run was armed with
``--speculate`` (pre-v16 streams degrade silently; serve_report.py
renders the full SPEC line) — and the multi-tenant stratum (schema
v17): the FLEET line carries the tenant-lane count, any breached
per-tenant SLO verdict and the fleet prefix-affinity hit rate when
the run was armed with ``--tenants`` (pre-v17 streams degrade
silently; fleet_report.py renders the full TENANT table).

Thin client of the obs JSONL schema (obs/schema.py) — it replaces the
eyeball-the-stdout-meters workflow for perf PRs: run train.py with
--metrics-jsonl, then

    python tools/telemetry_report.py out.jsonl

No jax import; works on any host with the file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Same no-jax file-path load as tools/metrics_lint.py: the report must run
# on hosts that only have the JSONL file and this checkout.
from metrics_lint import pct as _pct  # noqa: E402  (sibling import)
from metrics_lint import validate_stream  # noqa: E402


def report(path: str, out=sys.stdout) -> int:
    records = []
    with open(path) as fh:
        for n, line in enumerate(fh):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                # Killed runs legitimately truncate the last line
                # (JsonlSink's contract keeps everything before it).
                print(f"WARNING: line {n + 1}: not JSON, skipped",
                      file=sys.stderr)
    errors = validate_stream(records)
    for e in errors:
        print(f"WARNING: {e}", file=sys.stderr)

    header = next((r for r in records if r.get("record") == "run_header"),
                  None)
    summary = next((r for r in records if r.get("record") == "run_summary"),
                   None)
    crashes = [r for r in records if r.get("record") == "crash_dump"]
    stalls = [r for r in records if r.get("record") == "stall"]
    preemptions = [r for r in records if r.get("record") == "preemption"]
    restarts = [r for r in records if r.get("record") == "restart"]
    resumes = [r for r in records if r.get("record") == "resume"]
    overflow_events = [r for r in records
                       if r.get("record") == "overflow_event"]
    compile_events = [r for r in records
                      if r.get("record") == "compile_event"]
    cost_models = [r for r in records if r.get("record") == "cost_model"]
    trace_events = [r for r in records
                    if r.get("record") == "trace_event"]
    clock_syncs = [r for r in records
                   if r.get("record") == "clock_sync"]
    handoffs = [r for r in records if r.get("record") == "kv_handoff"]
    fleet_summaries = [r for r in records
                       if r.get("record") == "fleet_summary"]
    routes = [r for r in records if r.get("record") == "route"]
    replica_states = [r for r in records
                      if r.get("record") == "replica_state"]
    # Schema-invalid step records were warned about above; summarize only
    # the ones carrying the contract fields rather than crashing.
    steps = [r for r in records if r.get("record") == "step"
             and all(k in r for k in ("step_time_ms", "items_per_sec",
                                      "loss"))]

    if header:
        cfg = header.get("config", {})
        print(f"run {header['run_id']}  platform={header['platform']}  "
              f"devices={header['num_devices']}  "
              f"arch={header.get('arch', cfg.get('arch', '?'))}", file=out)
    # A TRAIN run is the happy path only when it closed with an unmarked
    # summary; everything else is an abort and says so up front.  Streams
    # with no run_header and no steps (bench.py / accuracy.py records)
    # never write a summary by design — not aborts.
    is_train_stream = header is not None or any(
        r.get("record") == "step" for r in records)
    is_supervisor_stream = (header or {}).get("platform") == "supervisor" \
        or bool(restarts or resumes)
    # Schema v10: a fleet-router stream closes with fleet_summary, not
    # run_summary — never an abort.  tools/fleet_report.py renders the
    # full per-replica story; this is the one-line acknowledgement.
    is_fleet_stream = (header or {}).get("platform") == "fleet-router" \
        or bool(fleet_summaries or routes)
    if is_fleet_stream:
        fs = fleet_summaries[-1] if fleet_summaries else None
        downs = [r for r in replica_states
                 if r.get("state") in ("crashed", "stalled")]
        if fs is not None:
            print(f"FLEET: {fs.get('replicas', '?')} replica(s), "
                  f"{fs.get('requests', '?')} request(s), availability "
                  f"{fs.get('availability', '?')}, lost "
                  f"{fs.get('lost', '?')}, {len(routes)} route(s), "
                  f"{len(downs)} crash/stall transition(s)"
                  + (f"  scenario {fs['scenario']}="
                     f"{fs.get('verdict', '?')}"
                     if "scenario" in fs else "")
                  # v17 passthrough: a --tenants fleet names its lane
                  # count and any failing per-tenant verdict here
                  # (fleet_report.py renders the full TENANT table);
                  # pre-v17 streams carry no tenants block and print
                  # nothing extra, like the spec passthrough below.
                  + (f"  {len(fs['tenants'])} tenant lane(s)"
                     + (lambda bad: f" ({', '.join(bad)} BREACHED)"
                        if bad else "")(
                         sorted(n for n, b in fs["tenants"].items()
                                if (b or {}).get("slo_verdict")
                                == "fail"))
                     if isinstance(fs.get("tenants"), dict)
                     and fs["tenants"] else "")
                  + (f"  prefix_hit_rate {fs['prefix_hit_rate']}"
                     if "prefix_hit_rate" in fs else "")
                  + "  (tools/fleet_report.py for the breakdown)",
                  file=out)
        else:
            print("TRUNCATED FLEET STREAM: ends without a "
                  "fleet_summary (router killed?)", file=out)
    def print_preempted(p, truncated=False):
        # A graceful preemption is NOT an abort: the run saved, exited
        # 75 and is resumable — the distinction supervisors key on.
        ck = p.get("checkpoint_step")
        print(f"PREEMPTED RUN (graceful): {p.get('signal', '?')} at step "
              f"{p.get('step', '?')}, "
              + (f"checkpoint at step {ck}" if ck is not None
                 else "nothing saved")
              + " — resumable"
              + (" (stream truncated before run_summary)" if truncated
                 else ""), file=out)

    # A serving stream closes with serve_summary, not run_summary —
    # never an abort (the full report lives in tools/serve_report.py).
    serve_summaries = [r for r in records
                       if r.get("record") == "serve_summary"]
    is_serve_stream = bool(serve_summaries or handoffs) or any(
        r.get("record") in ("request_complete", "request_failed",
                            "serve_drain")
        for r in records)
    if summary is None:
        if is_fleet_stream:
            pass                        # fleet_summary is its close
        elif is_serve_stream:
            if serve_summaries:
                s = serve_summaries[-1]
                # v16 passthrough: a --speculate stream names its
                # acceptance ledger here; pre-v16 streams carry no
                # speculate_k and print nothing extra (the SPEC line
                # proper lives in serve_report.py).
                spec = ""
                if "speculate_k" in s:
                    spec = (f", spec K={s['speculate_k']} acceptance "
                            f"{s.get('acceptance_rate', 0.0):.1%} "
                            f"tokens/tick {s.get('tokens_per_tick', 0.0)}")
                print(f"SERVE: {s.get('requests', '?')} request(s), "
                      f"role {s.get('role', 'both')}"
                      + (f", mesh {s['mesh']}" if "mesh" in s else "")
                      + f", availability {s.get('availability', '?')}"
                      + spec +
                      "  (tools/serve_report.py for the full report)",
                      file=out)
            else:
                print("TRUNCATED SERVE STREAM: ends without a "
                      "serve_summary (run killed or still in flight)",
                      file=out)
        elif is_supervisor_stream:
            # Supervisors have no flight recorder; a truncated stream
            # means the supervisor itself was killed mid-flight.
            print("TRUNCATED SUPERVISOR STREAM: ends without a "
                  "run_summary (supervisor killed?)", file=out)
        elif preemptions:
            # SIGKILL landed between the preemption record and the
            # summary: the grace checkpoint DID land first (the record
            # is written after the save), so the run is resumable.
            print_preempted(preemptions[-1], truncated=True)
        elif is_train_stream:
            print("ABORTED RUN: stream ends without a run_summary (killed "
                  "before the flight recorder could fire, or no "
                  "--flight-recorder)", file=out)
    elif summary.get("aborted"):
        reason = summary.get("abort_reason", "unknown reason")
        print(f"ABORTED RUN: {reason}", file=out)
    elif preemptions:
        print_preempted(preemptions[-1])
    if summary is not None and summary.get("restart_count"):
        print(f"restarts: {summary['restart_count']}"
              + (f"  (final exit {summary['exit_code']})"
                 if "exit_code" in summary else ""), file=out)
    for r in restarts[:10]:
        print(f"restart after attempt {r.get('attempt', '?')}: exit "
              f"{r.get('exit_code', '?')} ({r.get('reason', '?')}), "
              f"last step {r.get('last_step', '?')}, backoff "
              f"{r.get('backoff_s', 0):.1f}s", file=out)
    for r in resumes[:10]:
        print(f"resume attempt {r.get('attempt', '?')}: from step "
              f"{r.get('checkpoint_step', '?')} in "
              f"{r.get('resume_dir', '?')}", file=out)
    for c in crashes:
        where = f" at step {c['step']}" if "step" in c else ""
        print(f"crash_dump{where}: {c.get('reason', '?')}", file=out)
        tb = c.get("traceback", "").strip().splitlines()
        if tb:
            print(f"  {tb[-1]}", file=out)
    if stalls:
        worst = max(s.get("seconds_since_step", 0) for s in stalls)
        print(f"stalls: {len(stalls)} (longest {worst:.0f}s without a "
              "step)", file=out)
    if trace_events:
        # Schema v9 (--trace): the timeline lives in trace_export.py;
        # this line says there IS one and whether it can be exported
        # (no clock_sync = no wall-clock anchor).
        tid = next((t.get("trace_id") for t in trace_events
                    if t.get("trace_id")), "?")
        print(f"TRACE: {len(trace_events)} event(s), trace_id {tid}"
              + ("" if clock_syncs
                 else "  (NO clock_sync — not exportable)"), file=out)
    if handoffs:
        # Schema v12 (disaggregated serving): the per-request handoff
        # distribution lives in tools/serve_report.py; this line says
        # the stream took part in a prefill/decode split and on which
        # side(s).
        n_out = sum(1 for h in handoffs if h.get("direction") == "out")
        n_in = sum(1 for h in handoffs if h.get("direction") == "in"
                   and not h.get("duplicate"))
        moved = sum(h.get("payload_bytes", 0) for h in handoffs
                    if h.get("direction") != "quarantine")
        line = (f"HANDOFF: {n_out} out / {n_in} in, "
                f"{moved / 1024:.1f} KiB of KV blocks moved")
        # v13: the crash-safety counters, only when something recovered
        n_redeliv = sum(1 for h in handoffs if h.get("redelivered")
                        and not h.get("duplicate"))
        n_dup = sum(1 for h in handoffs if h.get("duplicate"))
        n_quar = sum(1 for h in handoffs
                     if h.get("direction") == "quarantine")
        if n_redeliv or n_dup or n_quar:
            line += (f" ({n_redeliv} redelivered, {n_dup} duplicate, "
                     f"{n_quar} quarantined)")
        print(line + " (tools/serve_report.py for latency percentiles)",
              file=out)
    slo_windows = [r for r in records if r.get("record") == "slo_window"]
    slo_breaches = [r for r in records
                    if r.get("record") == "slo_breach"]
    if slo_windows or slo_breaches:
        # Schema v14 (--slo): the window timeline and burn trajectory
        # live in tools/slo_report.py; this line says the run was
        # scored and how it ended.  The verdict comes from whichever
        # summary the stream carries; a stream that ends on a breaching
        # window WITHOUT a summary must not read as healthy.
        s_slo = (serve_summaries[-1].get("slo")
                 if serve_summaries else None)
        f_last = fleet_summaries[-1] if fleet_summaries else None
        if isinstance(s_slo, dict):
            verdict = s_slo.get("verdict", "?")
        elif f_last is not None and "slo_verdict" in f_last:
            verdict = f_last["slo_verdict"]
        elif slo_windows and slo_windows[-1].get("burn_rate", 0) > 1.0:
            verdict = "last window BREACHED, no summary (truncated?)"
        else:
            verdict = "no summary (truncated?)"
        print(f"SLO: {len(slo_windows)} window(s), "
              f"{len(slo_breaches)} breach(es), verdict {verdict}"
              "  (tools/slo_report.py for the burn trajectory)",
              file=out)
    overheads = [r for r in records
                 if r.get("record") == "overhead_summary"]
    if overheads:
        # Schema v15 (--tick-profile): the hot-path decomposition —
        # host-overhead fraction plus per-phase p50/p99 from the
        # profiler's online sketches.  tools/perf_ledger.py turns this
        # into the regression snapshot; pre-v15 streams carry no
        # overhead_summary and skip the line.
        ov = overheads[-1]
        print(f"OVERHEAD: kind {ov.get('kind', '?')}  "
              f"host_overhead_frac "
              f"{ov.get('host_overhead_frac', 0.0):.4f}  "
              f"(host_gap {ov.get('host_gap_ms', 0.0):.1f} ms of "
              f"{ov.get('wall_ms', 0.0):.1f} ms wall over "
              f"{ov.get('ticks', 0)} tick(s))", file=out)
        parts = "  ".join(
            f"{name} {p.get('p50', 0.0):.2f}/{p.get('p99', 0.0):.2f}"
            for name, p in (ov.get("phases") or {}).items()
            if isinstance(p, dict))
        if parts:
            print(f"  phases (p50/p99 ms): {parts}", file=out)
    if not steps:
        if is_fleet_stream:
            return 0 if fleet_summaries else 1
        if is_serve_stream:
            return 0 if serve_summaries else 1
        if is_supervisor_stream:
            # Supervisor streams carry no step records by design — the
            # child's stream(s) hold those.  A truncated one (no
            # run_summary) is unhealthy regardless.
            print("supervisor stream (step records live in the child's "
                  "metrics JSONL)", file=out)
            return 0 if summary is not None else 1
        print("no step records", file=out)
        return 1

    times = sorted(r["step_time_ms"] for r in steps)
    rates = sorted(r["items_per_sec"] for r in steps)
    losses = [r["loss"] for r in steps]
    print(f"steps {len(steps)}  loss {losses[0]:.4f} -> {losses[-1]:.4f}",
          file=out)
    print(f"step_time_ms  p50 {_pct(times, 50):.1f}  p95 {_pct(times, 95):.1f}"
          f"  max {times[-1]:.1f}", file=out)
    print(f"items_per_sec p50 {_pct(rates, 50):.1f}  max {rates[-1]:.1f}",
          file=out)
    overflow = max((r.get("overflow_count", 0) for r in steps), default=0)
    # .get throughout: this tool summarizes broken streams, it must not
    # crash on a record missing a field the schema calls required.
    overflow_at = [r.get("step", "?") for r in steps
                   if r.get("grads_finite", 1) < 1]
    shown = ", ".join(str(s) for s in overflow_at[:20]) + \
        (", ..." if len(overflow_at) > 20 else "")
    print(f"overflow steps {overflow}"
          + (f" (at {shown})" if overflow_at else ""), file=out)
    for ev in overflow_events[:10]:
        mods = ", ".join(ev.get("modules", [])) or "-"
        print(f"overflow_event step {ev.get('step', '?')}: non-finite "
              f"grads in [{mods}]", file=out)
    if len(overflow_events) > 10:
        print(f"... {len(overflow_events) - 10} more overflow_event "
              "record(s)", file=out)
    norms = [r["grad_norm"] for r in steps if "grad_norm" in r]
    if norms:
        s = sorted(norms)
        print(f"grad_norm     p50 {_pct(s, 50):.3g}  max {s[-1]:.3g}",
              file=out)
    for ev in compile_events[:10]:
        tag = ""
        if ev.get("n_compiles", 1) > 1:
            tag = f"  RECOMPILE #{ev['n_compiles']}"
        print(f"COMPILE {ev.get('name', '?')}  "
              f"{ev.get('compile_ms', 0):.0f} ms compile "
              f"+ {ev.get('lower_ms', 0):.0f} ms lower{tag}", file=out)
    if len(compile_events) > 10:
        print(f"... {len(compile_events) - 10} more compile_event "
              "record(s)", file=out)
    for c in cost_models[:10]:
        flops = c.get("flops")
        nbytes = c.get("bytes_accessed")
        # `is not None` throughout: 0 is a legitimate XLA count (a
        # data-movement-only program); null means the backend omitted
        # the analysis — the two must not render the same.
        print(f"COST {c.get('name', '?')}  "
              + (f"{flops / 1e9:.3f} GFLOP  " if flops is not None
                 else "flops n/a  ")
              + (f"{nbytes / 1e6:.1f} MB  " if nbytes is not None
                 else "bytes n/a  ")
              + (f"AI {c['arithmetic_intensity']:.1f}  "
                 if "arithmetic_intensity" in c else "")
              + c.get("roofline", ""), file=out)
    if len(cost_models) > 10:
        print(f"... {len(cost_models) - 10} more cost_model "
              "record(s)", file=out)
    if summary:
        # Measured compile time (schema v6, --cost-model) supersedes
        # the first-vs-steady estimate; the estimate stays as the
        # cross-check when both exist.
        if "compile_ms_total" in summary:
            print(f"compile       {summary['compile_ms_total']:.0f} ms "
                  f"measured over {summary.get('compile_events', 0)} "
                  "compilation(s)"
                  + (f"  (first-vs-steady estimate "
                     f"{summary['compile_est_ms']:.0f} ms)"
                     if "compile_est_ms" in summary else ""), file=out)
        elif "compile_est_ms" in summary:
            print(f"compile est   {summary['compile_est_ms']:.0f} ms "
                  f"(first {summary['first_step_ms']:.0f} ms vs steady "
                  f"{summary['steady_step_ms']:.0f} ms)", file=out)
        for name, hist in summary.get("spans", {}).items():
            print(f"{name}  n={hist.get('count', 0)}  "
                  f"p50 {hist.get('p50', 0):.1f} ms  "
                  f"p95 {hist.get('p95', 0):.1f} ms", file=out)
    mems = [r["memory"] for r in steps if "memory" in r]
    if mems:
        peak = max(m.get("peak_bytes_in_use", m.get("bytes_in_use", 0))
                   for m in mems)
        print(f"peak device memory {peak / 2**30:.2f} GiB", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("path")
    args = ap.parse_args(argv)
    return report(args.path)


if __name__ == "__main__":
    sys.exit(main())
