#!/usr/bin/env python
"""Summarize a --metrics-jsonl telemetry file: step-time distribution,
throughput, compile estimate, overflow accounting, span histograms.

Thin client of the obs JSONL schema (obs/schema.py) — it replaces the
eyeball-the-stdout-meters workflow for perf PRs: run train.py with
--metrics-jsonl, then

    python tools/telemetry_report.py out.jsonl

No jax import; works on any host with the file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Same no-jax file-path load as tools/metrics_lint.py: the report must run
# on hosts that only have the JSONL file and this checkout.
from metrics_lint import validate_stream  # noqa: E402  (sibling import)


def _pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(int(q / 100 * len(sorted_vals)),
                           len(sorted_vals) - 1)]


def report(path: str, out=sys.stdout) -> int:
    records = []
    with open(path) as fh:
        for n, line in enumerate(fh):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                # Killed runs legitimately truncate the last line
                # (JsonlSink's contract keeps everything before it).
                print(f"WARNING: line {n + 1}: not JSON, skipped",
                      file=sys.stderr)
    errors = validate_stream(records)
    for e in errors:
        print(f"WARNING: {e}", file=sys.stderr)

    header = next((r for r in records if r.get("record") == "run_header"),
                  None)
    summary = next((r for r in records if r.get("record") == "run_summary"),
                   None)
    # Schema-invalid step records were warned about above; summarize only
    # the ones carrying the contract fields rather than crashing.
    steps = [r for r in records if r.get("record") == "step"
             and all(k in r for k in ("step_time_ms", "items_per_sec",
                                      "loss"))]

    if header:
        cfg = header.get("config", {})
        print(f"run {header['run_id']}  platform={header['platform']}  "
              f"devices={header['num_devices']}  "
              f"arch={header.get('arch', cfg.get('arch', '?'))}", file=out)
    if not steps:
        print("no step records", file=out)
        return 1

    times = sorted(r["step_time_ms"] for r in steps)
    rates = sorted(r["items_per_sec"] for r in steps)
    losses = [r["loss"] for r in steps]
    print(f"steps {len(steps)}  loss {losses[0]:.4f} -> {losses[-1]:.4f}",
          file=out)
    print(f"step_time_ms  p50 {_pct(times, 50):.1f}  p95 {_pct(times, 95):.1f}"
          f"  max {times[-1]:.1f}", file=out)
    print(f"items_per_sec p50 {_pct(rates, 50):.1f}  max {rates[-1]:.1f}",
          file=out)
    overflow = max((r.get("overflow_count", 0) for r in steps), default=0)
    print(f"overflow steps {overflow}", file=out)
    norms = [r["grad_norm"] for r in steps if "grad_norm" in r]
    if norms:
        s = sorted(norms)
        print(f"grad_norm     p50 {_pct(s, 50):.3g}  max {s[-1]:.3g}",
              file=out)
    if summary:
        if "compile_est_ms" in summary:
            print(f"compile est   {summary['compile_est_ms']:.0f} ms "
                  f"(first {summary['first_step_ms']:.0f} ms vs steady "
                  f"{summary['steady_step_ms']:.0f} ms)", file=out)
        for name, hist in summary.get("spans", {}).items():
            print(f"{name}  n={hist.get('count', 0)}  "
                  f"p50 {hist.get('p50', 0):.1f} ms  "
                  f"p95 {hist.get('p95', 0):.1f} ms", file=out)
    mems = [r["memory"] for r in steps if "memory" in r]
    if mems:
        peak = max(m.get("peak_bytes_in_use", m.get("bytes_in_use", 0))
                   for m in mems)
        print(f"peak device memory {peak / 2**30:.2f} GiB", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("path")
    args = ap.parse_args(argv)
    return report(args.path)


if __name__ == "__main__":
    sys.exit(main())
