#!/usr/bin/env python
"""Roofline report over a --cost-model telemetry stream (schema v6).

Joins the ``cost_model`` records (what XLA compiled: flops, HBM bytes,
arithmetic intensity, the analytic step-time floor at the peak
constants) against the MEASURED ``step_time_ms`` distribution from the
same stream, and tallies ``compile_event`` records per function — the
decision-grade table the parallelism auto-planner (ROADMAP item 4) and
any img/s-gap analysis start from:

    python train.py ... --metrics-jsonl run.jsonl --cost-model
    python tools/cost_report.py run.jsonl

Per instrumented function the table shows the program cost (GFLOP, MB
accessed, arithmetic intensity), which roofline side binds it at the
record's peak constants, the analytic minimum step time, and — where
the stream carries a measured twin — the measured time, the
measured/analytic gap, and achieved MFU:

- ``train_step`` joins the ``step`` records' steady-state
  ``step_time_ms`` (median of steps after the first; the first is
  trace+compile+execute),
- ``serve_decode_step`` joins ``serve_summary``'s
  ``duration_s / compute_steps`` mean tick time.

Recompiles (more than one ``compile_event`` for one name) are listed
explicitly; ``--fail-on-recompile`` turns them into exit 1 so CI can
gate on the compile-once contract.

Thin client of the obs JSONL schema: NO jax import, same file-path
schema load as tools/metrics_lint.py — runs on any host with the file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from metrics_lint import pct as _pct  # noqa: E402  (sibling import)
from metrics_lint import validate_stream  # noqa: E402


def _read(path: str) -> List[Dict[str, Any]]:
    records = []
    with open(path) as fh:
        for n, line in enumerate(fh):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"WARNING: line {n + 1}: not JSON, skipped",
                      file=sys.stderr)
    return records


def _fmt(value, spec: str, missing: str = "-") -> str:
    return format(value, spec) if value is not None else missing


def measured_ms(name: str, records: List[Dict[str, Any]]
                ) -> Optional[float]:
    """The measured wall-time twin of one instrumented function, where
    the stream carries one (see module docstring for the join rules)."""
    if name == "train_step":
        times = [r["step_time_ms"] for r in records
                 if r.get("record") == "step" and "step_time_ms" in r]
        steady = sorted(times[1:])       # first step = compile + execute
        if steady:
            return _pct(steady, 50)
    if name == "serve_decode_step":
        summary = next((r for r in records
                        if r.get("record") == "serve_summary"), None)
        if summary and summary.get("compute_steps") \
                and summary.get("duration_s") is not None:
            # The AOT compile runs inside the engine loop, so the
            # summary's wall-clock contains it; subtract this
            # function's recorded lower+compile time or a short run's
            # mean tick is dominated by the one-off compile.
            compile_ms = sum(
                r.get("compile_ms", 0.0) + r.get("lower_ms", 0.0)
                for r in records
                if r.get("record") == "compile_event"
                and r.get("name") == name)
            total_ms = summary["duration_s"] * 1e3 - compile_ms
            if total_ms > 0:
                return total_ms / summary["compute_steps"]
    return None


def report(path: str, out=sys.stdout, fail_on_recompile: bool = False) -> int:
    records = _read(path)
    for e in validate_stream(records):
        print(f"WARNING: {e}", file=sys.stderr)

    costs: Dict[str, Dict[str, Any]] = {}
    for r in records:
        if r.get("record") == "cost_model" and "name" in r:
            costs[r["name"]] = r             # last per name wins
    compiles: Dict[str, List[Dict[str, Any]]] = {}
    for r in records:
        if r.get("record") == "compile_event" and "name" in r:
            compiles.setdefault(r["name"], []).append(r)

    if not costs and not compiles:
        print("no cost_model/compile_event records (run with "
              "--cost-model and --metrics-jsonl)", file=out)
        return 1

    head = (f"{'function':<20} {'GFLOP':>9} {'MB':>9} {'AI':>7} "
            f"{'roofline':<13} {'min_ms':>9} {'meas_ms':>9} {'gap':>7} "
            f"{'mfu%':>6}")
    print(head, file=out)
    print("-" * len(head), file=out)
    for name in sorted(costs):
        c = costs[name]
        flops = c.get("flops")
        nbytes = c.get("bytes_accessed")
        min_ms = c.get("analytic_min_ms")
        meas = measured_ms(name, records)
        gap = mfu = None
        if meas and min_ms:
            gap = meas / min_ms
        if meas and flops and c.get("peak_flops"):
            mfu = 100.0 * flops / (meas / 1e3) / c["peak_flops"]
        print(f"{name:<20} "
              f"{_fmt(flops and flops / 1e9, '9.3f'):>9} "
              f"{_fmt(nbytes and nbytes / 1e6, '9.2f'):>9} "
              f"{_fmt(c.get('arithmetic_intensity'), '7.1f'):>7} "
              f"{c.get('roofline', '-'):<13} "
              f"{_fmt(min_ms, '9.4f'):>9} "
              f"{_fmt(meas, '9.3f'):>9} "
              f"{_fmt(gap, '6.1f') + 'x' if gap else '-':>7} "
              f"{_fmt(mfu, '6.3f'):>6}", file=out)

    print("", file=out)
    total_ms = sum(e.get("compile_ms", 0.0)
                   for evs in compiles.values() for e in evs)
    n_events = sum(len(evs) for evs in compiles.values())
    print(f"compiles: {n_events} event(s), {total_ms:.0f} ms total",
          file=out)
    recompiled = {n: evs for n, evs in compiles.items() if len(evs) > 1}
    for name, evs in sorted(recompiled.items()):
        hashes = {e.get("lowering_hash", "?") for e in evs}
        print(f"RECOMPILE {name}: {len(evs)} compilations "
              f"({len(hashes)} distinct program(s))", file=out)
        for ev in evs:
            # schema v8: the recompile-cause diff (graftlint HLO
            # stratum) — the tally becomes a diagnosis.
            if ev.get("recompile_cause"):
                print(f"  cause (compile #{ev.get('n_compiles', '?')}): "
                      f"{ev['recompile_cause']}", file=out)
    if not recompiled and compiles:
        print("no recompiles: every instrumented function compiled once",
              file=out)
    if recompiled and fail_on_recompile:
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("path", help="JSONL file a --cost-model run wrote")
    ap.add_argument("--fail-on-recompile", action="store_true",
                    help="exit 1 when any function compiled more than "
                         "once (the CI gate on the compile-once "
                         "contract)")
    args = ap.parse_args(argv)
    return report(args.path, fail_on_recompile=args.fail_on_recompile)


if __name__ == "__main__":
    sys.exit(main())
