#!/bin/bash
# Round-5 campaign watcher: retry tools/measure_batch.py until it drains.
#
# measure_batch.py is self-guarding (cheap probe before every item; aborts
# rc 3 on a wedged tunnel, rc 4 on an item timeout) and resumable
# (MEASURE_R4.jsonl keys), so the watcher's only job is to keep offering
# it the tunnel until a healthy window appears and everything lands.
# Probing a wedged tunnel is safe — the wedge pathology is a kill
# mid-remote-COMPILE; a 256x256 matmul probe that hangs never reaches
# compile (PERF.md probe-log methodology, rounds 2-4).
cd "$(dirname "$0")/.." || exit 1
LOG=PERF_probe_r5.log
while true; do
  echo "=== $(date -u '+%F %T') UTC: campaign attempt ===" >> "$LOG"
  python tools/measure_batch.py >> "$LOG" 2>&1
  rc=$?
  echo "=== rc=$rc ===" >> "$LOG"
  if [ "$rc" -eq 0 ]; then
    echo "campaign COMPLETE $(date -u '+%F %T')" >> "$LOG"
    break
  fi
  sleep 900
done
