#!/usr/bin/env python
"""Auto-resume supervisor CLI: keep a training run — or a serving
process — alive across preemptions, drains and crashes.

    python tools/supervise.py [flags] -- python train.py --arch ... \
        --checkpoint-dir ck --preempt-grace --metrics-jsonl out.jsonl

    # over serve.py: drain-exit 75 restarts promptly, no --resume rewrite
    python tools/supervise.py --no-resume \
        --drop-flag-on-restart=--inject-fault \
        -- python serve.py --requests 32 --metrics-jsonl serve.jsonl

Everything after ``--`` is the child command, launched verbatim except:

- ``--resume <checkpoint-dir>`` is inserted (or replaced) whenever the
  checkpoint dir holds a step — attempt 0 included, so a re-launched
  supervisor continues where its predecessor's child left off
  (``--no-resume`` disables this for children like serve.py that have
  no resume flag);
- on restart attempts the child's ``--metrics-jsonl PATH`` becomes
  ``PATH.attempt<K>``, preserving each attempt's stream intact;
- ``--drop-flag-on-restart FLAG`` (repeatable) strips ``FLAG`` and its
  value from restart attempts — one-shot ``--inject-fault`` drills must
  not re-fire on a child that restarts from tick 0.  This covers the
  disagg handoff drills (``--inject-fault handoff_*@N``, ISSUE 15) the
  same way: a restarted decode worker replays the spool from its claim
  set, so an operation-ordinal drill would re-fire every attempt
  exactly like an exact-tick serve drill.

Child exit contract: 0 = done; 75 (EX_TEMPFAIL — train.py's
``--preempt-grace`` path and serve.py's SIGTERM drain alike) = graceful,
restart promptly; any other status = crash, restart with exponential
backoff.  Every restart consumes one unit of ``--max-restarts``.

``--metrics-jsonl`` here gives the SUPERVISOR its own schema-v10 stream
(``restart``/``resume`` records, ``run_summary`` with ``restart_count``
— obs/schema.py).  Each ``restart`` record carries the child's exit
``classification`` (``preempted`` / ``crashed`` / ``stall_killed``), so
fleet tooling (fleet/replica.py, tools/fleet_report.py) distinguishes a
drain from a crash without re-parsing the child's stream.
``--checkpoint-dir``/child metrics default from the child's own flags.

Thin client contract: **no jax import, direct or transitive** — the
supervisor's one job is to restart training on hosts where training
just died, including deaths caused by a broken jax install (graftlint's
static jax-free rule proves the whole import closure stays jax-free —
tools/graftlint/imports.py).  resilience/supervisor.py is therefore
loaded by file path:
importing the package would pull jax via apex_example_tpu/__init__.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys


def _load_supervisor():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "apex_example_tpu", "resilience",
                        "supervisor.py")
    spec = importlib.util.spec_from_file_location("apex_supervisor", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if "--" in argv:
        split = argv.index("--")
        sup_argv, child_argv = argv[:split], argv[split + 1:]
    else:
        sup_argv, child_argv = argv, []
    ap = argparse.ArgumentParser(
        description="auto-resume supervisor: tools/supervise.py [flags] "
                    "-- <child command>")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="dir to watch for checkpoints and rewrite "
                         "--resume to (default: the child's own "
                         "--checkpoint-dir flag)")
    ap.add_argument("--metrics-jsonl", default=None, metavar="PATH",
                    help="the supervisor's OWN telemetry stream (schema "
                         "v5 restart/resume records + run_summary with "
                         "restart_count)")
    ap.add_argument("--child-metrics", default=None, metavar="PATH",
                    help="the child's metrics JSONL to tail for the last "
                         "completed step (default: the child's own "
                         "--metrics-jsonl flag)")
    ap.add_argument("--max-restarts", type=int, default=3,
                    help="restart budget; a flapping run eventually "
                         "surfaces as a failure (default 3)")
    ap.add_argument("--backoff", type=float, default=1.0, metavar="S",
                    help="crash-restart backoff base: S * 2^k seconds "
                         "(default 1.0)")
    ap.add_argument("--backoff-max", type=float, default=60.0, metavar="S",
                    help="crash-restart backoff ceiling (default 60)")
    ap.add_argument("--preempt-delay", type=float, default=0.0, metavar="S",
                    help="delay before restarting after a graceful "
                         "preemption (exit 75; default 0 — the capacity "
                         "is back when the scheduler restarts us)")
    ap.add_argument("--stall-kill", type=float, default=0.0, metavar="S",
                    help="SIGKILL a child whose metrics JSONL stops "
                         "advancing for S seconds and restart it as a "
                         "crash (0 disables; the deadline covers "
                         "first-step compile — size it accordingly)")
    ap.add_argument("--no-resume", action="store_true",
                    help="never rewrite --resume into the child argv "
                         "(serving children restore params via their own "
                         "flags and have no resume concept)")
    ap.add_argument("--drop-flag-on-restart", action="append", default=[],
                    metavar="FLAG",
                    help="strip FLAG (and its value) from restart "
                         "attempts' argv; repeatable, use the = form for "
                         "flag-shaped values (--drop-flag-on-restart="
                         "--inject-fault) — e.g. a one-shot drill that "
                         "must not re-fire")
    args = ap.parse_args(sup_argv)
    if not child_argv:
        ap.error("no child command: tools/supervise.py [flags] -- "
                 "python train.py ...")
    sup_mod = _load_supervisor()
    sup = sup_mod.Supervisor(
        child_argv,
        checkpoint_dir=args.checkpoint_dir,
        metrics_jsonl=args.metrics_jsonl,
        child_metrics=args.child_metrics,
        max_restarts=args.max_restarts,
        backoff_s=args.backoff,
        backoff_max_s=args.backoff_max,
        preempt_delay_s=args.preempt_delay,
        stall_kill_s=args.stall_kill,
        resume=not args.no_resume,
        drop_flags_on_restart=args.drop_flag_on_restart)
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
