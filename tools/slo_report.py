#!/usr/bin/env python
"""SLO report: render a --slo-armed stream's windows, burn trajectory,
breaches and fleet rollups (ISSUE 16; README "SLO monitoring").

Works on either SLO-carrying stream — a serve.py replica stream
(``slo_window``/``slo_breach`` records + the ``serve_summary`` ``slo``
dict) or a fleet.py router stream (the same window records plus
``fleet_rollup`` merges and the ``fleet_summary`` ``slo_*`` fields):

    python serve.py --requests 32 --metrics-jsonl serve.jsonl \\
        --slo ttft_ms=250,tpot_ms=40,availability=0.99
    python tools/slo_report.py serve.jsonl
    #   slo spec: ttft_ms<=250.0 tpot_ms<=40.0 availability 0.99
    #   window  requests  good  bad  burn    ttft_p50  ttft_p99
    #   0       16        16    0    0.0     38.2      61.0
    #   ...
    #   burn trajectory: 0.00 0.00 1.25! 0.00
    #   BREACH: window 2 burn 1.25 (bad 2/16, budget 0.01)
    #   verdict: FAIL (1 breach in 4 windows, worst burn 1.25 @ window 2)

The burn trajectory marks breached windows with ``!`` — burn 1.0
spends a window's error budget exactly, anything past it is a breach.
A stream that ENDS on a breach is reported as failing even without a
summary record (a killed run's last window must not read as healthy).

Schema v17 (ISSUE 19) adds the per-tenant table: a ``--tenants``-armed
fleet_summary carries one verdict block per scheduling lane
(availability, per-tenant SLO verdict and breach count, budget
utilization), and lanes whose ``request_complete`` records ride the
same stream get their TTFT percentiles recomputed per tenant.  A
failing tenant verdict fails the report even when the fleet-level
verdict passes — that asymmetry IS the noisy-neighbor story.  Pre-v17
streams carry no tenants block and degrade silently.

jax-free by the thin-client contract (graftlint's import rule proves
it).  Exit codes: 0 = armed and passing, 1 = breaches / fail verdict /
schema errors, 2 = unusable input (no SLO records in the stream).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from metrics_lint import pct as _pct  # noqa: E402  (sibling import)
from metrics_lint import validate_stream  # noqa: E402


def load_records(path: str) -> List[Dict[str, Any]]:
    records: List[Dict[str, Any]] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue            # killed runs truncate the tail
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError as e:
        print(f"ERROR: {path}: {e}", file=sys.stderr)
    return records


def _spec_line(spec: Dict[str, Any]) -> str:
    parts = []
    for key in ("ttft_ms", "tpot_ms"):
        if spec.get(key) is not None:
            parts.append(f"{key}<={spec[key]}")
    parts.append(f"availability {spec.get('availability', '?')}")
    return " ".join(parts)


def report(path: str, out=sys.stdout) -> int:
    records = load_records(path)
    if not records:
        print(f"{path}: no records", file=sys.stderr)
        return 2
    for err in validate_stream(records):
        print(f"WARNING: {err}", file=sys.stderr)

    header = next((r for r in records
                   if r.get("record") == "run_header"), None)
    windows = [r for r in records if r.get("record") == "slo_window"]
    breaches = [r for r in records if r.get("record") == "slo_breach"]
    rollups = [r for r in records if r.get("record") == "fleet_rollup"]
    serve_summary = next((r for r in records
                          if r.get("record") == "serve_summary"), None)
    fleet_summary = next((r for r in records
                          if r.get("record") == "fleet_summary"), None)

    spec = None
    if header is not None:
        cfg = header.get("config")
        if isinstance(cfg, dict) and isinstance(cfg.get("slo"), dict):
            spec = cfg["slo"]
        elif isinstance(cfg, dict) and isinstance(cfg.get("slo"), str):
            spec = {"raw": cfg["slo"]}

    if not windows and not rollups and spec is None \
            and (serve_summary is None or "slo" not in serve_summary) \
            and (fleet_summary is None
                 or "slo_verdict" not in fleet_summary):
        print(f"{path}: no SLO records (run with --slo to arm the "
              "streaming SLO plane)", file=sys.stderr)
        return 2

    if spec is not None:
        if "raw" in spec:
            print(f"slo spec: {spec['raw']}", file=out)
        else:
            print(f"slo spec: {_spec_line(spec)}", file=out)

    # ---- window timeline --------------------------------------------
    rc = 0
    if windows:
        print("window  requests  good  bad   burn     ttft_p50  "
              "ttft_p99", file=out)
        for w in windows:
            t = w.get("ttft_ms") or {}
            print(f"{w['window']:<7} {w['requests']:<9} "
                  f"{w['good']:<5} {w['bad']:<5} "
                  f"{w['burn_rate']:<8.3g} "
                  f"{t.get('p50', 0.0):<9.1f} "
                  f"{t.get('p99', 0.0):<8.1f}", file=out)
        traj = " ".join(
            f"{w['burn_rate']:.2f}" + ("!" if w["burn_rate"] > 1.0
                                       else "")
            for w in windows)
        print(f"burn trajectory: {traj}", file=out)

    # ---- breach table -----------------------------------------------
    for b in breaches:
        rc = 1
        print(f"BREACH: window {b['window']} burn "
              f"{b['burn_rate']:.3g} (bad {b['bad']}/{b['requests']}"
              + (f", budget {b['budget']:.3g}" if "budget" in b else "")
              + ")", file=out)
    # Windows past burn 1.0 whose breach record is missing (torn tail)
    # still count — the stream must not read healthier than its data.
    breached_windows = {b.get("window") for b in breaches}
    for w in windows:
        if w["burn_rate"] > 1.0 and w["window"] not in breached_windows:
            rc = 1
            print(f"BREACH (no slo_breach record — torn tail?): window "
                  f"{w['window']} burn {w['burn_rate']:.3g}", file=out)

    # ---- fleet rollups ----------------------------------------------
    if rollups:
        last = rollups[-1]
        t = last.get("ttft_ms") or {}
        print(f"fleet rollups: {len(rollups)} record(s); last merges "
              f"{last['replicas']} replica(s), {last['count']} "
              f"sample(s), ttft p50 {t.get('p50', 0.0):.1f} "
              f"p99 {t.get('p99', 0.0):.1f}", file=out)
        for r in rollups:
            if r.get("straggler"):
                print(f"STRAGGLER: {r['straggler']} p50 = "
                      f"{r.get('skew', 0.0)}x the fleet median "
                      "(rollup)", file=out)
                break

    # ---- per-tenant verdicts (schema v17, ISSUE 19) -----------------
    # A --tenants-armed fleet_summary folds one verdict block per
    # scheduling lane; TTFT/TPOT percentiles are recomputed from the
    # lane's own request records when the stream interleaves them.
    # Unarmed (pre-v17) streams carry no tenants block and skip this.
    tenants = next((s.get("tenants") for s in (fleet_summary,
                                               serve_summary)
                    if isinstance((s or {}).get("tenants"), dict)),
                   None)
    if tenants:
        by: Dict[str, List[Dict[str, Any]]] = {}
        for r in records:
            if r.get("record") == "request_complete" \
                    and "tenant" in r and "ttft_ms" in r:
                by.setdefault(r["tenant"], []).append(r)
        print("tenant         avail   verdict  breaches  "
              "ttft p50/p99      budget", file=out)
        for name, blk in tenants.items():
            blk = blk or {}
            verdict = blk.get("slo_verdict", "-")
            ttfts = sorted(r["ttft_ms"] for r in by.get(name, ()))
            lat = (f"{_pct(ttfts, 50):7.1f}/{_pct(ttfts, 99):<9.1f}"
                   if ttfts else f"{'-':>7}/{'-':<9}")
            admitted = blk.get("admitted_tokens")
            cap = blk.get("budget")
            if cap:
                budget = (f"{admitted or 0}/{cap} "
                          f"({100.0 * (admitted or 0) / cap:.0f}%)")
            elif admitted is not None:
                budget = f"{admitted} (unbounded)"
            else:
                budget = "-"
            print(f"{name:<14} {blk.get('availability', '-'):<7} "
                  f"{verdict:<8} {blk.get('slo_breaches', 0):<9} "
                  f"{lat} {budget}", file=out)
            if verdict == "fail":
                rc = 1
                print(f"TENANT BREACH: {name} failed its per-tenant "
                      "SLO windows", file=out)

    # ---- verdict ----------------------------------------------------
    slo = (serve_summary or {}).get("slo")
    if isinstance(slo, dict):
        n_b = slo.get("breaches", 0)
        verdict = slo.get("verdict", "fail" if n_b else "pass")
        line = (f"verdict: {verdict.upper()} ({n_b} breach(es) in "
                f"{slo.get('windows', 0)} window(s)")
        if slo.get("worst_window") is not None:
            line += (f", worst burn {slo.get('worst_burn', 0.0):.3g} "
                     f"@ window {slo['worst_window']}")
        print(line + ")", file=out)
        if verdict != "pass":
            rc = 1
    elif fleet_summary is not None \
            and "slo_verdict" in fleet_summary:
        verdict = fleet_summary["slo_verdict"]
        line = (f"verdict: {verdict.upper()} "
                f"({fleet_summary.get('slo_breaches', 0)} breach(es) "
                f"in {fleet_summary.get('slo_windows', 0)} window(s)")
        if "slo_worst_window" in fleet_summary:
            line += (f", worst burn "
                     f"{fleet_summary.get('slo_worst_burn', 0.0):.3g} "
                     f"@ window {fleet_summary['slo_worst_window']}")
        print(line + ")", file=out)
        if verdict != "pass":
            rc = 1
    else:
        # No summary at all: a killed run.  The window data above is
        # the whole story — say so, and fail if it ended badly.
        print("verdict: NO SUMMARY (stream truncated? judged on "
              "window records alone)", file=out)
        if windows and windows[-1]["burn_rate"] > 1.0:
            rc = 1
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render an --slo-armed stream: window timeline, "
                    "burn-rate trajectory, breaches, fleet rollups")
    ap.add_argument("path", help="a serve.py or fleet.py --metrics-jsonl "
                                 "stream recorded with --slo")
    args = ap.parse_args(argv)
    return report(args.path)


if __name__ == "__main__":
    sys.exit(main())
