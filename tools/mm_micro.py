#!/usr/bin/env python
"""Gate test for the fused-bottleneck plan: can a Pallas matmul with
BN-apply prologue + stats epilogue stream the 1x1-conv shapes at HBM speed?

Shapes (bs256, 56^2): A=(802816,256)x(256,64)  B=(802816,64)x(64,256)
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import jax.experimental.pallas as pl
from jax.experimental.pallas import tpu as pltpu


def loop_time(fn, init, iters=30):
    @jax.jit
    def run(carry):
        return jax.lax.fori_loop(0, iters, lambda i, c: fn(c), carry)
    out = run(init)
    float(jax.tree_util.tree_leaves(out)[-1].ravel()[0])
    t0 = time.perf_counter()
    out = run(init)
    float(jax.tree_util.tree_leaves(out)[-1].ravel()[0])
    return (time.perf_counter() - t0) / iters


def make_mm(M, K, N, blk_m, prologue, epilogue):
    def kernel(*refs):
        if prologue:
            x_ref, m_ref, i_ref, g_ref, b_ref, w_ref = refs[:6]
            orefs = refs[6:]
        else:
            x_ref, w_ref = refs[:2]
            orefs = refs[2:]
        x = x_ref[...]
        if prologue:
            xf = x.astype(jnp.float32)
            xa = (xf - m_ref[...]) * i_ref[...] * g_ref[...] + b_ref[...]
            x = jnp.maximum(xa, 0.0).astype(jnp.bfloat16)
        y = jax.lax.dot_general(x, w_ref[...], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        yb = y.astype(jnp.bfloat16)
        orefs[0][...] = yb
        if epilogue:
            s_ref, ss_ref = orefs[1], orefs[2]

            @pl.when(pl.program_id(0) == 0)
            def _():
                s_ref[...] = jnp.zeros_like(s_ref)
                ss_ref[...] = jnp.zeros_like(ss_ref)
            s_ref[...] += jnp.sum(y, axis=0)
            ss_ref[...] += jnp.sum(y * y, axis=0)

    grid = (M // blk_m,)
    in_specs = [pl.BlockSpec((blk_m, K), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)]
    if prologue:
        in_specs += [pl.BlockSpec((K,), lambda i: (0,),
                                  memory_space=pltpu.VMEM)] * 4
    in_specs += [pl.BlockSpec((K, N), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)]
    out_specs = [pl.BlockSpec((blk_m, N), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((M, N), jnp.bfloat16)]
    if epilogue:
        out_specs += [pl.BlockSpec((N,), lambda i: (0,),
                                   memory_space=pltpu.VMEM)] * 2
        out_shape += [jax.ShapeDtypeStruct((N,), jnp.float32)] * 2

    def f(x, w, params=None):
        args = [x] + (list(params) if prologue else []) + [w]
        return pl.pallas_call(kernel, grid=grid, in_specs=in_specs,
                              out_specs=out_specs, out_shape=out_shape)(*args)
    return f


def bench_shape(M, K, N, blk_m=1024):
    x = jax.random.normal(jax.random.PRNGKey(0), (M, K), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (K, N), jnp.bfloat16) * 0.05
    params = [jnp.zeros((K,), jnp.float32), jnp.ones((K,), jnp.float32),
              jnp.ones((K,), jnp.float32), jnp.zeros((K,), jnp.float32)]
    bytes_min = (M * K + M * N) * 2
    flops = 2 * M * K * N

    def xla_mm(c):
        xx, ww, acc = c
        y = jnp.dot(xx, ww, preferred_element_type=jnp.float32).astype(jnp.bfloat16)
        return xx, ww, acc + y[0, 0].astype(jnp.float32)

    t = loop_time(xla_mm, (x, w, jnp.zeros((), jnp.float32)))
    print(f"  xla dot:            {t*1e3:7.3f} ms  {bytes_min/t/1e9:6.0f} GB/s  {flops/t/1e12:5.1f} TF/s")

    mm = make_mm(M, K, N, blk_m, False, False)
    def pl_plain(c):
        xx, ww, acc = c
        y, = mm(xx, ww)
        return xx, ww, acc + y[0, 0].astype(jnp.float32)
    t = loop_time(pl_plain, (x, w, jnp.zeros((), jnp.float32)))
    print(f"  pl  mm:             {t*1e3:7.3f} ms  {bytes_min/t/1e9:6.0f} GB/s")

    mmf = make_mm(M, K, N, blk_m, True, True)
    def pl_fused(c):
        xx, ww, acc = c
        y, s, ss = mmf(xx, ww, params)
        return xx, ww, acc + s[0] + ss[0] + y[0, 0].astype(jnp.float32)
    t = loop_time(pl_fused, (x, w, jnp.zeros((), jnp.float32)))
    print(f"  pl  mm+prol+stats:  {t*1e3:7.3f} ms  {bytes_min/t/1e9:6.0f} GB/s")

    # correctness
    y_ref = jnp.dot(jnp.maximum(x.astype(jnp.float32), 0.0).astype(jnp.bfloat16),
                    w, preferred_element_type=jnp.float32)
    y_pl, s, ss = mmf(x, w, params)
    err = float(jnp.max(jnp.abs(y_ref.astype(jnp.float32) - y_pl.astype(jnp.float32))))
    serr = float(jnp.max(jnp.abs(jnp.sum(y_ref, 0) - s)))
    print(f"  maxerr y {err:.3e}  s {serr:.3e}")


def main():
    for (M, K, N) in [(802816, 256, 64), (802816, 64, 256),
                      (200704, 512, 128), (802816, 256, 256)]:
        print(f"M={M} K={K} N={N}")
        bench_shape(M, K, N)


if __name__ == "__main__":
    main()
