#!/usr/bin/env python
"""Microbenchmark per-channel reductions on the real chip: XLA vs Pallas.

The C2 trace shows BN stat/backward reduce fusions running at ~130GB/s
effective — 16% of v5e HBM peak.  This probe measures, for a
bf16[256,56,56,C] activation:

  1. xla_sum:    jnp.sum(x, (0,1,2)) in fp32
  2. xla_bnstat: centered (Σ(x-c), Σ(x-c)²) pair (our BN fwd stats)
  3. xla_bnbwd:  (Σdy, Σdy·x̂) pair (BN bwd sums; x̂ recomputed)
  4. pl_bnstat:  Pallas one-pass (Σ, Σ²) kernel
  5. pl_bnbwd:   Pallas one-pass (Σdy, Σdy·x̂) kernel

Prints effective GB/s (bytes read / time) for each.
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timeit(fn, *args, iters=20):
    out = fn(*args)
    jax.tree_util.tree_map(lambda a: a.block_until_ready(), out)
    float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    # two-point chain through the tunnel
    def chain(n):
        t0 = time.perf_counter()
        r = None
        for _ in range(n):
            r = fn(*args)
        float(jax.tree_util.tree_leaves(r)[0].ravel()[0])
        return time.perf_counter() - t0
    t1 = chain(max(iters // 5, 1))
    t2 = chain(iters)
    return (t2 - t1) / (iters - max(iters // 5, 1))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--hw", type=int, default=56)
    ap.add_argument("--c", type=int, default=256)
    args = ap.parse_args()

    N, H, W, C = args.n, args.hw, args.hw, args.c
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (N, H, W, C), jnp.bfloat16)
    dy = jax.random.normal(jax.random.PRNGKey(1), (N, H, W, C), jnp.bfloat16)
    nbytes = x.size * 2
    c0 = jnp.zeros((C,), jnp.float32)
    mean = jnp.zeros((C,), jnp.float32)
    inv = jnp.ones((C,), jnp.float32)

    @jax.jit
    def xla_sum(x):
        return jnp.sum(x.astype(jnp.float32), axis=(0, 1, 2))

    @jax.jit
    def xla_bnstat(x, c):
        xc = x.astype(jnp.float32) - c
        return jnp.sum(xc, (0, 1, 2)), jnp.sum(xc * xc, (0, 1, 2))

    @jax.jit
    def xla_bnbwd(x, dy, mean, inv):
        xf = x.astype(jnp.float32)
        dyf = dy.astype(jnp.float32)
        xhat = (xf - mean) * inv
        return jnp.sum(dyf, (0, 1, 2)), jnp.sum(dyf * xhat, (0, 1, 2))

    t = timeit(xla_sum, x)
    print(f"xla_sum:     {t*1e3:7.3f} ms  {nbytes/t/1e9:7.1f} GB/s")
    t = timeit(xla_bnstat, x, c0)
    print(f"xla_bnstat:  {t*1e3:7.3f} ms  {nbytes/t/1e9:7.1f} GB/s")
    t = timeit(xla_bnbwd, x, dy, mean, inv)
    print(f"xla_bnbwd:   {t*1e3:7.3f} ms  {2*nbytes/t/1e9:7.1f} GB/s")

    # ---- Pallas kernels ----
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BN_BLOCK = 8  # rows of (H*W) per grid step? use batch blocking

    x3 = x.reshape(N * H * W, C)
    dy3 = dy.reshape(N * H * W, C)
    rows = x3.shape[0]
    blk = 2048

    def stat_kernel(x_ref, s_ref, ss_ref):
        i = pl.program_id(0)
        xf = x_ref[...].astype(jnp.float32)
        s = jnp.sum(xf, axis=0)
        ss = jnp.sum(xf * xf, axis=0)

        @pl.when(i == 0)
        def _():
            s_ref[...] = jnp.zeros_like(s_ref)
            ss_ref[...] = jnp.zeros_like(ss_ref)
        s_ref[...] += s
        ss_ref[...] += ss

    @jax.jit
    def pl_bnstat(x3):
        grid = rows // blk
        return pl.pallas_call(
            stat_kernel,
            grid=(grid,),
            in_specs=[pl.BlockSpec((blk, C), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=[pl.BlockSpec((C,), lambda i: (0,),
                                    memory_space=pltpu.VMEM),
                       pl.BlockSpec((C,), lambda i: (0,),
                                    memory_space=pltpu.VMEM)],
            out_shape=[jax.ShapeDtypeStruct((C,), jnp.float32),
                       jax.ShapeDtypeStruct((C,), jnp.float32)],
        )(x3)

    def bwd_kernel(x_ref, dy_ref, m_ref, i_ref, s_ref, sx_ref):
        i = pl.program_id(0)
        xf = x_ref[...].astype(jnp.float32)
        dyf = dy_ref[...].astype(jnp.float32)
        xhat = (xf - m_ref[...]) * i_ref[...]
        s = jnp.sum(dyf, axis=0)
        sx = jnp.sum(dyf * xhat, axis=0)

        @pl.when(i == 0)
        def _():
            s_ref[...] = jnp.zeros_like(s_ref)
            sx_ref[...] = jnp.zeros_like(sx_ref)
        s_ref[...] += s
        sx_ref[...] += sx

    @jax.jit
    def pl_bnbwd(x3, dy3, mean, inv):
        grid = rows // blk
        return pl.pallas_call(
            bwd_kernel,
            grid=(grid,),
            in_specs=[pl.BlockSpec((blk, C), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((blk, C), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((C,), lambda i: (0,),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((C,), lambda i: (0,),
                                   memory_space=pltpu.VMEM)],
            out_specs=[pl.BlockSpec((C,), lambda i: (0,),
                                    memory_space=pltpu.VMEM),
                       pl.BlockSpec((C,), lambda i: (0,),
                                    memory_space=pltpu.VMEM)],
            out_shape=[jax.ShapeDtypeStruct((C,), jnp.float32),
                       jax.ShapeDtypeStruct((C,), jnp.float32)],
        )(x3, dy3, mean, inv)

    t = timeit(pl_bnstat, x3)
    s_ref = xla_bnstat(x, c0)
    s_pl = pl_bnstat(x3)
    err = float(jnp.max(jnp.abs(s_ref[0] - s_pl[0])))
    print(f"pl_bnstat:   {t*1e3:7.3f} ms  {nbytes/t/1e9:7.1f} GB/s  (maxerr {err:.2e})")
    t = timeit(pl_bnbwd, x3, dy3, mean, inv)
    b_ref = xla_bnbwd(x, dy, mean, inv)
    b_pl = pl_bnbwd(x3, dy3, mean, inv)
    err = float(jnp.max(jnp.abs(b_ref[1] - b_pl[1])))
    print(f"pl_bnbwd:    {t*1e3:7.3f} ms  {2*nbytes/t/1e9:7.1f} GB/s  (maxerr {err:.2e})")


if __name__ == "__main__":
    main()
