#!/usr/bin/env python
"""Measure C2 step throughput under one configuration variant (one process
per variant so XLA flags and compile caches don't cross-contaminate).

Usage: python tools/perf_variants.py <variant> [--batch-size N]
Variants: base, bs512, bnbf16, s2d, s2d512, vmem64, vmem128
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "base"

if VARIANT in ("vmem64", "vmem128"):
    kib = {"vmem64": 65536, "vmem128": 131072}[VARIANT]
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               f" --xla_tpu_scoped_vmem_limit_kib={kib}")

import jax
import jax.numpy as jnp

from apex_example_tpu import amp
from apex_example_tpu.data import image_batch
from apex_example_tpu.engine import create_train_state, make_train_step
from apex_example_tpu.models import resnet50
from apex_example_tpu.optim import FusedSGD


def main():
    bs = 256
    if "512" in VARIANT:
        bs = 512
    if "1024" in VARIANT:
        bs = 1024
    for a in sys.argv[2:]:
        if a.startswith("--batch-size="):
            bs = int(a.split("=")[1])

    policy, scaler = amp.initialize("O2")
    kw = dict(num_classes=1000, dtype=policy.compute_dtype,
              param_dtype=policy.param_dtype, bn_dtype=policy.bn_dtype)
    if VARIANT == "bnbf16":
        kw["bn_dtype"] = jnp.bfloat16
    if VARIANT.startswith("s2d"):
        kw["stem_space_to_depth"] = True
    model = resnet50(**kw)
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)

    batch = image_batch(jnp.asarray(0), batch_size=bs, image_size=224,
                        channels=3, num_classes=1000, seed=0)
    batch = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, jax.devices()[0]), batch)
    state = create_train_state(jax.random.PRNGKey(0), model, opt,
                               batch[0][:1], policy, scaler)
    step = jax.jit(make_train_step(model, opt, policy), donate_argnums=(0,))

    for _ in range(5):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    def run_chain(n, state):
        t0 = time.perf_counter()
        for _ in range(n):
            state, metrics = step(state, batch)
        float(metrics["loss"])
        return time.perf_counter() - t0, state

    steps = 30
    n1 = steps // 5
    t1, state = run_chain(n1, state)
    t2, state = run_chain(steps, state)
    rate = (steps - n1) * bs / max(t2 - t1, 1e-9)
    ms = (t2 - t1) / (steps - n1) * 1e3
    print(f"{VARIANT:10s} bs={bs:5d}  {ms:7.2f} ms/step  {rate:7.1f} img/s")


if __name__ == "__main__":
    main()
