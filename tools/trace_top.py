#!/usr/bin/env python
"""Summarize a jax.profiler Chrome trace: top device ops by total duration.

Usage: python tools/trace_top.py /tmp/xprof_c2 [--top 40]

``find_trace`` / ``load_chrome_trace`` / ``device_pids`` are the shared
xprof-trace parser: ``tools/trace_export.py`` reuses them to merge a
device trace onto a host trace-event timeline (no jax import in either
tool — graftlint's jax-free rule covers both).
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re


def find_trace(logdir: str) -> str:
    """The newest ``*.trace.json.gz`` under a profiler logdir (what
    ``jax.profiler.start_trace`` leaves behind)."""
    traces = glob.glob(os.path.join(logdir, "**", "*.trace.json.gz"),
                       recursive=True)
    assert traces, f"no trace.json.gz under {logdir}"
    return max(traces, key=os.path.getmtime)


def resolve_trace(path: str) -> str:
    """A trace FILE for ``path``: logdirs resolve to their newest
    trace, files pass through — the one place this decision lives."""
    return find_trace(path) if os.path.isdir(path) else path


def load_chrome_trace(path: str):
    """Parse a Chrome trace file (gzipped or plain JSON) into its
    ``traceEvents`` list.  ``path`` may also be a profiler logdir."""
    path = resolve_trace(path)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        j = json.load(f)
    return j["traceEvents"] if isinstance(j, dict) else j


def device_pids(events):
    """(pid -> process name, device pid set): which process rows are
    TPU/device rows, by the trace's own name metadata."""
    pid_name = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_name[e["pid"]] = e["args"].get("name", "")
    dev = {pid for pid, n in pid_name.items()
           if re.search(r"TPU|/device", n, re.I)}
    return pid_name, dev


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("logdir")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--raw", action="store_true",
                    help="don't merge fusion instances (keep full names)")
    args = ap.parse_args()

    path = resolve_trace(args.logdir)
    events = load_chrome_trace(path)
    pid_name, dev_pids = device_pids(events)

    tot = collections.Counter()
    cnt = collections.Counter()
    total_time = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "")
        dur = e.get("dur", 0)  # microseconds
        key = name if args.raw else re.sub(r"\.\d+$", "", name)
        tot[key] += dur
        cnt[key] += 1
        total_time += dur
    print(f"trace: {path}")
    print(f"device pids: { {p: pid_name[p] for p in dev_pids} }")
    print(f"total device op time: {total_time/1e3:.2f} ms")
    print(f"{'us_total':>10} {'n':>5} {'%':>6}  name")
    for name, us in tot.most_common(args.top):
        print(f"{us:10.0f} {cnt[name]:5d} {us/total_time:6.1%}  {name[:110]}")


if __name__ == "__main__":
    main()
