#!/usr/bin/env python
"""Summarize a jax.profiler Chrome trace: top device ops by total duration.

Usage: python tools/trace_top.py /tmp/xprof_c2 [--top 40]
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("logdir")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--raw", action="store_true",
                    help="don't merge fusion instances (keep full names)")
    args = ap.parse_args()

    traces = glob.glob(os.path.join(args.logdir, "**", "*.trace.json.gz"),
                       recursive=True)
    assert traces, f"no trace.json.gz under {args.logdir}"
    path = max(traces, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        j = json.load(f)
    events = j["traceEvents"]

    # Identify device (TPU) process ids by name metadata.
    pid_name = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_name[e["pid"]] = e["args"].get("name", "")
    dev_pids = {pid for pid, n in pid_name.items()
                if re.search(r"TPU|/device", n, re.I)}

    tot = collections.Counter()
    cnt = collections.Counter()
    total_time = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "")
        dur = e.get("dur", 0)  # microseconds
        key = name if args.raw else re.sub(r"\.\d+$", "", name)
        tot[key] += dur
        cnt[key] += 1
        total_time += dur
    print(f"trace: {path}")
    print(f"device pids: { {p: pid_name[p] for p in dev_pids} }")
    print(f"total device op time: {total_time/1e3:.2f} ms")
    print(f"{'us_total':>10} {'n':>5} {'%':>6}  name")
    for name, us in tot.most_common(args.top):
        print(f"{us:10.0f} {cnt[name]:5d} {us/total_time:6.1%}  {name[:110]}")


if __name__ == "__main__":
    main()
