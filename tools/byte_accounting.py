#!/usr/bin/env python
"""Per-step HBM byte accounting for the C2 headline (ResNet-50 / 224 / amp-O2
bf16, batch 256) — the decision-grade form of PERF.md's "rig-bound at ~2555
img/s" claim (VERDICT r2 item 2).

Pure arithmetic (no device needed): enumerates every conv+BN+ReLU chain in
torchvision-parity ResNet-50, prices HBM traffic under explicit touch-count
models, and compares each against the MEASURED phase times (tools/
perf_probe.py: fwd 30.2 ms, bwd 69.8 ms, opt 0.75 ms at 99 ms/step) through
the measured bandwidth (tools/bw_micro.py: 375 GB/s on this tunnel chip).

Touch models (activation bf16 = 2 B; i/o = a chain's input/output bytes):

  FORWARD floor — conv+BN(stats-in-epilogue)+ReLU as ONE fused pass:
      read x_in (i) + write act_out (o); the residual skip adds one extra
      read of each block input at the add.  The saved set for backward is
      act_out itself (already materialized — saving it is free).

  BACKWARD floor — BN/ReLU-bwd folded into the conv grads:
      dy read twice (wgrad + dx-conv are separate loop nests: 2o),
      saved act_out read once for the BN backward (o),
      saved act_in read once for wgrad (i), dx written once (i)
      => 3o + 2i per chain (+ skip-grad add traffic per block).

  BN 2-pass — the form XLA's multi-output reduce fusions actually take
      (the 52%-of-device-time bucket): the stat sums (Σdy, Σdy·x̂) run as a
      SEPARATE pass over (dy, act_out) before the dx pass => floor + 2o.

  remat='conv' (models/resnet.py remat option) — saved set pinned to conv
      outputs y_conv: fwd additionally writes y_conv (+o), backward reads
      y_conv instead of act_out (same bytes) and recomputes BN/ReLU in
      registers/VMEM.  Net: helps only if XLA's default saves MORE than one
      tensor per chain (e.g. an explicit x̂) — measurement arbitrates.

Output: Σi/Σo totals, per-model GB + implied phase ms at the measured
bandwidth vs the measured phase times, and projected img/s at --spec-bw.
Run `python tools/byte_accounting.py` (no TPU touched).
"""

from __future__ import annotations

import argparse

BF16 = 2
FP32 = 4


def resnet50_chains(batch: int, image: int = 224):
    """(name, i_bytes, o_bytes, w_params, is_block_end, is_skip) per conv."""
    raw = [("stem", image, 3, image // 2, 64, 7, False, False)]
    stages = [(56, 64, 64, 3), (28, 256, 128, 4), (14, 512, 256, 6),
              (7, 1024, 512, 3)]
    for si, (h, cin_stage, f, blocks) in enumerate(stages):
        cin = cin_stage
        for b in range(blocks):
            hin = h * 2 if (si > 0 and b == 0) else h
            pre = f"s{si}b{b}"
            raw.append((f"{pre}.conv1", hin, cin, hin, f, 1, False, False))
            raw.append((f"{pre}.conv2", hin, f, h, f, 3, False, False))
            raw.append((f"{pre}.conv3", h, f, h, 4 * f, 1, True, False))
            if b == 0:
                raw.append((f"{pre}.down", hin, cin, h, 4 * f, 1,
                            False, True))
            cin = 4 * f
    out = []
    for name, hin, cin, hout, cout, k, end, skip in raw:
        out.append(dict(
            name=name, end=end, skip=skip,
            i=batch * hin * hin * cin * BF16,
            o=batch * hout * hout * cout * BF16,
            w=k * k * cin * cout))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--fwd-ms", type=float, default=30.2)
    ap.add_argument("--bwd-ms", type=float, default=69.8)
    ap.add_argument("--opt-ms", type=float, default=0.75)
    ap.add_argument("--measured-bw", type=float, default=375.0,
                    help="GB/s this rig delivers (tools/bw_micro.py)")
    ap.add_argument("--spec-bw", type=float, default=819.0)
    args = ap.parse_args()
    gbs = args.measured_bw

    ch = resnet50_chains(args.batch)
    Si = sum(c["i"] for c in ch)
    So = sum(c["o"] for c in ch)
    # one extra read of each block input at the residual add (16 blocks),
    # one extra read of each block-output grad in backward (fan-out 2)
    skip_fwd = sum(c["i"] for c in ch if c["name"].endswith("conv1"))
    skip_bwd = sum(c["o"] for c in ch if c["end"])
    params = sum(c["w"] for c in ch) + 2048 * 1000
    g = 1e9
    ms = lambda b: b / g / gbs * 1e3

    fwd_floor = Si + So + skip_fwd
    bwd_floor = 3 * So + 2 * Si + skip_bwd
    bwd_2pass = bwd_floor + 2 * So
    opt_bytes = params * (3 * FP32 * 2 + 2 * BF16)

    print(f"ResNet-50 batch {args.batch}: {len(ch)} conv chains, "
          f"{params/1e6:.1f}M params;  Σi={Si/g:.2f} GB  Σo={So/g:.2f} GB")
    print(f"measured: fwd {args.fwd_ms} ms, bwd {args.bwd_ms} ms, "
          f"opt {args.opt_ms} ms @ {gbs:.0f} GB/s measured bw\n")
    rows = [
        ("fwd floor (fused conv+BN+ReLU)", fwd_floor, args.fwd_ms),
        ("bwd floor (1-pass BN bwd)", bwd_floor, args.bwd_ms),
        ("bwd w/ 2-pass BN stat sums", bwd_2pass, args.bwd_ms),
        ("optimizer (p/m/v fp32 rw + bf16 copies)", opt_bytes, args.opt_ms),
    ]
    for name, b, meas in rows:
        print(f"  {name:<42} {b/g:6.2f} GB -> {ms(b):6.1f} ms  "
              f"(measured {meas:5.1f} ms => implied "
              f"{b/g/meas*1e3:5.0f} GB/s effective)")

    step_floor = fwd_floor + bwd_floor + opt_bytes
    step_2pass = fwd_floor + bwd_2pass + opt_bytes
    meas_total = args.fwd_ms + args.bwd_ms + args.opt_ms
    print(f"\n  step floor  {step_floor/g:6.2f} GB -> {ms(step_floor):6.1f} "
          f"ms; step 2-pass {step_2pass/g:6.2f} GB -> {ms(step_2pass):6.1f} "
          f"ms; measured {meas_total:.1f} ms")
    unexplained = meas_total - ms(step_floor)
    print(f"  measured minus floor: {unexplained:+.1f} ms "
          f"({unexplained/meas_total:+.1%} of step) — the 2-pass BN "
          f"backward models {ms(step_2pass)-ms(step_floor):.1f} ms of it")
    for name, b in [("floor", step_floor), ("2-pass", step_2pass)]:
        t_spec = b / g / args.spec_bw * 1e3
        print(f"  @spec {args.spec_bw:.0f} GB/s, {name}: {t_spec:5.1f} ms "
              f"-> {args.batch/t_spec*1e3:5.0f} img/s")
    # compute-bound floor for context: ~12.3 GFLOP/img fwd+bwd, bf16 MXU
    flops = 12.3e9 * args.batch
    for peak in (197e12,):
        print(f"  MXU floor @ {peak/1e12:.0f} TFLOP/s bf16: "
              f"{flops/peak*1e3:5.1f} ms -> {args.batch/(flops/peak)/1e0:,.0f}"
              f" img/s (not the binding constraint)")


if __name__ == "__main__":
    main()
