#!/usr/bin/env python
"""Calibrate achievable HBM bandwidth on this chip.

- xla elementwise scale (read+write) on 3.3GB
- xla sum (read) on 3.3GB
- pallas stream-sum, one launch over 3.3GB, parallel vs arbitrary semantics
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import jax.experimental.pallas as pl
from jax.experimental.pallas import tpu as pltpu

GB = 1e9


def timed(label, jfn, args, bytes_moved, iters=10):
    out = jfn(*args)
    float(jax.tree_util.tree_leaves(out)[-1].ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jfn(*args)
    float(jax.tree_util.tree_leaves(out)[-1].ravel()[0])
    dt = (time.perf_counter() - t0) / iters
    print(f"{label:28s} {dt*1e3:8.3f} ms  {bytes_moved/dt/GB:6.0f} GB/s")


def main():
    M, K = 8 * 802816, 256   # 3.29 GB bf16
    x = jax.random.normal(jax.random.PRNGKey(0), (M, K), jnp.bfloat16)
    nbytes = M * K * 2

    @jax.jit
    def scale(x):
        return x * jnp.bfloat16(1.001)

    timed("xla elementwise r+w", scale, (x,), 2 * nbytes)

    @jax.jit
    def xsum(x):
        return jnp.sum(x.astype(jnp.float32), axis=0)

    timed("xla colsum read", xsum, (x,), nbytes)

    for sem in ("parallel", "arbitrary"):
        blk = 4096

        def kernel(x_ref, s_ref):
            @pl.when(pl.program_id(0) == 0)
            def _():
                s_ref[...] = jnp.zeros_like(s_ref)
            s_ref[...] += jnp.sum(x_ref[...].astype(jnp.float32), axis=0)

        f = pl.pallas_call(
            kernel, grid=(M // blk,),
            in_specs=[pl.BlockSpec((blk, K), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((K,), lambda i: (0,),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((K,), jnp.float32),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=(sem,)))
        timed(f"pl stream sum ({sem})", jax.jit(f), (x,), nbytes)

    # bigger block
    for blk in (8192, 16384):
        def kernel(x_ref, s_ref):
            @pl.when(pl.program_id(0) == 0)
            def _():
                s_ref[...] = jnp.zeros_like(s_ref)
            s_ref[...] += jnp.sum(x_ref[...].astype(jnp.float32), axis=0)

        f = pl.pallas_call(
            kernel, grid=(M // blk,),
            in_specs=[pl.BlockSpec((blk, K), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((K,), lambda i: (0,),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((K,), jnp.float32),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)))
        timed(f"pl stream sum blk={blk}", jax.jit(f), (x,), nbytes)

    # bf16 accumulate (no convert): how much is the fp32 convert costing?
    blk = 8192

    def kernel_bf(x_ref, s_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            s_ref[...] = jnp.zeros_like(s_ref)
        s_ref[...] += jnp.sum(x_ref[...], axis=0, dtype=jnp.float32)

    f = pl.pallas_call(
        kernel_bf, grid=(M // blk,),
        in_specs=[pl.BlockSpec((blk, K), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((K,), lambda i: (0,),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((K,), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)))
    timed("pl sum dtype=f32 arg", jax.jit(f), (x,), nbytes)


if __name__ == "__main__":
    main()
