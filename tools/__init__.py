# Package marker so `python -m tools.graftlint` resolves.  Deliberately
# empty: tools/*.py scripts are standalone CLIs (many are jax-free thin
# clients loaded by file path) and must not gain import-time behavior.
