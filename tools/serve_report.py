#!/usr/bin/env python
"""Summarize a serving JSONL stream (serve.py --metrics-jsonl): request
and token totals, throughput, TTFT/TPOT/queue-wait percentiles, finish
reasons, slot occupancy — recomputed from the per-request
``request_complete`` records, with the stream's own ``serve_summary``
shown for cross-checking.

Schema v5 adds the resilience stratum: per-status accounting (ok /
timeout / shed / cancelled / failed / drained / rejected from
``request_failed`` / ``shed`` / ``serve_drain`` records), an
availability line, and drain rendering — a drained stream shows what
the server finished, evicted and handed back before exiting 75.

Schema v7 adds the block-paged KV line: block utilization (mean/max
held blocks vs the arena), block-accurate ``kv_waste_pct``, the
prefix-sharing hit rate and copy-on-write copy count.

Schema v12 adds the HANDOFF line (disaggregated serving,
serve/disagg.py): per-stream KV-transfer accounting — out/in counts,
blocks and bytes moved, and the decode side's transit-latency
percentiles (``kv_handoff.handoff_ms``: out-stamp -> admission, both
wall clocks, so cross-host runs inherit NTP skew like every
``time`` field).  A handed-off request continues on the decode role,
so like "drained" it sits outside this server's availability
denominator.

Schema v13 adds the REDELIVERY line (the leased-spool crash-safety
protocol, ISSUE 15): redelivered admissions — a reclaimed or adopted
lease finishing work its first consumer dropped — duplicates acked
without a second scatter (the ack-crash window), and corrupt payloads
quarantined at ``*.bad`` (each listed with its spool file and error).

Schema v9 adds the per-request CRITICAL-PATH table: each completed
request's e2e latency decomposed into queue wait / prefill / decode /
stall (the residual: eviction waits, harvest overhead), the mean share
each component takes of e2e, and the worst-p99 culprit — the component
that dominates the p99-latency request.  Derived from the
``request_complete`` timestamp trail, so it needs no ``--trace``; a
traced stream additionally surfaces the loadgen->queue handoff span
(``Request.t_submit``) as its own component.

Schema v15 adds the OVERHEAD lines (hot-path attribution, ISSUE 17):
on a ``--tick-profile`` stream, the host-overhead fraction and the
per-phase p50/p99 tick decomposition (admit / dispatch_enqueue /
device_wait / harvest / spool_io / telemetry) from the stream's
``overhead_summary``, plus the idle-spin accounting the summary now
carries.  Pre-v15 streams degrade gracefully (no line).

Schema v16 adds the SPEC line (speculative decoding, ISSUE 18): on a
``--speculate`` stream, the acceptance rate, drafted vs accepted vs
sampled token totals, and tokens/tick against the 1.0
one-token-per-tick baseline.  Pre-v16 (and unarmed) streams carry no
``speculate_k`` and degrade silently, exactly like OVERHEAD.

Schema v17 adds the TENANT table (multi-tenant scheduling, ISSUE 19):
on a ``--tenants`` stream, one row per scheduling lane — request
count, availability, TTFT/TPOT p50/p99 recomputed from that lane's
``request_complete`` records, and budget utilization (admitted tokens
over the lane's token budget, from the summary's ``tenants`` block).
Pre-v17 (and unarmed) streams carry no ``tenant`` fields and degrade
silently.

Schema v18 adds the MIGRATION line (live KV migration, ISSUE 20): on
a migration-armed stream, the mid-flight transfer ledger — out/in
counts, blocks and bytes moved, transit percentiles
(``kv_migration.migration_ms``: out-stamp -> admission), deferred
admissions, plus the same redelivered/duplicate/quarantine
crash-safety accounting HANDOFF gets; a migrating ``serve_drain``
additionally shows its ``migrated`` count.  A migrated-out request
resumes on another replica, so like "handoff" it sits outside this
server's availability denominator.  Pre-v18 (and unarmed) streams
carry no ``kv_migration`` records and degrade silently.

Thin client of the obs schema (obs/schema.py):

    python tools/serve_report.py serve.jsonl

No jax import; works on any host with the file (graftlint's static
jax-free rule proves the whole import closure stays jax-free).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Same no-jax file-path load as tools/telemetry_report.py.
from metrics_lint import pct as _pct  # noqa: E402  (sibling import)
from metrics_lint import validate_stream  # noqa: E402


def _dist(out, name, vals_ms):
    s = sorted(vals_ms)
    print(f"{name:14s} p50 {_pct(s, 50):8.1f}  p95 {_pct(s, 95):8.1f}  "
          f"max {s[-1]:8.1f}  (ms)", file=out)


def _trace_handoffs(records):
    """request_id -> loadgen->queue handoff ms, from a traced stream's
    "submit" spans (children of the per-request root spans)."""
    root_req = {}                      # span_id -> request_id
    for r in records:
        if r.get("record") == "trace_event" and r.get("ph") == "X" \
                and r.get("name") == "request" and "span_id" in r:
            rid = (r.get("args") or {}).get("request_id")
            if rid:
                root_req[r["span_id"]] = rid
    out = {}
    for r in records:
        if r.get("record") == "trace_event" and r.get("ph") == "X" \
                and r.get("name") == "submit" \
                and r.get("parent_id") in root_req:
            out[root_req[r["parent_id"]]] = r.get("dur", 0.0) * 1e3
    return out


def critical_path(records):
    """Per-request latency decomposition for every completed request:
    ``queue_ms`` (arrival -> admission), ``prefill_ms`` (admission ->
    first token), ``decode_ms`` (first token -> finish, from TPOT x
    (n-1)) and ``stall_ms`` — the residual of e2e the other three
    don't explain.  The components sum to ``e2e_ms`` exactly (modulo
    the records' ms rounding); on a traced stream ``handoff_ms`` rides
    along (informational — submission precedes arrival, so it is NOT
    part of the e2e the server owns)."""
    handoffs = _trace_handoffs(records)
    rows = []
    for r in records:
        if r.get("record") != "request_complete":
            continue
        if not all(k in r for k in ("ttft_ms", "tpot_ms", "e2e_ms",
                                    "queue_wait_ms", "output_tokens")):
            continue
        queue = r["queue_wait_ms"]
        prefill = max(r["ttft_ms"] - queue, 0.0)
        decode = r["tpot_ms"] * max(r["output_tokens"] - 1, 0)
        stall = r["e2e_ms"] - queue - prefill - decode
        row = {"request_id": r.get("request_id", "?"),
               "e2e_ms": r["e2e_ms"], "queue_ms": round(queue, 3),
               "prefill_ms": round(prefill, 3),
               "decode_ms": round(decode, 3),
               "stall_ms": round(stall, 3)}
        if r.get("request_id") in handoffs:
            row["handoff_ms"] = round(handoffs[r["request_id"]], 3)
        rows.append(row)
    return rows


_COMPONENTS = ("queue_ms", "prefill_ms", "decode_ms", "stall_ms")


def _print_tenants(out, records, summary):
    """Schema v17 (ISSUE 19): the per-tenant table, only when the run
    was armed with --tenants — per-lane counts/latencies recomputed
    from the tenant-stamped request records, budget utilization from
    the summary's ``tenants`` block.  Unarmed streams carry neither
    and print nothing."""
    blocks = (summary or {}).get("tenants")
    blocks = blocks if isinstance(blocks, dict) else {}
    by = {}
    for r in records:
        t = r.get("tenant")
        if t is None or r.get("record") not in (
                "request_complete", "request_failed", "shed"):
            continue
        d = by.setdefault(t, {"ok": [], "counts": {}})
        status = "ok" if r["record"] == "request_complete" \
            else r.get("status", "shed")
        d["counts"][status] = d["counts"].get(status, 0) + 1
        if r["record"] == "request_complete" \
                and "ttft_ms" in r and "tpot_ms" in r:
            d["ok"].append(r)
    if not by and not blocks:
        return
    names = list(blocks)
    names += [t for t in sorted(by) if t not in names]
    print("TENANT         reqs  avail   ttft p50/p99      "
          "tpot p50/p99      budget", file=out)
    for t in names:
        blk = blocks.get(t) or {}
        d = by.get(t, {"ok": [], "counts": {}})
        counts = d["counts"]
        owned = sum(counts.values())
        avail = f"{counts.get('ok', 0) / owned:.3f}" if owned else "-"
        ttfts = sorted(r["ttft_ms"] for r in d["ok"])
        tpots = sorted(r["tpot_ms"] for r in d["ok"])
        if ttfts:
            lat = (f"{_pct(ttfts, 50):7.1f}/{_pct(ttfts, 99):<9.1f} "
                   f"{_pct(tpots, 50):7.1f}/{_pct(tpots, 99):<9.1f}")
        else:
            lat = f"{'-':>7}/{'-':<9} {'-':>7}/{'-':<9}"
        admitted = blk.get("admitted_tokens")
        cap = blk.get("budget")
        if cap:
            budget = (f"{admitted or 0}/{cap} "
                      f"({100.0 * (admitted or 0) / cap:.0f}%)")
        elif admitted is not None:
            budget = f"{admitted} (unbounded)"
        else:
            budget = "-"
        print(f"{t:<14} {owned:<5} {avail:<7} {lat} {budget}",
              file=out)


def _print_critical_path(out, rows):
    total = sum(r["e2e_ms"] for r in rows)
    if not rows or total <= 0:
        return
    shares = "  ".join(
        f"{c[:-3]} {100.0 * sum(r[c] for r in rows) / total:.1f}%"
        for c in _COMPONENTS)
    print(f"critical path (share of total e2e): {shares}", file=out)
    if any("handoff_ms" in r for r in rows):
        hand = sorted(r["handoff_ms"] for r in rows if "handoff_ms" in r)
        print(f"handoff_ms (loadgen->queue, traced)   p50 "
              f"{_pct(hand, 50):8.1f}  max {hand[-1]:8.1f}  (ms)",
              file=out)
    by_e2e = sorted(rows, key=lambda r: r["e2e_ms"])
    worst = by_e2e[-1]
    p99 = _pct([r["e2e_ms"] for r in by_e2e], 99)
    p99_row = next(r for r in by_e2e if r["e2e_ms"] >= p99)
    for tag, row in (("worst", worst), ("p99", p99_row)):
        culprit = max(_COMPONENTS, key=lambda c: row[c])
        parts = " + ".join(f"{row[c]:.1f} {c[:-3]}" for c in _COMPONENTS)
        print(f"{tag:5s} {row['request_id']}  {row['e2e_ms']:.1f} ms = "
              f"{parts}; culprit {culprit[:-3]} "
              f"({100.0 * row[culprit] / max(row['e2e_ms'], 1e-9):.0f}%)",
              file=out)


def report(path: str, out=sys.stdout) -> int:
    records = []
    with open(path) as fh:
        for n, line in enumerate(fh):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                # Killed runs legitimately truncate the last line.
                print(f"WARNING: line {n + 1}: not JSON, skipped",
                      file=sys.stderr)
    for e in validate_stream(records):
        print(f"WARNING: {e}", file=sys.stderr)

    header = next((r for r in records if r.get("record") == "run_header"),
                  None)
    summary = next((r for r in records
                    if r.get("record") == "serve_summary"), None)
    handoffs = [r for r in records if r.get("record") == "kv_handoff"]
    migrations = [r for r in records
                  if r.get("record") == "kv_migration"]
    reqs = [r for r in records if r.get("record") == "request_complete"
            and all(k in r for k in ("ttft_ms", "tpot_ms",
                                     "output_tokens"))]
    failed = [r for r in records if r.get("record") == "request_failed"]
    shed = [r for r in records if r.get("record") == "shed"]
    drains = [r for r in records if r.get("record") == "serve_drain"]

    if header:
        cfg = header.get("config", {})
        print(f"run {header['run_id']}  platform={header['platform']}  "
              f"arch={header.get('arch', cfg.get('arch', '?'))}  "
              f"slots={cfg.get('slots', '?')}  "
              f"max_len={cfg.get('max_len', '?')}", file=out)
    if not reqs and not failed and not shed and not drains \
            and not handoffs and not migrations:
        print("no request records", file=out)
        return 1

    # Per-status accounting: ok from request_complete, the rest from the
    # failure-path records (drained counts ride serve_drain — a drained
    # request is requeued, not failed, so it has no per-request record).
    statuses = {"ok": len(reqs)}
    for r in failed:
        s = r.get("status", "failed")
        statuses[s] = statuses.get(s, 0) + 1
    if shed:
        statuses["shed"] = len(shed)
    requeued = sum(r.get("requeued", 0) for r in drains)
    if requeued:
        statuses["drained"] = requeued
    handed_off = sum(1 for h in handoffs if h.get("direction") == "out")
    if handed_off:
        statuses["handoff"] = handed_off
    migrated_out = sum(1 for m in migrations
                       if m.get("direction") == "out")
    if migrated_out:
        statuses["migrated"] = migrated_out
    print("status: " + ", ".join(f"{k} x{v}" for k, v in
                                 sorted(statuses.items())), file=out)
    # drained, handed-off AND migrated requests continue on another
    # replica/role — none belongs in this server's availability
    # denominator.
    owned = sum(v for k, v in statuses.items()
                if k not in ("drained", "handoff", "migrated"))
    if owned and len(statuses) > 1:
        print(f"availability {statuses.get('ok', 0) / owned:.3f}  "
              f"(ok / every status the server owned; drained requests "
              f"are requeued elsewhere)", file=out)

    _print_tenants(out, records, summary)

    out_tokens = sum(r["output_tokens"] for r in reqs)
    prompt_tokens = sum(r.get("prompt_tokens", 0) for r in reqs)
    print(f"requests {len(reqs)}  prompt_tokens {prompt_tokens}  "
          f"output_tokens {out_tokens}", file=out)
    if reqs:
        reasons = {}
        for r in reqs:
            reasons[r.get("finish_reason", "?")] = \
                reasons.get(r.get("finish_reason", "?"), 0) + 1
        print("finish reasons: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(reasons.items())), file=out)
        _dist(out, "ttft_ms", [r["ttft_ms"] for r in reqs])
        _dist(out, "tpot_ms", [r["tpot_ms"] for r in reqs])
        waits = [r["queue_wait_ms"] for r in reqs if "queue_wait_ms" in r]
        if waits:
            _dist(out, "queue_wait_ms", waits)
        rates = [r["output_tokens"] / (r["e2e_ms"] / 1e3)
                 for r in reqs if r.get("e2e_ms", 0) > 0]
        if rates:
            s = sorted(rates)
            print(f"tokens_per_sec p50 {_pct(s, 50):6.1f}  max "
                  f"{s[-1]:6.1f}  (per request)", file=out)
        _print_critical_path(out, critical_path(records))
    if handoffs:
        # Schema v12 (disaggregated serving): one line per stream
        # summarizing the KV transfers it took part in.  Transit
        # latency only exists on "in" records (the decode side stamps
        # out-wall -> admission); a pure prefill stream reports count
        # and bytes alone.  v13 adds quarantines (direction
        # "quarantine" — corrupt payloads parked, worker alive) and
        # the REDELIVERY line below.
        n_out = sum(1 for h in handoffs if h.get("direction") == "out")
        n_in = sum(1 for h in handoffs if h.get("direction") == "in"
                   and not h.get("duplicate"))
        moved = sum(h.get("payload_bytes", 0) for h in handoffs
                    if h.get("direction") != "quarantine")
        blocks = sum(h.get("blocks", 0) for h in handoffs)
        line = (f"HANDOFF: {n_out} out / {n_in} in  "
                f"{blocks} block(s), {moved / 1024:.1f} KiB moved")
        lats = sorted(h["handoff_ms"] for h in handoffs
                      if "handoff_ms" in h)
        if lats:
            line += (f"  transit p50 {_pct(lats, 50):.1f}  "
                     f"p99 {_pct(lats, 99):.1f}  max {lats[-1]:.1f} (ms)")
        requeued = sum(h.get("requeued", 0) for h in handoffs)
        if requeued:
            line += f"  requeued {requeued}"
        print(line, file=out)
        # v13 (ISSUE 15): the leased-spool crash-safety accounting —
        # redelivered admissions (a reclaimed/adopted lease finished
        # work its first consumer dropped), duplicates acked without a
        # second scatter (the ack-crash window closing), and
        # quarantined corrupt payloads.
        n_redeliv = sum(1 for h in handoffs
                        if h.get("direction") == "in"
                        and h.get("redelivered")
                        and not h.get("duplicate"))
        n_dup = sum(1 for h in handoffs if h.get("duplicate"))
        n_quar = sum(1 for h in handoffs
                     if h.get("direction") == "quarantine")
        if n_redeliv or n_dup or n_quar:
            print(f"REDELIVERY: {n_redeliv} redelivered admission(s)  "
                  f"{n_dup} duplicate(s) acked without scatter  "
                  f"{n_quar} payload(s) quarantined", file=out)
            for h in handoffs:
                if h.get("direction") == "quarantine":
                    print(f"  quarantined {h.get('request_id', '?')} "
                          f"({h.get('spool_file', '?')}): "
                          f"{h.get('error', '?')}", file=out)
        # The REAL first-token latency of handed-off requests lives on
        # the prefill side's out records (the decode side's
        # request_complete only sees its own clock domain).
        ttfts = sorted(h["ttft_ms"] for h in handoffs
                       if h.get("direction") == "out" and "ttft_ms" in h)
        if ttfts:
            print(f"handoff ttft_ms (prefill-side)  p50 "
                  f"{_pct(ttfts, 50):8.1f}  p99 {_pct(ttfts, 99):8.1f}  "
                  f"max {ttfts[-1]:8.1f}  (ms)", file=out)
    if migrations:
        # Schema v18 (live migration, ISSUE 20): the mid-flight
        # transfer ledger, same shape as HANDOFF — transit latency
        # only exists on "in" records (the destination stamps
        # out-wall -> admission); a source-only stream reports count
        # and bytes alone.  The leased-spool crash-safety provenance
        # (redelivered / duplicate / quarantine) rides along exactly
        # as it does for handoffs.
        n_out = sum(1 for m in migrations
                    if m.get("direction") == "out")
        n_in = sum(1 for m in migrations if m.get("direction") == "in"
                   and not m.get("duplicate"))
        moved = sum(m.get("payload_bytes", 0) for m in migrations
                    if m.get("direction") != "quarantine")
        blocks = sum(m.get("blocks", 0) for m in migrations)
        line = (f"MIGRATION: {n_out} out / {n_in} in  "
                f"{blocks} block(s), {moved / 1024:.1f} KiB moved")
        lats = sorted(m["migration_ms"] for m in migrations
                      if "migration_ms" in m)
        if lats:
            line += (f"  transit p50 {_pct(lats, 50):.1f}  "
                     f"p99 {_pct(lats, 99):.1f}  max {lats[-1]:.1f} (ms)")
        requeued = sum(m.get("requeued", 0) for m in migrations)
        if requeued:
            line += f"  requeued {requeued}"
        gen = sorted(m.get("tokens_generated", 0) for m in migrations
                     if m.get("direction") == "out")
        if gen:
            line += (f"  tokens riding p50 {_pct(gen, 50):.0f} "
                     f"max {gen[-1]}")
        print(line, file=out)
        n_redeliv = sum(1 for m in migrations
                        if m.get("direction") == "in"
                        and m.get("redelivered")
                        and not m.get("duplicate"))
        n_dup = sum(1 for m in migrations if m.get("duplicate"))
        n_quar = sum(1 for m in migrations
                     if m.get("direction") == "quarantine")
        if n_redeliv or n_dup or n_quar:
            print(f"  redelivery: {n_redeliv} redelivered "
                  f"admission(s)  {n_dup} duplicate(s) acked without "
                  f"scatter  {n_quar} payload(s) quarantined", file=out)
            for m in migrations:
                if m.get("direction") == "quarantine":
                    print(f"  quarantined {m.get('request_id', '?')} "
                          f"({m.get('spool_file', '?')}): "
                          f"{m.get('error', '?')}", file=out)
    for d in drains:
        line = (f"DRAIN: {d.get('signal', '?')} at step "
                f"{d.get('step', '?')}"
                f" — in_flight {d.get('in_flight', '?')}, completed "
                f"{d.get('completed', '?')}, evicted "
                f"{d.get('evicted', '?')}"
                f", requeued {d.get('requeued', '?')}")
        if "migrated" in d:
            # v18: a migrating drain ships its live slots instead of
            # ticking them out — show what it preserved.
            line += f", migrated {d['migrated']}"
        print(line, file=out)
    if summary:
        print(f"serve_summary: {summary['requests']} request(s)  "
              f"{summary['output_tokens']} token(s)  "
              f"{summary['tokens_per_sec']} tok/s aggregate  "
              f"occupancy {summary.get('occupancy', '?')}", file=out)
        quantized = (summary.get("kv_dtype") == "int8"
                     or summary.get("weight_dtype") in
                     ("int8", "float8_e4m3", "fp8_e4m3_emulated"))
        if quantized and "kv_bytes_per_token" in summary:
            # schema v11 QUANT line (ISSUE 13), only when some stratum
            # actually quantized — every v11 run carries the dtype
            # fields, and an unquantized fp32 run must not print a
            # sub-1.0 "compression" banner: dtypes, the per-request KV
            # cost vs its bf16-equivalent, and the compression ratio
            # ci_gate --quant-stream gates at >= 1.9x.
            per = summary["kv_bytes_per_token"]
            bf16 = summary.get("kv_bytes_per_token_bf16", per)
            ratio = bf16 / per if per else 0.0
            toks = (prompt_tokens + out_tokens) / len(reqs) if reqs \
                else 0.0
            print(f"QUANT: weights={summary.get('weight_dtype', '?')}  "
                  f"kv={summary['kv_dtype']}  "
                  f"kv_bytes/token {per} vs bf16-eq {bf16}  "
                  f"per-request kv {toks * per / 1024:.1f} KiB vs "
                  f"bf16-eq {toks * bf16 / 1024:.1f} KiB  "
                  f"compression {ratio:.2f}x", file=out)
        if "blocks_total" in summary:
            blk = summary.get("blocks_live") or {}
            total = summary["blocks_total"]
            mean = blk.get("mean", 0.0)
            util = 100.0 * mean / total if total else 0.0
            print(f"kv blocks: mean {mean:.1f} / max "
                  f"{blk.get('max', 0):.0f} of {total} "
                  f"x{summary.get('block_size', '?')} tokens "
                  f"({util:.1f}% util)  waste "
                  f"{summary.get('kv_waste_pct', '?')}%  "
                  f"prefix_hit_rate "
                  f"{summary.get('prefix_hit_rate', '?')}  "
                  f"cow_copies {summary.get('cow_copies', '?')}",
                  file=out)
        if "availability" in summary:
            print(f"serve_summary availability: "
                  f"{summary['availability']}", file=out)
        # schema v15 OVERHEAD lines (ISSUE 17), only when the run was
        # armed with --tick-profile: the host/device decomposition of
        # the serve tick — per-phase p50/p99 from the profiler's
        # online sketches and the host-overhead fraction (wall minus
        # device-wait, over wall).  Pre-v15 streams simply carry no
        # overhead_summary and skip this block.
        overhead = next((r for r in records
                         if r.get("record") == "overhead_summary"),
                        None)
        if overhead is not None:
            wall = overhead.get("wall", {})
            print(f"OVERHEAD: host_overhead_frac "
                  f"{overhead.get('host_overhead_frac', 0.0):.4f}  "
                  f"(host_gap {overhead.get('host_gap_ms', 0.0):.1f} ms"
                  f" of {overhead.get('wall_ms', 0.0):.1f} ms wall over"
                  f" {overhead.get('ticks', 0)} tick(s), wall p50 "
                  f"{wall.get('p50', 0.0):.2f} ms)", file=out)
            phases = overhead.get("phases") or {}
            parts = "  ".join(
                f"{name} {p.get('p50', 0.0):.2f}/{p.get('p99', 0.0):.2f}"
                for name, p in phases.items() if isinstance(p, dict))
            if parts:
                print(f"  phases (p50/p99 ms): {parts}", file=out)
        # schema v16 SPEC line (ISSUE 18), only when the run was armed
        # with --speculate: the speculation ledger — acceptance rate,
        # drafted vs accepted totals and tokens/tick against the
        # 1.0 one-token-per-tick baseline.  Pre-v16 streams carry no
        # speculate_k and skip this block, like OVERHEAD does.
        if "speculate_k" in summary:
            tpt = summary.get("tokens_per_tick", 0.0)
            print(f"SPEC: K={summary['speculate_k']} "
                  f"draft={summary.get('draft_kind', '?')}  "
                  f"acceptance "
                  f"{summary.get('acceptance_rate', 0.0):.1%} "
                  f"({summary.get('tokens_accepted', 0)} of "
                  f"{summary.get('tokens_drafted', 0)} drafted, "
                  f"{summary.get('tokens_sampled', 0)} sampled)  "
                  f"tokens/tick {tpt} vs 1.0 baseline "
                  f"({'+' if tpt > 1.0 else ''}"
                  f"{(tpt - 1.0) * 100.0:.0f}%)", file=out)
        if "idle_ticks" in summary:
            print(f"idle: {summary['idle_ticks']} idle tick(s), "
                  f"{summary.get('idle_wait_ms', 0.0)} ms waited",
                  file=out)
        if summary.get("aborted"):
            print(f"ABORTED RUN: {summary.get('abort_reason', '?')}",
                  file=out)
    elif any(r.get("record") == "run_header" for r in records):
        print("stream ends without a serve_summary (run killed or still "
              "in flight)", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("path", help="JSONL from serve.py --metrics-jsonl")
    args = ap.parse_args(argv)
    return report(args.path)


if __name__ == "__main__":
    sys.exit(main())
