"""Attention micro-benchmark: naive XLA vs flash kernel vs ring variants.

Standalone evidence tool for the PERF.md flash-attention table (run on the
real chip; safe anywhere).  Times fwd+bwd of each attention form at several
sequence lengths with the in-jit fori_loop chaining the tunnel rig requires
(see PERF.md measurement methodology: block_until_ready returns at enqueue;
only a scalar fetch is a real barrier).

    python tools/attn_bench.py [--seqs 512,2048,8192] [--iters 8]
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")


def _chain(fn, args, iters):
    """Time fn(*args) iterated with a carried data dependence, two chain
    lengths, differenced — immune to enqueue-only returns."""
    def run(n):
        def body(i, a):
            q, k, v = a
            g = fn(q, k, v)
            return (q + 0.0 * g[0], k, v)

        out = jax.lax.fori_loop(0, n, body, args)
        return out[0].sum()

    r1 = jax.jit(run, static_argnums=0)
    float(r1(1))                       # compile + warm
    t0 = time.time(); float(r1(1)); t1 = time.time() - t0
    t0 = time.time(); float(r1(1 + iters)); t2 = time.time() - t0
    return (t2 - t1) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="512,2048,4096")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--tokens", type=int, default=8192,
                    help="batch*seq kept ~constant across rows")
    args = ap.parse_args()

    from apex_example_tpu.ops.attention import (attention_reference,
                                                flash_attention)

    def grad_of(f):
        g = jax.grad(lambda q, k, v: jnp.sum(
            jnp.square(f(q, k, v).astype(jnp.float32))), argnums=(0, 1, 2))
        return lambda q, k, v: g(q, k, v)[0]

    for s in (int(x) for x in args.seqs.split(",")):
        b = max(1, args.tokens // s)
        ks = jax.random.split(jax.random.key(0), 3)
        q, k, v = (jax.random.normal(kk, (b, s, args.heads, args.head_dim),
                                     jnp.bfloat16) for kk in ks)
        for name, f in (("naive", attention_reference),
                        ("flash", flash_attention)):
            fwd = _chain(lambda q, k, v, f=f: f(q, k, v), (q, k, v),
                         args.iters)
            bwd = _chain(grad_of(f), (q, k, v), args.iters)
            print(f"S={s:6d} b={b:3d} {name:6s} "
                  f"fwd {fwd * 1e3:8.2f} ms  fwd+bwd {bwd * 1e3:8.2f} ms",
                  flush=True)


if __name__ == "__main__":
    main()
