#!/usr/bin/env python
"""Step-loop probe: is any of the C2 step time host-dispatch bubbles?

bench.py's two-point chain dispatches each jitted step from the host
through the axon tunnel; differencing two chain lengths cancels the
*fixed* fetch round-trip but cannot cancel a *per-step* dispatch cost if
the tunnel fails to pipeline enqueues behind execution.  The byte
accounting (PERF.md) says the measured step already sits at the HBM
roofline — i.e. predicts NO bubbles — but that inference has never been
tested directly.

This probe jits ONE XLA program that runs K train steps in a
`lax.fori_loop` (the batch is device-resident and reused, exactly like
bench.py's single-chip path), so the device executes K steps back to
back with zero host involvement.  Comparing img/s against bench.py's
number arbitrates:

  - steploop ~= chain   -> dispatch pipelines fine; chain number is pure
                           device throughput (the roofline story stands).
  - steploop >> chain   -> the tunnel leaves per-step bubbles; the
                           steploop form is the honest device number and
                           bench.py should grow a --steps-per-call mode.

Usage: python tools/steploop_probe.py [--batch-size 256] [--k 20]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--k", type=int, default=20,
                    help="steps fused into one XLA program")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed invocations of the fused program")
    args = ap.parse_args()

    from apex_example_tpu import amp
    from apex_example_tpu.engine import make_train_step
    from bench import _image_setup, chain_rate

    policy, scaler = amp.initialize("O2")
    model, opt, batch, state = _image_setup(
        policy, scaler, arch="resnet50", batch_size=args.batch_size,
        image_size=224, num_classes=1000)
    batch = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, jax.devices()[0]), batch)

    step = make_train_step(model, opt, policy)

    def body(_, carry):
        state, _metrics = carry
        return step(state, batch)

    @jax.jit
    def k_steps(state):
        # run step once outside the loop to get a metrics carry of the
        # right structure, then K-1 more inside the loop
        carry = step(state, batch)
        return lax.fori_loop(0, args.k - 1, body, carry)

    # warmup/compile
    state, metrics = k_steps(state)
    loss0 = float(metrics["loss"])

    # Two-point differencing over rep counts, exactly like bench.py's
    # chain_rate: each timed window ends in one scalar fetch (the only
    # real barrier through the tunnel), and differencing two window
    # lengths cancels that fetch RTT — otherwise the uncancelled RTT
    # biases the steploop rate low and can mask the very bubble signal
    # this probe exists to detect.
    def run(reps, state):
        t0 = time.perf_counter()
        for _ in range(reps):
            state, metrics = k_steps(state)
        float(metrics["loss"])
        return time.perf_counter() - t0, state

    r1 = max(args.reps // 3, 1)
    r2 = max(args.reps, r1 + 1)
    t1, state = run(r1, state)
    t2, state = run(r2, state)
    rate = (r2 - r1) * args.k * args.batch_size / max(t2 - t1, 1e-9)
    print(f"steploop: K={args.k} reps={r1}/{r2} "
          f"rate={rate:.1f} img/s (loss0={loss0:.4f})")

    # reference: the same setup through the per-step dispatch chain
    policy2, scaler2 = amp.initialize("O2")
    model2, opt2, batch2, state2 = _image_setup(
        policy2, scaler2, arch="resnet50", batch_size=args.batch_size,
        image_size=224, num_classes=1000)
    batch2 = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, jax.devices()[0]), batch2)
    jstep = jax.jit(make_train_step(model2, opt2, policy2),
                    donate_argnums=(0,))
    for _ in range(2):
        state2, m2 = jstep(state2, batch2)
    float(m2["loss"])
    crate = chain_rate(jstep, state2, batch2, 30, args.batch_size,
                       lambda m: float(m["loss"]))
    print(f"chain:    rate={crate:.1f} img/s")
    print(f"ratio steploop/chain = {rate / crate:.3f}")


if __name__ == "__main__":
    main()
