#!/usr/bin/env python
"""Export schema-v9 trace_event JSONL streams to Chrome/Perfetto trace
JSON — and structurally lint them (``--check``).

    # one run -> one timeline (load trace.json in ui.perfetto.dev)
    python tools/trace_export.py serve.jsonl -o trace.json

    # a supervised restart: every attempt stream + the supervisor's own
    # stream merge into ONE timeline (they share a trace_id via the
    # APEX_TRACE_ID env handoff; each stream gets its own process row)
    python tools/trace_export.py serve.jsonl serve.jsonl.attempt1 \\
        sup.jsonl -o trace.json

    # overlay the device-side xprof trace on the same wall-clock axis
    python tools/trace_export.py train.jsonl --xprof /tmp/xprof -o t.json

    # a --trace + --tick-profile stream additionally renders the
    # sampled host_gap_ms as a counter track (ph "C") on the stream's
    # process row — the hot-path overhead at a glance (schema v15)

    # a disaggregated pair (schema v12): the prefill worker's request
    # span joins its decode-worker continuation with a cross-stream
    # flow arrow keyed on the handoff uid (cat "handoff")
    python tools/trace_export.py prefill.jsonl decode.jsonl -o t.json

    # structural lint (the ci_gate --trace-stream gate): balanced B/E
    # per thread row, monotonic B/E timestamps, orphan parent_ids,
    # X-span containment, exactly one clock_sync
    python tools/trace_export.py --check serve.jsonl

Clock mapping: every ``ts``/``dur`` in a stream is monotonic
``perf_counter`` seconds; the stream's single ``clock_sync`` record
pairs one such reading with a back-to-back ``time.time()``, so
``wall = sync.time + (ts - sync.ts)`` places all streams — emitted by
different processes with unrelated perf_counter origins — on one
wall-clock axis.  Exported ``ts`` are microseconds relative to the
earliest event across streams.  An xprof trace whose timestamps are
epoch-microseconds (the TPU runtime's convention) lands on the same
axis; a relative-timestamped one is appended as-is from t=0 with a
warning (no clock pair to anchor it).

Thin client contract: no jax import, direct or transitive (graftlint's
static jax-free rule proves it) — shares the xprof parser with
tools/trace_top.py.

Exit codes: 0 = exported / check clean; 1 = --check found structural
errors; 2 = usage (missing file, no clock_sync to export against).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Same no-jax sibling imports as tools/serve_report.py.
from trace_top import load_chrome_trace  # noqa: E402

PHASES = ("B", "E", "X", "i")
# Containment slack for float round-trips; spans are differences of the
# same perf_counter readings, so anything past this is structural.
EPS = 1e-6


def read_stream(path: str) -> List[Dict[str, Any]]:
    """Parse one JSONL stream (tolerant of a killed writer's torn final
    line, like every thin client here)."""
    records = []
    with open(path) as fh:
        for n, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"WARNING: {path}:{n + 1}: not JSON, skipped",
                      file=sys.stderr)
    return records


def _trace_records(records):
    events = [r for r in records if r.get("record") == "trace_event"]
    syncs = [r for r in records if r.get("record") == "clock_sync"]
    return events, syncs


# ------------------------------------------------------------- check

def check_stream(records: List[Dict[str, Any]], path: str) -> List[str]:
    """Structural lint for one stream's trace events.  Schema-level
    validation is metrics_lint's job; this checks what a timeline
    viewer would silently mis-render:

    - exactly one ``clock_sync``, before the first event;
    - ``ph`` is B/E/X/i; X carries a non-negative ``dur``;
    - B/E are balanced stack-wise per ``tid`` row and their timestamps
      are monotonic per row in file order (they are emitted live —
      out-of-order B/E means interleaved writers or a clock step);
    - ``span_id`` unique; every ``parent_id`` resolves in-stream (no
      orphans);
    - a child span/instant lies inside its parent's window (X spans
      are emitted after the fact, so containment — not file order —
      is their structural invariant).
    """
    errors: List[str] = []

    def err(msg):
        errors.append(f"{path}: {msg}")

    events, syncs = _trace_records(records)
    if not events:
        err("no trace_event records (was the run started with --trace?)")
        return errors
    if len(syncs) != 1:
        err(f"{len(syncs)} clock_sync records (expected exactly 1)")
    first_event_line = min((i for i, r in enumerate(records)
                            if r.get("record") == "trace_event"),
                           default=None)
    first_sync_line = min((i for i, r in enumerate(records)
                           if r.get("record") == "clock_sync"),
                          default=None)
    if syncs and first_event_line is not None \
            and first_sync_line > first_event_line:
        err("clock_sync must precede the first trace_event")

    spans: Dict[str, Tuple[float, Optional[float]]] = {}
    open_b: Dict[str, List[Tuple[str, float, Optional[str]]]] = {}
    last_be_ts: Dict[str, float] = {}
    for n, e in enumerate(events):
        ph, name = e.get("ph"), e.get("name", "?")
        tid = e.get("tid", "main")
        ts = e.get("ts")
        where = f"event {n + 1} ({ph} {name!r}, tid {tid})"
        if ph not in PHASES:
            err(f"{where}: ph {ph!r} not one of {PHASES}")
            continue
        if not isinstance(ts, (int, float)):
            err(f"{where}: non-numeric ts")
            continue
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                err(f"{where}: X span needs a dur >= 0, got {dur!r}")
                dur = 0.0
            if "span_id" in e:
                if e["span_id"] in spans:
                    err(f"{where}: duplicate span_id {e['span_id']!r}")
                spans[e["span_id"]] = (ts, ts + dur)
        elif ph == "B":
            if tid in last_be_ts and ts < last_be_ts[tid] - EPS:
                err(f"{where}: B ts went backwards on its row "
                    f"({ts:.6f} < {last_be_ts[tid]:.6f})")
            last_be_ts[tid] = max(last_be_ts.get(tid, ts), ts)
            open_b.setdefault(tid, []).append((name, ts, e.get("span_id")))
            if "span_id" in e:
                if e["span_id"] in spans:
                    err(f"{where}: duplicate span_id {e['span_id']!r}")
                spans[e["span_id"]] = (ts, None)      # closed by its E
        elif ph == "E":
            if tid in last_be_ts and ts < last_be_ts[tid] - EPS:
                err(f"{where}: E ts went backwards on its row "
                    f"({ts:.6f} < {last_be_ts[tid]:.6f})")
            last_be_ts[tid] = max(last_be_ts.get(tid, ts), ts)
            stack = open_b.get(tid, [])
            if not stack:
                err(f"{where}: E with no open B on this row")
            else:
                b_name, b_ts, b_sid = stack.pop()
                if b_name != name:
                    err(f"{where}: E closes {b_name!r} (B/E must nest "
                        "stack-wise per row)")
                if b_sid is not None:
                    spans[b_sid] = (b_ts, ts)
    for tid, stack in open_b.items():
        for b_name, b_ts, _sid in stack:
            errors.append(f"{path}: unbalanced B {b_name!r} on tid "
                          f"{tid!r} never closed (ts {b_ts:.6f})")

    for n, e in enumerate(events):
        pid = e.get("parent_id")
        if pid is None:
            continue
        where = (f"event {n + 1} ({e.get('ph')} {e.get('name', '?')!r})")
        if pid not in spans:
            err(f"{where}: orphan parent_id {pid!r}")
            continue
        p0, p1 = spans[pid]
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            continue                    # already reported above
        dur = e.get("dur", 0.0)
        if not isinstance(dur, (int, float)):
            dur = 0.0                   # already reported above
        end = ts + dur if e.get("ph") == "X" else ts
        if ts < p0 - EPS or (p1 is not None and end > p1 + EPS):
            err(f"{where}: outside its parent {pid!r} window "
                f"[{p0:.6f}, {p1 if p1 is None else round(p1, 6)}]")
    return errors


# ------------------------------------------------------------ export

def _stream_label(path: str, records) -> str:
    header = next((r for r in records if r.get("record") == "run_header"),
                  None)
    base = os.path.basename(path)
    if header is None:
        return base
    cfg = header.get("config", {})
    arch = header.get("arch", cfg.get("arch"))
    platform = header.get("platform", "?")
    label = f"{base} [{platform}"
    if arch:
        label += f"/{arch}"
    return label + "]"


def export(streams: List[Tuple[str, List[Dict[str, Any]]]],
           xprof_events: Optional[list] = None) -> Dict[str, Any]:
    """Chrome trace-event JSON for one or more JSONL streams (each on
    its own process row) plus an optional xprof overlay.  Streams
    without a clock_sync cannot be placed on the shared axis and raise
    ValueError (the --check gate reports them first)."""
    anchored = []
    for path, records in streams:
        events, syncs = _trace_records(records)
        if not events:
            print(f"WARNING: {path}: no trace events, skipped",
                  file=sys.stderr)
            continue
        if not syncs:
            raise ValueError(f"{path}: no clock_sync record — cannot "
                             "place this stream on the shared timeline")
        sync = syncs[0]
        # wall = sync.time + (ts - sync.ts): the per-stream anchor.
        offset = sync["time"] - sync["ts"]
        anchored.append((path, records, events, offset))
    if not anchored:
        raise ValueError("no traced stream to export")

    t_base = min(e["ts"] + off for _p, _r, evs, off in anchored
                 for e in evs)
    xprof_epoch = None
    if xprof_events:
        xs = [e["ts"] for e in xprof_events
              if e.get("ph") == "X" and isinstance(e.get("ts"),
                                                   (int, float))]
        if xs and min(xs) > 1e14:      # epoch microseconds
            xprof_epoch = True
            t_base = min(t_base, min(xs) / 1e6)
        else:
            xprof_epoch = False
            print("WARNING: xprof timestamps are not epoch-anchored; "
                  "overlay starts at t=0 instead of wall-aligned",
                  file=sys.stderr)

    out: List[Dict[str, Any]] = []
    flow_id = 0
    # Cross-stream request continuations (schema v12, serve/disagg.py):
    # every request root span, keyed by request_id — a root that
    # terminated with status "handoff" in one stream (the prefill
    # worker) joins its continuation root in another (the decode
    # worker) with a flow arrow, so the two halves read as ONE request
    # on the merged timeline.
    req_roots: List[Dict[str, Any]] = []
    for pid0, (path, records, events, offset) in enumerate(anchored):
        pid = pid0 + 1
        out.append({"ph": "M", "name": "process_name", "pid": pid,
                    "tid": 0, "args": {"name": _stream_label(path,
                                                             records)}})
        out.append({"ph": "M", "name": "process_sort_index", "pid": pid,
                    "tid": 0, "args": {"sort_index": pid}})
        tids: Dict[str, int] = {}

        def tid_of(name: str) -> int:
            if name not in tids:
                tids[name] = len(tids)
                out.append({"ph": "M", "name": "thread_name", "pid": pid,
                            "tid": tids[name], "args": {"name": name}})
                out.append({"ph": "M", "name": "thread_sort_index",
                            "pid": pid, "tid": tids[name],
                            "args": {"sort_index": tids[name]}})
            return tids[name]

        def us(ts: float) -> float:
            return round((ts + offset - t_base) * 1e6, 3)

        roots: Dict[str, Dict[str, Any]] = {}   # request span_id -> event
        queued_end: Dict[str, float] = {}       # request span_id -> ts us
        for e in events:
            ph = e["ph"]
            ev: Dict[str, Any] = {
                "ph": ph, "name": e.get("name", "?"), "pid": pid,
                "tid": tid_of(e.get("tid", "main")), "ts": us(e["ts"])}
            if e.get("cat"):
                ev["cat"] = e["cat"]
            args = dict(e.get("args") or {})
            for k in ("span_id", "parent_id"):
                if k in e:
                    args[k] = e[k]
            if args:
                ev["args"] = args
            if ph == "X":
                ev["dur"] = round(e.get("dur", 0.0) * 1e6, 3)
            elif ph == "i":
                ev["s"] = "t"
            out.append(ev)
            if ph == "X" and e.get("cat") == "request":
                # Only ADMITTED requests (args.slot is set iff the
                # request reached a slot) get a flow arrow: a shed/
                # rejected/drained root also has a "queued" child, but
                # its end is the terminal time, not an admission.
                if e.get("name") == "request" and "span_id" in e \
                        and "slot" in (e.get("args") or {}):
                    roots[e["span_id"]] = ev
                elif e.get("name") == "queued" \
                        and e.get("parent_id") is not None:
                    queued_end[e["parent_id"]] = us(e["ts"]
                                                    + e.get("dur", 0.0))
                if e.get("name") == "request" \
                        and (e.get("args") or {}).get("request_id"):
                    eargs = e["args"]
                    req_roots.append({
                        "rid": eargs["request_id"],
                        "status": eargs.get("status", "?"),
                        "pid": pid, "tid": ev["tid"],
                        "ts": ev["ts"],
                        "end": ev["ts"] + ev.get("dur", 0.0)})
        # Host-gap counter track (schema v15): every sampled
        # tick_profile record lands as a Chrome counter sample, so the
        # Perfetto view carries the host-side overhead gap as its own
        # track under this stream's process row — the dispatch-gap
        # view the ISSUE 17 decomposition exists for.  tick_profile
        # ``ts`` is the same perf_counter domain as trace_event, so
        # the clock_sync anchor places the samples correctly.
        for r in records:
            if r.get("record") != "tick_profile":
                continue
            ts = r.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            out.append({"ph": "C", "name": "host_gap_ms", "pid": pid,
                        "tid": 0, "ts": us(ts),
                        "args": {"host_gap_ms":
                                 round(r.get("host_gap_ms", 0.0), 4)}})

        # Request admissions as flows: an arrow from the engine row to
        # the request row at the moment its queued span ends (= slot
        # admission), binding the scheduler's timeline to the request's.
        if "engine" in tids:
            for sid, root_ev in roots.items():
                if sid not in queued_end:
                    continue
                flow_id += 1
                ts = queued_end[sid]
                common = {"cat": "admit", "name": "admit", "id": flow_id,
                          "pid": pid}
                out.append(dict(common, ph="s", tid=tids["engine"],
                                ts=ts))
                out.append(dict(common, ph="f", bp="e",
                                tid=root_ev["tid"], ts=ts))

    # Prefill -> decode continuation arrows: the handoff root's end
    # meets the continuation root's start.  Clock-sync anchoring has
    # already placed both streams on one wall axis, so the arrow spans
    # real transit time (including NTP skew on cross-host runs — the
    # same caveat as every wall-clock join here).
    by_rid: Dict[str, List[Dict[str, Any]]] = {}
    for r in req_roots:
        by_rid.setdefault(r["rid"], []).append(r)
    for rid in sorted(by_rid):
        lst = by_rid[rid]
        handed = [r for r in lst if r["status"] == "handoff"]
        for h in handed:
            cont = next((c for c in lst
                         if c is not h and c["status"] != "handoff"),
                        None)
            if cont is None:
                continue
            flow_id += 1
            common = {"cat": "handoff", "name": "kv_handoff",
                      "id": flow_id}
            out.append(dict(common, ph="s", pid=h["pid"],
                            tid=h["tid"], ts=h["end"]))
            out.append(dict(common, ph="f", bp="e", pid=cont["pid"],
                            tid=cont["tid"], ts=cont["ts"]))

    if xprof_events:
        xpid = 1001
        seen_pids: Dict[Any, int] = {}
        for e in xprof_events:
            pid_in = e.get("pid", 0)
            if pid_in not in seen_pids:
                seen_pids[pid_in] = xpid + len(seen_pids)
            ev = dict(e)
            ev["pid"] = seen_pids[pid_in]
            if isinstance(ev.get("ts"), (int, float)) \
                    and ev.get("ph") != "M":
                ev["ts"] = round(ev["ts"] - t_base * 1e6, 3) \
                    if xprof_epoch else ev["ts"]
            out.append(ev)

    return {"traceEvents": out, "displayTimeUnit": "ms"}


# -------------------------------------------------------------- main

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="export/lint schema-v9 trace_event streams "
                    "(Chrome/Perfetto trace JSON)")
    ap.add_argument("streams", nargs="+", metavar="JSONL",
                    help="metrics stream(s) from --trace runs; pass a "
                         "run's attempt streams + the supervisor stream "
                         "together to merge one timeline")
    ap.add_argument("-o", "--out", default="trace.json",
                    help="output path (default trace.json)")
    ap.add_argument("--check", action="store_true",
                    help="structural lint only: balanced B/E per row, "
                         "monotonic B/E timestamps, orphan parent_ids, "
                         "span containment, one clock_sync per stream")
    ap.add_argument("--xprof", default=None, metavar="PATH",
                    help="xprof trace (*.trace.json.gz or a profiler "
                         "logdir) to overlay on the same timeline")
    args = ap.parse_args(argv)

    streams = []
    for path in args.streams:
        if not os.path.isfile(path):
            print(f"trace_export: no such stream: {path}",
                  file=sys.stderr)
            return 2
        streams.append((path, read_stream(path)))

    if args.check:
        errors: List[str] = []
        n_events = 0
        for path, records in streams:
            errors.extend(check_stream(records, path))
            n_events += sum(1 for r in records
                            if r.get("record") == "trace_event")
        for e in errors:
            print(e, file=sys.stderr)
        if errors:
            print(f"trace_export --check: {len(errors)} error(s) over "
                  f"{len(streams)} stream(s)")
            return 1
        print(f"trace_export --check: {len(streams)} stream(s) OK "
              f"({n_events} events)")
        return 0

    xprof_events = None
    if args.xprof:
        if not os.path.exists(args.xprof):
            print(f"trace_export: no such xprof trace: {args.xprof}",
                  file=sys.stderr)
            return 2
        xprof_events = load_chrome_trace(args.xprof)
    try:
        doc = export(streams, xprof_events=xprof_events)
    except ValueError as e:
        print(f"trace_export: {e}", file=sys.stderr)
        return 2
    with open(args.out, "w") as fh:
        json.dump(doc, fh)
    n = sum(1 for e in doc["traceEvents"] if e.get("ph") != "M")
    print(f"{args.out}: {n} event(s) from {len(streams)} stream(s)"
          + (" + xprof overlay" if xprof_events else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
