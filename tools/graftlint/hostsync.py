"""host-sync-in-step: device fetches and fresh-hash jits in traced code.

Two failure classes the runtime can't flag:

- **Host sync inside a traced function.**  ``float(x)`` / ``int(x)`` /
  ``bool(x)`` / ``x.item()`` / ``np.asarray(x)`` / ``jax.device_get(x)``
  applied to a traced value inside a jitted function either raises a
  ``TracerConversionError`` at trace time (the lucky case) or — via
  ``io_callback``-style wrappers and numpy fallbacks — silently forces
  a device round-trip per step.  The engine's ONE deliberate host sync
  (fetching sampled tokens in serve/engine.py) happens OUTSIDE the
  compiled step by design; nothing inside a step function may sync.

- **Fresh-hash jit.**  ``jax.jit(lambda ...)`` (or of a local ``def``)
  executed INSIDE A LOOP builds a new callable — hence a new dispatch
  cache key — per iteration: every call silently recompiles.  The
  repo's sanctioned shapes are factory functions called once per run
  (``make_*_step`` returning ``jax.jit(step)``) and lru-cached builders
  (``serve/engine._slot_step``); both jit a given function object once.

Step contexts recognized (per module, static):

1. functions decorated with ``jit`` / ``jax.jit`` / ``pjit`` /
   ``functools.partial(jax.jit, ...)``;
2. named functions passed to a ``jit(...)`` call anywhere in the module;
3. functions (and lambdas) defined inside — or passed as arguments to —
   a ``make_*step`` factory: the step/loss callables those factories
   close over run inside the traced program.

Static-shape escapes: an argument that touches ``.shape`` / ``.ndim``
/ ``.size`` / ``.dtype`` / ``len(...)`` is host-static metadata, not a
traced value, and stays quiet.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set, Tuple

from .base import Finding, SourceFile, Tree, dotted_name

RULE_SYNC = "host-sync-in-step"
RULE_JIT = "jit-in-loop"

_JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.experimental.pjit.pjit"}
_FACTORY = re.compile(r"^make_\w*step\w*$")
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize",
                 "num_devices", "block_size"}
_FETCHERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
             "onp.asarray", "jax.device_get", "device_get"}


def _is_jit_expr(node: ast.AST) -> bool:
    """Does this expression denote jit (possibly partial(jit, ...))?"""
    name = dotted_name(node)
    if name in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in ("functools.partial", "partial") and node.args:
            return _is_jit_expr(node.args[0])
        # decorator form ``@jax.jit`` with kwargs: jax.jit(static_...)
        if fname in _JIT_NAMES:
            return True
    return False


def _jitted_arg_names(tree: ast.AST) -> Set[str]:
    """Names of functions passed to a jit(...) call in this module."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node.func):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name):
                    out.add(arg.id)
                elif isinstance(arg, ast.Call):
                    # jax.jit(make_train_step(...)): the factory's inner
                    # defs are contexts via the factory-name rule.
                    pass
    return out


def _step_contexts(sf: SourceFile) -> List[ast.AST]:
    """Function/lambda nodes whose bodies execute under trace."""
    tree = sf.tree
    contexts: List[ast.AST] = []
    jitted_names = _jitted_arg_names(tree)
    from .base import walk_with_parents
    for node, ancestors in walk_with_parents(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(d) for d in node.decorator_list):
                contexts.append(node)
                continue
            if node.name in jitted_names:
                contexts.append(node)
                continue
            if any(isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and _FACTORY.match(a.name) for a in ancestors):
                contexts.append(node)
                continue
        if isinstance(node, ast.Lambda):
            if any(isinstance(a, ast.Call)
                   and _factory_call(a) and node in a.args + [
                       kw.value for kw in a.keywords]
                   for a in ancestors[-2:]):
                contexts.append(node)
    return contexts


def _factory_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if not name:
        return False
    return bool(_FACTORY.match(name.split(".")[-1]))


def _mentions_static(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id in ("len", "range"):
            return True
    return False


def _target_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in target.elts:
            out.extend(_target_names(elt))
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    return []


def _tainted_names(ctx: ast.AST) -> Set[str]:
    """Names that (transitively) derive from the step function's own
    parameters — the traced values.  Closure config (``bool(moe)`` in a
    factory) never taints: a factory's flags are host-side statics, and
    flagging them would bury the real syncs in noise."""
    args = ctx.args
    taint: Set[str] = set()
    for a in (args.posonlyargs + args.args + args.kwonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        taint.add(a.arg)
    taint.discard("self")
    body = ctx.body if isinstance(ctx.body, list) else [ctx.body]
    for _ in range(4):                    # cheap fixpoint
        grew = False
        for stmt in body:
            for node in ast.walk(stmt):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    targets, value = [node.target], node.iter
                if value is None:
                    continue
                if any(isinstance(s, ast.Name) and s.id in taint
                       for s in ast.walk(value)):
                    for t in targets:
                        for name in _target_names(t):
                            if name not in taint:
                                taint.add(name)
                                grew = True
        if not grew:
            break
    return taint


def _is_tainted(node: ast.AST, taint: Set[str]) -> bool:
    return any(isinstance(s, ast.Name) and s.id in taint
               for s in ast.walk(node))


def _walk_skipping(node: ast.AST, skip_ids: Set[int]):
    """ast.walk, but do not descend into nested nodes in ``skip_ids``
    (nested step contexts check their own bodies — double-reporting one
    sync under two context names would double the noise)."""
    for child in ast.iter_child_nodes(node):
        if id(child) in skip_ids:
            continue
        yield child
        yield from _walk_skipping(child, skip_ids)


def _check_context(sf: SourceFile, ctx: ast.AST,
                   findings: List[Finding],
                   skip_ids: Set[int] = frozenset()) -> None:
    body = ctx.body if isinstance(ctx.body, list) else [ctx.body]
    name = getattr(ctx, "name", "<lambda>")
    taint = _tainted_names(ctx)
    for stmt in body:
        for node in _walk_skipping_or_self(stmt, skip_ids):
            # float()/int()/bool() on a traced value
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int", "bool") \
                    and len(node.args) == 1 and not node.keywords:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) or _mentions_static(arg) \
                        or not _is_tainted(arg, taint):
                    continue
                _emit(sf, findings, node.lineno,
                      f"{node.func.id}() on a traced value inside step "
                      f"function '{name}' forces a host sync (or a "
                      "TracerConversionError)")
            # .item()
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args \
                    and _is_tainted(node.func.value, taint):
                _emit(sf, findings, node.lineno,
                      f".item() inside step function '{name}' is a "
                      "per-element device fetch")
            # np.asarray / device_get
            elif isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                if fname in _FETCHERS and node.args \
                        and _is_tainted(node.args[0], taint):
                    _emit(sf, findings, node.lineno,
                          f"{fname}() inside step function '{name}' "
                          "materializes a device array on the host")


def _walk_skipping_or_self(node: ast.AST, skip_ids: Set[int]):
    yield node
    yield from _walk_skipping(node, skip_ids)


def _emit(sf: SourceFile, findings: List[Finding], line: int,
          message: str) -> None:
    if not sf.suppressed(RULE_SYNC, line):
        findings.append(Finding(RULE_SYNC, sf.path, line, message))


def _check_jit_in_loop(sf: SourceFile, findings: List[Finding]) -> None:
    from .base import walk_with_parents
    local_defs_by_scope = {}
    for node, ancestors in walk_with_parents(sf.tree):
        if isinstance(node, ast.FunctionDef) and any(
                isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                for a in ancestors):
            scope = next(a for a in reversed(ancestors)
                         if isinstance(a, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)))
            local_defs_by_scope.setdefault(id(scope), set()).add(node.name)
    for node, ancestors in walk_with_parents(sf.tree):
        if not (isinstance(node, ast.Call) and _is_jit_expr(node.func)
                and node.args):
            continue
        in_loop = any(isinstance(a, (ast.For, ast.While, ast.AsyncFor))
                      for a in ancestors)
        if not in_loop:
            continue
        arg = node.args[0]
        fresh: Optional[str] = None
        if isinstance(arg, ast.Lambda):
            fresh = "a lambda"
        elif isinstance(arg, ast.Name):
            scope = next((a for a in reversed(ancestors)
                          if isinstance(a, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))), None)
            if scope is not None and arg.id in \
                    local_defs_by_scope.get(id(scope), ()):
                fresh = f"local def '{arg.id}'"
        if fresh and not sf.suppressed(RULE_JIT, node.lineno):
            findings.append(Finding(
                RULE_JIT, sf.path, node.lineno,
                f"jit({fresh}) inside a loop builds a fresh callable "
                "per iteration — every call silently recompiles "
                "(fresh dispatch-cache hash)"))


def check(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    for path, sf in sorted(tree.files.items()):
        if sf.tree is None:
            continue
        seen: Set[Tuple[int, int]] = set()
        contexts = []
        for ctx in _step_contexts(sf):
            key = (ctx.lineno, ctx.col_offset)
            if key in seen:          # decorated AND name-jitted
                continue
            seen.add(key)
            contexts.append(ctx)
        ctx_ids = {id(c) for c in contexts}
        for ctx in contexts:
            _check_context(sf, ctx, findings,
                           skip_ids=ctx_ids - {id(ctx)})
        _check_jit_in_loop(sf, findings)
    return findings
