"""jax-free-by-contract: a static, exhaustive transitive import check.

The repo's thin clients (tools/metrics_lint.py, tools/supervise.py, …),
the auto-resume supervisor and the telemetry schema are jax-free BY
CONTRACT: they must run on hosts where jax is broken or absent — the
supervisor's one job is to restart training after jax itself died.
PRs 2–7 enforced this at runtime: a subprocess per tool with a poisoned
``jax`` module first on PYTHONPATH.  That guard paid ~1–2 s of
interpreter startup per tool per suite run and only proved the code
paths the smoke arguments happened to execute.

This rule replaces it with a whole-file static proof: parse every
contract module, resolve every import edge (top-level AND
function-local — a lazy import still executes when the function runs)
against the repo tree, and walk the closure.  Any path that reaches a
jax-carrying root (jax, jaxlib, flax, optax, orbax, chex) is reported
with the full chain.  Imports inside ``try:`` blocks whose handler
catches ImportError (or a superclass) are runtime-safe degradation and
are excluded.

The contract set is computed, not listed: every ``tools/*.py`` whose
own source has no direct jax import is a thin client (growing a direct
jax import OPTS a tool OUT of the contract — same semantics as the old
runtime guard's discovery), plus the two named library modules.  What
this cannot see: ``importlib`` file-path loads (metrics_lint loads
obs/schema.py by path).  Those are covered by naming their TARGETS in
CONTRACT_FILES, which is exactly how the repo already uses them —
file-path loading exists to AVOID package imports.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, SourceFile, Tree

RULE = "jax-free"

# Roots whose import means "jax is in the process" — flax/optax/orbax/
# chex all import jax at their own import time.
JAX_ROOTS = {"jax", "jaxlib", "flax", "optax", "orbax", "chex"}

# Library modules that are jax-free by contract even though they live
# inside the (jax-carrying) package: loaded by FILE PATH, never via the
# package __init__ (tools/supervise.py, tools/metrics_lint.py,
# fleet.py).  The fleet stratum's three modules carry the contract the
# same way the supervisor does: the router must keep routing while a
# replica's jax is the thing that died (fleet/__init__.py is the
# in-process convenience surface and is deliberately NOT listed — it
# re-exports for callers that already carry jax).
CONTRACT_FILES = (
    "apex_example_tpu/resilience/supervisor.py",
    "apex_example_tpu/obs/schema.py",
    "apex_example_tpu/obs/slo.py",
    "apex_example_tpu/obs/tickprof.py",
    "apex_example_tpu/fleet/replica.py",
    "apex_example_tpu/fleet/router.py",
    "apex_example_tpu/fleet/scenarios.py",
    # ISSUE 18: draft proposers run on the host between ticks — the
    # engine imports them, never the reverse (spec/__init__.py is the
    # in-package convenience surface and, like fleet/__init__.py, is
    # deliberately NOT listed: loading it via the package walks the
    # jax-carrying apex_example_tpu/__init__.py edge).
    "apex_example_tpu/spec/proposers.py",
    # ISSUE 19: the scheduling stratum — tenant specs and prefix chain
    # hashes are loaded by FILE PATH on the router side (which must
    # keep routing while a replica's jax is the thing that died), and
    # the fair scheduler duck-types Request rather than import
    # serve.queue (sched/__init__.py is, as above, deliberately NOT
    # listed).
    "apex_example_tpu/sched/tenants.py",
    "apex_example_tpu/sched/fair.py",
    "apex_example_tpu/sched/prefix.py",
)

_IMPORT_EXC = {"ImportError", "ModuleNotFoundError", "Exception",
               "BaseException"}


def _soft_import(ancestors: Tuple[ast.AST, ...],
                 node: ast.AST) -> bool:
    """True when the import sits in the BODY of a try: whose handler
    catches ImportError — a runtime-guarded optional dependency, not an
    edge.  An import in the except handler itself (the classic
    fallback: ``except ImportError: import other``), or in else/
    finally, executes for real and stays a hard edge (review regression
    on the first cut of this rule)."""
    chain = list(ancestors) + [node]
    for i, anc in enumerate(chain[:-1]):
        if not isinstance(anc, ast.Try):
            continue
        child = chain[i + 1]
        if not any(child is stmt for stmt in anc.body):
            continue                 # handler/else/finally: hard edge
        for handler in anc.handlers:
            names: List[str] = []
            t = handler.type
            if t is None:
                return True                          # bare except
            for n in t.elts if isinstance(t, ast.Tuple) else [t]:
                if isinstance(n, ast.Name):
                    names.append(n.id)
                elif isinstance(n, ast.Attribute):
                    names.append(n.attr)
            if _IMPORT_EXC & set(names):
                return True
    return False


def module_imports(sf: SourceFile) -> List[Tuple[str, int, int]]:
    """(module, level, lineno) for every hard import edge in the file.
    ``from X import a, b`` yields X plus X.a / X.b — the submodule form
    must resolve too (``from apex_example_tpu.obs import schema``)."""
    if sf.tree is None:
        return []
    out: List[Tuple[str, int, int]] = []
    from .base import walk_with_parents
    for node, ancestors in walk_with_parents(sf.tree):
        if isinstance(node, ast.Import):
            if _soft_import(ancestors, node):
                continue
            for alias in node.names:
                out.append((alias.name, 0, node.lineno))
        elif isinstance(node, ast.ImportFrom):
            if _soft_import(ancestors, node):
                continue
            mod = node.module or ""
            out.append((mod, node.level, node.lineno))
            for alias in node.names:
                if alias.name != "*":
                    sub = f"{mod}.{alias.name}" if mod else alias.name
                    out.append((sub, node.level, node.lineno))
    return out


def _candidates(module: str, level: int, importer: str) -> List[str]:
    """Repo-relative paths a dotted import could resolve to, including
    every package __init__ along the dotted prefix (importing a
    submodule EXECUTES its ancestors' __init__)."""
    paths: List[str] = []
    if level:                                        # relative import
        base = os.path.dirname(importer)
        for _ in range(level - 1):
            base = os.path.dirname(base)
        prefix = base.replace(os.sep, "/")
        # A relative import executes the importing package's own
        # __init__ chain too: ``from . import helper`` in pkg/mod.py
        # pulls pkg/__init__.py (and every ancestor package's) before
        # helper — missing these edges let a jax import hide in a
        # subpackage __init__ (found by review of ISSUE 9's first cut).
        comps = prefix.split("/") if prefix else []
        for i in range(1, len(comps) + 1):
            paths.append("/".join(comps[:i]) + "/__init__.py")
    else:
        prefix = ""
    parts = [p for p in module.split(".") if p]
    for i in range(1, len(parts) + 1):
        stem = "/".join(([prefix] if prefix else []) + parts[:i])
        paths.append(f"{stem}/__init__.py")
        if i == len(parts):
            paths.append(f"{stem}.py")
    if level and not parts:
        # bare ``from . import name``: the names resolve as submodules
        # of the package itself (handled by module_imports emitting
        # ``.name``), but the package __init__ alone is also an edge.
        paths.append(f"{prefix}/__init__.py" if prefix
                     else "__init__.py")
    if not level and len(parts) == 1:
        # Bare sibling import (tools scripts sys.path-insert their own
        # directory: ``from metrics_lint import pct``).
        sib = os.path.dirname(importer).replace(os.sep, "/")
        if sib:
            paths.append(f"{sib}/{parts[0]}.py")
    return paths


def _resolve(module: str, level: int, importer: str,
             tree: Tree) -> List[str]:
    """Repo files a hard import edge lands on (empty = external)."""
    return [c for c in _candidates(module, level, importer)
            if c in tree.files or tree.exists(c)]


def has_direct_jax_import(sf: SourceFile) -> bool:
    """The contract OPT-OUT marker: a tool that imports ``jax`` (or
    ``jaxlib``) itself is declaring itself a jax tool — same discovery
    semantics as the retired runtime guard.  Deliberately NOT the full
    JAX_ROOTS set: a direct flax/optax import in an otherwise jax-free
    tool is a violation to report, not an opt-out."""
    return any(mod.split(".")[0] in ("jax", "jaxlib")
               for mod, _level, _line in module_imports(sf))


def contract_modules(tree: Tree) -> List[str]:
    """The jax-free-by-contract set at HEAD: every tools/*.py without a
    direct jax import, every tools/graftlint/*.py, plus the named
    library modules."""
    out: List[str] = []
    for path, sf in sorted(tree.files.items()):
        if not path.startswith("tools/"):
            continue
        if sf.tree is None:
            continue                                  # parse-error finding
        if not has_direct_jax_import(sf):
            out.append(path)
    for path in CONTRACT_FILES:
        if tree.exists(path):
            out.append(path)
    return out


def check(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    roots = contract_modules(tree)
    # parent chain for the report: file -> (importer, module, line)
    for root in roots:
        chain = _reaches_jax(root, tree)
        if chain:
            hops = " -> ".join(chain)
            findings.append(Finding(
                RULE, root, _first_hop_line(root, tree, chain),
                f"jax-free-by-contract module reaches jax: {hops}"))
    return findings


def _first_hop_line(root: str, tree: Tree, chain: List[str]) -> int:
    sf = tree.files.get(root)
    if sf is None or len(chain) < 2:
        return 0
    nxt = chain[1]
    for mod, level, lineno in module_imports(sf):
        resolved = _resolve(mod, level, root, tree)
        if nxt in resolved or mod.split(".")[0] in JAX_ROOTS \
                and nxt == mod:
            return lineno
    return 0


def _reaches_jax(root: str, tree: Tree) -> Optional[List[str]]:
    """BFS from ``root`` over hard import edges; returns the chain of
    repo files ending in the jax-carrying module name, or None."""
    seen: Set[str] = {root}
    parent: Dict[str, str] = {}
    queue: List[str] = [root]
    while queue:
        cur = queue.pop(0)
        sf = tree.files.get(cur)
        if sf is None:
            if tree.root:
                full = os.path.join(tree.root, cur)
                try:
                    with open(full, encoding="utf-8") as fh:
                        sf = SourceFile.from_text(cur, fh.read())
                except OSError:
                    continue
            else:
                continue
        for mod, level, lineno in module_imports(sf):
            if mod.split(".")[0] in JAX_ROOTS:
                chain = [mod]
                node = cur
                while node is not None:
                    chain.append(node)
                    node = parent.get(node)
                return list(reversed(chain))
            for target in _resolve(mod, level, cur, tree):
                if target not in seen:
                    seen.add(target)
                    parent[target] = cur
                    queue.append(target)
    return None
