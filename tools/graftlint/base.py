"""graftlint core: findings, source-tree loading, baselines, suppression.

Pure stdlib ON PURPOSE (the same contract as tools/supervise.py): the
linter's job includes proving that parts of the repo never import jax,
so it must itself run on a host where jax is broken or absent.  The
jax-free rule in imports.py covers this package too — a jax import
sneaking in here fails the lint it implements.

A :class:`Finding` carries a line number for humans but identifies
itself to the BASELINE by a line-free key (rule + path + message): an
unrelated edit above a baselined violation must not resurrect it, and a
new violation must not hide behind a stale line number.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# Directories never scanned: tests exercise the rules with deliberate
# positive fixtures, superseded/ is dead code kept for archaeology, and
# csrc/ is not python.
EXCLUDE_DIRS = {"tests", "__pycache__", "superseded", ".git", ".claude",
                "csrc", "related", "node_modules"}

_SUPPRESS = re.compile(r"#\s*graftlint:\s*ignore(?:\[([a-z0-9_,\- ]+)\])?")


def repo_root() -> str:
    """The checkout root (this file lives at tools/graftlint/base.py)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-based; 0 = file-level
    message: str
    baselined: bool = False

    @property
    def identity(self) -> str:
        """Line-free baseline key."""
        return f"{self.rule}::{self.path}::{self.message}"

    def render(self) -> str:
        mark = "  (baselined)" if self.baselined else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{mark}"

    def as_json(self) -> Dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "baselined": self.baselined}


@dataclass
class SourceFile:
    """One parsed python file.  ``tree`` is None when the file does not
    parse — the parse error itself becomes a finding, and every other
    rule skips the file."""

    path: str
    text: str
    tree: Optional[ast.AST] = None
    parse_error: Optional[str] = None
    lines: List[str] = field(default_factory=list)

    @classmethod
    def from_text(cls, path: str, text: str) -> "SourceFile":
        sf = cls(path=path, text=text, lines=text.splitlines())
        try:
            sf.tree = ast.parse(text, filename=path)
        except SyntaxError as e:
            sf.parse_error = f"{e.msg} (line {e.lineno})"
        return sf

    def suppressed(self, rule: str, line: int) -> bool:
        """``# graftlint: ignore`` (any rule) or ``# graftlint:
        ignore[rule-a, rule-b]`` on the finding's line suppresses it —
        the per-site escape hatch for a sanctioned violation; the
        baseline is the bulk one."""
        if 1 <= line <= len(self.lines):
            m = _SUPPRESS.search(self.lines[line - 1])
            if m:
                rules = m.group(1)
                if not rules:
                    return True
                return rule in [r.strip() for r in rules.split(",")]
        return False


class Tree:
    """The loaded source tree rules run over.

    ``files`` maps repo-relative posix paths to SourceFiles.  Tests
    build synthetic trees from string dicts (:func:`tree_from_sources`);
    the CLI loads the real checkout (:func:`load_tree`).
    """

    def __init__(self, files: Dict[str, SourceFile], root: str = ""):
        self.files = files
        self.root = root

    def exists(self, relpath: str) -> bool:
        if relpath in self.files:
            return True
        # Resolution must see repo files the scan skipped (nothing
        # currently — but a future exclude must not break import edges).
        return bool(self.root) and os.path.isfile(
            os.path.join(self.root, relpath))

    def parse_findings(self) -> List[Finding]:
        return [Finding("parse-error", sf.path, 0, sf.parse_error)
                for sf in self.files.values() if sf.parse_error]


def tree_from_sources(sources: Dict[str, str]) -> Tree:
    return Tree({p: SourceFile.from_text(p, s) for p, s in sources.items()})


def load_tree(root: Optional[str] = None) -> Tree:
    root = root or repo_root()
    files: Dict[str, SourceFile] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in EXCLUDE_DIRS)
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            try:
                with open(full, encoding="utf-8") as fh:
                    text = fh.read()
            except OSError as e:          # unreadable file: surface it
                files[rel] = SourceFile(path=rel, text="",
                                        parse_error=str(e))
                continue
            files[rel] = SourceFile.from_text(rel, text)
    return Tree(files, root=root)


# ------------------------------------------------------------- baseline

def load_baseline(path: str) -> List[str]:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if isinstance(data, dict):
        data = data.get("findings", [])
    if not isinstance(data, list) \
            or not all(isinstance(x, str) for x in data):
        raise ValueError(f"{path}: baseline must be a JSON list of "
                         "finding identities (or {'findings': [...]})")
    return data


def write_baseline(path: str, findings: List[Finding]) -> None:
    ids = sorted({f.identity for f in findings})
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"comment": "graftlint suppression baseline: known "
                              "pre-existing violations, keyed line-free "
                              "(rule::path::message).  Regenerate with "
                              "--write-baseline; shrink it, never grow "
                              "it.",
                   "findings": ids}, fh, indent=2)
        fh.write("\n")


def apply_baseline(findings: List[Finding], baseline: List[str]
                   ) -> List[Finding]:
    """Mark (not drop) baselined findings; callers decide whether
    baselined ones fail the run (--fail-on-new does not)."""
    known = set(baseline)
    for f in findings:
        f.baselined = f.identity in known
    return findings


# ------------------------------------------------ shared AST utilities

def walk_with_parents(tree: ast.AST):
    """Yield (node, ancestors) pairs, ancestors outermost-first."""
    stack: List[ast.AST] = []

    def rec(node):
        yield node, tuple(stack)
        stack.append(node)
        for child in ast.iter_child_nodes(node):
            yield from rec(child)
        stack.pop()

    yield from rec(tree)


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute(Name('jax'), 'jit'); None for anything
    that is not a plain dotted name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
