"""schema-emission: every emitted record matches obs/schema.py — statically.

``obs.schema.validate_record`` already rejects drift at RUNTIME, but
only on code paths a test actually drives; an emitter call site behind
a rarely-taken branch can ship a field the schema never learned about,
forking the JSONL contract for every downstream tool.  This rule checks
the EMITTING SOURCE against the schema tables:

- find every dict literal carrying ``"record": "<type>"`` in the
  package and the CLI scripts (the supervisor's hard-coded records
  included — its jax-free contract forbids importing the schema, not
  matching it);
- collect the statically-knowable field set: the literal's keys, plus
  later constant-key ``rec["field"] = ...`` assignments on the same
  variable in the same function (including keys bound by a ``for key in
  ("a", "b")`` loop over a constant tuple);
- unknown record types and fields absent from REQUIRED ∪ OPTIONAL are
  violations — a new field cannot ship without a schema bump;
- missing REQUIRED fields are violations unless the dict is built
  dynamically (``**`` expansion, non-constant subscript key that the
  loop resolution can't bind, or ``.update(...)`` with a non-literal
  argument) — dynamic builders degrade to the unknown-field check only.

The schema tables are read by AST from obs/schema.py, not imported:
the linter stays jax-free and needs no package on sys.path.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, SourceFile, Tree, walk_with_parents

RULE = "schema-emission"

SCHEMA_PATH = "apex_example_tpu/obs/schema.py"


def load_schema_fields(tree: Tree) -> Optional[Dict[str, Tuple[Set[str],
                                                               Set[str]]]]:
    """record type -> (required field names, optional field names),
    parsed from the REQUIRED/OPTIONAL table literals."""
    sf = tree.files.get(SCHEMA_PATH)
    if sf is None or sf.tree is None:
        return None
    tables: Dict[str, Dict[str, Set[str]]] = {}
    for node in ast.walk(sf.tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id in ("REQUIRED",
                                                    "OPTIONAL") \
                    and isinstance(value, ast.Dict):
                table: Dict[str, Set[str]] = {}
                for k, v in zip(value.keys, value.values):
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str) \
                            and isinstance(v, ast.Dict):
                        table[k.value] = {
                            fk.value for fk in v.keys
                            if isinstance(fk, ast.Constant)
                            and isinstance(fk.value, str)}
                tables[t.id] = table
    if "REQUIRED" not in tables:
        return None
    out: Dict[str, Tuple[Set[str], Set[str]]] = {}
    for rectype, req in tables["REQUIRED"].items():
        opt = tables.get("OPTIONAL", {}).get(rectype, set())
        out[rectype] = (req, opt)
    return out


def _constant_loop_bindings(func: ast.AST) -> Dict[str, Set[str]]:
    """Loop variables bound over a literal tuple/list of constants:
    ``for key in ("grad_norm", "lr"):`` -> {'key': {...}}.  Tuple
    targets over tuples of constant tuples bind each element name
    (``for attr, field in (("a", "b"), ...)``)."""
    out: Dict[str, Set[str]] = {}
    for node in ast.walk(func):
        if not isinstance(node, (ast.For, ast.AsyncFor)):
            continue
        it = node.iter
        if not isinstance(it, (ast.Tuple, ast.List)):
            continue
        if isinstance(node.target, ast.Name):
            vals = {e.value for e in it.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
            if vals and len(vals) == len(it.elts):
                out.setdefault(node.target.id, set()).update(vals)
        elif isinstance(node.target, ast.Tuple) and all(
                isinstance(n, ast.Name) for n in node.target.elts):
            width = len(node.target.elts)
            rows = []
            for e in it.elts:
                if isinstance(e, (ast.Tuple, ast.List)) \
                        and len(e.elts) == width and all(
                            isinstance(x, ast.Constant)
                            and isinstance(x.value, str)
                            for x in e.elts):
                    rows.append([x.value for x in e.elts])
                else:
                    rows = []
                    break
            for i, name_node in enumerate(node.target.elts):
                if rows:
                    out.setdefault(name_node.id, set()).update(
                        r[i] for r in rows)
    return out


class _Emission:
    def __init__(self, rectype: str, line: int):
        self.rectype = rectype
        self.line = line
        self.fields: Set[str] = set()
        self.dynamic = False


def _dict_literal_keys(d: ast.Dict) -> Tuple[Set[str], bool]:
    keys: Set[str] = set()
    dynamic = False
    for k in d.keys:
        if k is None:                      # ** expansion
            dynamic = True
        elif isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.add(k.value)
        else:
            dynamic = True
    return keys, dynamic


def _record_type(d: ast.Dict) -> Optional[str]:
    for k, v in zip(d.keys, d.values):
        if isinstance(k, ast.Constant) and k.value == "record" \
                and isinstance(v, ast.Constant) \
                and isinstance(v.value, str):
            return v.value
    return None


def _collect_emissions(sf: SourceFile) -> List[_Emission]:
    emissions: List[_Emission] = []
    # Scope = innermost function (or module).  For each record dict
    # literal, note the variable it is assigned to (if any), then fold
    # in later static subscript assignments in the same scope.
    for node, ancestors in walk_with_parents(sf.tree):
        if isinstance(node, ast.Dict):
            rectype = _record_type(node)
            if rectype is None:
                continue
            em = _Emission(rectype, node.lineno)
            keys, dynamic = _dict_literal_keys(node)
            em.fields |= keys
            em.dynamic |= dynamic
            scope = next((a for a in reversed(ancestors)
                          if isinstance(a, (ast.FunctionDef,
                                            ast.AsyncFunctionDef,
                                            ast.Lambda))), sf.tree)
            var = _assigned_name(node, ancestors)
            if var:
                _fold_subscript_assigns(scope, var, em)
            elif not _is_direct_emit(node, ancestors):
                # dict built inline into a larger expression we don't
                # track (e.g. returned then mutated by the caller):
                # keep the unknown-field check, skip missing-required.
                em.dynamic = True
            emissions.append(em)
    return emissions


def _assigned_name(d: ast.Dict, ancestors) -> Optional[str]:
    if not ancestors:
        return None
    parent = ancestors[-1]
    if isinstance(parent, ast.Assign) and parent.value is d:
        for t in parent.targets:
            if isinstance(t, ast.Name):
                return t.id
    if isinstance(parent, ast.AnnAssign) and parent.value is d \
            and isinstance(parent.target, ast.Name):
        return parent.target.id
    return None


def _is_direct_emit(d: ast.Dict, ancestors) -> bool:
    """True when the literal is consumed whole (a call argument or a
    return value built in place that nobody mutates afterwards)."""
    if not ancestors:
        return False
    parent = ancestors[-1]
    return isinstance(parent, (ast.Call, ast.Return, ast.Expr))


def _rebind_linenos(scope: ast.AST, var: str, after: int) -> int:
    """First line after ``after`` where ``var`` is rebound to a new
    value — the end of the current binding's live range.  Field
    assignments past a rebinding belong to a DIFFERENT record and must
    not contaminate this one (review regression: two records sharing a
    variable name in one function)."""
    nxt = float("inf")
    for node in ast.walk(scope):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == var \
                        and node.lineno > after:
                    nxt = min(nxt, node.lineno)
    return nxt


def _fold_subscript_assigns(scope: ast.AST, var: str,
                            em: _Emission) -> None:
    loops = _constant_loop_bindings(scope)
    until = _rebind_linenos(scope, var, em.line)
    for node in ast.walk(scope):
        if not (em.line <= getattr(node, "lineno", em.line) < until):
            continue
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == var:
                    key = t.slice
                    if isinstance(key, ast.Constant) \
                            and isinstance(key.value, str):
                        em.fields.add(key.value)
                    elif isinstance(key, ast.Name) \
                            and key.id in loops:
                        em.fields |= loops[key.id]
                    else:
                        em.dynamic = True
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "update" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == var:
            merged = False
            if len(node.args) == 1 and not node.keywords \
                    and isinstance(node.args[0], ast.Dict):
                keys, dynamic = _dict_literal_keys(node.args[0])
                em.fields |= keys
                em.dynamic |= dynamic
                merged = True
            if node.keywords and all(kw.arg for kw in node.keywords):
                em.fields |= {kw.arg for kw in node.keywords}
                merged = True
            if not merged:
                em.dynamic = True


def check(tree: Tree) -> List[Finding]:
    schema = load_schema_fields(tree)
    if schema is None:
        return [Finding(RULE, SCHEMA_PATH, 0,
                        "cannot load REQUIRED/OPTIONAL tables from the "
                        "schema module — schema-emission checks skipped")]
    findings: List[Finding] = []
    for path, sf in sorted(tree.files.items()):
        if sf.tree is None or path == SCHEMA_PATH:
            continue
        for em in _collect_emissions(sf):
            if em.rectype not in schema:
                if not sf.suppressed(RULE, em.line):
                    findings.append(Finding(
                        RULE, path, em.line,
                        f"unknown record type '{em.rectype}' "
                        "(not declared in obs/schema.py)"))
                continue
            required, optional = schema[em.rectype]
            known = required | optional
            for fieldname in sorted(em.fields - known):
                if not sf.suppressed(RULE, em.line):
                    findings.append(Finding(
                        RULE, path, em.line,
                        f"record '{em.rectype}' emits field "
                        f"'{fieldname}' that obs/schema.py does not "
                        "declare — bump the schema before shipping it"))
            if not em.dynamic:
                for fieldname in sorted(required - em.fields):
                    if not sf.suppressed(RULE, em.line):
                        findings.append(Finding(
                            RULE, path, em.line,
                            f"record '{em.rectype}' never sets required "
                            f"field '{fieldname}'"))
    return findings
