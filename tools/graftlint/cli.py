"""graftlint CLI: the two-stratum static gate.

    # source stratum over the checkout (the CI gate):
    python -m tools.graftlint --fail-on-new

    # human inspection, baseline management:
    python -m tools.graftlint [paths...] [--json]
    python -m tools.graftlint --write-baseline

    # HLO stratum over a saved lowering:
    python -m tools.graftlint --hlo step.mlir --policy bf16
    python -m tools.graftlint --hlo-diff first.mlir second.mlir

Exit codes: 0 clean (under ``--fail-on-new``: no finding outside the
baseline), 1 findings (or a structural divergence for ``--hlo-diff``),
2 usage / unreadable input.  ``--json`` emits one machine-readable
object for either stratum.

The default baseline is ``tools/graftlint/baseline.json`` — checked in,
line-free keys, and EMPTY at HEAD: every violation the rules found when
they landed was fixed in the same PR (ISSUE 9).  ``--write-baseline``
regenerates it; the only legitimate reason for it to grow is importing
a violation wholesale from an upstream merge, and then it should shrink
again in the next PR.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from . import hlo as hlo_rules
from . import hostsync, imports, locks, schema_rules
from .base import (Finding, Tree, apply_baseline, load_baseline,
                   load_tree, repo_root, write_baseline)

SOURCE_RULES = (imports.check, hostsync.check, locks.check,
                schema_rules.check)


def default_baseline_path() -> str:
    return os.path.join(repo_root(), "tools", "graftlint",
                        "baseline.json")


def run_source_lint(tree: Optional[Tree] = None) -> List[Finding]:
    """Every source-stratum rule over a loaded tree (the whole checkout
    by default).  Parse failures surface as findings, and the broken
    files are skipped by the rules rather than crashing them."""
    tree = tree if tree is not None else load_tree()
    findings: List[Finding] = list(tree.parse_findings())
    for rule in SOURCE_RULES:
        findings.extend(rule(tree))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


def _filter_paths(findings: List[Finding],
                  paths: List[str]) -> List[Finding]:
    if not paths:
        return findings
    root = repo_root()
    rel = []
    for p in paths:
        ap = os.path.abspath(p)
        rel.append(os.path.relpath(ap, root).replace(os.sep, "/")
                   if ap.startswith(root) else p.replace(os.sep, "/"))
    return [f for f in findings
            if any(f.path == r or f.path.startswith(r.rstrip("/") + "/")
                   for r in rel)]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="repo-custom two-stratum static analysis "
                    "(source AST rules + lowered-HLO lint)")
    ap.add_argument("paths", nargs="*",
                    help="restrict REPORTED findings to these files/"
                         "directories (rules still see the whole tree)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: tools/graftlint/"
                         "baseline.json when present)")
    ap.add_argument("--fail-on-new", action="store_true",
                    help="exit 0 when every finding is in the baseline "
                         "(the CI gate semantics)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings to the baseline "
                         "file and exit 0")
    ap.add_argument("--hlo", metavar="FILE",
                    help="lint one StableHLO text file instead of the "
                         "source tree")
    ap.add_argument("--policy", default="bf16",
                    choices=sorted(hlo_rules.WIDE) + ["none"],
                    help="--hlo: the AMP compute dtype the program "
                         "should honor (default bf16; 'none' skips the "
                         "upcast rule)")
    ap.add_argument("--allow-host-transfer", action="store_true",
                    help="--hlo: skip the host-transfer rule")
    ap.add_argument("--expect-unsharded", action="store_true",
                    help="--hlo: additionally flag custom_call "
                         "@Sharding (single-device step programs)")
    ap.add_argument("--hlo-diff", nargs=2, metavar=("A", "B"),
                    help="name the first divergent op between two "
                         "lowerings of the same step (exit 1 when they "
                         "diverge)")
    args = ap.parse_args(argv)

    if args.hlo_diff:
        return _run_hlo_diff(args)
    if args.hlo:
        return _run_hlo(args)
    return _run_source(args)


def _read(path: str) -> Optional[str]:
    try:
        with open(path, encoding="utf-8") as fh:
            return fh.read()
    except OSError as e:
        print(f"graftlint: cannot read {path}: {e}", file=sys.stderr)
        return None


def _run_hlo(args) -> int:
    text = _read(args.hlo)
    if text is None:
        return 2
    findings = hlo_rules.lint_hlo_text(
        text, path=args.hlo,
        compute_dtype=None if args.policy == "none" else args.policy,
        expect_no_host_transfer=not args.allow_host_transfer,
        allow_sharding=not args.expect_unsharded)
    return _report(args, findings)


def _run_hlo_diff(args) -> int:
    a, b = (_read(p) for p in args.hlo_diff)
    if a is None or b is None:
        return 2
    diff = hlo_rules.diff_lowerings(a, b)
    if args.json:
        print(json.dumps({"identical": diff is None, "diff": diff}))
    elif diff is None:
        print("lowerings are structurally identical (a recompile of "
              "this pair is a cache failure, not a program change)")
    else:
        print(diff["summary"])
    return 0 if diff is None else 1


def _run_source(args) -> int:
    findings = run_source_lint()

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        # Always write the WHOLE tree's findings: writing a
        # path-filtered subset would silently drop every baselined
        # violation outside the filter and fail the next CI run.
        write_baseline(baseline_path, findings)
        print(f"graftlint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0
    findings = _filter_paths(findings, args.paths)
    baseline: List[str] = []
    if args.baseline or os.path.isfile(baseline_path):
        try:
            baseline = load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"graftlint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
    apply_baseline(findings, baseline)

    new = [f for f in findings if not f.baselined]
    failing = new if args.fail_on_new else findings
    return _report(args, findings, failing)


def _report(args, findings: List[Finding],
            failing: Optional[List[Finding]] = None) -> int:
    if failing is None:
        failing = findings
    if args.json:
        print(json.dumps({
            "findings": [f.as_json() for f in findings],
            "new": sum(1 for f in findings if not f.baselined),
            "baselined": sum(1 for f in findings if f.baselined),
            "failed": bool(failing)}))
    else:
        for f in findings:
            print(f.render())
        n_base = sum(1 for f in findings if f.baselined)
        tail = f" ({n_base} baselined)" if n_base else ""
        print(f"graftlint: {len(findings)} finding(s){tail}")
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
