"""The lowered-HLO stratum: lint what XLA was actually asked to compile.

Source rules see what we WROTE; the StableHLO text ``obs/costmodel.py``
already produces per instrumented step function (``lowered.as_text()``)
shows what the tracer actually BUILT — dtype promotion, sharding
custom-calls and host transfers all appear here first, before any
runtime cost is paid ("Operator Fusion in XLA: Analysis and
Evaluation" motivates reading fusion/dtype structure off the compiled
graph; PAPERS.md).  Everything in this module is TEXT analysis — no
jax import, so the rules run over checked-in fixture lowerings and
over live ``--cost-model`` captures alike.

Rules:

- **upcast-leak** — wide-dtype (f32/f64) ``dot_general`` /
  ``convolution`` ops in a program whose AMP policy says compute runs
  in bf16/f16.  One leaked convert on an activation path silently
  doubles the MXU and HBM cost of every downstream matmul; the f32 op
  in the lowering is the first observable symptom.  The ``int8``
  policy is the CLAIMED-INT8 REGION mode (ISSUE 13): quantized
  programs must dequantize to the half compute dtype, so a dequant
  that pins a matmul in f32 fails the same rule (fixture pair
  tests/fixtures/hlo/int8_clean.mlir / int8_f32_leak.mlir).
- **host-transfer-in-step** — ``infeed`` / ``outfeed`` / ``send`` /
  ``recv`` (and optionally ``custom_call @Sharding``) inside a step
  program that is expected to be a pure device computation: a host
  round-trip per step caps throughput at PCIe/ICI latency.
- **recompile-cause diff** — given two lowerings of the SAME step name
  (``compile_counts`` > 1), name the first structurally divergent op.
  This turns the ``--fail-on-recompile`` tally into a diagnosis:
  obs/costmodel.py calls :func:`diff_lowerings` when it sees a repeat
  compile and ships the result as ``recompile_cause`` on the second
  ``compile_event`` record (schema v8).
"""

from __future__ import annotations

import difflib
import re
from typing import Dict, List, Optional

from .base import Finding

RULE_UPCAST = "hlo-upcast-leak"
RULE_HOST = "hlo-host-transfer"

# `%3 = stablehlo.dot_general %1, %2 ...` and the generic
# `%3 = "stablehlo.dot_general"(%1, %2) ...` form.
_OP = re.compile(r'=\s*"?(?:stablehlo|mhlo|chlo)\.([A-Za-z_][\w]*)"?')
# Uppercase allowed after the first char: MLIR spells fp8 types
# f8E4M3FN / f8E5M2 (the claimed-int8 mode accepts them as quantized
# storage alongside i8).
_TENSOR_DTYPE = re.compile(r"tensor<(?:[0-9x?*\[\],]+x)?"
                           r"([a-z][a-zA-Z0-9]*)(?:[,>])")
_CUSTOM_TARGET = re.compile(r'custom_call\s*@(\w+)'
                            r'|call_target_name\s*=\s*"(\w+)"')
_SSA = re.compile(r"%[\w#.]+")
_LOC = re.compile(r"\s*loc\(.*?\)\s*$")

HEAVY_OPS = {"dot_general", "dot", "convolution", "conv"}
HOST_OPS = {"infeed", "outfeed", "send", "recv"}
# "int8" is the CLAIMED-INT8 REGION mode (ISSUE 13): a program whose
# weights/KV are quantized dequantizes to the bf16/f16 compute dtype
# for the MXU op — scale-fused, so the matmul itself runs half.  A
# dequant that converts UP to f32 instead silently pins the whole
# matmul wide (4x the int8 HBM win gone, plus f32 MXU throughput);
# the f32 dot_general in the lowering is the first observable symptom,
# exactly like the bf16 policy's upcast leak.
WIDE = {"bf16": {"f32", "f64"}, "f16": {"f32", "f64"},
        "f32": {"f64"}, "int8": {"f32", "f64"}}


def ops(text: str):
    """(lineno, opname, line) for every HLO op line."""
    for i, line in enumerate(text.splitlines(), start=1):
        m = _OP.search(line)
        if m:
            yield i, m.group(1), line


def line_dtypes(line: str) -> List[str]:
    return _TENSOR_DTYPE.findall(line)


def upcast_leak(text: str, compute_dtype: str = "bf16",
                path: str = "<hlo>") -> List[Finding]:
    """Wide heavy ops in a reduced-precision program.  ``compute_dtype``
    is the AMP policy's MXU dtype (O1/O2 => bf16 on this repo); "int8"
    is the claimed-int8 region mode — quantized storage, half-dtype
    matmuls, and the claim itself is checked (a region that claims
    int8 but lowers no i8 tensor at all quantized nothing)."""
    wide = WIDE.get(compute_dtype)
    if wide is None:
        raise ValueError(f"unknown compute dtype {compute_dtype!r} "
                         f"(expected one of {sorted(WIDE)})")
    findings: List[Finding] = []
    for lineno, opname, line in ops(text):
        if opname not in HEAVY_OPS:
            continue
        hit = sorted(set(line_dtypes(line)) & wide)
        if hit:
            findings.append(Finding(
                RULE_UPCAST, path, lineno,
                f"{opname} runs in {'/'.join(hit)} inside a "
                f"{compute_dtype} policy region — an upcast leaked "
                "into the MXU path"))
    if compute_dtype == "int8":
        seen = {dt for _, _, line in ops(text)
                for dt in line_dtypes(line)}
        if not any(dt == "i8" or dt.startswith("f8") for dt in seen):
            findings.append(Finding(
                RULE_UPCAST, path, 1,
                "program claims an int8 policy region but lowers no "
                "i8/f8 tensor — quantization was silently skipped"))
    return findings


def host_transfer(text: str, path: str = "<hlo>",
                  allow_sharding: bool = True) -> List[Finding]:
    """Host-transfer ops in a program expected to stay on device.
    ``allow_sharding=False`` additionally flags ``custom_call
    @Sharding`` — a single-device step program has no business carrying
    partitioning annotations (they mean a sharded value escaped into
    the step's trace)."""
    findings: List[Finding] = []
    for lineno, opname, line in ops(text):
        if opname in HOST_OPS:
            findings.append(Finding(
                RULE_HOST, path, lineno,
                f"{opname} inside the step program — a host transfer "
                "per step caps throughput at interconnect latency"))
        elif opname == "custom_call" and not allow_sharding:
            m = _CUSTOM_TARGET.search(line)
            target = (m.group(1) or m.group(2)) if m else None
            if target == "Sharding":
                findings.append(Finding(
                    RULE_HOST, path, lineno,
                    "custom_call @Sharding inside a step expected to "
                    "be unsharded — a partitioned value leaked into "
                    "this trace"))
    return findings


# ------------------------------------------------- recompile-cause diff

# Diffing two multi-MB serve-step lowerings line-by-line is quadratic
# in the worst case; past this size the tally alone has to do.
MAX_DIFF_CHARS = 2_000_000


def _normalize(text: str) -> List[str]:
    """Strip the noise that differs between two compiles of the SAME
    program (SSA value numbering, location info, indentation) so the
    diff surfaces structural divergence only."""
    out = []
    for line in text.splitlines():
        line = _LOC.sub("", line.strip())
        if not line or line.startswith("//"):   # MLIR comments are noise
            continue
        out.append(_SSA.sub("%_", line))
    return out


def diff_lowerings(a: str, b: str) -> Optional[Dict[str, object]]:
    """First structurally divergent op between two lowerings.

    Returns None when the programs are structurally identical (a
    recompile with an identical program is a CACHE failure, not a graph
    change — also worth knowing).  Otherwise a dict with the divergent
    op name, both normalized lines (empty string for pure
    insertion/deletion) and their 0-based indices in the normalized
    listings.
    """
    if len(a) > MAX_DIFF_CHARS or len(b) > MAX_DIFF_CHARS:
        return {"op": None, "a": "", "b": "",
                "index_a": -1, "index_b": -1,
                "summary": "lowerings too large to diff "
                           f"(> {MAX_DIFF_CHARS} chars)"}
    na, nb = _normalize(a), _normalize(b)
    matcher = difflib.SequenceMatcher(a=na, b=nb, autojunk=False)
    for tag, i1, i2, j1, j2 in matcher.get_opcodes():
        if tag == "equal":
            continue
        line_a = na[i1] if i1 < i2 else ""
        line_b = nb[j1] if j1 < j2 else ""
        probe = line_b or line_a
        m = _OP.search(probe)
        op = m.group(1) if m else _first_word(probe)
        summary = f"first divergent op: {op or '?'}"
        if line_a and line_b:
            summary += f" ({_clip(line_a)} vs {_clip(line_b)})"
        elif line_b:
            summary += f" (only in recompile: {_clip(line_b)})"
        else:
            summary += f" (dropped in recompile: {_clip(line_a)})"
        return {"op": op, "a": line_a, "b": line_b,
                "index_a": i1, "index_b": j1, "summary": summary}
    return None


def _first_word(line: str) -> Optional[str]:
    m = re.search(r"[A-Za-z_][\w.]*", line)
    return m.group(0) if m else None


def _clip(line: str, n: int = 120) -> str:
    return line if len(line) <= n else line[: n - 3] + "..."


def lint_hlo_text(text: str, path: str = "<hlo>",
                  compute_dtype: Optional[str] = "bf16",
                  expect_no_host_transfer: bool = True,
                  allow_sharding: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    if compute_dtype:
        findings += upcast_leak(text, compute_dtype, path)
    if expect_no_host_transfer:
        findings += host_transfer(text, path,
                                  allow_sharding=allow_sharding)
    return findings
