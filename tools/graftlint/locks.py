"""lock-discipline: ``# guarded-by: _lock`` annotations, enforced.

The threaded host runtime (serve queue, JSONL sink, stall watchdog,
flight recorder) guards shared state with per-object locks, but nothing
stopped a new method from reading ``self._q`` without taking
``self._lock`` — the resulting race only surfaces as a rare torn read
under load.  This rule makes the guard declarative: an attribute whose
assignment line carries ``# guarded-by: <lockname>`` may only be
touched inside ``with self.<lockname>:`` within its class.

Semantics:

- the annotation line must assign ``self.<attr>`` (normally in
  ``__init__``); the enclosing class owns the contract;
- ``__init__`` itself is exempt (the object is not shared yet);
- every other method's load/store/augassign of ``self.<attr>`` must be
  lexically inside a ``with`` whose context expression is
  ``self.<lockname>``;
- any access to an annotated PRIVATE attribute from outside its class
  (``other._q``) is flagged unconditionally — cross-object pokes at
  guarded state cannot hold the right lock by construction;
- ``# graftlint: ignore[lock-discipline]`` on the access line is the
  per-site escape hatch for single-threaded phases (document why).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from .base import Finding, SourceFile, Tree, walk_with_parents

RULE = "lock-discipline"

_ANNOT = re.compile(r"#\s*guarded-by:\s*(\w+)")
_SELF_ATTR = re.compile(r"self\.(\w+)")


def _annotations(sf: SourceFile) -> Dict[int, Tuple[str, str]]:
    """line -> (attr, lockname) for every guarded-by comment."""
    out: Dict[int, Tuple[str, str]] = {}
    for i, line in enumerate(sf.lines, start=1):
        m = _ANNOT.search(line)
        if not m:
            continue
        attr = _SELF_ATTR.search(line)
        if attr:
            out[i] = (attr.group(1), m.group(1))
    return out


def _class_guards(sf: SourceFile) -> Dict[str, Dict[str, str]]:
    """class name -> {attr: lockname}, by mapping annotation lines into
    class extents."""
    annots = _annotations(sf)
    if not annots:
        return {}
    guards: Dict[str, Dict[str, str]] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef):
            end = getattr(node, "end_lineno", node.lineno)
            for line, (attr, lock) in annots.items():
                if node.lineno <= line <= end:
                    guards.setdefault(node.name, {})[attr] = lock
    return guards


def _holds_lock(ancestors, lockname: str) -> bool:
    for node in ancestors:
        if isinstance(node, ast.With):
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Attribute) \
                        and isinstance(expr.value, ast.Name) \
                        and expr.value.id == "self" \
                        and expr.attr == lockname:
                    return True
    return False


def _check_class(sf: SourceFile, cls: ast.ClassDef,
                 guards: Dict[str, str], findings: List[Finding]) -> None:
    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
            continue
        if method.name == "__init__":
            continue
        for node, ancestors in walk_with_parents(method):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in guards):
                continue
            lock = guards[node.attr]
            if _holds_lock(ancestors, lock):
                continue
            if sf.suppressed(RULE, node.lineno):
                continue
            findings.append(Finding(
                RULE, sf.path, node.lineno,
                f"{cls.name}.{method.name} touches self.{node.attr} "
                f"(guarded-by: {lock}) outside 'with self.{lock}'"))


def check(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    # attr -> (class, path) for the cross-class pass; only private
    # names participate (public guarded attrs would collide with
    # unrelated classes' unannotated fields).
    private_guarded: Dict[str, Tuple[str, str]] = {}
    per_file: List[Tuple[SourceFile, Dict[str, Dict[str, str]]]] = []
    for path, sf in sorted(tree.files.items()):
        if sf.tree is None:
            continue
        guards = _class_guards(sf)
        per_file.append((sf, guards))
        for cls_name, attrs in guards.items():
            for attr in attrs:
                if attr.startswith("_"):
                    private_guarded[attr] = (cls_name, path)

    for sf, guards in per_file:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name in guards:
                _check_class(sf, node, guards[node.name], findings)
        # Cross-class pokes at guarded private state.  Bare attribute
        # names are weak evidence on their own (another class may own
        # an unrelated ``_q``), so the access only fires when the file
        # also references the DECLARING class by name — the cheap
        # static proxy for "this code handles that type".
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Attribute) \
                    and node.attr in private_guarded \
                    and not (isinstance(node.value, ast.Name)
                             and node.value.id == "self"):
                cls_name, decl_path = private_guarded[node.attr]
                if cls_name not in sf.text:
                    continue
                if sf.suppressed(RULE, node.lineno):
                    continue
                findings.append(Finding(
                    RULE, sf.path, node.lineno,
                    f"access to {cls_name}.{node.attr} (guarded-by "
                    f"annotation in {decl_path}) from outside its "
                    "class — no lock can be held here"))
    return findings
