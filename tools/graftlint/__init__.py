"""graftlint — the repo-custom two-stratum static analysis pass.

**Source stratum** (pure ``ast``, never imports the code it checks):

- ``jax-free`` — static transitive import-graph proof that the thin
  clients (tools/*), the auto-resume supervisor and the telemetry
  schema never reach jax (imports.py);
- ``host-sync-in-step`` / ``jit-in-loop`` — device fetches inside
  traced step functions; fresh-hash jit of lambdas/local defs in loops
  (hostsync.py);
- ``lock-discipline`` — ``# guarded-by: _lock`` attributes touched
  outside ``with self._lock`` (locks.py);
- ``schema-emission`` — every emitted record's field set checked
  against obs/schema.py, so a new field cannot ship without a schema
  bump (schema_rules.py).

**HLO stratum** (StableHLO text, hlo.py): ``hlo-upcast-leak``,
``hlo-host-transfer``, and the recompile-cause diff that names the
first divergent op between two lowerings of one step.

CLI: ``python -m tools.graftlint [--fail-on-new] [--json] [paths…]``
(cli.py); ``tools/ci_gate.py`` bundles it with the cost_report
recompile gate into one CI command.  Pure stdlib throughout — the
linter runs wherever the checkout does, jax installed or not.
"""

from .base import (Finding, Tree, load_tree,  # noqa: F401
                   tree_from_sources)
from .cli import main, run_source_lint  # noqa: F401
from .hlo import (diff_lowerings, host_transfer,  # noqa: F401
                  lint_hlo_text, upcast_leak)
