#!/usr/bin/env python
"""Perf probe: apportion ResNet-50 O2 step time across phases on the real chip.

Times, with the same two-point chain method bench.py uses (value fetch as the
only reliable barrier through the remote-TPU tunnel):
  - fwd:       forward loss only
  - fwdbwd:    loss + grad
  - full:      the real train step (grad + allreduce-less + optimizer + scaler)
  - opt:       optimizer apply alone on a fixed grad tree

Usage: python tools/perf_probe.py [--batch-size 256] [--steps 20]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from apex_example_tpu import amp
from apex_example_tpu.data import image_batch
from apex_example_tpu.engine import (create_train_state, make_train_step,
                                     cross_entropy_loss, _apply_model)
from apex_example_tpu.models import resnet50
from apex_example_tpu.optim import FusedSGD


def chain_time(fn, state, n_warm, n1, n2, fetch):
    for _ in range(n_warm):
        state = fn(state)
    fetch(state)
    t0 = time.perf_counter()
    for _ in range(n1):
        state = fn(state)
    fetch(state)
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n2):
        state = fn(state)
    fetch(state)
    t2 = time.perf_counter() - t0
    return (t2 - t1) / (n2 - n1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--image-size", type=int, default=224)
    args = ap.parse_args()

    policy, scaler = amp.initialize("O2")
    model = resnet50(num_classes=1000, dtype=policy.compute_dtype,
                     param_dtype=policy.param_dtype, bn_dtype=policy.bn_dtype)
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)

    batch = image_batch(jnp.asarray(0), batch_size=args.batch_size,
                        image_size=args.image_size, channels=3,
                        num_classes=1000, seed=0)
    batch = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, jax.devices()[0]), batch)
    x, y = batch

    state = create_train_state(jax.random.PRNGKey(0), model, opt,
                               x[:1], policy, scaler)
    n1, n2 = max(args.steps // 5, 1), args.steps
    bs = args.batch_size

    # --- full step ---
    step = jax.jit(make_train_step(model, opt, policy),
                   donate_argnums=(0,))
    full = chain_time(lambda s: step(s, batch)[0], state, 3, n1, n2,
                      lambda s: float(s.step))
    print(f"full step:   {full*1e3:8.2f} ms  ({bs/full:7.1f} img/s)")

    # --- fwd only (train-mode apply + loss; carry loss to chain deps) ---
    def fwd(carry):
        p, s, acc = carry
        logits, new_stats = _apply_model(model, p, s, x, train=True)
        return p, new_stats, acc + cross_entropy_loss(logits, y)
    fwd_j = jax.jit(fwd, donate_argnums=(0,))
    state2 = create_train_state(jax.random.PRNGKey(0), model, opt, x[:1],
                                policy, scaler)
    c0 = (state2.params, state2.batch_stats, jnp.zeros((), jnp.float32))
    tf = chain_time(fwd_j, c0, 3, n1, n2, lambda c: float(c[2]))
    print(f"fwd only:    {tf*1e3:8.2f} ms  ({bs/tf:7.1f} img/s)")

    # --- fwd+bwd (grad, no optimizer) ---
    def fb(carry):
        p, s, acc = carry
        def loss_fn(params):
            logits, new_stats = _apply_model(model, params, s, x, train=True)
            return cross_entropy_loss(logits, y), new_stats
        g, new_stats = jax.grad(loss_fn, has_aux=True)(p)
        # fold grads back so the chain has a data dependence
        p2 = jax.tree_util.tree_map(lambda a, b: a - 0.0 * b, p, g)
        return p2, new_stats, acc + g["fc"]["bias"][0]
    fb_j = jax.jit(fb, donate_argnums=(0,))
    state3 = create_train_state(jax.random.PRNGKey(0), model, opt, x[:1],
                                policy, scaler)
    c0 = (state3.params, state3.batch_stats, jnp.zeros((), jnp.float32))
    tfb = chain_time(fb_j, c0, 3, n1, n2, lambda c: float(c[2]))
    print(f"fwd+bwd:     {tfb*1e3:8.2f} ms  ({bs/tfb:7.1f} img/s)")

    # --- optimizer alone ---
    state4 = create_train_state(jax.random.PRNGKey(0), model, opt, x[:1],
                                policy, scaler)
    grads = jax.tree_util.tree_map(lambda p: jnp.ones_like(p, jnp.float32),
                                   state4.params)

    def opt_only(carry):
        params, opt_state = carry
        return opt.apply(grads, opt_state, params)
    opt_j = jax.jit(opt_only, donate_argnums=(0,))
    c0 = (state4.params, state4.opt_state)
    topt = chain_time(opt_j, c0, 3, n1, n2,
                      lambda c: float(jax.tree_util.tree_leaves(c[0])[0].ravel()[0]))
    print(f"opt only:    {topt*1e3:8.2f} ms")

    print(f"derived bwd: {(tfb-tf)*1e3:8.2f} ms")
    print(f"step - fwdbwd - opt = {(full-tfb-topt)*1e3:8.2f} ms (scaler/misc)")


if __name__ == "__main__":
    main()
