#!/usr/bin/env python
"""Continuous-batching inference CLI (the serving counterpart of train.py).

Restores GPT params from a CheckpointManager checkpoint (template-free —
serving needs no optimizer state) or random-inits for smoke runs, then
drives the slot-based engine (apex_example_tpu/serve/) against a
deterministic synthetic request stream with staggered arrivals.

    # random-init smoke: 16 requests through 4 slots
    python serve.py --requests 16 --slots 4 --metrics-jsonl serve.jsonl

    # serve a trained checkpoint, sampled with per-request top-k
    python serve.py --arch gpt_tiny --checkpoint-dir ckpts \\
        --temperature 0.8 --top-k 40 --metrics-jsonl serve.jsonl

    # overload drill: bursts past the slot count + queue bound shed
    # deterministically, tight virtual deadlines exercise timeouts
    python serve.py --requests 24 --slots 2 --max-pending 4 --burst 12 \\
        --deadline-steps 40 --metrics-jsonl serve.jsonl

    # shared-system-prompt workload: prefix sharing packs the common
    # 16 tokens into refcounted blocks (COW on divergence)
    python serve.py --requests 16 --shared-prefix 16 \\
        --metrics-jsonl serve.jsonl

    # then summarize per-status accounting + latency (jax-free):
    python tools/serve_report.py serve.jsonl

The KV cache is BLOCK-PAGED (ISSUE 8; README "Paged KV cache"):
per-layer arenas of --num-blocks x --block-size token blocks, per-slot
block tables gathered inside the one compiled decode step, chunked
prefill (up to --block-size prompt tokens per tick), and admission by
worst-case block budget — out-of-blocks resolves as deterministic
head-of-line queueing, and a request that could never be served (its
prompt fills the cache) terminates with status "rejected" at admission.

Quantization (ISSUE 13; README "Quantization"): ``--weight-quant
{int8,fp8}`` quantizes the restored weights per-channel at restore
time (dequant runs scale-fused inside the one compiled decode step;
layernorms/biases stay high-precision per amp/lists.py) and
``--kv-quant`` stores the paged KV arenas as int8 with bf16 per-token
block scales — quantize on the scatter write, dequant in the gathered
attention, scales copied with their blocks under COW/prefix sharing.
Geometry stays static, so the program still compiles exactly once;
``serve_summary`` carries ``kv_dtype``/``weight_dtype`` and the
dtype-accurate vs bf16-equivalent per-token bytes (schema v11), and
``tools/ci_gate.py --quant-stream`` enforces the >= 1.9x compression
floor over a recorded stream.

Sharded + disaggregated serving (ISSUE 14; README "Sharded &
disaggregated serving"): ``--mesh dp,tp`` registers a
(data=dp, model=tp) device mesh and serves the Megatron-TP model —
weights and per-layer paged-KV arenas shard over heads on 'model',
block tables and admission stay host-side, the decode program lowers
once with GSPMD shardings, and TP-served greedy output is
token-identical to the dense path (int8 weights/KV included).
``--role prefill`` chunk-prefills prompts, samples each request's
first token and ships its KV blocks (storage-dtype-exact payloads +
scales + fill levels) to the ``--handoff-dir`` spool; ``--role
decode`` admits those payloads into its own arena and decodes with a
[slots, 1]-wide step — so long prompts stop stalling decode ticks.

The spool speaks a LEASED crash-safe protocol (ISSUE 15; README
"Disaggregated serving resilience"): decode workers claim files by
atomic rename and hold a ``--handoff-lease`` wall-clock lease,
ack-by-delete at admission, reclaim a dead peer's expired claims (or
adopt their own pre-crash claims on restart) so handoffs REDELIVER
instead of stranding, detect redeliveries of already-admitted uids
against the engine's seen-set (acked as duplicates, never scattered
twice), quarantine corrupt payloads to ``*.bad`` instead of dying,
and bound the wait for a producer that died sentinel-less
(``--handoff-idle-timeout``).  N decode workers can share one spool.
Both sides emit schema-v13 ``kv_handoff`` records (with
redelivered/duplicate/quarantine provenance) and ``tools/ci_gate.py
--disagg-stream`` checks a recorded deployment for conservation —
redelivery tolerated, exactly-once admission and terminal per uid.
A decode worker composes with the fleet protocol via ``--outbox``
alone (no ``--inbox`` — the spool is its intake); a prefill worker
takes the full inbox/outbox pair.

Resilience (README "Serving resilience"; ISSUE 5): SIGTERM/SIGUSR1
triggers a graceful drain — admission stops, queued requests are handed
back with status "drained" (requeue-able on another replica), in-flight
slots finish or deadline-evict, a ``serve_drain`` record plus the
normal un-aborted ``serve_summary`` close the stream, and the process
exits 75 (EX_TEMPFAIL) so a supervisor (tools/supervise.py --no-resume)
restarts it.  ``--inject-fault {crash,sigterm,hang,nan,slot_fail}@tick``
makes every failure path deterministic; ``--flight-recorder`` keeps
crash forensics for the paths that ARE crashes.

With --metrics-jsonl the run emits schema-v5 records through the obs
sink: a run_header, one ``request_complete`` / ``request_failed`` /
``shed`` per terminated request, an optional ``serve_drain``, and a
closing ``serve_summary`` (throughput, latency percentiles, per-status
counts, availability).  The stream passes tools/metrics_lint.py like
every other obs stream.

Live migration (ISSUE 20; README "Live migration & elastic
pools"): ``--migrate-dir`` arms a second leased spool for MID-FLIGHT
requests.  A SIGTERM drain then ships every live slot — KV blocks
(storage-dtype-exact, int8 + scales included), cursor/fill, generated
tokens and sampler state — to the spool instead of evicting or
requeueing it (status "migrated", outside the availability
denominator), and every tick the engine polls the spool and resumes
any peer's shipped request token-identically (``admit_migrated``
rides the same claim/ack/redelivery/duplicate machinery as the
prefill handoff).  The spool is shared and long-lived: no close
sentinel is ever written, so replicas can come and go.

Fleet replica mode (ISSUE 12; README "Fleet serving & chaos
scenarios"): ``--inbox``/``--outbox`` replace the synthetic workload
with the file-based fleet protocol — a router (fleet.py /
apex_example_tpu/fleet/) APPENDS request specs to the inbox and this
process APPENDS one terminal line per request to the outbox.  Both
files are append-only and replayed across supervised restarts: a
restarted attempt re-reads the whole inbox and skips every uid already
in the outbox, so a crash re-serves exactly the requests that never
reached a terminal status (crash-safe exactly-once).  A
``{"close": true}`` sentinel ends the stream (exit 0).  With
``--metrics-jsonl`` the replica also heartbeats schema-v10
``replica_state`` records (tick / queue depth / blocks_live / pid) the
router tails for health and its ``least_kv`` policy.
``--seed-substream I`` derives replica i's synthetic workload from
``substream(seed, i)`` so standalone fleet members sharing one base
seed serve disjoint, individually-deterministic streams.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from collections import deque


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="continuous-batching GPT inference")
    p.add_argument("--arch", default="gpt_tiny",
                   choices=["gpt_tiny", "gpt_base"])
    p.add_argument("--checkpoint-dir", default=None,
                   help="CheckpointManager directory to restore params "
                        "from (omit = random init, smoke mode)")
    p.add_argument("--checkpoint-step", type=int, default=None,
                   help="checkpoint step (default: latest)")
    p.add_argument("--slots", type=int, default=4,
                   help="KV-cache slot count (the max decode batch)")
    p.add_argument("--max-len", type=int, default=None,
                   help="per-slot cache length (default: the model's "
                        "position table, capped at 128 for gpt_tiny)")
    p.add_argument("--block-size", type=int, default=8,
                   help="KV arena block granularity in tokens: chunked "
                        "prefill feeds up to this many prompt tokens "
                        "per tick, and prefix sharing/allocation happen "
                        "per block (serve/slots.py)")
    p.add_argument("--num-blocks", type=int, default=None,
                   help="KV arena size in blocks per layer (default: "
                        "slots * ceil(max_len / block_size) — the dense "
                        "layout's capacity; admission reserves each "
                        "request's worst-case block budget against it)")
    p.add_argument("--requests", type=int, default=16,
                   help="synthetic request count")
    p.add_argument("--prompt-len", default="4:12",
                   help="prompt length, N or MIN:MAX tokens")
    p.add_argument("--shared-prefix", type=int, default=0,
                   help="prepend one common N-token system prompt to "
                        "every request (drawn once per seed) — the "
                        "prefix-sharing workload: shared KV blocks are "
                        "computed once and refcounted, measurable in "
                        "serve_summary's prefix_hit_rate/cow_copies")
    p.add_argument("--repetitive", action="store_true",
                   help="templated workload: each prompt tiles a short "
                        "per-request motif to its sampled length "
                        "(deterministic per seed) — self-repeating "
                        "spans the prompt-lookup drafter can exploit, "
                        "the honest traffic shape for --speculate "
                        "acceptance measurements")
    p.add_argument("--max-new", default="4:16",
                   help="output budget, N or MIN:MAX tokens")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy, >0 samples")
    p.add_argument("--top-k", type=int, default=0,
                   help="restrict sampling to the k highest logits "
                        "(0 = full softmax)")
    p.add_argument("--eos-id", type=int, default=None,
                   help="token id that ends a request early")
    p.add_argument("--stagger", type=int, default=2,
                   help="virtual engine steps between request arrivals "
                        "(0 = all arrive at once)")
    p.add_argument("--burst", type=int, default=1,
                   help="arrivals per wave: B requests land together "
                        "every --stagger ticks (deterministic overload "
                        "mode; 1 = the classic one-by-one stagger)")
    p.add_argument("--tenants", default=None, metavar="SPEC",
                   help="multi-tenant scheduling (ISSUE 19): arm "
                        "deficit-weighted round-robin admission over "
                        "per-tenant lanes instead of FIFO.  SPEC is "
                        "';'-separated clauses "
                        "name[:weight=W,budget=TOKENS,class="
                        "interactive|batch,mix=M,burst=B,"
                        "shared_prefix=P] — weight shapes the DWRR "
                        "share, budget caps admitted tokens (over-"
                        "budget requests park, then reject at drain), "
                        "interactive lanes preempt batch admission; "
                        "mix/burst/shared_prefix shape the synthetic "
                        "workload per tenant (sched/tenants.py)")
    p.add_argument("--advertise-prefixes", type=int, default=0,
                   metavar="N",
                   help="replica mode: advertise the N hottest prefix "
                        "chain-key digests + raw prefix-reuse counters "
                        "in replica_state heartbeats (schema v17) — "
                        "what the fleet router's prefix_affinity "
                        "policy routes on (0 = off, heartbeats "
                        "unchanged)")
    p.add_argument("--max-pending", type=int, default=None,
                   help="admission control: bound on the arrived request "
                        "backlog; overflow is shed deterministically "
                        "(default: unbounded)")
    p.add_argument("--shed-policy", default="newest",
                   choices=["newest", "oldest"],
                   help="which side of the backlog to shed on overflow "
                        "(newest = reject incoming, the default)")
    p.add_argument("--deadline-steps", type=int, default=None,
                   help="per-request deadline in engine ticks after "
                        "arrival (deterministic; expires queued requests "
                        "without admitting and evicts decoding slots "
                        "mid-flight)")
    p.add_argument("--deadline-s", type=float, default=None,
                   help="per-request wall-clock TTL from arrival")
    p.add_argument("--steps", type=int, default=0,
                   help="engine tick cap (0 = run until drained)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--seed-substream", type=int, default=None,
                   metavar="I",
                   help="derive the workload seed as substream(seed, I) "
                        "— fleet members sharing a base seed serve "
                        "disjoint yet deterministic prompt sets "
                        "(serve/loadgen.py)")
    p.add_argument("--inbox", default=None, metavar="JSONL",
                   help="fleet replica mode: serve request specs "
                        "APPENDED to this file by a router instead of "
                        "the synthetic workload; replayed from byte 0 "
                        "on every supervised restart; a "
                        "'{\"close\": true}' line ends the stream")
    p.add_argument("--outbox", default=None, metavar="JSONL",
                   help="fleet replica mode: append one terminal line "
                        "per request (uid/status/tokens); append-only "
                        "across restarts — the restart-skip set and "
                        "the router's completion feed")
    p.add_argument("--replica-id", default="replica",
                   help="this replica's name in heartbeat and fleet "
                        "records")
    p.add_argument("--heartbeat-s", type=float, default=0.25,
                   metavar="S",
                   help="replica-mode health heartbeat period: a "
                        "schema-v10 replica_state record (tick, queue "
                        "depth, blocks_live, pid) every S seconds on "
                        "the metrics stream")
    p.add_argument("--mesh", default=None, metavar="DP,TP",
                   help="serve TP-sharded: register a (data=DP, "
                        "model=TP) device mesh — weights and per-layer "
                        "paged-KV arenas shard over heads on 'model' "
                        "(the training TP layout), block tables and "
                        "admission stay host-side; the decode program "
                        "compiles once with GSPMD shardings and greedy "
                        "output stays token-identical to the dense "
                        "path.  Needs DP*TP visible devices (virtual "
                        "CPU devices via XLA_FLAGS="
                        "--xla_force_host_platform_device_count=N)")
    p.add_argument("--role", default="both",
                   choices=["both", "prefill", "decode"],
                   help="disaggregated serving (serve/disagg.py): "
                        "'prefill' chunk-prefills prompts, samples each "
                        "request's first token and ships its KV blocks "
                        "to --handoff-dir; 'decode' admits those "
                        "payloads and decodes with a [slots, 1]-wide "
                        "step (no prefill lanes); 'both' is the classic "
                        "interleaved engine")
    p.add_argument("--handoff-dir", default=None, metavar="DIR",
                   help="KV-handoff spool directory connecting a "
                        "--role prefill process to one or more --role "
                        "decode processes (atomic npz files claimed by "
                        "lease + a close sentinel; serve/disagg.py)")
    p.add_argument("--handoff-lease", type=float, default=30.0,
                   metavar="S",
                   help="decode role: wall-clock lease on each claimed "
                        "spool file — a claim whose holder dies is "
                        "reclaimed by any peer after S seconds and the "
                        "handoff redelivered (default 30)")
    p.add_argument("--handoff-idle-timeout", type=float, default=None,
                   metavar="S",
                   help="decode role: exit after S idle seconds when "
                        "the spool never closes (the producer died "
                        "before writing the sentinel) instead of "
                        "waiting forever (default: wait)")
    p.add_argument("--migrate-dir", default=None, metavar="DIR",
                   help="live-migration spool (ISSUE 20; --role both "
                        "only): a SIGTERM drain ships every in-flight "
                        "request's KV blocks + cursor + generated "
                        "tokens here instead of evicting it, and every "
                        "tick this replica polls the spool and resumes "
                        "peers' shipped requests token-identically "
                        "(leased claim/ack/redelivery, same protocol "
                        "as --handoff-dir; --handoff-lease sets the "
                        "lease).  Shared + long-lived: no close "
                        "sentinel is written")
    p.add_argument("--weight-quant", default="none",
                   choices=["none", "int8", "fp8"],
                   help="quantize the restored weights for serving "
                        "(ISSUE 13): symmetric per-channel int8, or "
                        "float8_e4m3 where this jax supports it (else "
                        "emulated on the e4m3 grid); layernorms/biases "
                        "stay high-precision per the AMP op tables "
                        "(amp/lists.py) and dequant runs scale-fused "
                        "inside the one compiled decode step")
    p.add_argument("--kv-quant", action="store_true",
                   help="store the paged KV arenas as int8 with bf16 "
                        "per-token block scales: quantize on the "
                        "scatter write, dequantize in the gathered "
                        "attention, scales copied with their blocks "
                        "under COW/prefix sharing (quant/kv.py) — "
                        "~1.9x the bf16 arena's bytes, ~3.9x fp32's")
    p.add_argument("--speculate", type=int, default=0, metavar="K",
                   help="speculative decoding (ISSUE 18): a host-side "
                        "proposer drafts up to K tokens per greedy slot "
                        "per tick and the engine verifies all lanes in "
                        "ONE [SLOTS, max(block_size, K+1)]-wide "
                        "dispatch, accepting the longest draft prefix "
                        "matching the model's own argmax — greedy "
                        "outputs stay token-identical to generate() "
                        "while tokens/tick rises above 1.0; rejected "
                        "lanes roll back for free (the cursor simply "
                        "does not advance).  0 = off, bit-identical to "
                        "the plain path")
    p.add_argument("--draft", default="ngram",
                   choices=["ngram", "none"],
                   help="draft proposer for --speculate: 'ngram' "
                        "matches the last N generated tokens against "
                        "the request's own prompt + history (no second "
                        "model); 'none' never drafts — the off-switch "
                        "that keeps the speculative program armed but "
                        "degenerates every tick to single-lane decode")
    p.add_argument("--draft-ngram", type=int, default=3, metavar="N",
                   help="match-window length for --draft ngram "
                        "(longest window tried first, falling back to "
                        "shorter suffixes)")
    p.add_argument("--metrics-jsonl", default=None,
                   help="emit schema-valid serving records to this JSONL")
    p.add_argument("--trace", action="store_true",
                   help="with --metrics-jsonl: emit schema-v9 "
                        "trace_event records — per-tick admit/dispatch/"
                        "harvest spans and a per-request lifecycle span "
                        "tree (queued -> prefill chunks -> first_token "
                        "-> decode -> terminal status) — exportable to "
                        "Perfetto via tools/trace_export.py; host-only, "
                        "the compiled decode step is untouched "
                        "(README 'Request tracing')")
    p.add_argument("--cost-model", action="store_true",
                   help="with --metrics-jsonl: AOT-compile the slot "
                        "decode step and emit schema-v6 compile_event + "
                        "cost_model records (per-tick decode flops/HBM "
                        "bytes + roofline verdict; obs/costmodel.py — "
                        "the decode program still compiles exactly once)")
    p.add_argument("--slo", default=None, metavar="SPEC",
                   help="with --metrics-jsonl: arm the streaming SLO "
                        "plane (ISSUE 16) — a comma list like "
                        "'ttft_ms=250,tpot_ms=40,availability=0.999'. "
                        "Terminal requests are scored good/bad against "
                        "the latency targets, folded into online "
                        "quantile sketches and tumbling windows, and "
                        "each window emits a schema-v14 slo_window "
                        "record (p50/p90/p99, counts, error-budget "
                        "burn rate) plus an slo_breach record when the "
                        "burn rate exceeds 1.0; serve_summary carries "
                        "the cumulative verdict (README 'SLO "
                        "monitoring').  Host-only: the compiled decode "
                        "step is untouched")
    p.add_argument("--slo-window-s", type=float, default=None,
                   metavar="S",
                   help="tumbling SLO window length in wall-clock "
                        "seconds (default 1.0); windows with no "
                        "terminal events are skipped, not emitted")
    p.add_argument("--slo-window-ticks", type=int, default=0,
                   metavar="N",
                   help="close SLO windows every N engine ticks "
                        "instead of on wall-clock — the deterministic "
                        "mode tests and recorded fixtures use "
                        "(0 = wall-clock windows)")
    p.add_argument("--tick-profile", action="store_true",
                   help="with --metrics-jsonl: arm the hot-path tick "
                        "profiler (obs/tickprof.py, ISSUE 17) — every "
                        "compute tick decomposes into admit / "
                        "dispatch_enqueue / device_wait (an explicit "
                        "block-until-ready boundary, the first time "
                        "enqueue cost and device execution are "
                        "separable) / harvest / spool_io / telemetry, "
                        "folded into online quantile sketches; every "
                        "Nth tick emits a schema-v15 tick_profile "
                        "record and the run closes with an "
                        "overhead_summary (host_gap_ms, per-phase "
                        "percentiles, host_overhead_frac — what "
                        "tools/perf_ledger.py regression-gates).  "
                        "Value-preserving and compile-free: greedy "
                        "outputs stay token-identical and no new "
                        "program compiles (README 'Hot-path "
                        "profiling')")
    p.add_argument("--tick-profile-every", type=int, default=16,
                   metavar="N",
                   help="emit a tick_profile record every N compute "
                        "ticks (default 16; the cumulative "
                        "overhead_summary always folds EVERY tick)")
    p.add_argument("--inject-fault", default="", metavar="KIND@TICK",
                   help="deterministic serve-path fault drill at a "
                        "1-based engine tick: crash | sigterm | hang | "
                        "nan | slot_fail (resilience/faults.py; sigterm "
                        "exercises the drain path, slot_fail the "
                        "slot-isolation path).  Handoff drills (the "
                        "disagg resilience path, @N = the Nth "
                        "send/admit): handoff_torn | sentinel_lost on "
                        "a --role prefill process, "
                        "handoff_crash_preack | handoff_dup on a "
                        "--role decode process")
    p.add_argument("--flight-recorder", action="store_true",
                   help="arm crash forensics (obs/flight.py): abnormal "
                        "exits write a crash_dump + aborted summary to "
                        "the metrics stream; SIGTERM stays with the "
                        "drain handler (release_signal handover)")
    p.add_argument("--no-drain", action="store_true",
                   help="do not catch SIGTERM/SIGUSR1 for graceful "
                        "drain (signals then kill the process as before)")
    return p


class _Outbox:
    """The replica-side completion outbox: APPEND-only (it must survive
    supervised restarts — truncation would forget what attempt K-1
    already served), one JSON line per terminal request.  On startup it
    replays itself into the inbox feeder's skip logic (crash-safe
    exactly-once):

    - a NON-drained terminal ends the uid for good — every later inbox
      occurrence is skipped;
    - a "drained" line consumed ONE inbox occurrence without serving it
      (the router requeued that copy — possibly to a sibling, possibly
      back to THIS replica as a fresh inbox line when it is the only
      survivor), so exactly that many occurrences are skipped and the
      next one is served.  Treating drained as terminal would silently
      lose requeue-to-self requests after a restart."""

    def __init__(self, path: str):
        self.path = path
        self.done = set()
        self._drained: dict = {}        # uid -> unconsumed drain count
        if os.path.exists(path):
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError:
                        continue        # a killed writer's torn tail
                    if isinstance(ev, dict) and "uid" in ev:
                        if ev.get("status") == "drained":
                            self._drained[ev["uid"]] = \
                                self._drained.get(ev["uid"], 0) + 1
                        else:
                            self.done.add(ev["uid"])
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fh = open(path, "a")
        self._consumed = 0

    def should_skip(self, uid: str) -> bool:
        """Called by the inbox feeder once per inbox OCCURRENCE of
        ``uid`` (feeder thread only — no locking needed)."""
        if uid in self.done:
            return True
        n = self._drained.get(uid, 0)
        if n > 0:
            self._drained[uid] = n - 1  # that occurrence was drained
            return True
        return False

    def flush_from(self, engine) -> None:
        comps = engine.completions
        # Redelivery provenance rides the outbox (ISSUE 15): the fleet
        # router's disagg accounting keys on which terminals came from
        # a redelivered handoff admission.
        redelivered = getattr(engine, "handoff_redelivered", ())
        with_tenant = getattr(engine, "sched", None) is not None
        for c in comps[self._consumed:]:
            ev = {"uid": c.request.uid, "status": c.status,
                  "finish_reason": c.finish_reason,
                  "tokens": [int(t) for t in c.tokens],
                  "tick": c.finished_step,
                  "ttft_ms": None if c.ttft_s is None
                  else c.ttft_s * 1e3,
                  "tpot_ms": None if c.tpot_s is None
                  else c.tpot_s * 1e3}
            if with_tenant:
                ev["tenant"] = getattr(c.request, "tenant", "default")
            if c.request.uid in redelivered:
                ev["redelivered"] = True
            self._fh.write(json.dumps(ev, separators=(",", ":")) + "\n")
        self._consumed = len(comps)
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


def _feed_inbox(path, queue, outbox, stop_event, request_cls):
    """Daemon thread: tail the inbox JSONL (which may not exist yet)
    and submit every spec occurrence the outbox replay does not skip
    (``_Outbox.should_skip``).  Only complete lines are consumed — a
    torn tail is retried whole.  Ends on the close sentinel (queue
    closed: the engine loop finishes and exits 0), on a drain closing
    the queue under us, or on ``stop_event``."""
    pos = 0
    while not stop_event.is_set():
        if not os.path.exists(path):
            time.sleep(0.02)
            continue
        with open(path) as fh:
            fh.seek(pos)
            chunk = fh.read()
        consumed = chunk.rfind("\n") + 1
        pos += consumed
        for line in chunk[:consumed].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                spec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(spec, dict):
                continue
            if spec.get("close"):
                queue.close()
                return
            uid = spec.get("uid")
            if uid is None or outbox.should_skip(uid):
                continue
            req = request_cls(
                prompt=spec["prompt"],
                max_new_tokens=int(spec["max_new_tokens"]),
                temperature=float(spec.get("temperature", 0.0)),
                top_k=int(spec.get("top_k", 0)),
                eos_id=spec.get("eos_id"),
                tenant=spec.get("tenant", "default"),
                priority=int(spec.get("priority", 0)),
                deadline_s=spec.get("deadline_s"),
                deadline_step=spec.get("deadline_step"),
                uid=uid)
            try:
                queue.submit(req)
            except RuntimeError:
                return                  # drain closed the queue
        if consumed == 0:
            time.sleep(0.02)


def run_serve(args):
    """Build, restore, drive — and drain gracefully on SIGTERM/SIGUSR1.
    Returns (completions, summary_record, rc) — split from main() so
    tests can assert on the served tokens; rc is 75 (EX_TEMPFAIL) after
    a drain so a supervisor restarts rather than buries the server."""
    import jax
    import jax.numpy as jnp

    from apex_example_tpu import obs
    from apex_example_tpu.models.gpt import gpt_base, gpt_tiny
    from apex_example_tpu.parallel.mesh import (parse_serve_mesh,
                                                serve_mesh)
    from apex_example_tpu.resilience import (EX_TEMPFAIL, FaultPlan,
                                             PreemptionHandler)
    from apex_example_tpu.resilience.faults import (HANDOFF_KINDS,
                                                    SERVE_KINDS)
    from apex_example_tpu.serve import (FileTransport, Request,
                                        RequestQueue, ServeEngine,
                                        parse_range, run_decode_role,
                                        synthetic_requests)
    from apex_example_tpu.transformer import parallel_state
    from apex_example_tpu.utils.checkpoint import restore_params

    mesh = None
    dp = tp = 1
    if args.mesh:
        try:
            dp, tp = parse_serve_mesh(args.mesh)
            if dp * tp > 1:
                mesh = serve_mesh(dp, tp)
        except ValueError as e:
            raise SystemExit(str(e))
    # tp > 1 serves the Megatron-TP model (identical param tree — dense
    # checkpoints restore unchanged; the layers' constraint points do
    # the sharding).
    model = {"gpt_tiny": gpt_tiny,
             "gpt_base": gpt_base}[args.arch](tensor_parallel=tp > 1)
    max_len = args.max_len
    if max_len is None:
        max_len = min(model.max_position, 128)
    prompt_len = parse_range(args.prompt_len, "prompt-len")
    max_new = parse_range(args.max_new, "max-new")
    if args.shared_prefix < 0:
        raise SystemExit(f"--shared-prefix must be >= 0, got "
                         f"{args.shared_prefix}")
    if prompt_len[1] + args.shared_prefix >= max_len:
        raise SystemExit(f"--prompt-len max {prompt_len[1]} plus "
                         f"--shared-prefix {args.shared_prefix} must be "
                         f"< --max-len {max_len}")
    if args.block_size < 1:
        raise SystemExit(f"--block-size must be >= 1, got "
                         f"{args.block_size}")
    if args.num_blocks is not None and args.num_blocks < 1:
        raise SystemExit(f"--num-blocks must be >= 1, got "
                         f"{args.num_blocks}")
    if args.flight_recorder and not args.metrics_jsonl:
        # Same guard as train.py: forensics need a stream to land in —
        # a silently-disarmed recorder is worse than an error.
        raise SystemExit("--flight-recorder requires --metrics-jsonl "
                         "(the crash_dump rides the metrics stream)")
    if args.cost_model and not args.metrics_jsonl:
        raise SystemExit("--cost-model requires --metrics-jsonl (the "
                         "compile_event/cost_model records ride the "
                         "metrics stream)")
    if args.trace and not args.metrics_jsonl:
        raise SystemExit("--trace requires --metrics-jsonl (the "
                         "trace_event records ride the metrics stream)")
    slo_spec = None
    if args.slo:
        if not args.metrics_jsonl:
            raise SystemExit("--slo requires --metrics-jsonl (the "
                             "slo_window/slo_breach records ride the "
                             "metrics stream)")
        from apex_example_tpu.obs.slo import parse_slo
        try:
            slo_spec = parse_slo(args.slo)
        except ValueError as e:
            raise SystemExit(f"--slo: {e}")
    if args.slo_window_s is not None and args.slo_window_s <= 0:
        raise SystemExit(f"--slo-window-s must be > 0, got "
                         f"{args.slo_window_s}")
    if args.slo_window_ticks < 0:
        raise SystemExit(f"--slo-window-ticks must be >= 0, got "
                         f"{args.slo_window_ticks}")
    if args.tick_profile and not args.metrics_jsonl:
        raise SystemExit("--tick-profile requires --metrics-jsonl (the "
                         "tick_profile/overhead_summary records ride "
                         "the metrics stream)")
    if args.tick_profile_every < 1:
        raise SystemExit(f"--tick-profile-every must be >= 1, got "
                         f"{args.tick_profile_every}")
    if args.speculate < 0:
        raise SystemExit(f"--speculate must be >= 0, got "
                         f"{args.speculate}")
    if args.speculate and args.role != "both":
        raise SystemExit("--speculate needs the interleaved engine "
                         "(--role both): disaggregated roles keep "
                         "their own step geometries")
    if args.speculate and args.speculate + 1 > max_len:
        raise SystemExit(f"--speculate {args.speculate} exceeds "
                         f"--max-len {max_len} lanes")
    if args.draft_ngram < 1:
        raise SystemExit(f"--draft-ngram must be >= 1, got "
                         f"{args.draft_ngram}")
    tenant_specs = None
    if args.tenants:
        from apex_example_tpu.sched.tenants import parse_tenants
        try:
            tenant_specs = parse_tenants(args.tenants)
        except ValueError as e:
            raise SystemExit(str(e))
        if args.shared_prefix or args.burst != 1:
            raise SystemExit("--tenants makes --shared-prefix/--burst "
                             "per-tenant (spec keys shared_prefix= / "
                             "burst=); drop the global flags")
        for tsp in tenant_specs.values():
            if prompt_len[1] + tsp.shared_prefix >= max_len:
                raise SystemExit(
                    f"--prompt-len max {prompt_len[1]} plus tenant "
                    f"{tsp.name!r} shared_prefix {tsp.shared_prefix} "
                    f"must be < --max-len {max_len}")
    if args.advertise_prefixes < 0:
        raise SystemExit(f"--advertise-prefixes must be >= 0, got "
                         f"{args.advertise_prefixes}")
    replica_mode = bool(args.inbox or args.outbox)
    if args.role == "decode":
        # A decode worker's intake is the --handoff-dir spool, never an
        # inbox; its fleet surface is the outbox alone (terminal lines
        # out, so a router can harvest what the spool fed it).
        if args.inbox:
            raise SystemExit("--role decode takes no --inbox (its "
                             "intake is the --handoff-dir spool); give "
                             "it --outbox alone for the fleet protocol")
    elif replica_mode and not (args.inbox and args.outbox):
        raise SystemExit("--inbox and --outbox come together (the "
                         "fleet replica protocol: specs in, terminal "
                         "lines out)")
    if args.role != "both" and not args.handoff_dir:
        raise SystemExit("--role prefill/decode needs --handoff-dir "
                         "(the KV-handoff spool both roles share)")
    if args.handoff_dir and args.role == "both":
        raise SystemExit("--handoff-dir only means something for a "
                         "--role prefill or decode process")
    if args.handoff_lease <= 0:
        raise SystemExit(f"--handoff-lease must be > 0, got "
                         f"{args.handoff_lease}")
    if args.migrate_dir and args.role != "both":
        raise SystemExit("--migrate-dir needs the interleaved engine "
                         "(--role both): disaggregated roles keep the "
                         "prefill->decode spool as their only transport")
    if args.heartbeat_s <= 0:
        raise SystemExit(f"--heartbeat-s must be > 0, got "
                         f"{args.heartbeat_s}")
    fault = None
    if args.inject_fault:
        try:
            fault = FaultPlan.parse(args.inject_fault, kinds=SERVE_KINDS)
        except ValueError as e:
            raise SystemExit(str(e))
    # Handoff drills fire inside the transport / decode drive loop, not
    # the engine tick loop — route the plan there, and reject a drill
    # the process's role could never express (a silently-inert drill is
    # worse than an error).
    handoff_fault = None
    if fault is not None and fault.kind in HANDOFF_KINDS:
        need = "prefill" if fault.kind in ("handoff_torn",
                                           "sentinel_lost") else "decode"
        if args.role != need:
            raise SystemExit(f"--inject-fault {fault.kind} is a "
                             f"{need}-side drill (this process is "
                             f"--role {args.role})")
        handoff_fault, fault = fault, None

    if args.checkpoint_dir:
        params = restore_params(args.checkpoint_dir, args.checkpoint_step)
        source = f"checkpoint {args.checkpoint_dir}"
    else:
        params = model.init(
            jax.random.PRNGKey(args.seed),
            jnp.zeros((1, 4), jnp.int32))["params"]
        source = "random init (smoke mode)"

    # Quantization applies at RESTORE time (ISSUE 13): the engine's
    # compiled step receives the int8/fp8 leaves as arguments and
    # dequantizes in-trace — low-bit bytes are what HBM holds/streams.
    quant_stats = None
    if args.weight_quant != "none":
        from apex_example_tpu.amp.policy import get_quant_policy
        from apex_example_tpu.quant import quantize_params
        qpolicy = get_quant_policy(args.weight_quant, args.kv_quant)
        params, quant_stats = quantize_params(params, args.weight_quant)
        source += f" -> {qpolicy.weight_dtype_name} weights"

    emitter = sink = recorder = None
    run_id = None
    # Clear any instance a previous in-process run leaked before this
    # run builds its engine (same hygiene as train.make_telemetry).
    obs.costmodel.set_default(None)
    obs.trace.set_default(None)
    if args.metrics_jsonl:
        sink = obs.JsonlSink(args.metrics_jsonl)
        emitter = obs.TelemetryEmitter(sink)
        emitter.run_header(config=vars(args), argv=sys.argv,
                           arch=args.arch)
        run_id = emitter.run_id
        if args.flight_recorder:
            recorder = obs.FlightRecorder(emitter, config=vars(args))
            recorder.install()
        if args.cost_model:
            # Process-default instance: the engine's decode step (and
            # any generate() call) picks it up without plumbing; the
            # finally below clears it.
            obs.costmodel.set_default(obs.CostModel(
                sink=sink, registry=emitter.registry, run_id=run_id))
        if args.trace:
            # Same process-default shape: the engine and the span
            # layer consult it; trace_id joins a supervising parent's
            # timeline via APEX_TRACE_ID (cross-restart continuity).
            obs.trace.set_default(obs.Tracer(sink, run_id=run_id))
        if quant_stats is not None:
            # schema v11: one quant_event per applied stratum — the
            # scale spread is the multiplier of every error bound
            # downstream tooling reasons about.  qpolicy is the policy
            # the restore block above actually APPLIED (one resolution,
            # one fp8-capability probe).
            rec = {"record": "quant_event", "time": time.time(),
                   "kind": "weights",
                   "dtype": qpolicy.weight_dtype_name,
                   "run_id": run_id}
            rec.update({k: quant_stats[k] for k in
                        ("tensors", "kept", "bytes_before",
                         "bytes_after", "scale_min", "scale_max",
                         "emulated")})
            sink.write(rec)
        if args.kv_quant:
            from apex_example_tpu.quant import kv as kv_quant_lib
            sink.write({"record": "quant_event", "time": time.time(),
                        "kind": "kv", "dtype": "int8",
                        "block_size": args.block_size,
                        "scale_dtype": str(jnp.dtype(
                            kv_quant_lib.KV_SCALE_DTYPE)),
                        "run_id": run_id})

    # The drain grace path (README "Serving resilience"): the handler
    # only sets a flag; the engine loop notices it at the next tick
    # boundary and run_serve runs the drain itself, outside signal
    # context — the same flag-and-handover shape as train.py's
    # --preempt-grace (the recorder releases SIGTERM/SIGUSR1 to us and
    # keeps excepthook/atexit for real crashes).
    preempt = None
    if not args.no_drain:
        preempt = PreemptionHandler(recorder=recorder)
        preempt.install()

    queue = RequestQueue(max_pending=args.max_pending,
                         shed_policy=args.shed_policy)

    def on_quarantine(uid, spool_name, error, nbytes):
        # A corrupt/truncated payload was parked at *.bad — the worker
        # keeps ticking; the stream records the disposition (schema
        # v13: kv_handoff direction "quarantine").
        print(f"WARNING: quarantined corrupt handoff {uid} "
              f"({spool_name}): {error}", file=sys.stderr)
        if sink is None:
            return
        sink.write({"record": "kv_handoff", "time": time.time(),
                    "request_id": uid, "direction": "quarantine",
                    "fill": 0, "blocks": 0,
                    "payload_bytes": int(nbytes),
                    "spool_file": spool_name,
                    "error": str(error)[:500], "run_id": run_id})

    transport = None
    if args.handoff_dir:
        transport = FileTransport(
            args.handoff_dir, worker=args.replica_id,
            lease_s=args.handoff_lease,
            fault=handoff_fault if args.role == "prefill" else None,
            on_quarantine=on_quarantine if args.role == "decode"
            else None)

    def on_mig_quarantine(uid, spool_name, error, nbytes):
        # Same disposition as a corrupt handoff, recorded on the v18
        # kv_migration stream: park, warn, keep serving.
        print(f"WARNING: quarantined corrupt migration {uid} "
              f"({spool_name}): {error}", file=sys.stderr)
        if sink is None:
            return
        sink.write({"record": "kv_migration", "time": time.time(),
                    "request_id": uid, "direction": "quarantine",
                    "fill": 0, "blocks": 0,
                    "payload_bytes": int(nbytes),
                    "spool_file": spool_name,
                    "error": str(error)[:500], "run_id": run_id})

    mig_transport = None
    if args.migrate_dir:
        mig_transport = FileTransport(
            args.migrate_dir, worker=args.replica_id,
            lease_s=args.handoff_lease,
            on_quarantine=on_mig_quarantine)
    # The mesh registers BEFORE the engine builds (construction shards
    # the restored — possibly quantized — params and the paged arenas
    # against it) and must STAY registered through the run: the TP
    # layers' constrain() points read it at trace time.  The run
    # section's finally clears it; a failure between here and that try
    # (engine construction, replica-mode setup) clears it on the way
    # out too, so an in-process caller (tests, supervisors) never
    # inherits a stale mesh.
    tickprof = None
    if args.tick_profile:
        from apex_example_tpu.obs.tickprof import TickProfiler
        tickprof = TickProfiler(kind="serve",
                                sample_every=args.tick_profile_every,
                                emit=sink.write if sink is not None
                                else None,
                                run_id=run_id)
    proposer = None
    if args.speculate:
        from apex_example_tpu.spec import get_proposer
        proposer = get_proposer(args.draft, ngram=args.draft_ngram)
    parallel_state.set_mesh(mesh)
    try:
        engine = ServeEngine(model, params, num_slots=args.slots,
                             max_len=max_len, block_size=args.block_size,
                             num_blocks=args.num_blocks,
                             rng=jax.random.PRNGKey(args.seed),
                             queue=queue, sink=sink, run_id=run_id,
                             fault=fault,
                             registry=emitter.registry if emitter
                             else None,
                             kv_quant=args.kv_quant,
                             weight_quant=args.weight_quant,
                             role=args.role,
                             handoff_sink=transport.send
                             if args.role == "prefill" else None,
                             slo=slo_spec,
                             slo_window_s=args.slo_window_s,
                             slo_window_ticks=args.slo_window_ticks,
                             tick_profiler=tickprof,
                             speculate=args.speculate,
                             proposer=proposer,
                             tenants=tenant_specs,
                             advertise_prefixes=args.advertise_prefixes)
        outbox = feeder_stop = on_tick = None
        idle_wait_s = 0.0
        if replica_mode:
            outbox = _Outbox(args.outbox)
            if args.role == "decode":
                # Crash-safe exactly-once across restarts: uids already
                # terminal in the outbox must never be served again —
                # the restarted worker replays the spool from its claim
                # set, and a handoff completed just before the crash
                # (terminal on disk, claim never acked) comes back as a
                # redelivery the seen-set turns into a duplicate-ack.
                engine.handoff_seen.update(outbox.done)
            else:
                feeder_stop = threading.Event()
                threading.Thread(
                    target=_feed_inbox,
                    args=(args.inbox, queue, outbox, feeder_stop,
                          Request),
                    name="inbox-feeder", daemon=True).start()
            idle_wait_s = 0.004             # wall-clock producer: don't spin

            def _beat(state: str) -> None:
                if sink is None:
                    return
                # v12: kv_bytes_live is the dtype-accurate gauge (int8
                # arenas count int8 bytes + scales) — what the fleet
                # router's least_kv policy prefers over the raw block
                # count when replicas mix precisions.  v13: the role
                # rides along so fleet tooling can tell a prefill
                # heartbeat from a decode one.
                rec = {"record": "replica_state", "time": time.time(),
                       "replica": args.replica_id, "state": state,
                       "role": args.role,
                       "tick": engine.step_count,
                       "pending": engine.unadmitted(),
                       "blocks_live": engine.pool.blocks_live(),
                       "kv_bytes_live": engine.pool.kv_bytes_live(),
                       "pid": os.getpid(), "run_id": run_id}
                # v14: with --slo the cumulative latency sketches ride
                # the heartbeat — the fleet router merges them into
                # fleet_rollup records (live cross-replica percentiles
                # without re-pooling raw samples).
                sk = engine.slo_sketch()
                if sk is not None:
                    rec["slo_sketch"] = sk
                # v15: with --tick-profile the cumulative host-overhead
                # fraction rides along — fleet_report ranks replicas by
                # it and names the worst.
                frac = engine.host_overhead_frac()
                if frac is not None:
                    rec["host_overhead_frac"] = round(frac, 6)
                # v17: with --advertise-prefixes the hot chain-key
                # digests + raw reuse counters ride along (the
                # prefix_affinity routing inputs); with --tenants the
                # per-tenant admitted-token totals do (fleet budget
                # accounting).  Both absent unarmed — heartbeats stay
                # byte-identical.
                adv = engine.prefix_advert()
                if adv is not None:
                    rec.update(adv)
                ta = engine.tenant_admitted()
                if ta is not None:
                    rec["tenant_admitted"] = ta
                sink.write(rec)

            last_beat = [0.0]

            def on_tick(eng) -> None:
                # With --slo, heartbeat BEFORE flushing new terminals:
                # the sketches already cover them (folded at slot
                # eviction), so the router can never tail the last
                # terminal without the matching sketch on disk — the
                # close-time fleet_rollup cannot race the child's exit.
                now = time.time()
                if (eng.slo is not None
                        and len(eng.completions) > outbox._consumed):
                    last_beat[0] = now
                    _beat("serving")
                outbox.flush_from(eng)
                if now - last_beat[0] >= args.heartbeat_s:
                    last_beat[0] = now
                    _beat("serving")
        elif args.role != "decode":
            # A decode-role engine's intake is the handoff transport, not a
            # workload of its own (run_decode_role closes the queue).
            if tenant_specs is not None:
                from apex_example_tpu.serve.loadgen import tenant_requests
                requests = tenant_requests(
                    args.requests, tenant_specs,
                    vocab_size=model.vocab_size, seed=args.seed,
                    prompt_len=prompt_len, max_new=max_new,
                    temperature=args.temperature, top_k=args.top_k,
                    eos_id=args.eos_id, stagger=args.stagger,
                    deadline_steps=args.deadline_steps,
                    deadline_s=args.deadline_s,
                    seed_substream=args.seed_substream,
                    repetitive=args.repetitive)
            else:
                requests = synthetic_requests(
                    args.requests, vocab_size=model.vocab_size,
                    seed=args.seed,
                    prompt_len=prompt_len, max_new=max_new,
                    temperature=args.temperature, top_k=args.top_k,
                    eos_id=args.eos_id, stagger=args.stagger,
                    burst=args.burst,
                    deadline_steps=args.deadline_steps,
                    deadline_s=args.deadline_s,
                    shared_prefix=args.shared_prefix,
                    seed_substream=args.seed_substream,
                    repetitive=args.repetitive)
            engine.queue.submit_all(requests)
            engine.queue.close()

        if mig_transport is not None:
            # Migration intake rides on_tick (same poll/renew/admit/ack
            # shape as run_decode_role's drive loop): deferred
            # admissions keep their claims renewed — a full pool must
            # not silently forfeit a live request to a peer.
            mig_pending: deque = deque()
            inner_on_tick = on_tick

            def on_tick(eng, _inner=inner_on_tick):
                polled = mig_transport.poll()
                if polled:
                    mig_pending.extend(polled)
                if mig_pending:
                    mig_transport.renew(mig_pending)
                while mig_pending and eng.admit_handoff(mig_pending[0]):
                    mig_transport.ack(mig_pending.popleft())
                if _inner is not None:
                    _inner(eng)

        pool = engine.pool
        if args.role == "decode":
            workload = f"decode role (handoffs from {args.handoff_dir})"
        elif replica_mode:
            workload = f"replica {args.replica_id} (inbox-fed)"
        else:
            workload = f"{args.requests} request(s)"
        shard = f"  mesh=data={dp},model={tp}" if mesh is not None else ""
        print(f"serve: {workload}  arch={args.arch}  role={args.role}  "
              f"slots={args.slots}  max_len={max_len}  "
              f"blocks={pool.num_blocks}x{pool.block_size}{shard}  "
              f"params from {source}")
    except BaseException:
        parallel_state.set_mesh(None)
        raise
    rc = 0
    try:
        if args.role == "decode":
            completions = run_decode_role(
                engine, transport,
                max_steps=args.steps or None,
                idle_wait_s=0.004,
                stop=(lambda: preempt.preempted) if preempt else None,
                on_tick=on_tick, fault=handoff_fault,
                idle_timeout_s=args.handoff_idle_timeout)
        else:
            completions = engine.run(
                max_steps=args.steps or None,
                idle_wait_s=idle_wait_s,
                stop=(lambda: preempt.preempted) if preempt else None,
                on_tick=on_tick)
        if preempt is not None and preempt.preempted:
            if feeder_stop is not None:
                feeder_stop.set()
            if replica_mode:
                _beat("draining")       # the router sees the drain start
            drain = engine.drain(preempt.signal_name,
                                 migrate=mig_transport.send
                                 if mig_transport is not None else None)
            completions = engine.completions
            migrated = (f"  migrated={drain['migrated']}"
                        if "migrated" in drain else "")
            print(f"drain ({drain['signal']}): admission stopped at tick "
                  f"{drain['step']}  in_flight={drain['in_flight']}  "
                  f"completed={drain['completed']}  "
                  f"evicted={drain['evicted']}  "
                  f"requeued={drain['requeued']}{migrated}; exiting "
                  f"{EX_TEMPFAIL} (resumable)")
            rc = EX_TEMPFAIL
        if args.role == "prefill" and rc == 0:
            # Close AFTER any drain: the drain's in-flight slots finish
            # by handing off, and the sentinel's count must cover them.
            # A DRAINED prefill (rc 75) writes no sentinel — the
            # supervisor restarts it to finish the stream, and an early
            # sentinel would let an idle decode worker exit while the
            # spool is only momentarily empty.
            transport.close()
        if outbox is not None:
            # Everything terminal — drained requeues included — must be
            # on disk before the summary: the restart-skip set and the
            # router's completion feed both read from here.
            outbox.flush_from(engine)
            # One last heartbeat AFTER the final terminals: the
            # cumulative SLO sketches and closing gauges land on disk
            # even when the run is shorter than the heartbeat cadence,
            # so the router's close-time fleet_rollup sees real data.
            _beat("serving")
        if tickprof is not None and sink is not None and tickprof.ticks:
            # The cumulative overhead fold closes just before the
            # serve_summary (same ordering contract as the SLO flush:
            # report tools read the stream tail).
            sink.write(tickprof.summary_record())
        summary = engine.summary_record()
        if transport is not None and transport.quarantined:
            summary["handoff_quarantined"] = transport.quarantined
        if sink is not None:
            sink.write(summary)
    finally:
        if feeder_stop is not None:
            feeder_stop.set()
        if outbox is not None:
            outbox.close()
        # Mirror train.close_telemetry: called while an exception is
        # unwinding (sys.exc_info live inside a finally — the crash
        # fault's path), route through the flight recorder (crash_dump +
        # aborted summary) before disarming; a drained/finished run is
        # not a crash and closes clean.
        exc = sys.exc_info()
        if recorder is not None and exc[0] is not None \
                and not issubclass(exc[0], SystemExit):
            recorder.crash_dump(f"exception:{exc[0].__name__}",
                                exc_info=exc)
        if recorder is not None:
            recorder.close()
        if preempt is not None:
            preempt.close()
        obs.costmodel.set_default(None)
        obs.trace.set_default(None)
        parallel_state.set_mesh(None)
        if sink is not None:
            sink.close()

    counts = engine.counts
    if args.role == "decode":
        # The decode role's workload is whatever the transport fed it
        # (replica mode included — its inbox IS the spool).  A --steps
        # cap can strand requests mid-flight AND leave un-acked
        # handoffs in the spool (claims and files survive —
        # re-servable by the next worker — but THIS run did not finish
        # them).
        stranded = len(engine.pool.live) + transport.pending_on_disk()
        n_expected = len(completions) + stranded
    elif replica_mode:
        # A --steps-capped replica can run out of ticks with inbox
        # requests still queued or mid-decode; they reached no terminal
        # status and no outbox line, so exiting 0 would hide the loss
        # (review finding, ISSUE 12).
        stranded = engine.queue.pending() + len(engine.pool.live)
        n_expected = len(completions) + stranded
    else:
        n_expected = args.requests
        stranded = n_expected - len(completions)
    print(f"done: {counts['ok']}/{n_expected} completed  "
          f"out_tokens={summary['output_tokens']}  "
          f"tok/s={summary['tokens_per_sec']}  "
          f"steps={summary['steps']}  "
          f"occupancy={summary.get('occupancy', 0.0)}")
    if "speculate_k" in summary:
        print(f"spec: K={summary['speculate_k']} "
              f"draft={summary['draft_kind']}  "
              f"accepted {summary['tokens_accepted']}"
              f"/{summary['tokens_drafted']} drafted "
              f"({summary['acceptance_rate']:.1%})  "
              f"tokens/tick={summary.get('tokens_per_tick', 0.0)}")
    nonsuccess = {k: v for k, v in counts.items() if k != "ok" and v}
    if nonsuccess:
        print("statuses: " + "  ".join(f"{k}={v}" for k, v in
                                       sorted(nonsuccess.items()))
              + f"  availability={summary['availability']}")
    for name in ("ttft_ms", "tpot_ms", "queue_wait_ms"):
        d = summary.get(name)
        if d:
            print(f"{name:14s} p50 {d['p50']:.1f}  p95 {d['p95']:.1f}  "
                  f"max {d['max']:.1f}")
    if rc == 0 and stranded:
        rc = 1
        print(f"WARNING: {stranded} request(s) unfinished at the --steps "
              f"cap", file=sys.stderr)
    return completions, summary, rc


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    _, _, rc = run_serve(args)
    return rc


if __name__ == "__main__":
    sys.exit(main())
