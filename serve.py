#!/usr/bin/env python
"""Continuous-batching inference CLI (the serving counterpart of train.py).

Restores GPT params from a CheckpointManager checkpoint (template-free —
serving needs no optimizer state) or random-inits for smoke runs, then
drives the slot-based engine (apex_example_tpu/serve/) against a
deterministic synthetic request stream with staggered arrivals.

    # random-init smoke: 16 requests through 4 slots
    python serve.py --requests 16 --slots 4 --metrics-jsonl serve.jsonl

    # serve a trained checkpoint, sampled with per-request top-k
    python serve.py --arch gpt_tiny --checkpoint-dir ckpts \\
        --temperature 0.8 --top-k 40 --metrics-jsonl serve.jsonl

    # then summarize latency percentiles (jax-free):
    python tools/serve_report.py serve.jsonl

With --metrics-jsonl the run emits schema-v3 records through the obs
sink: a run_header, one ``request_complete`` per finished request
(TTFT/TPOT/queue-wait/slot provenance) and a closing ``serve_summary``
(throughput, latency percentiles, slot occupancy).  The stream passes
tools/metrics_lint.py like every other obs stream.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="continuous-batching GPT inference")
    p.add_argument("--arch", default="gpt_tiny",
                   choices=["gpt_tiny", "gpt_base"])
    p.add_argument("--checkpoint-dir", default=None,
                   help="CheckpointManager directory to restore params "
                        "from (omit = random init, smoke mode)")
    p.add_argument("--checkpoint-step", type=int, default=None,
                   help="checkpoint step (default: latest)")
    p.add_argument("--slots", type=int, default=4,
                   help="KV-cache slot count (the max decode batch)")
    p.add_argument("--max-len", type=int, default=None,
                   help="per-slot cache length (default: the model's "
                        "position table, capped at 128 for gpt_tiny)")
    p.add_argument("--requests", type=int, default=16,
                   help="synthetic request count")
    p.add_argument("--prompt-len", default="4:12",
                   help="prompt length, N or MIN:MAX tokens")
    p.add_argument("--max-new", default="4:16",
                   help="output budget, N or MIN:MAX tokens")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy, >0 samples")
    p.add_argument("--top-k", type=int, default=0,
                   help="restrict sampling to the k highest logits "
                        "(0 = full softmax)")
    p.add_argument("--eos-id", type=int, default=None,
                   help="token id that ends a request early")
    p.add_argument("--stagger", type=int, default=2,
                   help="virtual engine steps between request arrivals "
                        "(0 = all arrive at once)")
    p.add_argument("--steps", type=int, default=0,
                   help="engine tick cap (0 = run until drained)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--metrics-jsonl", default=None,
                   help="emit schema-v3 serving records to this JSONL")
    return p


def run_serve(args):
    """Build, restore, drive.  Returns (completions, summary_record, rc)
    — split from main() so tests can assert on the served tokens."""
    import jax
    import jax.numpy as jnp

    from apex_example_tpu import obs
    from apex_example_tpu.models.gpt import gpt_base, gpt_tiny
    from apex_example_tpu.serve import (ServeEngine, parse_range,
                                        synthetic_requests)
    from apex_example_tpu.utils.checkpoint import restore_params

    model = {"gpt_tiny": gpt_tiny, "gpt_base": gpt_base}[args.arch]()
    max_len = args.max_len
    if max_len is None:
        max_len = min(model.max_position, 128)
    prompt_len = parse_range(args.prompt_len, "prompt-len")
    max_new = parse_range(args.max_new, "max-new")
    if prompt_len[1] >= max_len:
        raise SystemExit(f"--prompt-len max {prompt_len[1]} must be < "
                         f"--max-len {max_len}")

    if args.checkpoint_dir:
        params = restore_params(args.checkpoint_dir, args.checkpoint_step)
        source = f"checkpoint {args.checkpoint_dir}"
    else:
        params = model.init(
            jax.random.PRNGKey(args.seed),
            jnp.zeros((1, 4), jnp.int32))["params"]
        source = "random init (smoke mode)"

    emitter = sink = None
    run_id = None
    if args.metrics_jsonl:
        sink = obs.JsonlSink(args.metrics_jsonl)
        emitter = obs.TelemetryEmitter(sink)
        emitter.run_header(config=vars(args), argv=sys.argv,
                           arch=args.arch)
        run_id = emitter.run_id

    requests = synthetic_requests(
        args.requests, vocab_size=model.vocab_size, seed=args.seed,
        prompt_len=prompt_len, max_new=max_new,
        temperature=args.temperature, top_k=args.top_k,
        eos_id=args.eos_id, stagger=args.stagger)
    engine = ServeEngine(model, params, num_slots=args.slots,
                         max_len=max_len,
                         rng=jax.random.PRNGKey(args.seed),
                         sink=sink, run_id=run_id)
    engine.queue.submit_all(requests)
    engine.queue.close()

    print(f"serve: {args.requests} request(s)  arch={args.arch}  "
          f"slots={args.slots}  max_len={max_len}  params from {source}")
    completions = engine.run(max_steps=args.steps or None)
    summary = engine.summary_record()
    if sink is not None:
        sink.write(summary)
        sink.close()

    rc = 0 if len(completions) == len(requests) else 1
    print(f"done: {len(completions)}/{args.requests} completed  "
          f"out_tokens={summary['output_tokens']}  "
          f"tok/s={summary['tokens_per_sec']}  "
          f"steps={summary['steps']}  "
          f"occupancy={summary.get('occupancy', 0.0)}")
    for name in ("ttft_ms", "tpot_ms", "queue_wait_ms"):
        d = summary.get(name)
        if d:
            print(f"{name:14s} p50 {d['p50']:.1f}  p95 {d['p95']:.1f}  "
                  f"max {d['max']:.1f}")
    if rc:
        print(f"WARNING: {len(requests) - len(completions)} request(s) "
              f"unfinished at the --steps cap", file=sys.stderr)
    return completions, summary, rc


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    _, _, rc = run_serve(args)
    return rc


if __name__ == "__main__":
    sys.exit(main())
