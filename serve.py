#!/usr/bin/env python
"""Continuous-batching inference CLI (the serving counterpart of train.py).

Restores GPT params from a CheckpointManager checkpoint (template-free —
serving needs no optimizer state) or random-inits for smoke runs, then
drives the slot-based engine (apex_example_tpu/serve/) against a
deterministic synthetic request stream with staggered arrivals.

    # random-init smoke: 16 requests through 4 slots
    python serve.py --requests 16 --slots 4 --metrics-jsonl serve.jsonl

    # serve a trained checkpoint, sampled with per-request top-k
    python serve.py --arch gpt_tiny --checkpoint-dir ckpts \\
        --temperature 0.8 --top-k 40 --metrics-jsonl serve.jsonl

    # overload drill: bursts past the slot count + queue bound shed
    # deterministically, tight virtual deadlines exercise timeouts
    python serve.py --requests 24 --slots 2 --max-pending 4 --burst 12 \\
        --deadline-steps 40 --metrics-jsonl serve.jsonl

    # shared-system-prompt workload: prefix sharing packs the common
    # 16 tokens into refcounted blocks (COW on divergence)
    python serve.py --requests 16 --shared-prefix 16 \\
        --metrics-jsonl serve.jsonl

    # then summarize per-status accounting + latency (jax-free):
    python tools/serve_report.py serve.jsonl

The KV cache is BLOCK-PAGED (ISSUE 8; README "Paged KV cache"):
per-layer arenas of --num-blocks x --block-size token blocks, per-slot
block tables gathered inside the one compiled decode step, chunked
prefill (up to --block-size prompt tokens per tick), and admission by
worst-case block budget — out-of-blocks resolves as deterministic
head-of-line queueing, and a request that could never be served (its
prompt fills the cache) terminates with status "rejected" at admission.

Resilience (README "Serving resilience"; ISSUE 5): SIGTERM/SIGUSR1
triggers a graceful drain — admission stops, queued requests are handed
back with status "drained" (requeue-able on another replica), in-flight
slots finish or deadline-evict, a ``serve_drain`` record plus the
normal un-aborted ``serve_summary`` close the stream, and the process
exits 75 (EX_TEMPFAIL) so a supervisor (tools/supervise.py --no-resume)
restarts it.  ``--inject-fault {crash,sigterm,hang,nan,slot_fail}@tick``
makes every failure path deterministic; ``--flight-recorder`` keeps
crash forensics for the paths that ARE crashes.

With --metrics-jsonl the run emits schema-v5 records through the obs
sink: a run_header, one ``request_complete`` / ``request_failed`` /
``shed`` per terminated request, an optional ``serve_drain``, and a
closing ``serve_summary`` (throughput, latency percentiles, per-status
counts, availability).  The stream passes tools/metrics_lint.py like
every other obs stream.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="continuous-batching GPT inference")
    p.add_argument("--arch", default="gpt_tiny",
                   choices=["gpt_tiny", "gpt_base"])
    p.add_argument("--checkpoint-dir", default=None,
                   help="CheckpointManager directory to restore params "
                        "from (omit = random init, smoke mode)")
    p.add_argument("--checkpoint-step", type=int, default=None,
                   help="checkpoint step (default: latest)")
    p.add_argument("--slots", type=int, default=4,
                   help="KV-cache slot count (the max decode batch)")
    p.add_argument("--max-len", type=int, default=None,
                   help="per-slot cache length (default: the model's "
                        "position table, capped at 128 for gpt_tiny)")
    p.add_argument("--block-size", type=int, default=8,
                   help="KV arena block granularity in tokens: chunked "
                        "prefill feeds up to this many prompt tokens "
                        "per tick, and prefix sharing/allocation happen "
                        "per block (serve/slots.py)")
    p.add_argument("--num-blocks", type=int, default=None,
                   help="KV arena size in blocks per layer (default: "
                        "slots * ceil(max_len / block_size) — the dense "
                        "layout's capacity; admission reserves each "
                        "request's worst-case block budget against it)")
    p.add_argument("--requests", type=int, default=16,
                   help="synthetic request count")
    p.add_argument("--prompt-len", default="4:12",
                   help="prompt length, N or MIN:MAX tokens")
    p.add_argument("--shared-prefix", type=int, default=0,
                   help="prepend one common N-token system prompt to "
                        "every request (drawn once per seed) — the "
                        "prefix-sharing workload: shared KV blocks are "
                        "computed once and refcounted, measurable in "
                        "serve_summary's prefix_hit_rate/cow_copies")
    p.add_argument("--max-new", default="4:16",
                   help="output budget, N or MIN:MAX tokens")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy, >0 samples")
    p.add_argument("--top-k", type=int, default=0,
                   help="restrict sampling to the k highest logits "
                        "(0 = full softmax)")
    p.add_argument("--eos-id", type=int, default=None,
                   help="token id that ends a request early")
    p.add_argument("--stagger", type=int, default=2,
                   help="virtual engine steps between request arrivals "
                        "(0 = all arrive at once)")
    p.add_argument("--burst", type=int, default=1,
                   help="arrivals per wave: B requests land together "
                        "every --stagger ticks (deterministic overload "
                        "mode; 1 = the classic one-by-one stagger)")
    p.add_argument("--max-pending", type=int, default=None,
                   help="admission control: bound on the arrived request "
                        "backlog; overflow is shed deterministically "
                        "(default: unbounded)")
    p.add_argument("--shed-policy", default="newest",
                   choices=["newest", "oldest"],
                   help="which side of the backlog to shed on overflow "
                        "(newest = reject incoming, the default)")
    p.add_argument("--deadline-steps", type=int, default=None,
                   help="per-request deadline in engine ticks after "
                        "arrival (deterministic; expires queued requests "
                        "without admitting and evicts decoding slots "
                        "mid-flight)")
    p.add_argument("--deadline-s", type=float, default=None,
                   help="per-request wall-clock TTL from arrival")
    p.add_argument("--steps", type=int, default=0,
                   help="engine tick cap (0 = run until drained)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--metrics-jsonl", default=None,
                   help="emit schema-valid serving records to this JSONL")
    p.add_argument("--trace", action="store_true",
                   help="with --metrics-jsonl: emit schema-v9 "
                        "trace_event records — per-tick admit/dispatch/"
                        "harvest spans and a per-request lifecycle span "
                        "tree (queued -> prefill chunks -> first_token "
                        "-> decode -> terminal status) — exportable to "
                        "Perfetto via tools/trace_export.py; host-only, "
                        "the compiled decode step is untouched "
                        "(README 'Request tracing')")
    p.add_argument("--cost-model", action="store_true",
                   help="with --metrics-jsonl: AOT-compile the slot "
                        "decode step and emit schema-v6 compile_event + "
                        "cost_model records (per-tick decode flops/HBM "
                        "bytes + roofline verdict; obs/costmodel.py — "
                        "the decode program still compiles exactly once)")
    p.add_argument("--inject-fault", default="", metavar="KIND@TICK",
                   help="deterministic serve-path fault drill at a "
                        "1-based engine tick: crash | sigterm | hang | "
                        "nan | slot_fail (resilience/faults.py; sigterm "
                        "exercises the drain path, slot_fail the "
                        "slot-isolation path)")
    p.add_argument("--flight-recorder", action="store_true",
                   help="arm crash forensics (obs/flight.py): abnormal "
                        "exits write a crash_dump + aborted summary to "
                        "the metrics stream; SIGTERM stays with the "
                        "drain handler (release_signal handover)")
    p.add_argument("--no-drain", action="store_true",
                   help="do not catch SIGTERM/SIGUSR1 for graceful "
                        "drain (signals then kill the process as before)")
    return p


def run_serve(args):
    """Build, restore, drive — and drain gracefully on SIGTERM/SIGUSR1.
    Returns (completions, summary_record, rc) — split from main() so
    tests can assert on the served tokens; rc is 75 (EX_TEMPFAIL) after
    a drain so a supervisor restarts rather than buries the server."""
    import jax
    import jax.numpy as jnp

    from apex_example_tpu import obs
    from apex_example_tpu.models.gpt import gpt_base, gpt_tiny
    from apex_example_tpu.resilience import (EX_TEMPFAIL, FaultPlan,
                                             PreemptionHandler)
    from apex_example_tpu.resilience.faults import SERVE_KINDS
    from apex_example_tpu.serve import (RequestQueue, ServeEngine,
                                        parse_range, synthetic_requests)
    from apex_example_tpu.utils.checkpoint import restore_params

    model = {"gpt_tiny": gpt_tiny, "gpt_base": gpt_base}[args.arch]()
    max_len = args.max_len
    if max_len is None:
        max_len = min(model.max_position, 128)
    prompt_len = parse_range(args.prompt_len, "prompt-len")
    max_new = parse_range(args.max_new, "max-new")
    if args.shared_prefix < 0:
        raise SystemExit(f"--shared-prefix must be >= 0, got "
                         f"{args.shared_prefix}")
    if prompt_len[1] + args.shared_prefix >= max_len:
        raise SystemExit(f"--prompt-len max {prompt_len[1]} plus "
                         f"--shared-prefix {args.shared_prefix} must be "
                         f"< --max-len {max_len}")
    if args.block_size < 1:
        raise SystemExit(f"--block-size must be >= 1, got "
                         f"{args.block_size}")
    if args.num_blocks is not None and args.num_blocks < 1:
        raise SystemExit(f"--num-blocks must be >= 1, got "
                         f"{args.num_blocks}")
    if args.flight_recorder and not args.metrics_jsonl:
        # Same guard as train.py: forensics need a stream to land in —
        # a silently-disarmed recorder is worse than an error.
        raise SystemExit("--flight-recorder requires --metrics-jsonl "
                         "(the crash_dump rides the metrics stream)")
    if args.cost_model and not args.metrics_jsonl:
        raise SystemExit("--cost-model requires --metrics-jsonl (the "
                         "compile_event/cost_model records ride the "
                         "metrics stream)")
    if args.trace and not args.metrics_jsonl:
        raise SystemExit("--trace requires --metrics-jsonl (the "
                         "trace_event records ride the metrics stream)")
    fault = None
    if args.inject_fault:
        try:
            fault = FaultPlan.parse(args.inject_fault, kinds=SERVE_KINDS)
        except ValueError as e:
            raise SystemExit(str(e))

    if args.checkpoint_dir:
        params = restore_params(args.checkpoint_dir, args.checkpoint_step)
        source = f"checkpoint {args.checkpoint_dir}"
    else:
        params = model.init(
            jax.random.PRNGKey(args.seed),
            jnp.zeros((1, 4), jnp.int32))["params"]
        source = "random init (smoke mode)"

    emitter = sink = recorder = None
    run_id = None
    # Clear any instance a previous in-process run leaked before this
    # run builds its engine (same hygiene as train.make_telemetry).
    obs.costmodel.set_default(None)
    obs.trace.set_default(None)
    if args.metrics_jsonl:
        sink = obs.JsonlSink(args.metrics_jsonl)
        emitter = obs.TelemetryEmitter(sink)
        emitter.run_header(config=vars(args), argv=sys.argv,
                           arch=args.arch)
        run_id = emitter.run_id
        if args.flight_recorder:
            recorder = obs.FlightRecorder(emitter, config=vars(args))
            recorder.install()
        if args.cost_model:
            # Process-default instance: the engine's decode step (and
            # any generate() call) picks it up without plumbing; the
            # finally below clears it.
            obs.costmodel.set_default(obs.CostModel(
                sink=sink, registry=emitter.registry, run_id=run_id))
        if args.trace:
            # Same process-default shape: the engine and the span
            # layer consult it; trace_id joins a supervising parent's
            # timeline via APEX_TRACE_ID (cross-restart continuity).
            obs.trace.set_default(obs.Tracer(sink, run_id=run_id))

    # The drain grace path (README "Serving resilience"): the handler
    # only sets a flag; the engine loop notices it at the next tick
    # boundary and run_serve runs the drain itself, outside signal
    # context — the same flag-and-handover shape as train.py's
    # --preempt-grace (the recorder releases SIGTERM/SIGUSR1 to us and
    # keeps excepthook/atexit for real crashes).
    preempt = None
    if not args.no_drain:
        preempt = PreemptionHandler(recorder=recorder)
        preempt.install()

    requests = synthetic_requests(
        args.requests, vocab_size=model.vocab_size, seed=args.seed,
        prompt_len=prompt_len, max_new=max_new,
        temperature=args.temperature, top_k=args.top_k,
        eos_id=args.eos_id, stagger=args.stagger, burst=args.burst,
        deadline_steps=args.deadline_steps, deadline_s=args.deadline_s,
        shared_prefix=args.shared_prefix)
    queue = RequestQueue(max_pending=args.max_pending,
                         shed_policy=args.shed_policy)
    engine = ServeEngine(model, params, num_slots=args.slots,
                         max_len=max_len, block_size=args.block_size,
                         num_blocks=args.num_blocks,
                         rng=jax.random.PRNGKey(args.seed),
                         queue=queue, sink=sink, run_id=run_id,
                         fault=fault,
                         registry=emitter.registry if emitter else None)
    engine.queue.submit_all(requests)
    engine.queue.close()

    pool = engine.pool
    print(f"serve: {args.requests} request(s)  arch={args.arch}  "
          f"slots={args.slots}  max_len={max_len}  "
          f"blocks={pool.num_blocks}x{pool.block_size}  "
          f"params from {source}")
    rc = 0
    try:
        completions = engine.run(
            max_steps=args.steps or None,
            stop=(lambda: preempt.preempted) if preempt else None)
        if preempt is not None and preempt.preempted:
            drain = engine.drain(preempt.signal_name)
            completions = engine.completions
            print(f"drain ({drain['signal']}): admission stopped at tick "
                  f"{drain['step']}  in_flight={drain['in_flight']}  "
                  f"completed={drain['completed']}  "
                  f"evicted={drain['evicted']}  "
                  f"requeued={drain['requeued']}; exiting {EX_TEMPFAIL} "
                  f"(resumable)")
            rc = EX_TEMPFAIL
        summary = engine.summary_record()
        if sink is not None:
            sink.write(summary)
    finally:
        # Mirror train.close_telemetry: called while an exception is
        # unwinding (sys.exc_info live inside a finally — the crash
        # fault's path), route through the flight recorder (crash_dump +
        # aborted summary) before disarming; a drained/finished run is
        # not a crash and closes clean.
        exc = sys.exc_info()
        if recorder is not None and exc[0] is not None \
                and not issubclass(exc[0], SystemExit):
            recorder.crash_dump(f"exception:{exc[0].__name__}",
                                exc_info=exc)
        if recorder is not None:
            recorder.close()
        if preempt is not None:
            preempt.close()
        obs.costmodel.set_default(None)
        obs.trace.set_default(None)
        if sink is not None:
            sink.close()

    counts = engine.counts
    stranded = args.requests - len(completions)
    print(f"done: {counts['ok']}/{args.requests} completed  "
          f"out_tokens={summary['output_tokens']}  "
          f"tok/s={summary['tokens_per_sec']}  "
          f"steps={summary['steps']}  "
          f"occupancy={summary.get('occupancy', 0.0)}")
    nonsuccess = {k: v for k, v in counts.items() if k != "ok" and v}
    if nonsuccess:
        print("statuses: " + "  ".join(f"{k}={v}" for k, v in
                                       sorted(nonsuccess.items()))
              + f"  availability={summary['availability']}")
    for name in ("ttft_ms", "tpot_ms", "queue_wait_ms"):
        d = summary.get(name)
        if d:
            print(f"{name:14s} p50 {d['p50']:.1f}  p95 {d['p95']:.1f}  "
                  f"max {d['max']:.1f}")
    if rc == 0 and stranded:
        rc = 1
        print(f"WARNING: {stranded} request(s) unfinished at the --steps "
              f"cap", file=sys.stderr)
    return completions, summary, rc


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    _, _, rc = run_serve(args)
    return rc


if __name__ == "__main__":
    sys.exit(main())
