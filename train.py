#!/usr/bin/env python
"""train.py — the reference-parity training entrypoint, TPU-native.

CLI surface preserved from the reference harness (SURVEY.md §3.5/§6: argparse
flags --arch --opt-level --loss-scale --sync_bn --delay-allreduce ... as in
apex's examples/imagenet/main_amp.py pattern), so invocations carry over.
Flags that configure CUDA-specific machinery (--local_rank process binding,
--workers, channels-last) are accepted and recorded but are no-ops on TPU —
one process drives all local devices and the mesh replaces process groups.

Examples
--------
C1 (ResNet-18 / CIFAR-shaped, fp32, single device):
    python train.py --arch resnet18 --dataset cifar10 --opt-level O0 \
        --epochs 2 --batch-size 256

C2/C3 (ResNet-50 / ImageNet-shaped, amp O2 bf16, DDP over all devices):
    python train.py --arch resnet50 --dataset imagenet --opt-level O2 \
        --sync_bn --batch-size 256 --opt sgd
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp

from apex_example_tpu import amp
from apex_example_tpu import obs
from apex_example_tpu.data import CIFAR10, IMAGENET, image_batch, lm_batch, \
    mlm_batch
from apex_example_tpu.engine import (
    create_train_state, make_eval_step, make_sharded_train_step,
    make_train_step)
from apex_example_tpu.models import ARCHS
from apex_example_tpu.models.bert import bert_base, bert_tiny
from apex_example_tpu.models.transformer_xl import (transformer_xl_base,
                                                    transformer_xl_tiny)
from apex_example_tpu.optim import (DistributedFusedAdam, FusedAdagrad,
                                    FusedAdam, FusedLAMB, FusedNovoGrad,
                                    FusedSGD, build_schedule,
                                    make_zero_train_step)
from apex_example_tpu.parallel import (DDPConfig, LARC, is_main_process,
                                       make_data_mesh,
                                       maybe_initialize_distributed)
from apex_example_tpu.obs import (TelemetryEmitter, TensorBoardAdapter,
                                  make_profiler_window, rank_print, span)
from apex_example_tpu.resilience import (EX_TEMPFAIL, FaultPlan,
                                         PreemptionHandler)
from apex_example_tpu.utils import AverageMeter, Throughput
from apex_example_tpu.utils.checkpoint import (CheckpointManager,
                                               restore_under_mesh)
from apex_example_tpu.workloads import (lm_loss,
                                        make_sharded_txl_train_step,
                                        make_txl_train_step, mlm_loss)

LM_ARCHS = ["bert_base", "bert_tiny", "gpt_base", "gpt_tiny",
            "transformer_xl", "transformer_xl_tiny"]


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="TPU-native apex-parity trainer")
    p.add_argument("--arch", "-a", default="resnet18",
                   choices=sorted(ARCHS) + LM_ARCHS)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--fused-attention", action="store_true",
                   help="blockwise flash-attention kernel for BERT archs "
                        "(ops/attention.py; fp32-softmax opt levels only)")
    p.add_argument("--vocab-size", type=int, default=30522)
    p.add_argument("--max-grad-norm", type=float, default=0.25,
                   help="global-norm grad clip (transformer_xl)")
    p.add_argument("--dataset", default="cifar10",
                   choices=["cifar10", "imagenet"])
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--steps-per-epoch", type=int, default=100)
    p.add_argument("--batch-size", "-b", type=int, default=256,
                   help="global batch size (split across devices)")
    p.add_argument("--lr", type=float, default=0.1)
    # LR schedule (reference harness: step-decay adjust_learning_rate with
    # warmup; BERT/LAMB uses warmup+poly — SURVEY.md §3.5, §7)
    p.add_argument("--lr-schedule", default="const",
                   choices=["const", "step", "cosine", "poly"])
    p.add_argument("--warmup-steps", type=int, default=0)
    p.add_argument("--lr-decay-epochs", default="",
                   help='comma epochs for step decay, e.g. "30,60,90" '
                        "(default: 1/3 and 2/3 of the run)")
    p.add_argument("--lr-gamma", type=float, default=0.1)
    p.add_argument("--lr-min", type=float, default=0.0)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", "--wd", type=float, default=1e-4)
    p.add_argument("--opt", default="sgd",
                   choices=["sgd", "adam", "lamb", "novograd", "adagrad"])
    p.add_argument("--larc", action="store_true",
                   help="wrap the optimizer in LARC layer-wise adaptive "
                        "rate control (parallel/larc.py; apex.parallel.LARC)")
    p.add_argument("--larc-trust", type=float, default=0.02,
                   help="LARC trust coefficient")
    # amp surface (apex parity)
    p.add_argument("--opt-level", default="O0",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--loss-scale", default=None,
                   help='None, a number, or "dynamic"')
    p.add_argument("--keep-batchnorm-fp32", default=None, type=lambda s:
                   None if s in (None, "None") else s.lower() == "true")
    # DDP surface (apex parity)
    p.add_argument("--sync_bn", action="store_true",
                   help="use cross-replica SyncBatchNorm")
    p.add_argument("--zero", action="store_true",
                   help="ZeRO-1 optimizer-state sharding over the data "
                        "axis (DistributedFusedAdam; forces --opt adam, "
                        "image workloads, >1 device, static loss scale)")
    p.add_argument("--delay-allreduce", action="store_true", default=True)
    p.add_argument("--gradient-predivide-factor", type=float, default=1.0)
    p.add_argument("--quantized-allreduce", default="off",
                   choices=["off", "int8"],
                   help="DDP gradient exchange precision (ISSUE 13; "
                        "EQuARX, PAPERS.md): int8 reduces each "
                        "--quant-chunk-element chunk under one "
                        "pmax-shared max-abs scale (error bound "
                        "world*scale/2 per element, see "
                        "parallel/distributed.py); off is bit-identical "
                        "to the unquantized path")
    p.add_argument("--quant-chunk", type=int, default=1024,
                   help="chunk size (elements) for --quantized-allreduce "
                        "scales")
    p.add_argument("--num-devices", type=int, default=None,
                   help="devices to use (default: all)")
    # Megatron-style model parallelism (apex.transformer parity, GSPMD form)
    p.add_argument("--tensor-parallel", type=int, default=1, metavar="TP",
                   help="shard attention heads / MLP features / vocab over "
                        "a 'model' mesh axis of this size (BERT archs); "
                        "remaining devices form the data axis")
    p.add_argument("--sequence-parallel", action="store_true",
                   help="with --tensor-parallel: keep activations outside "
                        "the TP blocks sequence-sharded (Megatron-SP)")
    p.add_argument("--pipeline-parallel", type=int, default=1, metavar="PP",
                   help="split BERT/GPT's encoder layers into this many "
                        "stages "
                        "driven by the SPMD ring schedule "
                        "(transformer/bert_pipeline.py); remaining devices "
                        "form the data axis")
    p.add_argument("--microbatches", type=int, default=4,
                   help="ring slots per data shard under "
                        "--pipeline-parallel")
    p.add_argument("--pipeline-schedule", default="ring",
                   choices=["ring", "1f1b", "interleaved"],
                   help="pipeline program: SPMD ring (autodiff backward; "
                        "composes with TP), true 1F1B (bounded in-flight "
                        "activations), or interleaved virtual stages "
                        "(apex's three schedule entry points)")
    p.add_argument("--virtual-stages", type=int, default=None,
                   help="chunks per device for --pipeline-schedule "
                        "interleaved (default 2; rejected with other "
                        "schedules rather than silently ignored)")
    p.add_argument("--context-parallel", type=int, default=1, metavar="CP",
                   help="shard BERT's sequence over a 'context' mesh axis "
                        "of this size (ppermute KV-ring attention — the "
                        "long-context training path); remaining devices "
                        "form the data axis")
    p.add_argument("--cp-mode", default="ring",
                   choices=["ring", "zigzag", "ulysses"],
                   help="attention program under --context-parallel: "
                        "'ring' (ppermute KV ring), 'zigzag' (load-"
                        "balanced CAUSAL ring, gpt archs — each device "
                        "holds chunks (i, 2n-1-i) so every ring step does "
                        "identical live work), 'ulysses' (all-to-all head "
                        "sharding: full sequence per device, H/N heads "
                        "per device; needs heads divisible by CP)")
    p.add_argument("--moe-experts", type=int, default=0, metavar="E",
                   help="switch-MoE BERT/GPT FFNs with E experts, E/n per "
                        "device over the 'data' axis of size n (expert "
                        "parallelism via all_to_all dispatch; requires "
                        "E to be a multiple of the data-axis size)")
    p.add_argument("--moe-aux-weight", type=float, default=1e-2,
                   help="weight of the Switch load-balancing aux loss in "
                        "the --moe-experts objective")
    p.add_argument("--moe-top-k", type=int, default=1, choices=[1, 2],
                   help="router fan-out under --moe-experts: 1 = Switch "
                        "top-1, 2 = GShard-style top-2 (renormalized "
                        "gates; second choices dropped first under "
                        "capacity pressure)")
    p.add_argument("--moe-capacity-factor", type=float, default=1.25,
                   help="per-expert token capacity multiplier under "
                        "--moe-experts (overflow tokens ride the residual "
                        "only)")
    # harness
    p.add_argument("--resume", default="", help="checkpoint dir to resume")
    p.add_argument("--checkpoint-dir", default="")
    p.add_argument("--async-checkpoint", action="store_true",
                   help="don't block training on checkpoint IO (orbax "
                        "background write; joined before the next save)")
    p.add_argument("--save-every-steps", type=int, default=0, metavar="N",
                   help="also checkpoint every N optimizer steps (requires "
                        "--checkpoint-dir; epoch boundaries still save) — "
                        "bounds how stale the preemption grace path's "
                        "'last checkpoint' can be on long epochs")
    p.add_argument("--remat", default="none",
                   choices=["none", "conv", "block"],
                   help="rematerialization for image archs: 'conv' saves "
                        "only conv outputs (BN/ReLU recomputed in backward)"
                        ", 'block' saves only block inputs")
    p.add_argument("--host-pipeline", action="store_true",
                   help="feed batches from the native C++ prefetcher "
                        "(csrc/; the reference's fast_collate analog) "
                        "instead of on-device synthesis")
    p.add_argument("--print-freq", type=int, default=10)
    # observability (obs/ subsystem; README "Observability")
    p.add_argument("--metrics-jsonl", default="", metavar="PATH",
                   help="emit one schema-valid telemetry record per step "
                        "(loss, scale, grad norm, step time, items/sec, "
                        "overflow count) plus run header/summary to this "
                        "JSONL file; rank 0 writes by default "
                        "(tools/metrics_lint.py validates)")
    p.add_argument("--metrics-all-ranks", action="store_true",
                   help="with --metrics-jsonl: every process writes its "
                        "own per-host file (PATH.rank<K> for K > 0)")
    p.add_argument("--profile-window", default="", metavar="N:M",
                   help="capture a jax profiler trace for exactly run-"
                        "relative steps N..M (1-based, inclusive) instead "
                        "of --prof's whole-run dump")
    p.add_argument("--cost-model", action="store_true",
                   help="with --metrics-jsonl: compile the step/eval "
                        "functions through the AOT path and emit one "
                        "schema-v6 'compile_event' (compile wall time, "
                        "lowering hash) + 'cost_model' (XLA flops/HBM "
                        "bytes/memory + roofline verdict) record per "
                        "compilation (obs/costmodel.py; zero extra "
                        "compiles — tools/cost_report.py reports)")
    p.add_argument("--trace", action="store_true",
                   help="with --metrics-jsonl: emit schema-v9 "
                        "trace_event records for every host span "
                        "(data / step / checkpoint) — a per-step "
                        "timeline exportable to Perfetto via "
                        "tools/trace_export.py; histograms and stdout "
                        "unchanged (README 'Request tracing')")
    p.add_argument("--tick-profile", action="store_true",
                   help="with --metrics-jsonl: arm the hot-path step "
                        "profiler (obs/tickprof.py, ISSUE 17) — every "
                        "image-loop training step decomposes into "
                        "data_wait / dispatch / device (an explicit "
                        "block-until-ready boundary separating enqueue "
                        "cost from device execution) / telemetry / "
                        "checkpoint, folded into online quantile "
                        "sketches; every Nth step emits a schema-v15 "
                        "tick_profile record and the run closes with an "
                        "overhead_summary (host_gap_ms, per-phase "
                        "percentiles, host_overhead_frac — what "
                        "tools/perf_ledger.py regression-gates).  The "
                        "boundary sync trades host/device overlap for "
                        "attribution, so keep it off for BENCH numbers; "
                        "LM loops are not decomposed (README 'Hot-path "
                        "profiling')")
    p.add_argument("--tick-profile-every", type=int, default=16,
                   metavar="N",
                   help="emit a tick_profile record every N steps "
                        "(default 16; the cumulative overhead_summary "
                        "always folds EVERY step)")
    # diagnostics stratum (obs/flight.py, obs/watchdog.py, obs/numerics.py;
    # README "Diagnostics") — all write to the --metrics-jsonl sink
    p.add_argument("--flight-recorder", action="store_true",
                   help="with --metrics-jsonl: keep a ring of the last K "
                        "step records and, on crash/SIGTERM/SIGINT, emit "
                        "a 'crash_dump' record plus an aborted run "
                        "summary to the JSONL sink (obs/flight.py)")
    p.add_argument("--flight-recorder-keep", type=int, default=64,
                   metavar="K",
                   help="step records the flight recorder's ring retains")
    p.add_argument("--stall-timeout", type=float, default=0.0, metavar="S",
                   help="with --metrics-jsonl: if no step completes for S "
                        "seconds, dump all-thread stacks and emit a "
                        "'stall' record (0 disables; the deadline covers "
                        "the first step's compile — size it accordingly)")
    p.add_argument("--stall-trace", action="store_true",
                   help="with --stall-timeout: on the first stall, arm a "
                        "one-shot profiler trace (stall start to first "
                        "recovered step) in the --profile-window trace dir")
    p.add_argument("--numerics-check", default="off",
                   choices=["off", "overflow", "always"],
                   help="overflow provenance fused into the engine's "
                        "finite-check pass: per-module non-finite counts "
                        "+ grad norms, emitted as 'overflow_event' "
                        "records naming the offending module(s) "
                        "('overflow': only on overflow steps; 'always': "
                        "every step; requires --metrics-jsonl)")
    # resilience stratum (apex_example_tpu/resilience/; README
    # "Resilience") — preemption grace, supervised auto-resume, fault
    # drills.  tools/supervise.py is the restart supervisor.
    p.add_argument("--preempt-grace", action="store_true",
                   help="catch SIGTERM/SIGUSR1 and exit gracefully at the "
                        "next step boundary: join pending checkpoint IO, "
                        "save a final checkpoint (with --checkpoint-dir), "
                        "emit a 'preemption' record (with --metrics-jsonl) "
                        "and exit 75/EX_TEMPFAIL so a supervisor "
                        "(tools/supervise.py) restarts the run instead of "
                        "declaring it broken")
    p.add_argument("--inject-fault", default="", metavar="KIND@STEP",
                   help="deterministic fault drill at a 1-based global "
                        "step: crash | sigterm | hang | nan "
                        "(resilience/faults.py); a resumed run already "
                        "past STEP never re-fires")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--eval", action="store_true")
    p.add_argument("--eval-batches", type=int, default=10,
                   help="validation batches per eval pass")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="microbatches accumulated per optimizer step")
    p.add_argument("--tensorboard", default="",
                   help="write scalars to this tensorboard logdir")
    p.add_argument("--prof", action="store_true",
                   help="capture a jax profiler trace of a few steps")
    p.add_argument("--prof-server", type=int, default=0, metavar="PORT",
                   help="start jax.profiler.start_server(PORT) for live "
                        "xprof/tensorboard capture (SURVEY.md §6 tracing)")
    # accepted no-ops (CUDA-specific in the reference)
    p.add_argument("--local_rank", type=int, default=0)
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--deterministic", action="store_true")
    return p.parse_args(argv)


def select_devices(args):
    devices = jax.devices()[:args.num_devices] if args.num_devices \
        else jax.devices()
    if args.batch_size % len(devices):
        raise SystemExit(f"--batch-size {args.batch_size} not divisible by "
                         f"{len(devices)} devices")
    return devices


def build_lr(args):
    """Float or traced schedule f(step), fed to the fused optimizers'
    callable-lr path."""
    total = args.epochs * args.steps_per_epoch
    boundaries = [int(e) * args.steps_per_epoch
                  for e in args.lr_decay_epochs.split(",") if e]
    return build_schedule(args.lr_schedule, args.lr, total,
                          warmup_steps=args.warmup_steps,
                          boundaries=boundaries, gamma=args.lr_gamma,
                          min_lr=args.lr_min)


def make_writer(args):
    """Optional tensorboard writer (SURVEY.md §6 metrics row: stdout meters
    are the contract; tensorboardX sits behind a flag), rank-0 only."""
    if not args.tensorboard or not is_main_process():
        return None
    from tensorboardX import SummaryWriter
    return SummaryWriter(args.tensorboard)


def make_telemetry(args):
    """Flag-gated obs wiring shared by the image and LM loops: the per-step
    telemetry emitter (--metrics-jsonl), the profiler window
    (--profile-window), and the diagnostics stratum (--flight-recorder /
    --stall-timeout / --numerics-check) riding the emitter as observers.
    Also binds the span registry so host spans ("data"/"step") aggregate
    into the run_summary."""
    emitter = recorder = watchdog = None
    # Clear any cost-model/tracer instance a previous in-process run
    # leaked (e.g. it died between telemetry setup and its finally):
    # this run's instrument() sites run after us, so a stale default
    # must not write records into the old run's stream.
    obs.costmodel.set_default(None)
    obs.trace.set_default(None)
    if args.metrics_jsonl:
        registry = obs.MetricsRegistry()
        obs.set_default_registry(registry)
        sink = obs.JsonlSink(args.metrics_jsonl,
                             all_ranks=args.metrics_all_ranks)
        emitter = TelemetryEmitter(sink, registry=registry)
        emitter.run_header(config=vars(args), argv=sys.argv[1:],
                           arch=args.arch)
        if args.cost_model:
            # Installed as the process default so the loops' single
            # instrument() call sites stay no-ops when the flag is off;
            # close_telemetry clears it (a programmatic caller must not
            # inherit the instance).
            obs.costmodel.set_default(obs.CostModel(
                sink=sink, registry=registry, run_id=emitter.run_id))
        if getattr(args, "trace", False):
            # Same process-default shape: the span layer (obs/spans.py)
            # consults it, so every data/step/checkpoint span lands as
            # a schema-v9 trace_event alongside its histogram; a
            # supervised restart joins the parent timeline via
            # APEX_TRACE_ID (obs/trace.py).
            obs.trace.set_default(obs.Tracer(sink, run_id=emitter.run_id))
        if args.flight_recorder:
            recorder = obs.FlightRecorder(emitter, config=vars(args),
                                          keep=args.flight_recorder_keep)
            recorder.install()
            emitter.add_observer(recorder.on_record)
        if args.stall_timeout > 0:
            from apex_example_tpu.obs import DEFAULT_TRACE_DIR
            watchdog = obs.StallWatchdog(
                sink, deadline_s=args.stall_timeout, run_id=emitter.run_id,
                trace_dir=DEFAULT_TRACE_DIR if args.stall_trace else None)
            watchdog.start()
            emitter.add_observer(watchdog.on_record)
        if args.numerics_check != "off":
            monitor = obs.NumericsMonitor(sink, mode=args.numerics_check,
                                          run_id=emitter.run_id)
            emitter.add_observer(monitor.on_record)
    return emitter, make_profiler_window(args.profile_window or None), \
        recorder, watchdog


def make_tickprof(args, emitter):
    """--tick-profile wiring (ISSUE 17): the hot-path step profiler,
    sharing the emitter's sink and run id.  The image loop feeds it one
    data_wait/dispatch/device/telemetry/checkpoint decomposition per
    step; arming it costs one block_until_ready per step at the
    enqueue/device boundary — attribution in exchange for host/device
    overlap (README 'Hot-path profiling')."""
    if not getattr(args, "tick_profile", False):
        return None
    if emitter is None:
        raise SystemExit("--tick-profile requires --metrics-jsonl (the "
                         "tick_profile/overhead_summary records ride "
                         "the metrics stream)")
    if args.tick_profile_every < 1:
        raise SystemExit(f"--tick-profile-every must be >= 1, got "
                         f"{args.tick_profile_every}")
    from apex_example_tpu.obs.tickprof import TickProfiler
    return TickProfiler(kind="train",
                        sample_every=args.tick_profile_every,
                        emit=emitter.sink.write, run_id=emitter.run_id)


def close_telemetry(emitter, profwin, recorder=None, watchdog=None):
    """Counterpart of make_telemetry for the finally blocks: stop an open
    trace window, disarm the watchdog, flush the run_summary, unbind the
    span registry (a programmatic caller must not inherit it).  Called
    while an exception is unwinding (sys.exc_info is live inside a
    finally), it routes through the flight recorder instead: crash_dump +
    aborted summary, not a clean close."""
    if profwin is not None:
        profwin.close()
    if watchdog is not None:
        watchdog.close()
    exc = sys.exc_info()
    if recorder is not None and exc[0] is not None \
            and not issubclass(exc[0], SystemExit):
        recorder.crash_dump(f"exception:{exc[0].__name__}", exc_info=exc)
    if recorder is not None:
        recorder.close()
    if emitter is not None:
        emitter.close()
    obs.set_default_registry(None)
    obs.costmodel.set_default(None)
    obs.trace.set_default(None)


def make_resilience(args, recorder):
    """--preempt-grace handler + --inject-fault plan for a train loop.
    Installed AFTER make_telemetry so the grace handler can take SIGTERM
    ownership over from the flight recorder (release_signal handover —
    a preempted run saves and exits 75 instead of crash-dumping 143);
    the recorder keeps excepthook/atexit/faulthandler for real crashes."""
    preempt = fault = None
    if args.preempt_grace:
        preempt = PreemptionHandler(recorder=recorder)
        preempt.install()
    if args.inject_fault:
        fault = FaultPlan.parse(args.inject_fault)
    return preempt, fault


def host_loop_state(args, global_step):
    """The host-state checkpoint sidecar (utils/checkpoint.py): loop
    position + host PRNG state — everything resume needs that lives
    outside the TrainState.  The synthetic data streams are index-driven
    (data/__init__.py: batch_fn(global_step)), so ``data_index`` IS the
    stream position; persisting it (with the python PRNG, for host-side
    augmentation) makes mid-epoch resume continue the exact stream
    instead of restarting the epoch."""
    import random
    rng_version, rng_state, rng_gauss = random.getstate()
    return {
        "step": int(global_step),
        "data_index": int(global_step),
        "steps_per_epoch": int(args.steps_per_epoch),
        "epoch": int(global_step) // args.steps_per_epoch,
        "step_in_epoch": int(global_step) % args.steps_per_epoch,
        "seed": int(args.seed),
        "python_random": [rng_version, list(rng_state), rng_gauss],
    }


def restore_loop_position(args, rmgr, global_step):
    """(start_epoch, start_step_in_epoch) for a resumed run, restoring
    the host PRNG from the sidecar when one exists.  Falls back to
    deriving position from the restored step alone (pre-sidecar
    checkpoints stay resumable — at epoch granularity both forms agree;
    mid-epoch they also agree as long as --steps-per-epoch is
    unchanged)."""
    hs = rmgr.load_host_state(global_step)
    start_epoch = global_step // args.steps_per_epoch
    start_i = global_step % args.steps_per_epoch
    if hs:
        if hs.get("step") == global_step \
                and hs.get("steps_per_epoch") == args.steps_per_epoch:
            start_epoch = int(hs.get("epoch", start_epoch))
            start_i = int(hs.get("step_in_epoch", start_i))
        rng = hs.get("python_random")
        if rng:
            import random
            random.setstate((rng[0], tuple(rng[1]), rng[2]))
    return start_epoch, start_i


def graceful_preempt_exit(args, mgr, state, preempt, emitter, global_step,
                          last_saved=None):
    """The preemption grace sequence (resilience/preemption.py docstring;
    runs at a step boundary, NOT in signal context): join any pending
    async orbax write, save a final checkpoint + host-state sidecar,
    emit the schema-v4 ``preemption`` record, and hand back EX_TEMPFAIL
    (75) so the supervisor restarts rather than buries the run.  The
    caller's finally still runs close_telemetry — with no exception
    unwinding, so the stream closes with a normal (un-aborted)
    run_summary after the preemption record."""
    if args.prof:
        # The returns below skip the loops' post-try stop_trace — an
        # unstopped trace is never finalized on disk.
        jax.profiler.stop_trace()
        rank_print("profile written to /tmp/apex_tpu_trace")
    ckstep = None
    if mgr is not None:
        if is_main_process():
            mgr.wait_until_finished()
            if last_saved != int(state.step):
                mgr.save(state, wait=True,
                         host_state=host_loop_state(args, global_step))
            else:
                # This exact step is already on disk (a --save-every-steps
                # boundary); just refresh its sidecar.
                mgr.save_host_state(int(state.step),
                                    host_loop_state(args, global_step))
        # ckstep/saved describe the RUN, not this rank: rank 0 owns the
        # write (state is replicated), so every rank's preemption record
        # reports the same run-level outcome — fleet_report must not see
        # contradictory saved flags for one run.
        ckstep = int(state.step)
        rank_print(f"preempted by {preempt.signal_name}: saved checkpoint "
                   f"at step {ckstep}; exiting {EX_TEMPFAIL} (resumable)")
    else:
        rank_print(f"preempted by {preempt.signal_name}: no "
                   f"--checkpoint-dir, nothing saved; exiting "
                   f"{EX_TEMPFAIL}")
    if emitter is not None:
        emitter.preemption(preempt.signal_name, step=int(global_step),
                           checkpoint_step=ckstep,
                           saved=ckstep is not None)
    return EX_TEMPFAIL


def build_optimizer(args):
    lr = build_lr(args)
    # Under LARC, weight decay moves INTO the trust ratio (apex zeroes the
    # group's wd and folds it into the LARC denominator; wd applied by the
    # inner optimizer after the scaling would be a different update).
    wd = 0.0 if args.larc else args.weight_decay
    if args.opt == "sgd":
        opt = FusedSGD(lr=lr, momentum=args.momentum, weight_decay=wd)
    elif args.opt == "adam":
        opt = FusedAdam(lr=lr, weight_decay=wd)
    elif args.opt == "novograd":
        opt = FusedNovoGrad(lr=lr, weight_decay=wd)
    elif args.opt == "adagrad":
        opt = FusedAdagrad(lr=lr, weight_decay=wd)
    else:
        opt = FusedLAMB(lr=lr, weight_decay=wd)
    if args.larc:
        # apex recipe shape: LARC wraps the inner optimizer and scales each
        # leaf's update by the trust ratio ||p||/||g|| (parallel/larc.py).
        # Clip mode needs the outer lr; under an LR schedule the BASE lr
        # bounds the ratio (apex clamps against the per-step group lr).
        opt = LARC(opt.as_optax(), trust_coefficient=args.larc_trust,
                   lr=args.lr, weight_decay=args.weight_decay)
    return opt


def pick_devices(args):
    """Device list without main()'s batch-divisibility check (the TP/PP
    paths divide the batch by their data-axis size instead)."""
    return jax.devices()[:args.num_devices] if args.num_devices \
        else jax.devices()


def build_zero_optimizer(args, n_dev, gspmd=False,
                         global_mean_grads=False):
    """Optimizer for the --zero paths.

    shard_map path (tp == 1): DistributedFusedAdam, the explicit flat-buffer
    reduce-scatter/all-gather program.  GSPMD path (--tensor-parallel): plain
    FusedAdam — there the ZeRO-1 contract lives entirely in the opt-state
    shardings (engine.gspmd_state_shardings zero_axis), not in the optimizer.
    """
    if args.larc:
        raise SystemExit("--larc does not compose with --zero (the sharded "
                         "optimizer owns its update)")
    if n_dev < 2:
        raise SystemExit("--zero needs >1 device on the data axis (state "
                         "shards over it)")
    if args.opt != "adam":
        raise SystemExit("--zero is wired for --opt adam "
                         "(DistributedFusedAdam)")
    if args.grad_accum != 1:
        raise SystemExit("--zero does not support --grad-accum")
    if args.gradient_predivide_factor != 1.0:
        raise SystemExit("--zero does not support "
                         "--gradient-predivide-factor (the reduction "
                         "lives inside the sharded optimizer)")
    if gspmd:
        return FusedAdam(lr=build_lr(args), weight_decay=args.weight_decay)
    return DistributedFusedAdam(lr=build_lr(args),
                                weight_decay=args.weight_decay,
                                world=n_dev,
                                # the CP losses are psum-normalized
                                # GLOBALLY, so their implicitly psum-ed
                                # grads are already the true global mean
                                # (optim/distributed.py ctor docstring)
                                grads_global_mean=global_mean_grads)


def main(argv=None):
    args = parse_args(argv)
    if args.grad_accum > 1 and args.batch_size % args.grad_accum:
        # Uniform rejection for every path (the microbatch split would
        # otherwise surface as a reshape TypeError deep inside tracing).
        raise SystemExit(f"--batch-size {args.batch_size} not divisible by "
                         f"--grad-accum {args.grad_accum}")
    # Multi-host rendezvous (no-op single-host): must precede first device
    # use.  Launch contract in parallel/launch.py — JAX_COORDINATOR_ADDRESS
    # or the reference's MASTER_ADDR/PORT + WORLD_SIZE/RANK (hosts).
    proc_id, n_procs = maybe_initialize_distributed()
    # Reference behavior: only rank 0 writes to stdout.  rank_print (the
    # old global-print monkeypatch's replacement, obs/logging.py) keeps
    # rank 0 byte-identical to print() and routes worker lines to the
    # package logger at DEBUG instead of deleting them.
    if args.prof and args.profile_window:
        raise SystemExit("--prof traces the whole run; pick it or "
                         "--profile-window N:M, not both")
    if (args.flight_recorder or args.stall_timeout > 0
            or args.numerics_check != "off") and not args.metrics_jsonl:
        raise SystemExit("--flight-recorder/--stall-timeout/"
                         "--numerics-check write to the telemetry sink; "
                         "add --metrics-jsonl PATH")
    if args.cost_model and not args.metrics_jsonl:
        raise SystemExit("--cost-model emits compile_event/cost_model "
                         "records to the telemetry sink; add "
                         "--metrics-jsonl PATH")
    if args.trace and not args.metrics_jsonl:
        raise SystemExit("--trace emits trace_event records to the "
                         "telemetry sink; add --metrics-jsonl PATH")
    if args.stall_trace and args.stall_timeout <= 0:
        raise SystemExit("--stall-trace arms on a stall; it needs "
                         "--stall-timeout S")
    if args.save_every_steps < 0:
        raise SystemExit(f"--save-every-steps {args.save_every_steps} "
                         "must be >= 0")
    if args.save_every_steps and not args.checkpoint_dir:
        raise SystemExit("--save-every-steps writes through "
                         "--checkpoint-dir; add it")
    if args.inject_fault:
        # Early CLI gate only (uniform SystemExit before devices/model
        # build); make_resilience re-parses to build each loop's plan.
        try:
            FaultPlan.parse(args.inject_fault)
        except ValueError as e:
            raise SystemExit(str(e))
    if args.numerics_check != "off" and (
            args.zero or args.pipeline_parallel > 1
            or args.context_parallel > 1 or args.moe_experts
            or args.arch.startswith("transformer_xl")):
        raise SystemExit("--numerics-check rides the shared engine step's "
                         "finite-check pass (engine.make_train_step); the "
                         "--zero/--pipeline-parallel/--context-parallel/"
                         "--moe-experts and transformer_xl steps own their "
                         "own grad pipelines and are not wired yet")
    if args.profile_window:
        from apex_example_tpu.obs import parse_window
        try:
            parse_window(args.profile_window)
        except ValueError as e:
            raise SystemExit(str(e))
    if args.prof_server:
        # Per-process port offset: single-host multi-process launches (the
        # localhost rendezvous tests/test_launch.py exercises) would
        # otherwise all bind the same port.
        port = args.prof_server + jax.process_index()
        jax.profiler.start_server(port)
        rank_print(f"profiler server on :{port}")
    policy, scaler = amp.initialize(
        args.opt_level, loss_scale=args.loss_scale,
        keep_batchnorm_fp32=args.keep_batchnorm_fp32)
    if args.fused_attention and not args.arch.startswith(("bert", "gpt")):
        # Uniform rejection (not a silent no-op): the kernel is wired into
        # the BERT/GPT attention module only — see lm_main for the
        # transformer_xl rationale.
        raise SystemExit("--fused-attention is wired for the BERT/GPT "
                         "archs only")
    if args.fused_attention and args.opt_level == "O3":
        # The kernel's softmax is always fp32; O3's contract is half softmax
        # and the module gate would silently fall back to the naive path.
        raise SystemExit("--fused-attention requires fp32 softmax "
                         "(opt levels O0-O2); O3 runs softmax half")
    if args.arch in LM_ARCHS:
        return lm_main(args, policy, scaler)

    if args.tensor_parallel > 1:
        raise SystemExit("--tensor-parallel is wired for the transformer "
                         "archs (bert_*, transformer_xl*); image models "
                         "scale by DP/--zero")
    if args.pipeline_parallel > 1:
        raise SystemExit("--pipeline-parallel is wired for the BERT archs; "
                         "image models scale by DP/--zero")
    if args.context_parallel > 1:
        raise SystemExit("--context-parallel is wired for the BERT archs "
                         "(sequence sharding; images have no sequence)")
    if args.moe_experts:
        raise SystemExit("--moe-experts is wired for the BERT archs "
                         "(switch-MoE replaces the transformer FFN)")
    if args.cp_mode != "ring":
        raise SystemExit(f"--cp-mode {args.cp_mode} only applies with "
                         "--context-parallel on the LM archs")

    spec = CIFAR10 if args.dataset == "cifar10" else IMAGENET
    devices = select_devices(args)
    n_dev = len(devices)

    # Per-op-class dtypes from the policy + white/blacklist tables (O1's
    # call-site classification; O0/O2/O3 collapse to the uniform table).
    md = amp.module_dtypes(policy)
    model = ARCHS[args.arch](
        num_classes=spec["num_classes"],
        dtype=md.compute,
        param_dtype=md.param,
        bn_dtype=md.bn_stats,
        bn_io_dtype=md.bn_io,
        bn_axis_name="data" if (args.sync_bn and n_dev > 1) else None,
        remat=args.remat)

    optimizer = build_zero_optimizer(args, n_dev) if args.zero \
        else build_optimizer(args)
    if args.host_pipeline:
        from apex_example_tpu import host_runtime
        if not host_runtime.available():
            raise SystemExit("--host-pipeline: native runtime not buildable")
    else:
        batch_fn = lambda i: image_batch(
            jnp.asarray(i, jnp.int32), batch_size=args.batch_size,
            image_size=spec["image_size"], channels=spec["channels"],
            num_classes=spec["num_classes"], seed=args.seed)

    sample = jnp.zeros((1, spec["image_size"], spec["image_size"],
                        spec["channels"]), jnp.float32)
    state = create_train_state(jax.random.PRNGKey(args.seed), model,
                               optimizer, sample, policy, scaler)

    ddp = DDPConfig(
        delay_allreduce=args.delay_allreduce,
        gradient_predivide_factor=args.gradient_predivide_factor,
        quantized_allreduce=args.quantized_allreduce == "int8",
        quant_chunk=args.quant_chunk)

    if n_dev > 1:
        mesh = make_data_mesh(devices=devices)
        if args.zero:
            step_fn = make_zero_train_step(mesh, model, optimizer, policy)
            rank_print(f"ZeRO-1 DDP over {n_dev} devices: {mesh}")
        else:
            step_fn = make_sharded_train_step(
                mesh, model, optimizer, policy, ddp=ddp,
                grad_accum=args.grad_accum,
                numerics=args.numerics_check != "off")
            rank_print(f"DDP over {n_dev} devices: {mesh}")
    else:
        step_fn = jax.jit(make_train_step(
            model, optimizer, policy, grad_accum=args.grad_accum,
            numerics=args.numerics_check != "off"),
            donate_argnums=(0,))
    eval_fn = jax.jit(make_eval_step(model))

    mgr = CheckpointManager(args.checkpoint_dir) if args.checkpoint_dir \
        else None
    writer = make_writer(args)
    tb = TensorBoardAdapter(writer)
    emitter, profwin, recorder, watchdog = make_telemetry(args)
    tickprof = make_tickprof(args, emitter)
    preempt, fault = make_resilience(args, recorder)
    # --cost-model: re-route the step through the AOT path so its one
    # compilation is harvested (compile_event + cost_model records); a
    # no-op identity without the flag (obs/costmodel.instrument).
    step_fn = obs.costmodel.instrument("train_step", step_fn)
    eval_fn = obs.costmodel.instrument("eval_step", eval_fn)
    start_epoch = start_i = 0
    if args.resume:
        rmgr = CheckpointManager(args.resume)
        if n_dev > 1:
            state = restore_under_mesh(
                rmgr, state, mesh, optimizer if args.zero else None)
        else:
            state = rmgr.restore(state)
        start_epoch, start_i = restore_loop_position(args, rmgr,
                                                     int(state.step))
        rank_print(f"resumed from step {int(state.step)} (epoch {start_epoch})")

    if args.prof:
        jax.profiler.start_trace("/tmp/apex_tpu_trace")

    global_step = int(state.step)
    prefetcher = None
    if args.host_pipeline:
        # Created AFTER resume so the native stream continues at the exact
        # batch index training stopped at (start_index); the eval stream
        # lives at a far-offset index range, disjoint from training — the
        # same contract as the on-device batch_fn(10_000 + epoch) path.
        mk = lambda start: host_runtime.NativePrefetcher(
            batch=args.batch_size, image_size=spec["image_size"],
            num_classes=spec["num_classes"], channels=spec["channels"],
            seed=args.seed, start_index=start)
        prefetcher = mk(global_step)

        def batch_fn(i):
            images, labels = next(prefetcher)
            return jnp.asarray(images), jnp.asarray(labels)

        def eval_batch_fn(i):
            # Deterministic in the batch index alone (a fresh stream per
            # call), so fresh and resumed runs evaluate identical batches —
            # the same contract as the on-device batch_fn(10_000 + epoch).
            pf = mk(10_000_000 + i)
            try:
                images, labels = next(pf)
            finally:
                pf.close()
            return jnp.asarray(images), jnp.asarray(labels)
    else:
        eval_batch_fn = batch_fn

    run_step = 0                    # run-relative step index (1-based in
    last_saved = None               # the loop; drives the profiler window)
    try:
        for epoch in range(start_epoch, args.epochs):
            losses, top1s = AverageMeter("loss"), AverageMeter("top1")
            thr = Throughput(warmup_steps=2)
            # Mid-epoch resume (host-state sidecar): the first resumed
            # epoch continues at its saved position instead of rerunning
            # the whole epoch — data indices stay continuous either way
            # (batch_fn is index-driven), this keeps the STEP COUNT exact.
            for i in range(start_i if epoch == start_epoch else 0,
                           args.steps_per_epoch):
                run_step += 1
                t_tick_start = time.perf_counter() \
                    if tickprof is not None else 0.0
                if profwin is not None:
                    profwin.on_step_start(run_step)
                with span("data"):
                    batch = batch_fn(global_step)
                if fault is not None:
                    batch = fault.maybe_poison(global_step + 1, batch)
                t_data_end = time.perf_counter() \
                    if tickprof is not None else 0.0
                t0 = time.perf_counter()
                with span("step"):
                    state, metrics = step_fn(state, batch)
                    global_step += 1
                    if tickprof is not None:
                        # The dispatch/device boundary (ISSUE 17): the
                        # jitted call has returned, its outputs may
                        # still be computing — block HERE so enqueue
                        # cost and device time separate.  Value-
                        # preserving: on_step's metric fetch was about
                        # to block on the same values anyway.
                        t_enqueue_end = time.perf_counter()
                        jax.block_until_ready((state, metrics))
                        t_device_end = time.perf_counter()
                    if emitter is not None:
                        # Inside the span: the blocking metric fetch is
                        # part of what "step" means when telemetry is on
                        # (obs.spans.PHASES).
                        emitter.on_step(global_step=global_step,
                                        epoch=epoch, metrics=metrics,
                                        items=args.batch_size, t_start=t0)
                thr.step(args.batch_size)
                if profwin is not None:
                    profwin.on_step_end(run_step, blocker=metrics)
                if (i + 1) % args.print_freq == 0 \
                        or i + 1 == args.steps_per_epoch:
                    losses.update(float(metrics["loss"]))
                    top1s.update(float(metrics["top1"]))
                    rank_print(f"epoch {epoch} step "
                          f"{i + 1}/{args.steps_per_epoch} "
                          f"{losses} {top1s} "
                          f"{thr.rate:.1f} img/s "
                          f"scale {float(metrics['scale']):.0f}")
                    tb.scalars({"train/loss": losses.val,
                                "train/top1": top1s.val,
                                "train/img_per_sec": thr.rate},
                               global_step)
                t_tel_end = time.perf_counter() \
                    if tickprof is not None else 0.0
                if args.save_every_steps and mgr is not None \
                        and is_main_process() \
                        and global_step % args.save_every_steps == 0:
                    with span("checkpoint"):
                        mgr.save(state, wait=not args.async_checkpoint,
                                 host_state=host_loop_state(args,
                                                            global_step))
                    last_saved = global_step
                    rank_print(f"saved checkpoint at step {global_step}")
                if tickprof is not None:
                    # Contiguous boundaries: the five phases telescope
                    # to the measured wall (perf_ledger's 1% gate).
                    # checkpoint covers the save-every-steps window and
                    # is 0.0 on steps that skip it.
                    t_tick_end = time.perf_counter()
                    tickprof.observe_tick(
                        t_tick_start,
                        (t_tick_end - t_tick_start) * 1e3,
                        data_wait=(t_data_end - t_tick_start) * 1e3,
                        dispatch=(t_enqueue_end - t_data_end) * 1e3,
                        device=(t_device_end - t_enqueue_end) * 1e3,
                        telemetry=(t_tel_end - t_device_end) * 1e3,
                        checkpoint=(t_tick_end - t_tel_end) * 1e3)
                if fault is not None:
                    # After the step's telemetry AND any interval save
                    # landed: forensics hold the last good step, and a
                    # crash@N drill with --save-every-steps N resumes
                    # PAST the fault instead of crash-looping.
                    fault.maybe_fire(global_step)
                if preempt is not None and preempt.preempted:
                    break               # grace sequence below the loops
            if preempt is not None and preempt.preempted:
                break
            if args.eval:
                # Full validation loop (reference harness shape: N batches,
                # top-1/top-5 meters, SURVEY.md §3.5) on a held-out index
                # range disjoint from training.
                el, e1, e5 = (AverageMeter("loss"), AverageMeter("top1"),
                              AverageMeter("top5"))
                for j in range(args.eval_batches):
                    em = eval_fn(state, eval_batch_fn(
                        10_000 + epoch * args.eval_batches + j))
                    el.update(float(em["loss"]))
                    e1.update(float(em["top1"]))
                    e5.update(float(em["top5"]))
                rank_print(f"epoch {epoch} EVAL loss {el.avg:.4f} "
                      f"top1 {e1.avg:.2f} top5 {e5.avg:.2f} "
                      f"({args.eval_batches} batches)")
                tb.scalars({"eval/loss": el.avg, "eval/top1": e1.avg,
                            "eval/top5": e5.avg}, global_step)
            if mgr is not None and is_main_process() \
                    and last_saved != int(state.step):
                # Reference: rank 0 writes the checkpoint (SURVEY.md §4.5);
                # state is replicated so one host's copy is the full state.
                # (last_saved guard: a --save-every-steps boundary landing
                # on the epoch end already wrote this exact step.)
                with span("checkpoint"):
                    mgr.save(state, wait=not args.async_checkpoint,
                             host_state=host_loop_state(args, global_step))
                last_saved = int(state.step)
                rank_print(f"saved checkpoint at step {int(state.step)}")
            if preempt is not None and preempt.preempted:
                # Re-poll AFTER eval + the epoch-end save: a SIGTERM that
                # lands during either must not cost one more training
                # step of the scheduler's kill-escalation window.
                break
        if preempt is not None and preempt.preempted:
            return graceful_preempt_exit(args, mgr, state, preempt,
                                         emitter, global_step,
                                         last_saved=last_saved)
        if tickprof is not None and tickprof.ticks:
            # Clean-exit close: the cumulative overhead fold lands
            # before close_telemetry's run_summary, so report tools
            # find it ahead of the stream tail.
            emitter.sink.write(tickprof.summary_record())
    finally:
        if preempt is not None:
            preempt.close()
        close_telemetry(emitter, profwin, recorder, watchdog)
        if prefetcher is not None:
            prefetcher.close()
        tb.close()
        if mgr is not None:
            mgr.wait_until_finished()

    if args.prof:
        jax.profiler.stop_trace()
        rank_print("profile written to /tmp/apex_tpu_trace")
    return 0


def lm_main(args, policy, scaler):
    """C4 (BERT-base MLM + FusedLAMB) and C5 (Transformer-XL) workloads."""
    try:
        return _lm_main_impl(args, policy, scaler)
    finally:
        if (args.tensor_parallel > 1 or args.pipeline_parallel > 1
                or args.context_parallel > 1):
            # Undo the TP/PP/CP paths' process-global kernel-dispatch
            # override and mesh registration even when SETUP raises (bad
            # --resume dir, indivisible batch, ...): a programmatic caller
            # must not inherit them.
            from apex_example_tpu.ops import _config as ops_config
            from apex_example_tpu.transformer import parallel_state
            ops_config.set_force_xla(False)
            parallel_state.set_mesh(None)


def _lm_main_impl(args, policy, scaler):
    tp = args.tensor_parallel
    pp = args.pipeline_parallel
    cp = args.context_parallel
    is_bert = args.arch.startswith("bert")
    is_gpt = args.arch.startswith("gpt")
    if args.moe_experts:
        if not (is_bert or is_gpt):
            raise SystemExit("--moe-experts is wired for the BERT/GPT "
                             "archs (switch-MoE replaces the "
                             "transformer FFN)")
        if args.sequence_parallel or args.zero:
            raise SystemExit("--moe-experts does not compose with "
                             "--sequence-parallel or --zero yet; "
                             "--tensor-parallel, --context-parallel and "
                             "--pipeline-parallel compose")
        if pp > 1:
            # EP x PP (round 5): experts inside the ring schedule's stage
            # cells, aux loss riding the schedule carry.  Bounds:
            if args.pipeline_schedule != "ring":
                raise SystemExit("--moe-experts composes with "
                                 "--pipeline-schedule ring only (the 1F1B "
                                 "value program has no aux-loss channel)")
            if tp > 1 or cp > 1:
                raise SystemExit("--moe-experts --pipeline-parallel "
                                 "composes pairwise only (no MoE x PP x "
                                 "TP/CP triple yet)")
            if args.eval:
                raise SystemExit("--eval under --moe-experts "
                                 "--pipeline-parallel is not wired (the "
                                 "dense unpacked eval would route with a "
                                 "different global capacity)")
            ep_pp = len(pick_devices(args)) // pp
            if ep_pp < 1:
                raise SystemExit(f"--pipeline-parallel {pp} exceeds the "
                                 f"{len(pick_devices(args))} devices")
            if args.moe_experts % ep_pp:
                raise SystemExit(f"--moe-experts {args.moe_experts} must "
                                 f"be a multiple of the data-axis size "
                                 f"{ep_pp} (= devices / "
                                 f"--pipeline-parallel)")
        # EP x CP, EP x TP and the EP x CP x TP triple all compose: the
        # expert all_to_all (manual 'data'), the KV ring (manual
        # 'context') and the GSPMD TP collectives (automatic 'model') are
        # independent; see workloads._moe_cp_axis_names.
        if args.opt in ("lamb", "novograd") or args.larc:
            raise SystemExit("--opt lamb/novograd and --larc compute "
                             "per-tensor statistics that collapse on the "
                             "EP-sharded [E, ...] expert stacks; use adam/"
                             "sgd/adagrad with --moe-experts")
    if cp > 1:
        if not (is_bert or is_gpt):
            raise SystemExit("--context-parallel is wired for the BERT/GPT "
                             "archs (transformer_xl's long-context story "
                             "is its segment recurrence)")
        if args.zero and tp > 1:
            raise SystemExit("--zero --context-parallel --tensor-parallel "
                             "(the ZeRO x CP x TP triple) is not wired "
                             "yet; drop one")
        # (--zero + --pipeline-parallel is rejected by the pp block below)
        # --zero + --context-parallel composes (round 5): the flat
        # (mu, nu) buffers shard over 'data' inside the CP shard_map
        # (workloads._cp_state_spec); params stay replicated over both
        # axes, so the sharded update is context-invariant.
        # CP x PP composes (round 5): the KV ring rides inside the
        # schedule's stage cells on a third manual axis — and the
        # CP x PP x TP TRIPLE composes too (manual pipe/data/context,
        # automatic 'model', branch-free cells; parity-tested).  All
        # three --cp-mode layouts ride the schedules (zigzag is gpt-only
        # per the check below; the factory's zigzag_shard pre-pass +
        # schedule-embed position ids handle the reorder).
        if args.sequence_parallel:
            raise SystemExit("--sequence-parallel shards activations along "
                             "the sequence dim --context-parallel already "
                             "owns; CP composes with plain "
                             "--tensor-parallel")
        if args.fused_attention:
            raise SystemExit("--context-parallel composes the flash kernel "
                             "inside its KV ring already; drop "
                             "--fused-attention")
        if amp.module_dtypes(policy).softmax != jnp.float32:
            raise SystemExit("--context-parallel computes fp32 softmax in "
                             "its KV ring; O3's half-softmax contract does "
                             "not compose (opt levels O0-O2 only)")
        if args.seq_len % cp:
            raise SystemExit(f"--seq-len {args.seq_len} not divisible by "
                             f"--context-parallel {cp}")
        if args.cp_mode == "zigzag":
            if not is_gpt:
                raise SystemExit("--cp-mode zigzag balances the CAUSAL "
                                 "mask's ring work (gpt archs); BERT "
                                 "attention is bidirectional — every "
                                 "device already does uniform work on the "
                                 "plain ring")
            if args.seq_len % (2 * cp):
                raise SystemExit(f"--cp-mode zigzag needs --seq-len "
                                 f"({args.seq_len}) divisible by 2x"
                                 f"--context-parallel ({2 * cp})")
        if args.cp_mode == "ulysses":
            arch_heads = {"bert_base": 12, "bert_tiny": 4,
                          "gpt_base": 12, "gpt_tiny": 4}[args.arch]
            if arch_heads % (cp * tp):
                raise SystemExit(
                    f"--cp-mode ulysses splits the {arch_heads} attention "
                    f"heads over --context-parallel {cp}"
                    + (f" x --tensor-parallel {tp}" if tp > 1 else "")
                    + " — not divisible")
    elif args.cp_mode != "ring":
        raise SystemExit(f"--cp-mode {args.cp_mode} only applies with "
                         "--context-parallel > 1")
    if pp > 1:
        if not (is_bert or is_gpt):
            raise SystemExit("--pipeline-parallel is wired for the "
                             "BERT/GPT archs (transformer_xl's recurrence "
                             "carry spans all layers every segment)")
        # --zero composes with --pipeline-parallel (round 5): the stage-
        # local flat optimizer buffers shard over 'data' WITHIN the pipe
        # sharding — PipelineZeroAdam, wired in the pp branch below.
        if args.zero and (tp > 1 or cp > 1 or args.moe_experts):
            raise SystemExit("--zero --pipeline-parallel composes "
                             "pairwise only (no ZeRO x PP x TP/CP/MoE "
                             "triple yet)")
        if args.larc:
            raise SystemExit("--larc does not compose with "
                             "--pipeline-parallel (the LARC wrapper computes "
                             "per-leaf trust ratios, which collapse on "
                             "stacked per-layer params; --opt lamb has a "
                             "PP form that keeps per-layer ratios)")
        if args.opt == "novograd":
            raise SystemExit("--opt novograd does not compose with "
                             "--pipeline-parallel (its per-tensor second "
                             "moment collapses on stacked per-layer params)")
        # --tensor-parallel composes with ALL THREE schedules (round 5):
        # the 1F1B/interleaved cells run branch-free under TP
        # (schedules.pipeline_1f1b uniform_collectives — one collective
        # order on every device; the cond form deadlocks).
        if args.virtual_stages is not None \
                and args.pipeline_schedule != "interleaved":
            raise SystemExit("--virtual-stages only applies to "
                             "--pipeline-schedule interleaved")
        if args.pipeline_schedule == "interleaved":
            if args.virtual_stages is not None and args.virtual_stages < 2:
                raise SystemExit("--pipeline-schedule interleaved needs "
                                 "--virtual-stages >= 2")
            if args.microbatches % pp:
                raise SystemExit(f"--pipeline-schedule interleaved needs "
                                 f"--microbatches ({args.microbatches}) "
                                 f"divisible by --pipeline-parallel ({pp})")
        if args.grad_accum != 1:
            raise SystemExit("--pipeline-parallel owns microbatching "
                             "(--microbatches); drop --grad-accum")
    if args.zero:
        if not (is_bert or is_gpt):
            raise SystemExit("--zero is wired for the image and BERT/GPT "
                             "workloads (transformer_xl's step owns its "
                             "own grad-clip path)")
        # tp > 1 composes: ZeRO-1 under GSPMD shards optimizer state over
        # 'data' while params keep their 'model'-axis TP specs (both are
        # partitioner-visible mesh axes — engine.gspmd_state_shardings).
    if tp > 1:
        # (pure TP and the TP×PP composition alike)
        if args.sequence_parallel and not (is_bert or is_gpt):
            raise SystemExit("--sequence-parallel is wired for the BERT/GPT "
                             "archs (transformer_xl's recurrence carry is "
                             "batch-sharded, not sequence-sharded)")
        if args.fused_attention:
            raise SystemExit("--tensor-parallel runs the SPMD-partitionable "
                             "einsum attention; drop --fused-attention")
    if tp > 1 or pp > 1 or cp > 1:
        # One shared shard-arithmetic check for every model-parallel
        # composition: the data axis absorbs what pp*tp*cp leaves over
        # (mesh.initialize_model_parallel's contract).
        devices = pick_devices(args)
        denom = pp * tp * cp
        if len(devices) % denom:
            raise SystemExit(f"pp {pp} x tp {tp} x cp {cp} = {denom} does "
                             f"not divide {len(devices)} devices")
        data = max(1, len(devices) // denom)
        if args.batch_size % data:
            raise SystemExit(f"--batch-size {args.batch_size} not divisible "
                             f"by the data-axis size {data}")
        if pp > 1 and (args.batch_size // data) % args.microbatches:
            raise SystemExit(f"per-shard batch {args.batch_size // data} "
                             f"not divisible by --microbatches "
                             f"{args.microbatches}")
        if cp > 1 and (args.batch_size // data) % args.grad_accum:
            raise SystemExit(f"per-shard batch {args.batch_size // data} "
                             f"not divisible by --grad-accum "
                             f"{args.grad_accum}")
        n_dev = len(devices)
    else:
        devices = select_devices(args)
        n_dev = len(devices)
    from apex_example_tpu.models.gpt import gpt_base, gpt_tiny
    builder = {"bert_base": bert_base, "bert_tiny": bert_tiny,
               "gpt_base": gpt_base, "gpt_tiny": gpt_tiny,
               "transformer_xl": transformer_xl_base,
               "transformer_xl_tiny": transformer_xl_tiny}[args.arch]
    md = amp.module_dtypes(policy)
    mkw = dict(dtype=md.compute, param_dtype=md.param, ln_dtype=md.ln_io,
               softmax_dtype=md.softmax)
    if args.arch in ("bert_base", "gpt_base", "transformer_xl"):
        mkw["vocab_size"] = args.vocab_size
    if is_bert or is_gpt:
        # (transformer_xl is rejected in main(): its relative-position
        # logits are q·r terms, not an additive bias — blockwise attention
        # for it needs the rel-shift inside the kernel; its long-context
        # story is the segment recurrence itself, SURVEY.md §6.)
        # flag set => force the kernel; absent => the measured-crossover
        # "auto" default (kernel at seq >= 2048; models/bert.py)
        mkw["fused_attention"] = args.fused_attention or "auto"
        # Long sequences need a position table that covers them — the
        # nn.Embed gather otherwise silently CLAMPS out-of-range position
        # ids to the last row (no error, garbage embeddings).
        arch_maxpos = {"bert_base": 512, "bert_tiny": 128,
                       "gpt_base": 1024, "gpt_tiny": 128}[args.arch]
        if args.seq_len > arch_maxpos:
            mkw["max_position"] = args.seq_len
        if tp > 1:
            mkw["tensor_parallel"] = True
            mkw["sequence_parallel"] = args.sequence_parallel
        if args.moe_experts:
            from apex_example_tpu.parallel.mesh import DATA_AXIS
            mkw["moe_experts"] = args.moe_experts
            mkw["moe_capacity_factor"] = args.moe_capacity_factor
            mkw["moe_top_k"] = args.moe_top_k
            # bind the MoE collectives to the axis the EP step maps over
            mkw["moe_axis_name"] = DATA_AXIS
    elif tp > 1:
        mkw["tensor_parallel"] = True
    model = builder(**mkw)
    # Under TP/CP/PP the data axis only gets n_dev/(tp*cp*pp) devices —
    # that is the axis ZeRO shards over, so it is the size the >=2 check
    # applies to (and DistributedFusedAdam's static world).
    optimizer = build_zero_optimizer(args, n_dev // (tp * cp * pp),
                                     gspmd=tp > 1,
                                     global_mean_grads=cp > 1 or pp > 1) \
        if args.zero else build_optimizer(args)

    V = model.vocab_size
    if is_bert:
        def batch_fn(i):
            ids, labels, w = mlm_batch(
                jnp.asarray(i, jnp.int32), batch_size=args.batch_size,
                seq_len=args.seq_len, vocab_size=V, mask_token_id=V - 1,
                seed=args.seed)
            return ids, (labels, w)
    else:
        def batch_fn(i):
            toks = lm_batch(jnp.asarray(i, jnp.int32),
                            batch_size=args.batch_size,
                            seq_len=args.seq_len, vocab_size=V,
                            seed=args.seed)
            return toks[:, :-1], toks[:, 1:]

    # Index-driven generators serve the held-out eval range directly; the
    # host-pipeline block below swaps in a one-shot-stream form.
    eval_batch_fn = batch_fn

    sample = batch_fn(0)[0]
    if pp > 1:
        # Pipeline parallelism: encoder layers stacked and sharded over the
        # 'pipe' mesh axis, driven by the SPMD ring schedule
        # (transformer/bert_pipeline.py); remaining devices data-parallel.
        # With --tensor-parallel the layer leaves ALSO shard over 'model'
        # and the shard_map stays manual over (pipe, data) only, so the
        # GSPMD TP layers run inside each ring tick (the reference's
        # parallel_state exists precisely to run TP+PP+DP jointly,
        # SURVEY.md:149-151).
        from apex_example_tpu.engine import TrainState
        from apex_example_tpu.ops import _config as ops_config
        from apex_example_tpu.transformer import parallel_state
        from apex_example_tpu.transformer.bert_pipeline import (
            PipelineFusedLAMB, bert_pp_state_shardings,
            make_bert_pp_train_step, pack_params, pack_params_1f1b)
        pp_sched = args.pipeline_schedule
        pp_chunks = (args.virtual_stages or 2) \
            if pp_sched == "interleaved" else 1
        if args.opt == "lamb":
            # C4's optimizer rides the pipeline with per-LAYER trust ratios
            # and a pipe-global clip norm (bare FusedLAMB would collapse
            # both on the stacked per-stage params).  The 1F1B arranged
            # pack carries 3 leading per-layer index dims ([S, V, per]).
            optimizer = PipelineFusedLAMB(
                optimizer, stacked_dims=1 if pp_sched == "ring" else 3)
        if args.zero:
            # ZeRO x PP: stage-local flat (m, v) buffers sharded over
            # 'data' within the pipe sharding.
            from apex_example_tpu.transformer.bert_pipeline import (
                PipelineZeroAdam)
            optimizer = PipelineZeroAdam(optimizer, stages=pp)
        if tp > 1:
            # Pallas custom calls are opaque to the SPMD partitioner; the
            # model axis stays automatic inside the PP shard_map, so pin
            # the XLA reference ops (restored by lm_main's outer finally).
            ops_config.set_force_xla(True)
        mesh = parallel_state.initialize_model_parallel(
            tensor_parallel=tp, pipeline_parallel=pp, context_parallel=cp,
            devices=devices)
        # CP x PP: the schedule's stage cells run the KV ring on the
        # 'context' axis; the step's model twin carries the CP flags
        # (init uses the dense twin — identical param tree).
        model_pp = builder(**mkw, context_parallel=True,
                           cp_mode=args.cp_mode) if cp > 1 else model
        if model.num_layers % (pp * pp_chunks):
            raise SystemExit(f"--pipeline-parallel {pp} x --virtual-stages "
                             f"{pp_chunks} does not divide "
                             f"{model.num_layers} encoder layers")
        # jit the init: under a traced program the TP layers' batch-axis
        # constraints tolerate the size-1 init sample (GSPMD pads); the
        # eager path would reject 1 % data != 0.
        dense_state = jax.jit(
            lambda r: create_train_state(r, model, optimizer, sample[:1],
                                         policy, scaler)
        )(jax.random.PRNGKey(args.seed))
        if pp_sched == "ring":
            packed = pack_params(dense_state.params, model.num_layers)
        else:
            packed = pack_params_1f1b(dense_state.params, model.num_layers,
                                      pp, pp_chunks)
        state = TrainState(step=dense_state.step, params=packed,
                           batch_stats={},
                           opt_state=optimizer.init(packed),
                           scaler=dense_state.scaler)
        state = jax.device_put(
            state, bert_pp_state_shardings(mesh, state, optimizer,
                                           model=model))
        step_fn = make_bert_pp_train_step(mesh, model_pp, optimizer, policy,
                                          microbatches=args.microbatches,
                                          schedule=pp_sched,
                                          num_chunks=pp_chunks,
                                          moe_aux_weight=args.moe_aux_weight)
        mems = None
        rank_print(f"PP over {pp} stages ({pp_sched}"
              + (f", V={pp_chunks}" if pp_chunks > 1 else "")
              + f"), TP over {tp}, CP over {cp}, DP over "
              f"{n_dev // (pp * tp * cp)}, "
              f"{args.microbatches} microbatches/shard: {mesh}")
    elif tp > 1 and cp == 1 and not args.moe_experts:
        # GSPMD tensor parallelism: one (pipe, data, context, model) mesh,
        # params carrying the TP layers' partitioning metadata, the plain
        # single-device step jitted with those shardings — collectives are
        # compiler-inserted at the layers' constraint points (engine.
        # make_gspmd_train_step).  Pallas custom calls are opaque to the
        # SPMD partitioner, so the TP path pins the XLA reference ops.
        from apex_example_tpu.engine import (create_gspmd_train_state,
                                             make_gspmd_train_step)
        from apex_example_tpu.ops import _config as ops_config
        from apex_example_tpu.transformer import parallel_state
        from apex_example_tpu.workloads import make_gspmd_txl_train_step
        # Restored by lm_main's outer finally: retracing happens inside the
        # run loop, so the flag must live for the whole run.
        ops_config.set_force_xla(True)
        mesh = parallel_state.initialize_model_parallel(
            tensor_parallel=tp, devices=devices)
        from apex_example_tpu.parallel.mesh import DATA_AXIS as _DATA
        state, shardings = create_gspmd_train_state(
            jax.random.PRNGKey(args.seed), mesh, model, optimizer,
            sample[:1], policy, scaler,
            zero_axis=_DATA if args.zero else None)
        if is_bert or is_gpt:
            step_fn = make_gspmd_train_step(
                mesh, model, optimizer, policy, shardings,
                loss_fn=mlm_loss if is_bert else lm_loss,
                compute_accuracy=False, grad_accum=args.grad_accum,
                numerics=args.numerics_check != "off")
            mems = None
        else:
            step_fn = make_gspmd_txl_train_step(
                mesh, model, optimizer, policy, shardings,
                max_grad_norm=args.max_grad_norm,
                grad_accum=args.grad_accum)
            mems = model.init_mems(args.batch_size)
        rank_print(f"TP over {tp} devices, DP over {n_dev // tp}"
              + (", ZeRO-1 opt-state over data" if args.zero else "")
              + f": {mesh}")
    elif cp > 1:
        # Ring context parallelism: init via the twin WITHOUT
        # context_parallel (identical param tree; the CP module's
        # collectives only trace inside shard_map), step from the CP twin
        # (workloads.make_bert_cp_train_step).  With --tensor-parallel the
        # shard_map stays manual over (data, context) only and the GSPMD
        # TP layers run inside the KV ring (model axis automatic; the same
        # partially-manual composition as TP×PP) — long context AND wide
        # models jointly.
        from apex_example_tpu.ops import _config as ops_config
        from apex_example_tpu.transformer import parallel_state
        from apex_example_tpu.workloads import (make_bert_cp_train_step,
                                                make_gpt_cp_train_step)
        if tp > 1:
            ops_config.set_force_xla(True)
        mesh = parallel_state.initialize_model_parallel(
            tensor_parallel=tp, context_parallel=cp, devices=devices)
        model_cp = builder(**mkw, context_parallel=True,
                           cp_mode=args.cp_mode)
        cp_shardings = None
        if args.moe_experts:
            # EP x CP (the long-context MoE stack): experts over 'data',
            # KV ring over 'context' — two manual axes, two independent
            # collectives in one step (workloads.make_bert_moe_train_step
            # context_parallel=True).  Init runs the dense twin (full
            # [E, ...] stacks); device_put shards experts one-per-
            # data-device, everything else replicated over both axes.
            from apex_example_tpu.workloads import (
                bert_moe_state_shardings, make_bert_moe_train_step)
            ep = n_dev // (cp * tp)
            if args.moe_experts % ep:
                raise SystemExit(f"--moe-experts {args.moe_experts} must "
                                 f"be a multiple of the data-axis size "
                                 f"{ep} (= devices / cp / tp)")
            moe_shardings = None
            if tp > 1:
                # EP x CP x TP: GSPMD placement for the TP leaves, expert
                # stacks overridden to P('data') (the same overlay the
                # MoE x TP path uses).
                from apex_example_tpu.engine import create_gspmd_train_state
                state, gsh = create_gspmd_train_state(
                    jax.random.PRNGKey(args.seed), mesh, model, optimizer,
                    sample[:1], policy, scaler)
                moe_shardings = bert_moe_state_shardings(
                    mesh, state, optimizer, base_shardings=gsh)
                state = jax.device_put(state, moe_shardings)
            else:
                state = create_train_state(jax.random.PRNGKey(args.seed),
                                           model, optimizer, sample[:1],
                                           policy, scaler)
                state = jax.device_put(
                    state, bert_moe_state_shardings(mesh, state, optimizer))
            step_fn = make_bert_moe_train_step(
                mesh, model_cp, optimizer, policy, state_template=state,
                aux_weight=args.moe_aux_weight,
                grad_accum=args.grad_accum,
                objective="mlm" if is_bert else "lm",
                context_parallel=True, mode=args.cp_mode,
                state_shardings=moe_shardings)
        elif tp > 1:
            from apex_example_tpu.engine import create_gspmd_train_state
            state, cp_shardings = create_gspmd_train_state(
                jax.random.PRNGKey(args.seed), mesh, model, optimizer,
                sample[:1], policy, scaler)
        else:
            state = create_train_state(jax.random.PRNGKey(args.seed), model,
                                       optimizer, sample[:1], policy, scaler)
        if args.moe_experts:
            pass                                   # step_fn built above
        elif is_gpt:
            step_fn = make_gpt_cp_train_step(mesh, model_cp, optimizer,
                                             policy,
                                             grad_accum=args.grad_accum,
                                             state_shardings=cp_shardings,
                                             mode=args.cp_mode)
        else:
            step_fn = make_bert_cp_train_step(mesh, model_cp, optimizer,
                                              policy,
                                              grad_accum=args.grad_accum,
                                              state_shardings=cp_shardings)
        mems = None
        rank_print(f"CP over {cp} sequence shards (local seq "
              f"{args.seq_len // cp}), TP over {tp}, DP over "
              f"{n_dev // (cp * tp)}"
              + (f", MoE over {args.moe_experts} experts"
                 if args.moe_experts else "")
              + f": {mesh}")
    elif args.moe_experts:
        # Expert parallelism: one switch expert per device over the 'data'
        # axis (workloads.make_bert_moe_train_step).  Init runs the dense-
        # reference MoE path (no mesh axis bound), yielding the full
        # [E, ...] stacks; device_put shards them one-expert-per-device.
        # With --tensor-parallel the shard_map goes manual over 'data'
        # only: the GSPMD TP attention/embeddings/head run on the
        # automatic 'model' axis around the expert block (the same
        # partially-manual composition as CP x TP).
        from apex_example_tpu.workloads import (bert_moe_state_shardings,
                                                make_bert_moe_train_step)
        ep = n_dev // tp
        if args.moe_experts % ep:
            raise SystemExit(f"--moe-experts {args.moe_experts} must be a "
                             f"multiple of the data-axis size {ep} "
                             f"(each device owns moe_experts/{ep} experts)")
        if args.batch_size % ep:
            raise SystemExit(f"--batch-size {args.batch_size} not "
                             f"divisible by the data-axis size {ep}")
        if (args.batch_size // ep) % args.grad_accum:
            raise SystemExit(f"per-shard batch {args.batch_size // ep} "
                             f"not divisible by --grad-accum "
                             f"{args.grad_accum}")
        if tp > 1:
            from apex_example_tpu.engine import create_gspmd_train_state
            from apex_example_tpu.ops import _config as ops_config
            from apex_example_tpu.transformer import parallel_state
            ops_config.set_force_xla(True)
            mesh = parallel_state.initialize_model_parallel(
                tensor_parallel=tp, devices=devices)
            state, gsh = create_gspmd_train_state(
                jax.random.PRNGKey(args.seed), mesh, model, optimizer,
                sample[:1], policy, scaler)
            shardings = bert_moe_state_shardings(mesh, state, optimizer,
                                                 base_shardings=gsh)
            state = jax.device_put(state, shardings)
        else:
            mesh = make_data_mesh(devices=devices)
            shardings = None
            state = create_train_state(jax.random.PRNGKey(args.seed),
                                       model, optimizer, sample[:1],
                                       policy, scaler)
            state = jax.device_put(
                state, bert_moe_state_shardings(mesh, state, optimizer))
        step_fn = make_bert_moe_train_step(
            mesh, model, optimizer, policy, state_template=state,
            aux_weight=args.moe_aux_weight, grad_accum=args.grad_accum,
            objective="mlm" if is_bert else "lm",
            state_shardings=shardings)
        mems = None
        rank_print(f"MoE over {args.moe_experts} experts "
              f"({args.moe_experts // ep}/device, capacity factor "
              f"{args.moe_capacity_factor}), TP over {tp}, DP over {ep}: "
              f"{mesh}")
    else:
        state = create_train_state(
            jax.random.PRNGKey(args.seed), model, optimizer, sample[:1],
            policy, scaler,
            train_kwargs={} if not (is_bert or is_gpt) else None)
        mems = None if (is_bert or is_gpt) \
            else model.init_mems(args.batch_size)

    if tp > 1 or pp > 1 or cp > 1 or args.moe_experts:
        pass                                   # step_fn built above
    elif is_bert or is_gpt:
        loss_fn = mlm_loss if is_bert else lm_loss
        if args.zero:
            mesh = make_data_mesh(devices=devices)
            step_fn = make_zero_train_step(mesh, model, optimizer, policy,
                                           loss_fn=loss_fn,
                                           compute_accuracy=False)
            rank_print(f"ZeRO-1 DDP over {n_dev} devices: {mesh}")
        elif n_dev > 1:
            mesh = make_data_mesh(devices=devices)
            step_fn = make_sharded_train_step(
                mesh, model, optimizer, policy, loss_fn=loss_fn,
                compute_accuracy=False, grad_accum=args.grad_accum,
                numerics=args.numerics_check != "off")
        else:
            step_fn = jax.jit(make_train_step(
                model, optimizer, policy, loss_fn=loss_fn,
                compute_accuracy=False, grad_accum=args.grad_accum,
                numerics=args.numerics_check != "off"),
                donate_argnums=(0,))
    else:
        # grad accumulation slices the BATCH axis (independent streams), so
        # each stream's recurrence carry stays exact — see
        # workloads.make_txl_train_step.
        if n_dev > 1:
            mesh = make_data_mesh(devices=devices)
            step_fn = make_sharded_txl_train_step(
                mesh, model, optimizer, policy,
                max_grad_norm=args.max_grad_norm,
                grad_accum=args.grad_accum)
        else:
            step_fn = jax.jit(make_txl_train_step(
                model, optimizer, policy, max_grad_norm=args.max_grad_norm,
                grad_accum=args.grad_accum),
                donate_argnums=(0, 1))

    eval_fn = None
    if args.eval:
        from apex_example_tpu.workloads import (make_bert_eval_step,
                                                make_gpt_eval_step,
                                                make_txl_eval_step)
        if is_bert or is_gpt:
            if pp > 1:
                # PP (and CP x PP) eval: unpack the packed/stacked params
                # into the dense layout and run the dense eval step — the
                # trees are content-identical by construction.  (Under
                # CP x PP this evaluates the full sequence densely; the
                # schedule's own KV ring is a training program.)
                from apex_example_tpu.transformer.bert_pipeline import (
                    unpack_params, unpack_params_1f1b)
                core = make_gpt_eval_step(model) if is_gpt \
                    else make_bert_eval_step(model)
                if pp_sched == "ring":
                    unp = lambda p: unpack_params(p, model.num_layers)
                else:
                    unp = lambda p: unpack_params_1f1b(
                        p, model.num_layers, pp, pp_chunks)
                eval_fn = jax.jit(lambda p, b: core(unp(p), b))
            elif cp > 1 and args.moe_experts:
                # EP x CP eval: same KV ring + per-column expert dispatch
                # as training.
                from apex_example_tpu.workloads import (
                    make_bert_moe_eval_step)
                eval_fn = make_bert_moe_eval_step(
                    mesh, model_cp, state.params,
                    objective="mlm" if is_bert else "lm",
                    context_parallel=True, mode=args.cp_mode)
            elif cp > 1:
                # Sequence-sharded eval under the same KV ring as training
                # — held-out loss AT the training context length (a dense
                # eval forward would materialize the (L, L) scores CP
                # exists to shard).
                from apex_example_tpu.workloads import (
                    make_bert_cp_eval_step, make_gpt_cp_eval_step)
                eval_fn = make_gpt_cp_eval_step(
                    mesh, model_cp, mode=args.cp_mode) if is_gpt \
                    else make_bert_cp_eval_step(mesh, model_cp)
            elif args.moe_experts:
                # Same mesh + all_to_all dispatch as training: a dense
                # eval would need the expert stacks gathered onto one
                # device and would route with a different (global)
                # capacity.
                from apex_example_tpu.workloads import make_bert_moe_eval_step
                eval_fn = make_bert_moe_eval_step(
                    mesh, model, state.params,
                    objective="mlm" if is_bert else "lm")
            else:
                eval_fn = jax.jit((make_gpt_eval_step if is_gpt
                                   else make_bert_eval_step)(model))
        else:
            eval_fn = jax.jit(make_txl_eval_step(model))

    mgr = CheckpointManager(args.checkpoint_dir) if args.checkpoint_dir \
        else None
    writer = make_writer(args)
    tb = TensorBoardAdapter(writer)
    emitter, profwin, recorder, watchdog = make_telemetry(args)
    if getattr(args, "tick_profile", False):
        # The LM builders end in jitted callables with workload-specific
        # shapes (DDP shard_map, GSPMD TP, PP microbatching); the
        # decomposition is wired into the image loop only.
        rank_print("WARNING: --tick-profile instruments the image loop "
                   "only; LM steps are not decomposed")
    preempt, fault = make_resilience(args, recorder)
    # --cost-model hookup: see the image loop.  One call site covers
    # every LM step builder above (single-device, DDP shard_map, GSPMD
    # TP/ZeRO, CP, MoE, PP, TXL) — they all end in a jitted callable.
    step_fn = obs.costmodel.instrument("train_step", step_fn)
    eval_fn = obs.costmodel.instrument("eval_step", eval_fn)
    start_epoch = start_i = 0
    if args.resume:
        # TXL mems are transient per-segment activations and restart cold on
        # resume (matches the reference harness, which does not persist
        # them); the host-state sidecar carries the loop position + host
        # PRNG, and the index-driven token streams continue at
        # batch_fn(global_step) — so BERT/GPT resume is exact mid-epoch.
        rmgr = CheckpointManager(args.resume)
        if tp == 1 and pp == 1 and not args.moe_experts and n_dev > 1:
            # (tp/pp > 1 and MoE templates are already mesh-placed above;
            # DP and CP templates are not — CP state is replicated, so the
            # replicated template is the right restore target for it too.)
            state = restore_under_mesh(
                rmgr, state, mesh, optimizer if args.zero else None)
        else:
            state = rmgr.restore(state)
        start_epoch, start_i = restore_loop_position(args, rmgr,
                                                     int(state.step))
        rank_print(f"resumed from step {int(state.step)} (epoch {start_epoch})")

    if args.prof:
        jax.profiler.start_trace("/tmp/apex_tpu_trace")

    global_step = int(state.step)
    prefetcher = None
    if args.host_pipeline:
        # Native C++ token stream (the image path's LM counterpart):
        # created AFTER resume so start_index continues the exact stream.
        from apex_example_tpu import host_runtime
        if not host_runtime.available():
            raise SystemExit("--host-pipeline: native runtime not buildable")
        prefetcher = host_runtime.NativeLMPrefetcher(
            batch=args.batch_size, seq_len=args.seq_len, vocab_size=V,
            mlm=is_bert, mask_token_id=V - 1 if is_bert else -1,
            seed=args.seed, start_index=global_step)

        if is_bert:
            def batch_fn(i):
                ids, labels, w = next(prefetcher)
                return jnp.asarray(ids), (jnp.asarray(labels),
                                          jnp.asarray(w))
        else:
            def batch_fn(i):
                ids, labels, _ = next(prefetcher)
                return jnp.asarray(ids), jnp.asarray(labels)

        def eval_batch_fn(i):
            # One-shot stream at the held-out index (deterministic in i
            # alone, like the image path's eval prefetcher).
            pf = host_runtime.NativeLMPrefetcher(
                batch=args.batch_size, seq_len=args.seq_len, vocab_size=V,
                mlm=is_bert, mask_token_id=V - 1 if is_bert else -1,
                seed=args.seed, start_index=i)
            try:
                ids, labels, w = next(pf)
            finally:
                pf.close()
            if is_bert:
                return jnp.asarray(ids), (jnp.asarray(labels),
                                          jnp.asarray(w))
            return jnp.asarray(ids), jnp.asarray(labels)
    run_step = 0
    last_saved = None
    try:
        for epoch in range(start_epoch, args.epochs):
            losses = AverageMeter("loss")
            thr = Throughput(warmup_steps=2)
            # Mid-epoch resume: see the image loop.
            for i in range(start_i if epoch == start_epoch else 0,
                           args.steps_per_epoch):
                run_step += 1
                if profwin is not None:
                    profwin.on_step_start(run_step)
                with span("data"):
                    batch = batch_fn(global_step)
                if fault is not None:
                    batch = fault.maybe_poison(global_step + 1, batch)
                t0 = time.perf_counter()
                with span("step"):
                    if is_bert or is_gpt:
                        state, metrics = step_fn(state, batch)
                    else:
                        state, mems, metrics = step_fn(state, mems, batch)
                    global_step += 1
                    if emitter is not None:
                        # Inside the span: see the image loop.
                        emitter.on_step(
                            global_step=global_step, epoch=epoch,
                            metrics=metrics,
                            items=args.batch_size * args.seq_len,
                            t_start=t0)
                thr.step(args.batch_size * args.seq_len)
                if profwin is not None:
                    profwin.on_step_end(run_step, blocker=metrics)
                if (i + 1) % args.print_freq == 0 \
                        or i + 1 == args.steps_per_epoch:
                    losses.update(float(metrics["loss"]))
                    extra = (f"ppl {float(metrics['ppl']):.1f} " if "ppl" in
                             metrics else "")
                    rank_print(f"epoch {epoch} step {i + 1}/"
                          f"{args.steps_per_epoch} "
                          f"{losses} {extra}{thr.rate:.0f} tok/s "
                          f"scale {float(metrics['scale']):.0f}")
                    tb.scalars({"train/loss": losses.val,
                                "train/tok_per_sec": thr.rate},
                               global_step)
                if args.save_every_steps and mgr is not None \
                        and is_main_process() \
                        and global_step % args.save_every_steps == 0:
                    with span("checkpoint"):
                        mgr.save(state, wait=not args.async_checkpoint,
                                 host_state=host_loop_state(args,
                                                            global_step))
                    last_saved = global_step
                    rank_print(f"saved checkpoint at step {global_step}")
                if fault is not None:
                    # See the image loop: after telemetry + interval save.
                    fault.maybe_fire(global_step)
                if preempt is not None and preempt.preempted:
                    break
            if preempt is not None and preempt.preempted:
                break
            if eval_fn is not None:
                # Held-out token streams at a disjoint index range (the
                # image path's contract); TXL threads fresh eval mems.
                # TXL ppl = exp(mean loss) over all eval batches (the
                # corpus-level metric; a mean of per-batch exps would be
                # Jensen-biased toward outlier batches).
                import math
                el = AverageMeter("loss")
                e2 = AverageMeter("masked_acc")
                emems = None if (is_bert or is_gpt) \
                    else model.init_mems(args.batch_size)
                for j in range(args.eval_batches):
                    b = eval_batch_fn(
                        10_000_000 + epoch * args.eval_batches + j)
                    if is_bert:
                        em = eval_fn(state.params, b)
                        e2.update(float(em["masked_acc"]))
                    elif is_gpt:
                        em = eval_fn(state.params, b)
                    else:
                        emems, em = eval_fn(state.params, emems, b)
                    el.update(float(em["loss"]))
                metric = ("masked_acc", e2.avg) if is_bert \
                    else ("ppl", math.exp(el.avg))
                rank_print(f"epoch {epoch} EVAL loss {el.avg:.4f} "
                      f"{metric[0]} {metric[1]:.2f} "
                      f"({args.eval_batches} batches)")
                tb.scalars({"eval/loss": el.avg,
                            f"eval/{metric[0]}": metric[1]}, global_step)
            if mgr is not None and is_main_process() \
                    and last_saved != int(state.step):
                with span("checkpoint"):
                    mgr.save(state, wait=not args.async_checkpoint,
                             host_state=host_loop_state(args, global_step))
                last_saved = int(state.step)
                rank_print(f"saved checkpoint at step {int(state.step)}")
            if preempt is not None and preempt.preempted:
                break                # re-poll after eval: see image loop
        if preempt is not None and preempt.preempted:
            return graceful_preempt_exit(args, mgr, state, preempt,
                                         emitter, global_step,
                                         last_saved=last_saved)
    finally:
        # Join pending async checkpoint writes even when unwinding on an
        # exception — an announced save must exist on disk (main() gives
        # its image path the same protection).
        if preempt is not None:
            preempt.close()
        close_telemetry(emitter, profwin, recorder, watchdog)
        if prefetcher is not None:
            prefetcher.close()
        tb.close()
        if mgr is not None:
            mgr.wait_until_finished()
    if args.prof:
        jax.profiler.stop_trace()
        rank_print("profile written to /tmp/apex_tpu_trace")
    return 0


if __name__ == "__main__":
    sys.exit(main())
