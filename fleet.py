#!/usr/bin/env python
"""Fleet CLI: a router over N serve replicas, with scripted chaos.

    # 2 supervised subprocess replicas, rolling restart under load,
    # scored on zero lost requests (the ROADMAP item-5 acceptance):
    python fleet.py --replicas 2 --transport proc \\
        --scenario rolling_restart --requests 24 \\
        --metrics-jsonl fleet.jsonl --workdir /tmp/fleet --trace

    # in-process replicas over one shared compiled decode program,
    # k replicas crashed mid-serve via a deterministic drill:
    python fleet.py --replicas 3 --transport thread \\
        --scenario crash_storm --crash-replicas 1 --fault-tick 6 \\
        --requests 18 --metrics-jsonl fleet.jsonl

    # then render the router stream (jax-free):
    python tools/fleet_report.py fleet.jsonl

Transports (fleet/replica.py):

- ``proc``    spawns N ``tools/supervise.py``-wrapped ``serve.py``
              children fed through file-based inbox/outbox pairs under
              ``--workdir``.  This path is **jax-free**: the fleet
              modules are loaded by file path (the supervisor
              pattern), so the router keeps running when the replicas'
              jax is the thing that is dying.
- ``thread``  drives N in-process ``ServeEngine``s (jax imported
              lazily); every replica shares ONE compiled decode
              program (the step cache keys on the module-clone
              config), so an N-replica fleet costs one compile.

The router (fleet/router.py) dispatches by ``--policy`` (round_robin /
least_pending / least_kv via the tailed replica gauges), requeues
drained requests to siblings, deadline-aware-retries requests lost to
crashes, and circuit-breaks dead replicas with half-open probes.  Its
``--metrics-jsonl`` stream carries schema-v10 ``route`` /
``replica_state`` / ``fleet_summary`` records; ``--trace`` adds
trace events and exports ``APEX_TRACE_ID`` so one
``tools/trace_export.py`` merge shows the whole fleet on a single
Perfetto timeline.

Scenarios (fleet/scenarios.py; ``--scenario``): ``none``,
``rolling_restart`` (SIGTERM each replica in turn; zero lost requests
required), ``crash_storm`` (``--crash-replicas`` k die at
``--fault-tick``), ``straggler`` (one replica hangs; the router's
stall detector rescues its requests).  The run exits 0 only when the
scenario verdict is "pass".

SLO plane (ISSUE 16; README "SLO monitoring"): ``--slo
'ttft_ms=250,tpot_ms=40,availability=0.999'`` makes the router score
every fleet-terminal event against the targets, emit one schema-v14
``slo_window`` record per ``--slo-window`` terminals (an
``slo_breach`` when a window's error-budget burn rate exceeds 1.0)
and periodic ``fleet_rollup`` records merging the replicas'
heartbeat latency sketches (fleet-wide p50/p90/p99 + per-replica
skew/straggler), and fold an ``slo_verdict`` into ``fleet_summary``
— a chaos scenario whose windows burn past budget FAILS even when
nothing was lost.  ``tools/slo_report.py`` renders the stream;
``tools/ci_gate.py --slo-stream`` checks it.

Disaggregated fleets (ISSUE 15): ``--decode-replicas K`` runs the
last K replicas as ``--role decode`` workers off one shared leased
KV-handoff spool (never routed prompts; their outboxes report the
spool-fed terminals) with the rest as ``--role prefill``.  Two disagg
chaos scenarios ride the same verdict machinery:
``decode_crash_midspool`` (a decode worker dies in the ack-crash
window holding claimed-but-unacked handoffs; peers must reclaim the
expired leases and finish the redelivered work) and ``prefill_crash``
(the prefill role dies mid-serve; its queued requests re-route on
restart while spooled requests keep decoding).

    # 1 prefill + 2 decode, kill one decode worker mid-spool:
    python fleet.py --replicas 3 --decode-replicas 2 \\
        --transport proc --scenario decode_crash_midspool \\
        --requests 10 --handoff-lease 1.0 --metrics-jsonl fleet.jsonl

Multi-tenant fleets (ISSUE 19): ``--tenants`` arms DWRR fair admission
on every replica engine and per-tenant ledgers on the router (schema
v17: ``tenant`` on terminal events, a ``tenants`` block + per-tenant
SLO verdicts in ``fleet_summary``).  ``--policy prefix_affinity``
routes each prompt to the replica advertising the deepest hot-prefix
chain-key overlap (``--advertise-prefixes`` arms the heartbeat
advertisement; falls back to least_kv on zero overlap), and
``fleet_summary`` gains a fleet-level ``prefix_hit_rate``.  Three
scored scenarios ride the machinery: ``noisy_neighbor`` (flooding
tenant vs deadline-carrying interactive victim; ``--expect-breach``
runs the FIFO control arm that must demonstrably breach),
``tenant_burst_starvation`` and ``prefix_heavy``:

    # fair keeps the victim inside its virtual deadline:
    python fleet.py --replicas 1 --scenario noisy_neighbor \\
        --tenants 'noisy:mix=6;victim:class=interactive,mix=1' \\
        --requests 14 --metrics-jsonl fleet.jsonl

Live migration + elastic pools (ISSUE 20): three scenarios ride the
mid-flight KV migration spool (``ServeEngine.extract_live`` ->
leased FileTransport -> ``admit_migrated``, token-identical).
``drain_zero_evictions`` is the rolling restart that kills no
request: every ``interrupt(mode="migrate")`` ships live slots to the
spool and a peer resumes them (zero evictions at availability 1.0).
``migrate_under_crash_storm`` kills the migration DESTINATION between
``admit_migrated`` and ack — the surviving peers must reclaim the
expired leases and finish the redelivered payloads exactly once
(thread transport; the drill rides the migration intake).
``autoscale_flap`` drives bursty load against the ``ElasticPool``
controller (``--autoscale MIN:MAX``), which spawns/retires thread
replicas off the router's backlog + TTFT gauges under cooldown
hysteresis — retirement drains without eviction.  Outside the
scenarios, ``--rebalance-kv-ratio`` arms continuous KV-pressure
rebalancing: the router asks the hottest replica (by the
dtype-accurate ``kv_bytes_live`` gauge) to migrate one live request
whenever it exceeds the ratio x the fleet mean.

    # rolling restart, zero evictions, migrations scored:
    python fleet.py --replicas 3 --transport thread \\
        --scenario drain_zero_evictions --requests 18 \\
        --max-new 10:14 --metrics-jsonl fleet.jsonl
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))


def _load_fleet(name: str):
    """File-path load (tools/supervise.py pattern): the proc transport
    must work on hosts where importing the package — which pulls jax —
    is exactly what cannot happen."""
    path = os.path.join(REPO, "apex_example_tpu", "fleet", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"apex_fleet_{name}",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_sched(name: str):
    """Same file-path stance for the sched/ stratum (jax-free by the
    graftlint contract): --tenants parsing must not pull the package."""
    path = os.path.join(REPO, "apex_example_tpu", "sched", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"apex_sched_{name}",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    # Registered BEFORE exec: tenants.py defines dataclasses, and the
    # dataclass machinery resolves cls.__module__ through sys.modules.
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


class ElasticPool:
    """Elastic replica pool controller (ISSUE 20): scales a fleet
    between ``min_replicas`` and ``max_replicas`` off two router
    gauges — spool depth (``router.backlog()``: parked work plus every
    routable replica's pending) and, when armed, the fleet TTFT p50
    (``router.ttft_p50_ms()``) — with cooldown hysteresis: at most one
    scale action per ``cooldown_s``, scale-up above ``up_backlog``,
    scale-down only at or below ``down_backlog`` (strictly less than
    ``up_backlog``, so the two thresholds can never chase each other).

    Stdlib-only and duck-typed like the rest of the fleet stratum:
    ``spawn(i)`` returns an UNSTARTED replica handle; retirement goes
    through ``router.retire_replica`` (unroutable but still polled, so
    late terminals land) and then drains the handle WITHOUT eviction —
    ``interrupt(mode="migrate")`` when it has a migration spool, a
    graceful non-blocking ``stop`` otherwise.  Every action is
    ledgered via ``router.note_autoscale`` (schema v18
    ``scale_up_events``/``scale_down_events``) and appended to
    ``self.events`` for the scenario score."""

    def __init__(self, router, spawn, *, min_replicas: int = 1,
                 max_replicas: int = 4, up_backlog: int = 4,
                 down_backlog: int = 0, cooldown_s: float = 0.5,
                 ttft_p50_ms=None, initial=()):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(f"need 1 <= min <= max, got "
                             f"{min_replicas}:{max_replicas}")
        if down_backlog >= up_backlog:
            raise ValueError(f"hysteresis needs down_backlog < "
                             f"up_backlog, got {down_backlog} >= "
                             f"{up_backlog}")
        if cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s}")
        self.router = router
        self._spawn = spawn
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_backlog = int(up_backlog)
        self.down_backlog = int(down_backlog)
        self.cooldown_s = float(cooldown_s)
        self.ttft_p50_ms = ttft_p50_ms
        self.active = list(initial)
        self.retired = []
        self.events = []
        self._spawned = 0
        self._last_action = 0.0         # epoch 0: first decision free

    def size(self) -> int:
        return len(self.active)

    def within_bounds(self) -> bool:
        return self.min_replicas <= len(self.active) <= self.max_replicas

    def step(self):
        """One control decision (call from the drive loop, router-poll
        cadence).  Returns ("up"|"down", replica_name) when an action
        fired, else None."""
        now = time.time()
        if now - self._last_action < self.cooldown_s:
            return None
        backlog = self.router.backlog()
        ttft = self.router.ttft_p50_ms() \
            if self.ttft_p50_ms is not None else None
        hot = backlog > self.up_backlog \
            or (ttft is not None and ttft > self.ttft_p50_ms)
        if hot and len(self.active) < self.max_replicas:
            handle = self._spawn(self._spawned)
            self._spawned += 1
            handle.start()
            self.router.add_replica(handle)
            self.active.append(handle)
            reason = (f"backlog {backlog} > {self.up_backlog}"
                      if backlog > self.up_backlog
                      else f"ttft_p50 {ttft:.0f}ms > "
                           f"{self.ttft_p50_ms:.0f}ms")
            self.router.note_autoscale("up", handle.name, reason)
            self.events.append(("up", handle.name, reason))
            self._last_action = now
            return ("up", handle.name)
        if not hot and backlog <= self.down_backlog \
                and len(self.active) > self.min_replicas:
            handle = self.active.pop()  # LIFO: newest spawned first
            self.router.retire_replica(handle.name)
            # Drain WITHOUT eviction when the handle can migrate; a
            # non-blocking graceful stop either way (the drive thread
            # finishes held work, and a stopping replica never claims
            # new spool payloads).
            if getattr(handle, "migrate_tx", None) is not None:
                handle.interrupt(mode="migrate")
            handle.stop(timeout_s=0.0)
            self.retired.append(handle)
            reason = f"backlog {backlog} <= {self.down_backlog}"
            self.router.note_autoscale("down", handle.name, reason)
            self.events.append(("down", handle.name, reason))
            self._last_action = now
            return ("down", handle.name)
        return None


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="route a workload over N serve replicas, "
                    "optionally under scripted chaos")
    p.add_argument("--replicas", type=int, default=2,
                   help="replica count (default 2)")
    p.add_argument("--transport", default="thread",
                   choices=["thread", "proc"],
                   help="thread = in-process ServeEngines (one shared "
                        "compiled program); proc = supervised serve.py "
                        "subprocesses over file inbox/outbox (jax-free "
                        "router path)")
    p.add_argument("--policy", default="round_robin",
                   choices=["round_robin", "least_pending", "least_kv",
                            "prefix_affinity"],
                   help="dispatch policy (fleet/router.py); "
                        "prefix_affinity follows the hot-prefix keys "
                        "replicas advertise (--advertise-prefixes) and "
                        "falls back to least_kv on zero overlap")
    p.add_argument("--scenario", default="none",
                   choices=["none", "rolling_restart", "crash_storm",
                            "straggler", "prefill_crash",
                            "decode_crash_midspool", "noisy_neighbor",
                            "tenant_burst_starvation", "prefix_heavy",
                            "drain_zero_evictions",
                            "migrate_under_crash_storm",
                            "autoscale_flap"],
                   help="scripted chaos scenario, scored into "
                        "fleet_summary (fleet/scenarios.py; the "
                        "*_crash* disagg scenarios need "
                        "--decode-replicas, the tenant scenarios need "
                        "--tenants, the migration/autoscale scenarios "
                        "need the homogeneous both-role fleet)")
    p.add_argument("--decode-replicas", type=int, default=0,
                   metavar="K",
                   help="disaggregated fleet (ISSUE 15): the LAST K "
                        "replicas run --role decode off a shared "
                        "KV-handoff spool and are never routed prompts "
                        "(their outboxes report the spool-fed "
                        "terminals); the rest run --role prefill.  "
                        "0 = classic homogeneous fleet")
    p.add_argument("--handoff-lease", type=float, default=2.0,
                   metavar="S",
                   help="disagg fleet: wall-clock lease on claimed "
                        "spool files — a dead worker's claims are "
                        "reclaimed by peers after S seconds "
                        "(default 2)")
    p.add_argument("--spool-timeout", type=float, default=None,
                   metavar="S",
                   help="disagg fleet: a uid parked on the spool "
                        "longer than S seconds is presumed eaten by a "
                        "worker that died after acking its claim and "
                        "is re-routed through prefill from scratch "
                        "(default max(4*lease, 5); raise it when the "
                        "rig is slow enough that honest spool dwell — "
                        "a restarting decode child recompiling — can "
                        "cross the sweep threshold)")
    p.add_argument("--requests", type=int, default=16,
                   help="workload size (synthetic specs)")
    p.add_argument("--prompt-len", default="3:8",
                   help="prompt length, N or MIN:MAX tokens")
    p.add_argument("--max-new", default="3:10",
                   help="output budget, N or MIN:MAX tokens")
    p.add_argument("--vocab-size", type=int, default=256,
                   help="prompt token range for proc replicas (thread "
                        "mode reads it off the model; 256 = gpt_tiny)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--slots", type=int, default=4,
                   help="per-replica KV slot count")
    p.add_argument("--max-len", type=int, default=None,
                   help="per-replica cache length (default: serve.py's)")
    p.add_argument("--block-size", type=int, default=8,
                   help="per-replica KV block size")
    p.add_argument("--deadline-s", type=float, default=None,
                   help="per-request wall deadline the router's retry "
                        "path honors (default: none)")
    p.add_argument("--max-retries", type=int, default=2,
                   help="re-dispatch budget for requests lost to "
                        "replica crashes (default 2)")
    p.add_argument("--breaker-backoff", type=float, default=0.25,
                   metavar="S",
                   help="circuit-breaker backoff base (default 0.25)")
    p.add_argument("--stall-after", type=float, default=None,
                   metavar="S",
                   help="mark a replica stalled after S seconds "
                        "without progress while holding work "
                        "(default: 0.75 under --scenario straggler, "
                        "else off)")
    p.add_argument("--crash-replicas", type=int, default=1,
                   help="crash_storm: how many replicas get the "
                        "crash drill (default 1)")
    p.add_argument("--fault-tick", type=int, default=6,
                   help="engine tick the chaos drill fires at "
                        "(crash_storm/straggler; default 6)")
    p.add_argument("--timeout", type=float, default=120.0, metavar="S",
                   help="scenario wall-clock budget (default 120)")
    p.add_argument("--availability-min", type=float, default=1.0,
                   help="fleet availability the verdict requires "
                        "(default 1.0)")
    p.add_argument("--slo", default=None, metavar="SPEC",
                   help="arm the fleet SLO plane (ISSUE 16): e.g. "
                        "'ttft_ms=250,tpot_ms=40,availability=0.999'. "
                        "The router scores every fleet-terminal event "
                        "good/bad, emits one schema-v14 slo_window "
                        "record per --slo-window terminals (slo_breach "
                        "past burn 1.0) plus periodic fleet_rollup "
                        "records merged from replica heartbeat "
                        "sketches, and the scenario verdict fails when "
                        "any window breaches its error budget")
    p.add_argument("--slo-window", type=int, default=16, metavar="N",
                   help="router SLO window size in fleet-terminal "
                        "events (default 16; event-count windows keep "
                        "chaos scores deterministic)")
    p.add_argument("--slo-rollup-s", type=float, default=2.0,
                   metavar="S",
                   help="period of the router's fleet_rollup records "
                        "(merged replica sketches; default 2)")
    p.add_argument("--tick-profile", action="store_true",
                   help="arm every replica's hot-path profiler "
                        "(ISSUE 17): heartbeats advertise the "
                        "cumulative host_overhead_frac, the router "
                        "re-emits it on replica_state records, and "
                        "fleet_report names the worst-host-overhead "
                        "replica.  Proc children additionally emit "
                        "schema-v15 tick_profile/overhead_summary "
                        "records into their own streams")
    p.add_argument("--tick-profile-every", type=int, default=16,
                   metavar="N",
                   help="proc children's tick_profile sampling period "
                        "(default 16)")
    p.add_argument("--tenants", default=None, metavar="SPEC",
                   help="multi-tenant fleet (ISSUE 19): "
                        "'name[:key=value,...];...' with keys weight/"
                        "budget/class/mix/burst/shared_prefix "
                        "(sched/tenants.py).  Arms DWRR fair admission "
                        "on every replica engine and per-tenant "
                        "ledgers + SLO verdicts on the router")
    p.add_argument("--advertise-prefixes", type=int, default=0,
                   metavar="N",
                   help="replicas advertise their top-N hot prefix "
                        "chain keys in heartbeats (what "
                        "--policy prefix_affinity routes on; "
                        "0 = off, auto-armed to 4 under "
                        "--scenario prefix_heavy)")
    p.add_argument("--deadline-step", type=int, default=None,
                   metavar="N",
                   help="virtual-step deadline stamped on INTERACTIVE "
                        "tenants' requests in the tenant scenarios "
                        "(default 20 there; virtual steps make the "
                        "noisy_neighbor breach bit-reproducible)")
    p.add_argument("--expect-breach", action="store_true",
                   help="noisy_neighbor control arm: replicas run "
                        "FIFO admission (no fair scheduler) and the "
                        "scenario passes only when the victim tenant "
                        "DEMONSTRABLY breaches its SLO")
    p.add_argument("--min-hit-rate", type=float, default=None,
                   help="prefix_heavy: fleet prefix_hit_rate the "
                        "verdict requires (default: just measured)")
    p.add_argument("--rebalance-kv-ratio", type=float, default=None,
                   metavar="R",
                   help="live KV-pressure rebalance (ISSUE 20): when "
                        "the hottest both-role replica's kv_bytes_live "
                        "exceeds R x the fleet mean, the router asks it "
                        "to migrate one live request to the migration "
                        "spool (R > 1.0; default: off)")
    p.add_argument("--rebalance-cooldown", type=float, default=1.0,
                   metavar="S",
                   help="min seconds between rebalance asks "
                        "(default 1.0)")
    p.add_argument("--autoscale", default=None, metavar="MIN:MAX",
                   help="elastic pool (ISSUE 20, thread transport): "
                        "start with --replicas handles and let the "
                        "ElasticPool controller spawn/retire between "
                        "MIN and MAX replicas off the router's backlog "
                        "+ TTFT gauges (auto-armed by --scenario "
                        "autoscale_flap)")
    p.add_argument("--autoscale-up-backlog", type=int, default=4,
                   metavar="N",
                   help="scale up when router backlog exceeds N "
                        "(default 4)")
    p.add_argument("--autoscale-down-backlog", type=int, default=0,
                   metavar="N",
                   help="scale down only at backlog <= N (must be < "
                        "the up threshold — the hysteresis band; "
                        "default 0)")
    p.add_argument("--autoscale-cooldown", type=float, default=0.5,
                   metavar="S",
                   help="min seconds between scale actions "
                        "(default 0.5)")
    p.add_argument("--autoscale-ttft-ms", type=float, default=None,
                   metavar="MS",
                   help="also scale up when the fleet TTFT p50 (merged "
                        "replica sketches; needs --slo) exceeds MS "
                        "(default: backlog gauge only)")
    p.add_argument("--bursts", type=int, default=3,
                   help="autoscale_flap: number of load bursts "
                        "(default 3)")
    p.add_argument("--burst-gap", type=float, default=0.5, metavar="S",
                   help="autoscale_flap: idle gap between bursts — the "
                        "scale-down side's chance to fire (default 0.5)")
    p.add_argument("--workdir", default=None,
                   help="proc transport scratch dir (inbox/outbox/"
                        "metrics per replica; default: alongside "
                        "--metrics-jsonl, else /tmp)")
    p.add_argument("--metrics-jsonl", default=None,
                   help="the ROUTER's schema-v10 stream (route/"
                        "replica_state/fleet_summary)")
    p.add_argument("--trace", action="store_true",
                   help="emit trace events from the router and serve "
                        "children and share one APEX_TRACE_ID so "
                        "trace_export merges the whole fleet")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="proc transport: per-replica supervisor "
                        "restart budget (default 3)")
    return p


def run_fleet(args):
    """Build replicas + router, run the scenario, shut down.  Returns
    (summary_record, rc)."""
    replica_mod = _load_fleet("replica")
    router_mod = _load_fleet("router")
    scen_mod = _load_fleet("scenarios")

    if args.replicas < 1:
        raise SystemExit(f"--replicas must be >= 1, got {args.replicas}")
    if args.scenario == "crash_storm" \
            and args.crash_replicas >= args.replicas:
        raise SystemExit("crash_storm needs at least one surviving "
                         f"replica (--crash-replicas {args.crash_replicas}"
                         f" vs --replicas {args.replicas})")
    if not 0 <= args.decode_replicas < args.replicas:
        raise SystemExit("--decode-replicas must leave at least one "
                         f"prefill replica (got {args.decode_replicas} "
                         f"of {args.replicas})")
    if args.scenario in ("prefill_crash", "decode_crash_midspool") \
            and args.decode_replicas < 1:
        raise SystemExit(f"--scenario {args.scenario} is a disagg "
                         "scenario: set --decode-replicas >= 1")
    if args.scenario == "decode_crash_midspool" \
            and args.decode_replicas < 2:
        raise SystemExit("decode_crash_midspool needs a surviving peer "
                         "decode worker: set --decode-replicas >= 2")
    migration_scenarios = ("drain_zero_evictions",
                           "migrate_under_crash_storm")
    if args.scenario in migration_scenarios + ("autoscale_flap",) \
            and args.decode_replicas:
        raise SystemExit(f"--scenario {args.scenario} needs the "
                         "homogeneous both-role fleet (extract_live "
                         "lives on the interleaved engine); drop "
                         "--decode-replicas")
    if args.scenario in ("migrate_under_crash_storm",
                         "autoscale_flap") \
            and args.transport != "thread":
        raise SystemExit(f"--scenario {args.scenario} is thread-"
                         "transport only (the preack drill rides the "
                         "in-process migration intake; the elastic "
                         "pool spawns in-process handles)")
    if args.scenario == "migrate_under_crash_storm" \
            and args.replicas < 3:
        raise SystemExit("migrate_under_crash_storm needs >= 3 "
                         "replicas: source, doomed destination, and a "
                         "surviving peer")
    if args.rebalance_kv_ratio is not None \
            and args.rebalance_kv_ratio <= 1.0:
        raise SystemExit(f"--rebalance-kv-ratio must be > 1.0, got "
                         f"{args.rebalance_kv_ratio}")
    if args.autoscale and args.scenario != "autoscale_flap":
        raise SystemExit("--autoscale only applies to --scenario "
                         "autoscale_flap (the scenario steps the "
                         "controller)")
    scale_bounds = None
    if args.scenario == "autoscale_flap":
        autoscale = args.autoscale \
            or f"{args.replicas}:{args.replicas + 2}"
        try:
            lo, hi = (int(x) for x in autoscale.split(":"))
        except ValueError:
            raise SystemExit(f"--autoscale wants MIN:MAX, got "
                             f"{autoscale!r}")
        if not 1 <= lo <= hi:
            raise SystemExit(f"--autoscale: need 1 <= MIN <= MAX, got "
                             f"{autoscale!r}")
        if args.autoscale_down_backlog >= args.autoscale_up_backlog:
            raise SystemExit("--autoscale-down-backlog must be < "
                             "--autoscale-up-backlog (the hysteresis "
                             "band)")
        if args.autoscale_ttft_ms is not None and not args.slo:
            raise SystemExit("--autoscale-ttft-ms needs --slo (the "
                             "TTFT sketches ride the SLO plane)")
        if args.bursts < 1:
            raise SystemExit(f"--bursts must be >= 1, got {args.bursts}")
        scale_bounds = (lo, hi)
    # Migration spool: armed by the migration scenarios, by continuous
    # rebalancing, and by the elastic pool (retirement drains without
    # eviction through it).
    mig_armed = (args.scenario in migration_scenarios
                 or args.rebalance_kv_ratio is not None
                 or scale_bounds is not None)
    stall_after = args.stall_after
    if stall_after is None and args.scenario == "straggler":
        stall_after = 0.75
    slo_spec = None
    if args.slo:
        if args.slo_window < 1:
            raise SystemExit(f"--slo-window must be >= 1, got "
                             f"{args.slo_window}")
        if args.slo_rollup_s <= 0:
            raise SystemExit(f"--slo-rollup-s must be > 0, got "
                             f"{args.slo_rollup_s}")
        # Validate the spec HERE (jax-free path load — obs/slo.py is
        # stdlib self-contained) so a typo dies before replicas spawn.
        try:
            slo_spec = router_mod._load_slo().parse_slo(args.slo)
        except ValueError as e:
            raise SystemExit(f"--slo: {e}")
    if args.tick_profile_every < 1:
        raise SystemExit(f"--tick-profile-every must be >= 1, got "
                         f"{args.tick_profile_every}")

    # Multi-tenant plane (ISSUE 19): parse the spec via the jax-free
    # sched stratum, pick the victim (first interactive tenant) for
    # the tenant scenarios, and auto-arm what those scenarios need.
    tenant_scenarios = ("noisy_neighbor", "tenant_burst_starvation",
                        "prefix_heavy")
    tenant_specs = None
    if args.tenants:
        try:
            tenant_specs = _load_sched("tenants").parse_tenants(
                args.tenants)
        except ValueError as e:
            raise SystemExit(f"--tenants: {e}")
    if args.scenario in tenant_scenarios and tenant_specs is None:
        raise SystemExit(f"--scenario {args.scenario} needs --tenants")
    if args.expect_breach and args.scenario != "noisy_neighbor":
        raise SystemExit("--expect-breach only applies to "
                         "--scenario noisy_neighbor")
    if args.advertise_prefixes < 0:
        raise SystemExit(f"--advertise-prefixes must be >= 0, got "
                         f"{args.advertise_prefixes}")
    advertise = args.advertise_prefixes
    if not advertise and args.scenario == "prefix_heavy":
        advertise = 4                   # the hit rate must be measured
    victim_name = None
    deadline_step = args.deadline_step
    if args.scenario in ("noisy_neighbor", "tenant_burst_starvation"):
        interactive = [n for n, t in tenant_specs.items()
                       if t.slo_class == "interactive"]
        batch = [n for n, t in tenant_specs.items()
                 if t.slo_class != "interactive"]
        if not interactive or not batch:
            raise SystemExit(f"--scenario {args.scenario} needs at "
                             "least one interactive tenant (the "
                             "victim) and one batch tenant (the "
                             "noisy one) in --tenants")
        victim_name = interactive[0]
        if deadline_step is None:
            deadline_step = 20
        if slo_spec is None:
            # Availability-only spec: per-tenant verdicts need scoring
            # armed, and a latency target would make the verdict ride
            # wall clocks instead of the virtual-step deadlines.
            slo_spec = {"availability": 0.9}
    # FIFO control arm: the ENGINES drop fair admission, the router
    # keeps the per-tenant ledger (that is where the breach shows).
    engine_tenants = tenant_specs \
        if not args.expect_breach else None

    def lohi(spec, name):
        parts = spec.split(":")
        try:
            lo, hi = (int(parts[0]), int(parts[-1]))
        except ValueError:
            raise SystemExit(f"--{name} wants N or MIN:MAX, got {spec!r}")
        if len(parts) > 2 or lo < 1 or lo > hi:
            raise SystemExit(f"--{name}: bad range {spec!r}")
        return lo, hi

    prompt_len = lohi(args.prompt_len, "prompt-len")
    max_new = lohi(args.max_new, "max-new")

    # Topology: the last --decode-replicas names run role "decode" off
    # a shared spool, the rest "prefill" (or everything "both" in the
    # classic homogeneous fleet).
    names = [f"r{i}" for i in range(args.replicas)]
    n_decode = args.decode_replicas
    if n_decode:
        roles = {name: ("decode" if i >= args.replicas - n_decode
                        else "prefill")
                 for i, name in enumerate(names)}
    else:
        roles = {name: "both" for name in names}
    prefill_names = [n for n in names if roles[n] != "decode"]
    decode_names = [n for n in names if roles[n] == "decode"]
    crashed_names = names[:args.crash_replicas] \
        if args.scenario == "crash_storm" else []
    if args.scenario == "prefill_crash":
        crashed_names = [prefill_names[0]]
    elif args.scenario == "decode_crash_midspool":
        crashed_names = [decode_names[0]]
    straggler_name = names[0] if args.scenario == "straggler" else None
    mig_source_name = mig_crashed_name = None
    if args.scenario == "migrate_under_crash_storm":
        # Deterministic staging: r0 drains outbound-only, r1 claims
        # first and dies in the ack window, the rest reclaim.
        mig_source_name, mig_crashed_name = names[0], names[1]
        crashed_names = [mig_crashed_name]

    # Lazy: only the proc transport and a disagg spool need scratch
    # space — a plain thread fleet must not litter /tmp.
    workdir = args.workdir
    if workdir is None and (n_decode or mig_armed
                            or args.transport == "proc"):
        workdir = (os.path.join(os.path.dirname(args.metrics_jsonl)
                                or ".", "fleet_work")
                   if args.metrics_jsonl
                   else tempfile.mkdtemp(prefix="apex_fleet_"))
    spool = os.path.join(workdir, "spool") if n_decode else None
    if spool:
        os.makedirs(spool, exist_ok=True)
    mig_spool = os.path.join(workdir, "migrate") if mig_armed else None
    if mig_spool:
        os.makedirs(mig_spool, exist_ok=True)

    fleet_stream = None     # thread+tenants: shared router/engine tee
    elastic_spawn = None    # set by the thread branch (pool spawns)
    if args.transport == "proc":
        replicas = []
        for name in names:
            serve_args = ["--slots", str(args.slots),
                          "--block-size", str(args.block_size)]
            if args.max_len is not None:
                serve_args += ["--max-len", str(args.max_len)]
            if args.trace:
                serve_args += ["--trace"]
            if args.slo:
                # Children score their own windows (wall-clock mode)
                # and heartbeat cumulative sketches the router's
                # fleet_rollup merges.  (An AUTO-armed tenant-scenario
                # spec stays router-only: it has no latency target to
                # hand a child's --slo parser.)
                serve_args += ["--slo", args.slo]
            if args.tick_profile:
                # Children decompose their own ticks (v15 records in
                # their streams) and heartbeat host_overhead_frac.
                serve_args += ["--tick-profile", "--tick-profile-every",
                               str(args.tick_profile_every)]
            if engine_tenants is not None:
                # Children run DWRR fair admission and heartbeat their
                # per-tenant admitted-token ledgers (v17).
                serve_args += ["--tenants", args.tenants]
            if advertise:
                serve_args += ["--advertise-prefixes", str(advertise)]
            if roles[name] == "decode":
                serve_args += ["--handoff-lease",
                               str(args.handoff_lease)]
            if roles[name] == "both" and mig_spool:
                # Children on the shared migration spool: SIGTERM now
                # drains without eviction, the tick loop claims peers'
                # payloads (serve.py --migrate-dir).
                serve_args += ["--migrate-dir", mig_spool,
                               "--handoff-lease",
                               str(args.handoff_lease)]
            if name in crashed_names:
                drill = f"crash@{args.fault_tick}"
                if args.scenario == "decode_crash_midspool":
                    # The ack-crash window, on the first admit: the
                    # supervisor strips the drill from the restart
                    # attempt (a decode worker replays the spool from
                    # its claim set, so it would re-fire).
                    drill = "handoff_crash_preack@1"
                serve_args += ["--inject-fault", drill]
            sup_args = ["--max-restarts", str(args.max_restarts),
                        "--backoff", "0.2"]
            if name == straggler_name:
                serve_args += ["--inject-fault",
                               f"hang@{args.fault_tick}"]
                # The supervisor's stall-kill is the hung child's only
                # way out; the router rescues the requests first.
                sup_args += ["--stall-kill", "10"]
            replicas.append(replica_mod.ProcReplica(
                name, workdir, REPO, serve_args=serve_args,
                supervise_args=sup_args, role=roles[name],
                spool_dir=spool))
        vocab = args.vocab_size
    else:
        import jax
        import jax.numpy as jnp

        from apex_example_tpu.models.gpt import gpt_tiny
        from apex_example_tpu.resilience.faults import (SERVE_KINDS,
                                                        FaultPlan)
        from apex_example_tpu.serve import (FileTransport, Request,
                                            ServeEngine)

        model = gpt_tiny()
        params = model.init(jax.random.PRNGKey(args.seed),
                            jnp.zeros((1, 4), jnp.int32))["params"]
        vocab = int(model.vocab_size)
        max_len = args.max_len or min(model.max_position, 128)

        def make_profiler():
            # Thread replicas have no per-engine sink, so the profiler
            # only ACCUMULATES (emit=None): host_overhead_frac reaches
            # the router through state() heartbeats, and no v15 records
            # land anywhere — the router's stream stays fleet-only.
            if not args.tick_profile:
                return None
            from apex_example_tpu.obs.tickprof import TickProfiler
            return TickProfiler(kind="serve",
                                sample_every=args.tick_profile_every)

        tee_sink = None
        tee_kinds = set()
        if tenant_specs is not None:
            # --tenants arms ci_gate --tenant-stream, whose
            # conservation ledger needs every routed uid to reach a
            # terminal record IN THE SAME STREAM.
            tee_kinds |= {"request_complete", "request_failed", "shed"}
        if mig_armed:
            # Migration arms ci_gate --migrate-stream, whose ledger
            # matches every kv_migration "out" against its admission
            # and terminal record, and checks serve_drain evictions —
            # all of which the engines emit, not the router.
            tee_kinds |= {"request_complete", "request_failed", "shed",
                          "kv_migration", "serve_drain"}
        if tee_kinds:
            # The router only writes route/fleet records, so tee the
            # engines' gate-relevant records into the router's own
            # locked writer — one self-contained stream, engine records
            # interleaved with routes.  Everything else an engine-side
            # sink would emit (run_header, serve_summary, slo windows)
            # is dropped here: the router owns the fleet stream.
            # Unarmed fleets keep sink=None so their streams stay
            # byte-identical.
            fleet_stream = router_mod._Stream(args.metrics_jsonl)

            class _TerminalTee:
                def write(self, rec):
                    if rec.get("record") in tee_kinds:
                        fleet_stream.write(rec)

            tee_sink = _TerminalTee()

        def factory():
            # Every replica's engine clones the same module config, so
            # the jitted decode step is built ONCE and shared.  With
            # --slo the engine grows a tracker whose cumulative
            # sketches surface through state() into the router's
            # fleet_rollup (no sink here, so per-engine window records
            # stay off — the ROUTER's stream carries the fleet ones).
            return ServeEngine(model, params, num_slots=args.slots,
                               max_len=max_len,
                               block_size=args.block_size,
                               rng=jax.random.PRNGKey(args.seed),
                               slo=slo_spec,
                               tenants=engine_tenants,
                               tag_tenants=tenant_specs is not None,
                               advertise_prefixes=advertise,
                               sink=tee_sink,
                               tick_profiler=make_profiler())

        def role_factories(name):
            # Disagg roles over one shared spool: a prefill engine
            # ships handoffs through its own producer-side transport; a
            # decode replica gets a consumer transport under ITS name,
            # so a rebuilt instance adopts its own pre-crash claims.
            from apex_example_tpu.serve import FileTransport

            def prefill_engine():
                tx = FileTransport(spool, worker=f"{name}.tx")
                return ServeEngine(model, params, num_slots=args.slots,
                                   max_len=max_len,
                                   block_size=args.block_size,
                                   rng=jax.random.PRNGKey(args.seed),
                                   role="prefill",
                                   handoff_sink=tx.send,
                                   slo=slo_spec,
                                   tick_profiler=make_profiler())

            def decode_engine():
                return ServeEngine(model, params, num_slots=args.slots,
                                   max_len=max_len,
                                   block_size=args.block_size,
                                   rng=jax.random.PRNGKey(args.seed),
                                   role="decode",
                                   slo=slo_spec,
                                   tick_profiler=make_profiler())

            def decode_transport():
                return FileTransport(spool, worker=name,
                                     lease_s=args.handoff_lease)

            return prefill_engine, decode_engine, decode_transport

        def make_request(spec):
            return Request(prompt=spec["prompt"],
                           max_new_tokens=int(spec["max_new_tokens"]),
                           temperature=float(spec.get("temperature", 0)),
                           top_k=int(spec.get("top_k", 0)),
                           eos_id=spec.get("eos_id"),
                           deadline_s=spec.get("deadline_s"),
                           deadline_step=spec.get("deadline_step"),
                           tenant=spec.get("tenant", "default"),
                           priority=int(spec.get("priority", 0)),
                           uid=spec["uid"])

        def mig_factory(name):
            # One consumer transport per replica NAME (not instance):
            # a rebuilt replica adopts its own pre-crash claims, a
            # peer adopts them only after the lease expires.
            if mig_spool is None:
                return None
            return lambda: FileTransport(mig_spool, worker=name,
                                         lease_s=args.handoff_lease)

        def spawn_elastic(i):
            # ElasticPool spawn: same engine factory, so the scaled-up
            # replica reuses the fleet's one compiled decode program.
            nm = f"r{args.replicas + i}"
            return replica_mod.ThreadReplica(
                nm, factory, make_request,
                migrate_factory=mig_factory(nm))

        elastic_spawn = spawn_elastic
        replicas = []
        for name in names:
            fault = None
            if name in crashed_names:
                kind = "handoff_crash_preack" \
                    if args.scenario in ("decode_crash_midspool",
                                         "migrate_under_crash_storm") \
                    else "crash"
                tick = 1 if kind == "handoff_crash_preack" \
                    else args.fault_tick
                fault = FaultPlan(kind, tick, kinds=SERVE_KINDS)
            elif name == straggler_name:
                fault = FaultPlan("hang", args.fault_tick,
                                  kinds=SERVE_KINDS)
            if roles[name] == "both":
                replicas.append(replica_mod.ThreadReplica(
                    name, factory, make_request, fault=fault,
                    migrate_factory=mig_factory(name),
                    migrate_intake=name != mig_source_name))
            else:
                pre, dec, tx_factory = role_factories(name)
                if roles[name] == "prefill":
                    replicas.append(replica_mod.ThreadReplica(
                        name, pre, make_request, fault=fault,
                        role="prefill"))
                else:
                    replicas.append(replica_mod.ThreadReplica(
                        name, dec, fault=fault, role="decode",
                        transport_factory=tx_factory))

    if tenant_specs is not None:
        # Per-tenant spec streams: requests apportioned by mix
        # (largest remainder), each tenant drawing from its own
        # crc32-derived substream (the loadgen discipline, stdlib
        # here) with its spec-declared shared prefix.  For the
        # starvation scenarios the batch tenants' whole backlog is
        # ordered AHEAD of the interactive tenants' deadline-carrying
        # requests — the worst case fair admission must beat.
        import zlib
        tnames = list(tenant_specs)
        mixes = [float(tenant_specs[t].mix) for t in tnames]
        total_mix = sum(mixes)
        raw = [args.requests * m / total_mix for m in mixes]
        alloc = [int(r) for r in raw]
        for _ in range(args.requests - sum(alloc)):
            rems = [(raw[i] - alloc[i], -i) for i in range(len(tnames))]
            alloc[-max(rems)[1]] += 1
        per_tenant = {}
        for i, tname in enumerate(tnames):
            if not alloc[i]:
                continue
            ts = tenant_specs[tname]
            dstep = deadline_step \
                if (victim_name is not None
                    and ts.slo_class == "interactive") else None
            per_tenant[tname] = scen_mod.synthetic_specs(
                alloc[i], vocab_size=vocab,
                seed=zlib.crc32(f"{args.seed}/{i}".encode())
                & 0x7FFFFFFF,
                prompt_len=prompt_len, max_new=max_new,
                deadline_s=args.deadline_s, deadline_step=dstep,
                tenant=tname, shared_prefix=int(ts.shared_prefix),
                uid_prefix=f"fl-{tname}")
        if victim_name is not None:
            order = [t for t in tnames
                     if tenant_specs[t].slo_class != "interactive"] \
                + [t for t in tnames
                   if tenant_specs[t].slo_class == "interactive"]
        else:
            order = tnames
        specs = [s for t in order for s in per_tenant.get(t, ())]
    else:
        specs = scen_mod.synthetic_specs(
            args.requests, vocab_size=vocab, seed=args.seed,
            prompt_len=prompt_len, max_new=max_new,
            deadline_s=args.deadline_s)

    router = router_mod.FleetRouter(
        replicas, policy=args.policy,
        metrics_jsonl=args.metrics_jsonl,
        sink=fleet_stream,
        max_retries=args.max_retries,
        breaker_backoff_s=args.breaker_backoff,
        stall_after_s=stall_after,
        default_deadline_s=args.deadline_s,
        # Disagg self-healing: well past the lease, so live
        # redelivery always gets first go at a dead worker's claims.
        spool_timeout_s=(args.spool_timeout
                         if args.spool_timeout is not None
                         else max(4.0 * args.handoff_lease, 5.0))
        if n_decode else None,
        slo=slo_spec, slo_window=args.slo_window,
        slo_rollup_s=args.slo_rollup_s,
        tenant_specs=tenant_specs,
        prefix_block_size=args.block_size,
        rebalance_kv_ratio=args.rebalance_kv_ratio,
        rebalance_cooldown_s=args.rebalance_cooldown,
        trace=args.trace)
    print(f"fleet: {args.replicas} x {args.transport} replica(s)  "
          f"policy={args.policy}  scenario={args.scenario}  "
          f"requests={args.requests}")

    kw = {"timeout_s": args.timeout,
          "availability_min": args.availability_min}
    if args.scenario == "crash_storm":
        kw["crashed_names"] = crashed_names
        kw["restart_crashed"] = args.transport == "thread"
    elif args.scenario == "straggler":
        kw["straggler_name"] = straggler_name
    elif args.scenario == "prefill_crash":
        kw["crashed_name"] = crashed_names[0]
        kw["restart_crashed"] = args.transport == "thread"
    elif args.scenario == "decode_crash_midspool":
        kw["crashed_name"] = crashed_names[0]
    elif args.scenario in ("noisy_neighbor", "tenant_burst_starvation"):
        kw["victim"] = victim_name
        if args.scenario == "noisy_neighbor":
            kw["expect_breach"] = args.expect_breach
    elif args.scenario == "prefix_heavy":
        kw["min_hit_rate"] = args.min_hit_rate
    elif args.scenario == "migrate_under_crash_storm":
        kw["source_name"] = mig_source_name
        kw["crashed_name"] = mig_crashed_name
    pool = None
    if scale_bounds is not None:
        try:
            pool = ElasticPool(
                router, elastic_spawn,
                min_replicas=scale_bounds[0],
                max_replicas=scale_bounds[1],
                up_backlog=args.autoscale_up_backlog,
                down_backlog=args.autoscale_down_backlog,
                cooldown_s=args.autoscale_cooldown,
                ttft_p50_ms=args.autoscale_ttft_ms,
                initial=replicas)
        except ValueError as e:
            raise SystemExit(f"--autoscale: {e}")
        kw["pool"] = pool
        kw["bursts"] = args.bursts
        kw["gap_s"] = args.burst_gap
    try:
        summary = scen_mod.run_scenario(args.scenario, router, replicas,
                                        specs, **kw)
    finally:
        handles = list(replicas)
        if pool is not None:
            handles += [h for h in pool.active + pool.retired
                        if h not in handles]
        for r in handles:
            if args.transport == "proc":
                r.close()
            elif router.replica_state(r.name) not in ("stalled",):
                r.stop(timeout_s=5.0)
        if args.transport == "proc":
            for r in handles:
                if r.wait(30.0) is None:
                    r.terminate()

    per = summary.get("per_replica", {})
    for name in names + sorted(set(per) - set(names)):
        stats = per.get(name, {})
        print(f"  {name}: dispatches={stats.get('dispatches', 0)}  "
              f"ok={stats.get('ok', 0)}  "
              f"drained={stats.get('drained', 0)}  "
              f"lost={stats.get('lost', 0)}  "
              f"availability={stats.get('availability', 1.0)}  "
              f"state={stats.get('state', '?')}")
    print(f"fleet_summary: availability={summary['availability']}  "
          f"lost={summary['lost']}  retries={summary['retries']}  "
          f"requeued={summary['drained_requeued']}  "
          f"skew={summary['routing']['balance_skew']}"
          + (f"  verdict={summary['verdict']}"
             if "verdict" in summary else ""))
    if summary.get("tenants"):
        tl = summary["tenants"]
        starved = min(tl, key=lambda t: (tl[t]["availability"], t))
        noisiest = max(tl, key=lambda t:
                       (tl[t].get("admitted_tokens", 0),
                        sum(tl[t]["counts"].values()), t))
        for tname, ent in tl.items():
            print(f"  tenant {tname}: counts={ent['counts']}  "
                  f"availability={ent['availability']}"
                  + (f"  slo_verdict={ent['slo_verdict']}"
                     if "slo_verdict" in ent else ""))
        print(f"tenants: starved={starved} "
              f"(availability={tl[starved]['availability']})  "
              f"noisiest={noisiest} "
              f"(admitted_tokens="
              f"{tl[noisiest].get('admitted_tokens', 0)})")
    if "prefix_hit_rate" in summary:
        print(f"prefix: fleet hit_rate={summary['prefix_hit_rate']}")
    if "slo_verdict" in summary:
        print(f"slo: verdict={summary['slo_verdict']}  "
              f"windows={summary['slo_windows']}  "
              f"breaches={summary['slo_breaches']}  "
              f"worst_burn={round(summary['slo_worst_burn'], 3)}"
              + (f"  worst_window={summary['slo_worst_window']}"
                 if "slo_worst_window" in summary else ""))
    rc = 0 if summary.get("verdict") == "pass" else 1
    return summary, rc


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    _, rc = run_fleet(args)
    return rc


if __name__ == "__main__":
    sys.exit(main())
