"""Expert-parallel (switch-MoE) BERT training from the harness
(workloads.make_bert_moe_train_step; train.py --moe-experts).

The golden is the BLOCKED DENSE construction: routing/capacity are
per-device by design (the same contract the layer-level EP tests pin), so
the reference trajectory applies the dense-reference MoE model to each
shard's batch block independently, combines the blocks' losses with the
same globally-normalized weighted CE + mean aux objective, and takes the
same fused-optimizer step on the full [E, ...] stacks.  The EP step must
reproduce it exactly — all_to_all dispatch, shard-local expert grads,
implicit psum of replicated grads and all."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_example_tpu import amp
from apex_example_tpu.data import mlm_batch
from apex_example_tpu.engine import create_train_state
from apex_example_tpu.models.bert import bert_tiny
from apex_example_tpu.optim import FusedAdam, FusedSGD
from apex_example_tpu.ops.xentropy import softmax_cross_entropy
from apex_example_tpu.workloads import (bert_moe_state_shardings,
                                        make_bert_moe_train_step)

BATCH, SEQ, E = 16, 16, 8
AUX_W = 1e-2


def _moe_model(**kw):
    kw.setdefault("moe_experts", E)
    kw.setdefault("moe_axis_name", "data")
    return bert_tiny(**kw)


def _batch(i, vocab):
    ids, lab, w = mlm_batch(jnp.asarray(i, jnp.int32), batch_size=BATCH,
                            seq_len=SEQ, vocab_size=vocab,
                            mask_token_id=vocab - 1, seed=0)
    return ids, (lab, w)


def _golden_step(model, optimizer, state, n_blocks=E):
    """Blocked dense-reference step: n_blocks batch blocks through the
    full-stack dense MoE path, one global objective, one optimizer step."""
    from apex_example_tpu.engine import TrainState, _wrap_optimizer
    opt = _wrap_optimizer(optimizer)
    E_ = n_blocks
    b = BATCH // E_

    def loss_fn(params, batch):
        ids, (labels, weights) = batch
        num = jnp.zeros((), jnp.float32)
        aux_sum = jnp.zeros((), jnp.float32)
        for s in range(E_):
            sl = slice(s * b, (s + 1) * b)
            logits, aux = model.apply({"params": params}, ids[sl],
                                      train=True)
            ce = softmax_cross_entropy(logits, labels[sl])
            num = num + (ce * weights[sl]).sum()
            aux_sum = aux_sum + aux
        den = jnp.maximum(weights.sum(), 1.0)
        return num / den + AUX_W * aux_sum / E_

    @jax.jit
    def step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        new_params, new_opt = opt.apply(grads, state.opt_state, state.params)
        return TrainState(step=state.step + 1, params=new_params,
                          batch_stats=state.batch_stats, opt_state=new_opt,
                          scaler=state.scaler), loss

    return step


@pytest.mark.parametrize("n_experts", [E, 2 * E])
def test_moe_train_matches_blocked_dense_golden(devices8, n_experts):
    """n_experts = 2*E runs TWO experts per device: the grouped
    all_to_all's backward (reshape/transpose pairs), the shard-local
    [k, ...] expert grads, and the optimizer on the k-stacked shards are
    the parts only this variant exercises."""
    mesh = Mesh(np.asarray(devices8), ("data",))
    policy, scaler = amp.initialize("O0")
    model = _moe_model(moe_experts=n_experts)
    V = model.vocab_size
    # SGD+momentum, not adam: attention's key bias takes a mathematically
    # ~zero gradient, and adam's m/sqrt(v) normalization would amplify the
    # all_to_all-vs-einsum rounding noise on it to lr-scale updates —
    # a tolerance problem, not a semantics one (adam is exercised by the
    # CLI/scaling tests).
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
    state_g = create_train_state(jax.random.PRNGKey(0), model, opt(),
                                 _batch(0, V)[0][:1], policy, scaler)
    golden = _golden_step(model, opt(), state_g, n_blocks=E)

    zopt = opt()
    state_e = create_train_state(jax.random.PRNGKey(0), model, zopt,
                                 _batch(0, V)[0][:1], policy, scaler)
    state_e = jax.device_put(state_e,
                             bert_moe_state_shardings(mesh, state_e, zopt))
    step_e = make_bert_moe_train_step(mesh, model, zopt, policy,
                                      state_template=state_e,
                                      aux_weight=AUX_W, donate=False)

    for i in range(30):
        batch = _batch(i, V)
        state_g, loss_g = golden(state_g, batch)
        state_e, m_e = step_e(state_e, batch)
        np.testing.assert_allclose(float(loss_g), float(m_e["loss"]),
                                   rtol=2e-5 * (1 + i / 3))
    for (ka, a), (kb, b2) in zip(
            jax.tree_util.tree_leaves_with_path(state_g.params),
            jax.tree_util.tree_leaves_with_path(state_e.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=1e-3, atol=1e-5, err_msg=str(ka))


def test_moe_tp_train_matches_blocked_dense_golden(devices8):
    """MoE x TP (partially-manual shard_map: experts over manual 'data',
    GSPMD TP attention/embeddings/head on automatic 'model') == the same
    blocked dense golden, fed identical params — and the state is provably
    sharded on BOTH axes."""
    from apex_example_tpu.engine import create_gspmd_train_state
    from apex_example_tpu.ops import _config as ops_config
    mesh = Mesh(np.asarray(devices8).reshape(4, 2), ("data", "model"))
    policy, scaler = amp.initialize("O0")
    dense = _moe_model(moe_experts=4)
    tp_model = _moe_model(moe_experts=4, tensor_parallel=True)
    V = dense.vocab_size
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
    state_g = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                 _batch(0, V)[0][:1], policy, scaler)
    golden = _golden_step(dense, opt(), state_g, n_blocks=4)

    ops_config.set_force_xla(True)
    try:
        zopt = opt()
        state_e, gsh = create_gspmd_train_state(
            jax.random.PRNGKey(0), mesh, tp_model, zopt,
            _batch(0, V)[0][:1], policy, scaler)
        sh = bert_moe_state_shardings(mesh, state_e, zopt,
                                      base_shardings=gsh)
        # same starting point as the golden (identical param tree)
        state_e = jax.device_put(state_g.replace(
            opt_state=state_e.opt_state), sh)
        step_e = make_bert_moe_train_step(mesh, tp_model, zopt, policy,
                                          state_template=state_e,
                                          aux_weight=AUX_W, donate=False,
                                          state_shardings=sh)
        for i in range(30):
            batch = _batch(i, V)
            state_g, loss_g = golden(state_g, batch)
            state_e, m_e = step_e(state_e, batch)
            np.testing.assert_allclose(float(loss_g), float(m_e["loss"]),
                                       rtol=3e-5 * (1 + i / 3))
        p0 = state_e.params["layer_0"]
        assert p0["moe"]["w_in"].sharding.spec == P("data")
        q_spec = p0["attention"]["query"]["kernel"].sharding.spec
        assert "model" in jax.tree_util.tree_leaves(tuple(q_spec)), q_spec
        for (ka, a), (kb, b2) in zip(
                jax.tree_util.tree_leaves_with_path(state_g.params),
                jax.tree_util.tree_leaves_with_path(state_e.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                       rtol=2e-4, atol=1e-5,
                                       err_msg=str(ka))
    finally:
        ops_config.set_force_xla(False)


def test_train_py_cli_moe_tp(devices8, capsys):
    """MoE x TP from the CLI (both families' routing already covered; this
    pins the composed path end-to-end)."""
    import train as train_mod
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "bert_tiny", "--moe-experts", "4",
            "--tensor-parallel", "2", "--batch-size", str(BATCH),
            "--seq-len", str(SEQ), "--epochs", "1", "--steps-per-epoch",
            "2", "--opt", "adam", "--lr", "1e-3", "--opt-level", "O0",
            "--print-freq", "1", "--eval", "--eval-batches", "2"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)
    assert "masked_acc" in capsys.readouterr().out


def test_moe_state_actually_sharded(devices8):
    """The expert stacks shard one-per-device over 'data'; the router and
    everything else replicate."""
    mesh = Mesh(np.asarray(devices8), ("data",))
    policy, scaler = amp.initialize("O0")
    model = _moe_model()
    opt = FusedAdam(lr=1e-3)
    state = create_train_state(jax.random.PRNGKey(0), model, opt,
                               _batch(0, model.vocab_size)[0][:1], policy,
                               scaler)
    state = jax.device_put(state, bert_moe_state_shardings(mesh, state, opt))
    p0 = state.params["layer_0"]["moe"]
    assert p0["w_in"].sharding.spec == P("data")
    local = p0["w_in"].addressable_shards[0].data
    assert local.shape[0] == 1 and p0["w_in"].shape[0] == E
    assert p0["router"].sharding.spec == P()


def test_moe_fp16_dynamic_scaling_skips_globally(devices8):
    """An overflow landing in ONE shard's expert grads must skip the update
    and halve the scale on EVERY shard (the finite_reduce_axes pmean) —
    without it the replicated scaler state diverges across the mesh."""
    mesh = Mesh(np.asarray(devices8), ("data",))
    policy, scaler = amp.initialize("O2", loss_scale="dynamic",
                                    half_dtype=jnp.float16,
                                    init_scale=2.0 ** 4)
    model = _moe_model(dtype=jnp.float16)
    V = model.vocab_size
    opt = FusedAdam(lr=1e-3)
    state = create_train_state(jax.random.PRNGKey(0), model, opt,
                               _batch(0, V)[0][:1], policy, scaler)
    state = jax.device_put(state, bert_moe_state_shardings(mesh, state, opt))
    step = make_bert_moe_train_step(mesh, model, opt, policy,
                                    state_template=state, aux_weight=AUX_W,
                                    donate=False)
    ids, (labels, w) = _batch(0, V)
    w_bad = w.at[0, 0].set(jnp.inf)        # lands in shard 0 only
    p_before = jax.tree_util.tree_map(lambda p: np.asarray(p), state.params)
    state, m = step(state, (ids, (labels, w_bad)))
    assert float(m["grads_finite"]) == 0.0
    assert float(state.scaler.scale) == 2.0 ** 3
    for a, b in zip(jax.tree_util.tree_leaves(p_before),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    state, m = step(state, (ids, (labels, w)))
    assert float(m["grads_finite"]) == 1.0


def test_train_py_cli_moe(devices8, capsys):
    import train as train_mod
    argv = ["--arch", "bert_tiny", "--moe-experts", "8",
            "--batch-size", str(BATCH), "--seq-len", str(SEQ),
            "--epochs", "1", "--steps-per-epoch", "3", "--opt", "adam",
            "--opt-level", "O0", "--print-freq", "1",
            "--eval", "--eval-batches", "2"]
    assert train_mod.main(argv) == 0
    assert "masked_acc" in capsys.readouterr().out


def test_train_py_moe_rejections(devices8):
    import train as train_mod
    base = ["--arch", "bert_tiny", "--batch-size", "16", "--seq-len", "16",
            "--epochs", "1", "--steps-per-epoch", "1"]
    with pytest.raises(SystemExit):       # lamb collapses on expert stacks
        train_mod.main(base + ["--moe-experts", "8", "--opt", "lamb"])
    with pytest.raises(SystemExit):       # no ZeRO composition
        train_mod.main(base + ["--moe-experts", "8", "--zero"])
    with pytest.raises(SystemExit):       # no SP composition
        train_mod.main(base + ["--moe-experts", "4",
                               "--tensor-parallel", "2",
                               "--sequence-parallel"])
    with pytest.raises(SystemExit):       # experts != device count
        train_mod.main(base + ["--moe-experts", "3"])
    with pytest.raises(SystemExit):       # image archs have no FFN to swap
        train_mod.main(["--arch", "resnet18", "--moe-experts", "8",
                        "--epochs", "1", "--steps-per-epoch", "1"])


# ---------------------------------------------------------------------------
# EP x CP (VERDICT r4 item 4): experts over 'data', KV ring over 'context'
# — two manual axes, two independent collectives in one step (train.py
# --moe-experts --context-parallel).  The golden is EXACT: the same
# (data, context) shard_map and the same CP attention program, but MoEMLP
# bound to an UNBOUND axis name ('expert' is not a mesh axis), so every
# shard runs the dense-reference expert compute on the replicated full
# [E, ...] stacks with the SAME per-(data, context)-shard routing/capacity
# the EP dispatch uses.  The EP x CP step must reproduce it exactly —
# aux loss and capacity drops included.
# ---------------------------------------------------------------------------

def _golden_moe_cp_step(mesh, model_gold, optimizer, policy, mode):
    from apex_example_tpu.engine import make_train_step
    from apex_example_tpu.workloads import (_cp_layout_wrap,
                                            _global_lm_loss)
    try:
        from jax import shard_map as smap
    except ImportError:
        from jax.experimental.shard_map import shard_map as smap

    def gold_loss(out, y):
        logits, aux = out
        aux = jax.lax.pmean(aux, ("data", "context"))
        return _global_lm_loss(logits, y, ("data", "context")) + AUX_W * aux

    per_shard = make_train_step(model_gold, optimizer, policy,
                                axis_name=None, loss_fn=gold_loss,
                                compute_accuracy=False)
    b = P("data", "context")
    sharded = smap(per_shard, mesh=mesh, in_specs=(P(), (b, b)),
                   out_specs=(P(), P()))
    return jax.jit(_cp_layout_wrap(sharded, mesh, model_gold, mode),
                   donate_argnums=())


def _lm_batch(i, vocab, batch=8, seq=16):
    from apex_example_tpu.data import lm_batch
    toks = lm_batch(jnp.asarray(i, jnp.int32), batch_size=batch,
                    seq_len=seq, vocab_size=vocab, seed=0)
    return toks[:, :-1], toks[:, 1:]


@pytest.mark.parametrize("mode", ["ring", "zigzag", "ulysses"])
def test_moe_cp_train_matches_dense_ref_golden(devices8, mode):
    """30 lockstep steps of GPT EP x CP (dp=4, cp=2) == the dense-reference
    golden under the identical mesh/attention/routing — exact semantics,
    not tolerance hand-waving (SGD+momentum per the suite's parity
    convention; adam's near-zero-grad sign flips are a tolerance artifact,
    not semantics)."""
    from apex_example_tpu.models.gpt import gpt_tiny
    from apex_example_tpu.workloads import make_bert_moe_train_step

    mesh = Mesh(np.asarray(devices8).reshape(4, 2), ("data", "context"))
    policy, scaler = amp.initialize("O0")
    kw = dict(moe_experts=4, context_parallel=True, cp_mode=mode)
    ep_model = gpt_tiny(**kw, moe_axis_name="data")
    gold_model = gpt_tiny(**kw, moe_axis_name="expert")   # unbound => dense
    dense_init = gpt_tiny(moe_experts=4, moe_axis_name="data")
    V = dense_init.vocab_size
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)

    # 17-token stream => x,y are [8, 16]; seq 16 = 2 context shards x 8
    sample = _lm_batch(0, V)[0][:1]
    state_g = create_train_state(jax.random.PRNGKey(0), dense_init, opt(),
                                 sample, policy, scaler)
    golden = _golden_moe_cp_step(mesh, gold_model, opt(), policy, mode)

    zopt = opt()
    state_e = create_train_state(jax.random.PRNGKey(0), dense_init, zopt,
                                 sample, policy, scaler)
    state_e = jax.device_put(state_e,
                             bert_moe_state_shardings(mesh, state_e, zopt))
    step_e = make_bert_moe_train_step(mesh, ep_model, zopt, policy,
                                      state_template=state_e,
                                      aux_weight=AUX_W, donate=False,
                                      objective="lm",
                                      context_parallel=True, mode=mode)

    for i in range(30):
        batch = _lm_batch(i, V)
        state_g, m_g = golden(state_g, batch)
        state_e, m_e = step_e(state_e, batch)
        np.testing.assert_allclose(float(m_g["loss"]), float(m_e["loss"]),
                                   rtol=2e-5)
    for (ka, a), (kb, b2) in zip(
            jax.tree_util.tree_leaves_with_path(state_g.params),
            jax.tree_util.tree_leaves_with_path(state_e.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=2e-4, atol=1e-6, err_msg=str(ka))


def test_moe_cp_expert_state_sharded(devices8):
    """The EP x CP state really is placed expert-per-data-device and
    replicated over 'context' (1/dp expert bytes per device)."""
    from apex_example_tpu.models.gpt import gpt_tiny
    mesh = Mesh(np.asarray(devices8).reshape(4, 2), ("data", "context"))
    policy, scaler = amp.initialize("O0")
    model = gpt_tiny(moe_experts=4, moe_axis_name="data")
    V = model.vocab_size
    opt = FusedSGD(lr=0.05, momentum=0.9)
    state = create_train_state(jax.random.PRNGKey(0), model, opt,
                               _lm_batch(0, V)[0][:1], policy, scaler)
    state = jax.device_put(state,
                           bert_moe_state_shardings(mesh, state, opt))
    w_in = state.params["layer_0"]["moe"]["w_in"]
    assert w_in.shape[0] == 4
    assert w_in.addressable_shards[0].data.shape[0] == 1   # 1 expert/device
    assert "data" in w_in.sharding.spec


def test_train_py_moe_cp_rejections():
    import train as train_mod
    base = ["--batch-size", "16", "--seq-len", "16", "--opt", "adam"]
    with pytest.raises(SystemExit):   # PP still rejected with MoE
        train_mod.main(["--arch", "gpt_tiny", "--moe-experts", "4",
                        "--context-parallel", "2", "--pipeline-parallel",
                        "2", "--microbatches", "2"] + base)
    with pytest.raises(SystemExit):   # SP still rejected with MoE
        train_mod.main(["--arch", "bert_tiny", "--moe-experts", "8",
                        "--sequence-parallel"] + base)


def test_train_py_cli_moe_context_parallel(devices8):
    """CLI end to end: GPT EP x CP (zigzag) and BERT EP x CP with eval."""
    import train as train_mod
    base = ["--batch-size", "16", "--seq-len", "16", "--epochs", "1",
            "--steps-per-epoch", "2", "--opt", "adam", "--opt-level", "O0",
            "--print-freq", "1"]
    assert train_mod.main(
        ["--arch", "gpt_tiny", "--moe-experts", "4",
         "--context-parallel", "2", "--cp-mode", "zigzag"] + base) == 0
    assert train_mod.main(
        ["--arch", "bert_tiny", "--moe-experts", "4",
         "--context-parallel", "2", "--eval", "--eval-batches", "2"]
        + base) == 0


def test_moe_cp_tp_triple_matches_dense_ref_golden(devices8):
    """EP x CP x TP (round 5): expert all_to_all over manual 'data', KV
    ring over manual 'context', GSPMD TP over automatic 'model' — 10
    lockstep steps against the same EXACT dense-reference golden the
    EP x CP test uses (on its own (data=2, context=2) 4-device mesh,
    identical init and batches), expert stacks AND attention provably
    sharded."""
    from apex_example_tpu.engine import create_gspmd_train_state
    from apex_example_tpu.models.gpt import gpt_tiny
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    from apex_example_tpu.workloads import make_bert_moe_train_step

    gold_mesh = Mesh(np.asarray(devices8[:4]).reshape(2, 2),
                     ("data", "context"))
    mesh = Mesh(np.asarray(devices8).reshape(2, 2, 2),
                ("data", "context", "model"))
    policy, scaler = amp.initialize("O0")
    kw = dict(moe_experts=2, moe_axis_name="data")
    dense_init = gpt_tiny(**kw)
    gold_model = gpt_tiny(moe_experts=2, moe_axis_name="expert",
                          context_parallel=True, cp_mode="ring")
    triple = gpt_tiny(**kw, tensor_parallel=True, context_parallel=True,
                      cp_mode="ring")
    V = dense_init.vocab_size
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)

    sample = _lm_batch(0, V)[0][:1]
    state_g = create_train_state(jax.random.PRNGKey(0), dense_init, opt(),
                                 sample, policy, scaler)
    golden = _golden_moe_cp_step(gold_mesh, gold_model, opt(), policy,
                                 "ring")

    parallel_state.set_mesh(mesh)
    ops_config.set_force_xla(True)
    try:
        zopt = opt()
        state_e, gsh = create_gspmd_train_state(
            jax.random.PRNGKey(0), mesh,
            gpt_tiny(**kw, tensor_parallel=True), zopt, sample, policy,
            scaler)
        sh = bert_moe_state_shardings(mesh, state_e, zopt,
                                      base_shardings=gsh)
        # same starting point as the golden (identical param tree)
        state_e = jax.device_put(
            state_g.replace(opt_state=state_e.opt_state), sh)
        step_e = make_bert_moe_train_step(mesh, triple, zopt, policy,
                                          state_template=state_e,
                                          aux_weight=AUX_W, donate=False,
                                          objective="lm",
                                          context_parallel=True,
                                          mode="ring", state_shardings=sh)
        for i in range(10):
            batch = _lm_batch(i, V)
            state_g, m_g = golden(state_g, batch)
            state_e, m_e = step_e(state_e, batch)
            np.testing.assert_allclose(float(m_g["loss"]),
                                       float(m_e["loss"]),
                                       rtol=3e-5 * (1 + i / 3))
        for (ka, a), (kb, b2) in zip(
                jax.tree_util.tree_leaves_with_path(state_g.params),
                jax.tree_util.tree_leaves_with_path(state_e.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                       rtol=1e-3, atol=1e-5,
                                       err_msg=str(ka))
        w_in = state_e.params["layer_0"]["moe"]["w_in"]
        qk = state_e.params["layer_0"]["attention"]["query"]["kernel"]
        assert w_in.addressable_shards[0].data.shape[0] == \
            w_in.shape[0] // 2                       # experts over data
        assert qk.addressable_shards[0].data.shape[-1] == \
            qk.shape[-1] // 2                        # heads over model
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


def test_train_py_cli_moe_cp_tp(devices8):
    """The EP x CP x TP triple from the CLI."""
    import train as train_mod
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "gpt_tiny", "--moe-experts", "2",
            "--context-parallel", "2", "--tensor-parallel", "2",
            "--batch-size", "8", "--seq-len", "16", "--epochs", "1",
            "--steps-per-epoch", "2", "--opt", "adam", "--opt-level", "O0",
            "--print-freq", "1"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


# ---------------------------------------------------------------------------
# EP x PP (round 5): switch-MoE experts INSIDE the ring pipeline schedule —
# expert stacks shard [layers->pipe, experts->data], the per-(stage,
# microbatch) aux loss rides the schedule carry (spmd_pipeline with_aux).
# ---------------------------------------------------------------------------

def test_moe_pp_matches_blocked_dense_golden(devices8):
    """10 lockstep EP x PP steps on a (pipe=2, data=4) mesh == an
    INDEPENDENT blocked-dense golden (no schedule code shared): the dense
    MoE model applied per (data-shard, microbatch) row block — the
    per-device routing contract — with CE globally normalized and the aux
    term the mean over blocks of aux_total/L.  Independence matters: a
    bug in the schedule's aux normalization would cancel in a golden
    built from the same factory."""
    from apex_example_tpu.engine import TrainState, _wrap_optimizer
    from apex_example_tpu.models.gpt import gpt_tiny
    from apex_example_tpu.transformer.bert_pipeline import (
        bert_pp_state_shardings, make_bert_pp_train_step, pack_params,
        unpack_params)

    B, L, M, DP = 8, 16, 2, 4
    mesh = Mesh(np.asarray(devices8).reshape(2, DP), ("pipe", "data"))
    policy, scaler = amp.initialize("O0")
    ep_model = gpt_tiny(moe_experts=4, moe_axis_name="data")
    dense = gpt_tiny(moe_experts=4, moe_axis_name="expert")  # dense ref
    V = dense.vocab_size
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)

    def batch(i):
        return _lm_batch(i, V, batch=B, seq=L)

    state0 = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                batch(0)[0][:1], policy, scaler)

    # ---- independent golden: dense model per row block (B blocks of 1
    # row: data shard d owns rows [2d, 2d+1], microbatch m takes row m of
    # the shard => block index 2d+m runs row 2d+m).
    gopt = _wrap_optimizer(opt())

    def gold_loss(params, b):
        x, y = b
        num = jnp.zeros((), jnp.float32)
        aux_sum = jnp.zeros((), jnp.float32)
        for r in range(B):
            logits, aux = dense.apply({"params": params}, x[r:r + 1],
                                      train=True)
            ce = softmax_cross_entropy(logits, y[r:r + 1])
            num = num + ce.sum()
            aux_sum = aux_sum + aux           # model returns aux_total/L
        return num / (B * L) + AUX_W * aux_sum / B

    @jax.jit
    def gold_step(state, b):
        loss, grads = jax.value_and_grad(gold_loss)(state.params, b)
        new_p, new_o = gopt.apply(grads, state.opt_state, state.params)
        return TrainState(step=state.step + 1, params=new_p,
                          batch_stats=state.batch_stats, opt_state=new_o,
                          scaler=state.scaler), {"loss": loss}

    state_g = state0

    # ---- the EP x PP step under test
    eopt = opt()
    packed = pack_params(state0.params, dense.num_layers)
    state_e = TrainState(step=jnp.zeros((), jnp.int32), params=packed,
                         batch_stats={}, opt_state=eopt.init(packed),
                         scaler=state0.scaler)
    state_e = jax.device_put(
        state_e, bert_pp_state_shardings(mesh, state_e, eopt,
                                         model=ep_model))
    step_e = make_bert_pp_train_step(mesh, ep_model, eopt, policy,
                                     microbatches=M, donate=False,
                                     moe_aux_weight=AUX_W)

    for i in range(10):
        b = batch(i)
        state_g, m_g = gold_step(state_g, b)
        state_e, m_e = step_e(state_e, b)
        np.testing.assert_allclose(float(m_g["loss"]), float(m_e["loss"]),
                                   rtol=3e-5 * (1 + i / 3))
    un = unpack_params(state_e.params, dense.num_layers)
    key = lambda kv: str(kv[0])
    for (ka, a), (kb, b2) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(state_g.params),
                   key=key),
            sorted(jax.tree_util.tree_leaves_with_path(un), key=key)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=1e-3, atol=1e-5, err_msg=str(ka))
    # expert stacks jointly sharded [layers->pipe, experts->data]
    w_in = state_e.params["layers"]["moe"]["w_in"]
    assert w_in.addressable_shards[0].data.shape[0] == w_in.shape[0] // 2
    assert w_in.addressable_shards[0].data.shape[1] == w_in.shape[1] // DP


def test_train_py_cli_moe_pp(devices8):
    """EP x PP from the CLI (+ the rejection bounds)."""
    import train as train_mod
    from apex_example_tpu.transformer import parallel_state
    base = ["--batch-size", "8", "--seq-len", "16", "--epochs", "1",
            "--steps-per-epoch", "2", "--opt", "adam", "--opt-level", "O0",
            "--print-freq", "1"]
    try:
        assert train_mod.main(
            ["--arch", "gpt_tiny", "--moe-experts", "4",
             "--pipeline-parallel", "2", "--microbatches", "2"]
            + base) == 0
    finally:
        parallel_state.set_mesh(None)
    with pytest.raises(SystemExit):      # 1f1b has no aux channel
        train_mod.main(["--arch", "gpt_tiny", "--moe-experts", "4",
                        "--pipeline-parallel", "2", "--microbatches", "2",
                        "--pipeline-schedule", "1f1b"] + base)
    with pytest.raises(SystemExit):      # no MoE x PP x TP triple
        train_mod.main(["--arch", "gpt_tiny", "--moe-experts", "4",
                        "--pipeline-parallel", "2", "--microbatches", "2",
                        "--tensor-parallel", "2"] + base)
