"""Streaming SLO plane (obs/slo.py, schema v14; ISSUE 16):

- sketch correctness: the DDSketch-style log-bucket sketch's declared
  relative-error bound holds against exact numpy percentiles across
  magnitudes and alphas; merging is associative, commutative, and
  equals the pooled sketch bit-for-bit; the JSON-serialized form
  round-trips,
- spec parsing + burn-rate scoring edges (drained outside the
  denominator, missing spec'd latency counts bad, empty windows burn
  nothing, trailing partials included),
- SloTracker windows on a fake clock: tick and wall modes, breach
  emission, empty windows skipped, every emitted record schema-valid,
- Histogram.merge regression vs pooled ground truth (exact while the
  pooled trail fits the bound) and the LogBucketHistogram face,
- router SLO on no-jax FakeReplicas: windows/breaches on the stream,
  spec announced in the header, summary verdict PURE (two calls
  agree and match the emitted records), fleet_rollup sketch merges
  with conserved counts + straggler detection,
- chaos verdicts on the in-process thread fleet (the session's
  SLOTS=4/MAX_LEN=32 compiled program, zero new compiles): an
  unsatisfiable spec fails the scenario with the breached window
  identified, a lax spec passes, both bit-reproducible on double-run,
- ci_gate --slo-stream + slo_report + the telemetry_report SLO line
  over the checked-in recorded fixtures (tests/fixtures/slo/),
  tamper and torn-tail cases included.
"""

import importlib.util
import json
import math
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_example_tpu import obs
from apex_example_tpu.fleet import (FleetRouter, ThreadReplica,
                                    run_scenario, synthetic_specs)
from apex_example_tpu.models.gpt import gpt_tiny
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.obs import slo
from apex_example_tpu.serve import Request, ServeEngine

pytestmark = pytest.mark.slo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVE_FIXTURE = os.path.join(REPO, "tests", "fixtures", "slo",
                             "serve_slo.jsonl")
FLEET_FIXTURE = os.path.join(REPO, "tests", "fixtures", "slo",
                             "fleet_slo.jsonl")
SLOTS, MAX_LEN = 4, 32          # the session-shared decode geometry


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _exact_pct(sorted_vals, q):
    """Nearest-rank ground truth, same rank convention as the sketch
    (and tools/metrics_lint.pct): the ceil(q/100 * n)-th value."""
    rank = min(max(math.ceil(q / 100.0 * len(sorted_vals)), 1),
               len(sorted_vals))
    return float(sorted_vals[rank - 1])


# ======================================================= sketch math

def _samples():
    rng = np.random.default_rng(0)
    return np.concatenate([
        rng.lognormal(mean=0.0, sigma=2.0, size=400),   # spans decades
        rng.uniform(0.001, 5.0, size=200),
        rng.uniform(100.0, 1e6, size=200)])


def test_sketch_relative_error_bound_across_magnitudes_and_alphas():
    """The sketch's one promise: every percentile estimate within
    relative error alpha of the exact sample percentile — checked
    against numpy ground truth over samples spanning nine decades, at
    both the default and a coarse alpha."""
    vals = _samples()
    srt = np.sort(vals)
    for alpha in (slo.DEFAULT_ALPHA, 0.05):
        sk = slo.sketch_new(alpha)
        for v in vals:
            slo.sketch_add(sk, float(v))
        assert sk["count"] == len(vals)
        for q in (1, 10, 25, 50, 75, 90, 95, 99, 99.9):
            ex = _exact_pct(srt, q)
            est = slo.sketch_percentile(sk, q)
            # the bucket-midpoint estimate attains the bound at bucket
            # boundaries; the 1e-9 term is float slack only
            assert abs(est - ex) <= alpha * ex + 1e-9 * ex, (alpha, q)
    # min/max are tracked exactly, not bucket-estimated
    assert sk["min"] == float(srt[0]) and sk["max"] == float(srt[-1])


def test_sketch_merge_equals_pooled_and_is_assoc_commutative():
    vals = _samples()
    a, b, c = np.array_split(vals, 3)

    def fold(part):
        sk = slo.sketch_new()
        for v in part:
            slo.sketch_add(sk, float(v))
        return sk

    sa, sb, sc = fold(a), fold(b), fold(c)
    pooled = fold(vals)
    left = slo.sketch_merge(slo.sketch_merge(sa, sb), sc)
    right = slo.sketch_merge(sa, slo.sketch_merge(sb, sc))
    assert left == right == pooled              # associative, == pooled
    assert slo.sketch_merge(sa, sb) == slo.sketch_merge(sb, sa)
    # merge is out-of-place: the inputs are untouched
    assert sa["count"] == len(a) and sb["count"] == len(b)
    # alphas must match — silently inheriting the looser bound is the
    # failure mode this guards
    with pytest.raises(ValueError, match="alpha"):
        slo.sketch_merge(sa, slo.sketch_new(0.05))


def test_sketch_serde_roundtrip_is_lossless():
    sk = slo.sketch_new()
    for v in (0.5, 3.0, 3.0, 250.0, 9e5):
        slo.sketch_add(sk, v)
    back = json.loads(json.dumps(sk))
    assert back == sk                       # JSON-native: keys already str
    for q in (50, 90, 99):
        assert slo.sketch_percentile(back, q) == \
            slo.sketch_percentile(sk, q)
    # and a deserialized sketch merges like a live one
    merged = slo.sketch_merge(back, sk)
    assert merged["count"] == 2 * sk["count"]


def test_sketch_edge_cases():
    sk = slo.sketch_new()
    assert slo.sketch_percentile(sk, 50) == 0.0     # empty -> 0.0
    assert slo.sketch_summary(sk)["count"] == 0
    slo.sketch_add(sk, 42.0)
    for q in (0, 50, 100):                          # one sample: all ranks
        assert abs(slo.sketch_percentile(sk, q) - 42.0) \
            <= slo.DEFAULT_ALPHA * 42.0
    # zeros and negatives share the zero bucket, estimated 0.0
    zk = slo.sketch_new()
    slo.sketch_add(zk, 0.0)
    slo.sketch_add(zk, -3.0)
    slo.sketch_add(zk, 10.0)
    assert zk["zero"] == 2 and zk["min"] == -3.0
    assert slo.sketch_percentile(zk, 50) == 0.0
    assert slo.sketch_percentile(zk, 99) > 0.0
    with pytest.raises(ValueError, match="alpha"):
        slo.sketch_new(1.0)
    # counted adds (n>1) weight the bucket, not just the value
    nk = slo.sketch_new()
    slo.sketch_add(nk, 5.0, n=10)
    assert nk["count"] == 10


# ================================================= spec + burn scoring

def test_parse_slo_specs_and_errors():
    spec = slo.parse_slo("ttft_ms=500,tpot_ms=50,availability=0.99")
    assert spec == {"ttft_ms": 500.0, "tpot_ms": 50.0,
                    "availability": 0.99}
    # availability defaults to three nines; single-target specs are fine
    assert slo.parse_slo("tpot_ms=40") == {
        "ttft_ms": None, "tpot_ms": 40.0,
        "availability": slo.DEFAULT_AVAILABILITY}
    for bad in ("", "ttft_ms", "p50=3", "ttft_ms=abc",
                "ttft_ms=500,ttft_ms=300", "ttft_ms=0",
                "availability=0.9",             # no latency target
                "ttft_ms=5,availability=1.0",   # zero error budget
                "ttft_ms=5,availability=0"):
        with pytest.raises(ValueError):
            slo.parse_slo(bad)


def test_score_event_and_burn_rate():
    spec = slo.parse_slo("ttft_ms=100,tpot_ms=10")
    assert slo.score_event(spec, "ok", ttft_ms=50.0, tpot_ms=5.0) is True
    assert slo.score_event(spec, "ok", ttft_ms=150.0, tpot_ms=5.0) is False
    assert slo.score_event(spec, "ok", ttft_ms=50.0, tpot_ms=15.0) is False
    # an ok completion MISSING a spec'd latency is bad, not good — an
    # unmeasured target is not a met one
    assert slo.score_event(spec, "ok", ttft_ms=None, tpot_ms=5.0) is False
    assert slo.score_event(spec, "failed") is False
    assert slo.score_event(spec, "timeout") is False
    # drained leaves the denominator (requeued elsewhere)
    assert slo.score_event(spec, "drained") is None
    # a spec with no ttft target doesn't judge ttft
    tp_only = slo.parse_slo("tpot_ms=10")
    assert slo.score_event(tp_only, "ok", ttft_ms=None,
                           tpot_ms=5.0) is True

    assert slo.burn_rate(0, 0, 0.999) == 0.0        # empty burns nothing
    assert slo.burn_rate(99, 1, 0.99) == pytest.approx(1.0)
    assert slo.burn_rate(98, 2, 0.99) == pytest.approx(2.0)
    assert slo.burn_rate(10, 0, 0.99) == 0.0


def test_score_windows_and_worst_window():
    scored = [True] * 4 + [False] * 4 + [True, None, True]
    wins = slo.score_windows(scored, 4, availability=0.9)
    assert [w["requests"] for w in wins] == [4, 4, 3]   # trailing partial
    assert [w["good"] for w in wins] == [4, 0, 2]
    assert [w["bad"] for w in wins] == [0, 4, 0]        # None not counted
    assert wins[1]["burn_rate"] == pytest.approx(10.0)
    idx, burn = slo.worst_window(wins)
    assert idx == 1 and burn == pytest.approx(10.0)
    assert slo.worst_window([]) == (None, 0.0)
    # ties go to the FIRST window (stable across re-scoring)
    tie = slo.score_windows([False, False], 1, availability=0.9)
    assert slo.worst_window(tie)[0] == 0


# ============================================================ tracker

class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def test_tracker_tick_windows_breach_and_schema():
    clock, emitted = FakeClock(), []
    tr = slo.SloTracker({"ttft_ms": 100.0, "availability": 0.9},
                        window_ticks=2, emit=emitted.append,
                        run_id="t1", clock=clock)
    tr.observe_request("ok", ttft_ms=50.0, tpot_ms=5.0,
                       queue_wait_ms=1.0)
    tr.observe_request("ok", ttft_ms=500.0, tpot_ms=5.0)   # over target
    tr.observe_tick(live_slots=2, num_slots=4)
    assert emitted == []                    # window closes at tick 2
    tr.observe_tick(live_slots=4, num_slots=4, blocks_live=3,
                    kv_bytes_live=4096)
    kinds = [r["record"] for r in emitted]
    assert kinds == ["slo_window", "slo_breach"]
    w, b = emitted
    assert w["window"] == 0 and w["requests"] == 2
    assert w["good"] == 1 and w["bad"] == 1
    assert w["burn_rate"] == pytest.approx(5.0)     # 0.5 bad / 0.1 budget
    assert w["counts"] == {"ok": 2}
    assert w["ticks"] == 2 and w["occupancy"] == pytest.approx(0.75)
    assert w["blocks_live"] == 3 and w["kv_bytes_live"] == 4096
    assert w["ttft_ms"]["count"] == 2 and w["queue_wait_ms"]["count"] == 1
    assert b["window"] == 0 and b["burn_rate"] == w["burn_rate"]
    assert b["budget"] == pytest.approx(0.1)
    for rec in emitted:                     # every emission schema-valid
        assert obs_schema.validate_record(rec) == [], rec
    # empty windows are skipped, not emitted
    tr.observe_tick()
    tr.observe_tick()
    assert len(emitted) == 2
    # flush closes the trailing partial exactly once (idempotent)
    tr.observe_request("drained")           # outside the denominator
    tr.flush()
    tr.flush()
    assert [r["record"] for r in emitted] == \
        ["slo_window", "slo_breach", "slo_window"]
    assert emitted[-1]["requests"] == 1 and emitted[-1]["bad"] == 0
    assert emitted[-1]["burn_rate"] == 0.0
    s = tr.summary()
    assert s["verdict"] == "fail" and s["breaches"] == 1
    assert s["windows"] == 2                # matches the emitted records
    assert s["worst_window"] == 0 and s["worst_burn"] == \
        pytest.approx(5.0)
    assert s["good"] == 1 and s["bad"] == 1
    assert obs_schema.validate_record(
        {"record": "serve_summary", "time": 0.0, "requests": 2,
         "output_tokens": 4, "tokens_per_sec": 1.0, "slo": s}) == []


def test_tracker_wall_windows_roll_on_the_clock():
    clock, emitted = FakeClock(), []
    tr = slo.SloTracker("ttft_ms=100", window_s=1.0,
                        emit=emitted.append, clock=clock)
    tr.observe_request("ok", ttft_ms=10.0)
    assert emitted == []                    # deadline not reached
    clock.t += 1.5
    tr.observe_tick()                       # ticks roll wall windows too
    assert len(emitted) == 1 and emitted[0]["requests"] == 1
    tr.observe_request("ok", ttft_ms=20.0)
    clock.t += 1.5
    tr.observe_request("ok", ttft_ms=30.0)  # folds, THEN rolls: both land
    assert len(emitted) == 2 and emitted[1]["requests"] == 2


# ============================================ metrics faces (satellite)

def test_histogram_merge_matches_pooled_ground_truth():
    a, b, pooled = (obs.Histogram("t") for _ in range(3))
    # integer-valued floats: sums stay exact regardless of fold order,
    # so merged-vs-pooled equality is bitwise, not approximate
    rng = np.random.default_rng(1)
    xs = [float(v) for v in rng.integers(1, 1000, 90)]
    ys = [float(v) for v in rng.integers(500, 5000, 60)]
    for v in xs:
        a.observe(v)
        pooled.observe(v)
    for v in ys:
        b.observe(v)
        pooled.observe(v)
    a.merge(b)
    # while the pooled trail fits max_samples the merge is EXACT: the
    # ground truth fleet_report re-pools raw trails for
    assert a.count == pooled.count == 150
    assert a.sum == pooled.sum
    assert a.min == pooled.min and a.max == pooled.max
    for q in (50, 90, 95, 99):
        assert a.percentile(q) == pooled.percentile(q)
    assert a.summary() == pooled.summary()
    # merging an empty histogram is the identity
    before = a.summary()
    a.merge(obs.Histogram("empty"))
    assert a.summary() == before
    # past the bound the subsample keeps count/sum/min/max exact
    small = obs.Histogram("s", max_samples=16)
    other = obs.Histogram("s", max_samples=16)
    for v in xs:
        small.observe(v)
    for v in ys:
        other.observe(v)
    small.merge(other)
    assert small.count == 150 and small.sum == pooled.sum
    assert len(small._samples) == 16
    assert small.min == pooled.min and small.max == pooled.max


def test_log_bucket_histogram_face_and_serde():
    h = obs.LogBucketHistogram("ttft_ms")
    vals = [3.0, 7.0, 7.0, 120.0, 4000.0]
    for v in vals:
        h.observe(v)
    assert h.count == 5 and h.alpha == slo.DEFAULT_ALPHA
    srt = sorted(vals)
    for q in (50, 99):
        ex = _exact_pct(srt, q)
        assert abs(h.percentile(q) - ex) <= h.alpha * ex
    assert h.summary()["count"] == 5
    # serde round-trips through the SAME dict form replica heartbeats
    # carry, and a serialized dict merges directly
    d = h.to_dict()
    assert obs.LogBucketHistogram.from_dict(d).summary() == h.summary()
    h2 = obs.LogBucketHistogram("ttft_ms")
    h2.observe(9.0)
    h2.merge(d)
    assert h2.count == 6
    with pytest.raises(ValueError, match="alpha"):
        h2.merge(obs.LogBucketHistogram("other", alpha=0.05))


# ================================================ router SLO (no jax)

class FakeReplica:
    """The replica contract, scripted (the test_fleet pattern): no
    engine, no thread, no jax — sub-second router tests."""

    def __init__(self, name, pending=0):
        self.name = name
        self.specs = []
        self.events = []
        self._state = {"state": "healthy", "pending": pending,
                       "blocks_live": 0, "progress_age_s": 0.0,
                       "pid": None, "restarts": 0}

    def submit(self, spec):
        self.specs.append(spec)
        return True

    def poll(self):
        out, self.events = self.events, []
        return out

    def state(self):
        return dict(self._state, name=self.name)

    def set_state(self, **kw):
        self._state.update(kw)

    def report(self, uid, status, **kw):
        self.events.append(dict({"uid": uid, "status": status,
                                 "replica": self.name}, **kw))

    def start(self):
        return self

    def stop(self, *a, **k):
        pass


class ListSink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)

    def close(self):
        pass


def _spec(uid):
    return {"uid": uid, "prompt": [1, 2, 3], "max_new_tokens": 4}


def test_router_slo_windows_breaches_and_pure_summary():
    reps = [FakeReplica("a"), FakeReplica("b")]
    sink = ListSink()
    router = FleetRouter(reps, policy="round_robin", sink=sink, log=None,
                         slo={"ttft_ms": 100.0, "availability": 0.9},
                         slo_window=4)
    header = sink.records[0]
    assert header["record"] == "run_header"
    assert header["config"]["slo"]["ttft_ms"] == 100.0
    assert header["config"]["slo_window"] == 4
    for i in range(8):
        router.submit(_spec(f"u{i}"))
    # each replica holds 4 uids; 2 fast + 2 slow each -> every window
    # (events absorb replica-by-replica) is 2 good / 2 bad
    for rep in reps:
        for j, s in enumerate(rep.specs):
            ttft = 50.0 if j < 2 else 500.0
            rep.report(s["uid"], "ok", tokens=[7], ttft_ms=ttft,
                       tpot_ms=5.0)
    router.poll()
    assert router.done()
    # summary is PURE: two calls agree bit-for-bit
    s1 = router.summary_record()
    s2 = router.summary_record()
    slo_keys = ("slo_verdict", "slo_windows", "slo_breaches",
                "slo_worst_burn", "slo_worst_window")
    assert {k: s1.get(k) for k in slo_keys} == \
        {k: s2.get(k) for k in slo_keys}
    summary = router.close()
    windows = [r for r in sink.records if r["record"] == "slo_window"]
    breaches = [r for r in sink.records if r["record"] == "slo_breach"]
    assert len(windows) == 2 and len(breaches) == 2
    for w in windows:
        assert w["requests"] == 4 and w["good"] == 2 and w["bad"] == 2
        assert w["burn_rate"] == pytest.approx(5.0)    # 0.5 / 0.1
        assert w["ttft_ms"]["count"] == 4
    assert summary["slo_verdict"] == "fail"
    assert summary["slo_windows"] == 2
    assert summary["slo_breaches"] == 2
    assert summary["slo_worst_burn"] == pytest.approx(5.0)
    assert summary["slo_worst_window"] == 0            # first on ties
    assert obs_schema.validate_stream(sink.records) == []


def test_router_slo_unarmed_stream_is_byte_identical_to_v13_shape():
    """No --slo: no slo_* summary fields, no slo_window records, no
    spec in the header — the plane is pay-for-what-you-arm."""
    reps = [FakeReplica("a")]
    sink = ListSink()
    router = FleetRouter(reps, sink=sink, log=None)
    router.submit(_spec("u0"))
    reps[0].report("u0", "ok", tokens=[1], ttft_ms=10.0, tpot_ms=1.0)
    router.poll()
    summary = router.close()
    assert "slo" not in sink.records[0]["config"]
    assert not any(r["record"].startswith("slo_")
                   or r["record"] == "fleet_rollup"
                   for r in sink.records)
    assert not any(k.startswith("slo_") for k in summary)


def test_router_fleet_rollup_merges_sketches_and_names_straggler():
    mod = slo                       # same math the router path-loads
    fast = mod.sketch_new()
    for _ in range(20):
        mod.sketch_add(fast, 10.0)
    slow = mod.sketch_new()
    for _ in range(10):
        mod.sketch_add(slow, 100.0)
    reps = [FakeReplica("r0"), FakeReplica("r1"), FakeReplica("r2")]
    reps[0].set_state(slo_sketch={"ttft_ms": fast,
                                  "tpot_ms": mod.sketch_new()})
    reps[1].set_state(slo_sketch={"ttft_ms": json.loads(
        json.dumps(fast)), "tpot_ms": mod.sketch_new()})
    reps[2].set_state(slo_sketch={"ttft_ms": slow,
                                  "tpot_ms": mod.sketch_new()})
    sink = ListSink()
    router = FleetRouter(reps, sink=sink, log=None,
                         slo={"ttft_ms": 100.0}, slo_rollup_s=0.0)
    router.poll()
    rollups = [r for r in sink.records if r["record"] == "fleet_rollup"]
    assert rollups
    r = rollups[-1]
    assert r["replicas"] == 3 and r["count"] == 50
    # count conservation — what ci_gate --slo-stream re-checks
    assert r["count"] == sum(v["count"]
                             for v in r["per_replica"].values())
    assert r["ttft_ms"]["count"] == 50
    # merged p50 is the fast cohort's (40 of 50 samples at ~10ms)
    assert abs(r["ttft_ms"]["p50"] - 10.0) <= mod.DEFAULT_ALPHA * 10.0
    # r2's p50 is ~10x the fleet median: named straggler
    assert r["straggler"] == "r2"
    assert r["skew"] == pytest.approx(10.0, rel=0.05)
    assert obs_schema.validate_record(r) == []
    router.close()


def test_router_rollup_skips_unmergeable_alpha_but_conserves_counts():
    coarse = slo.sketch_new(0.05)
    slo.sketch_add(coarse, 10.0)
    fine = slo.sketch_new()
    for _ in range(5):
        slo.sketch_add(fine, 20.0)
    reps = [FakeReplica("fine"), FakeReplica("coarse")]
    reps[0].set_state(slo_sketch={"ttft_ms": fine})
    reps[1].set_state(slo_sketch={"ttft_ms": coarse})
    sink = ListSink()
    router = FleetRouter(reps, sink=sink, log=None,
                         slo={"ttft_ms": 100.0}, slo_rollup_s=0.0)
    router.poll()
    r = [x for x in sink.records if x["record"] == "fleet_rollup"][-1]
    # the mismatched-alpha sketch is skipped, not silently merged into
    # a looser bound — and the record's count stays conserved
    assert r["replicas"] == 1 and r["count"] == 5
    assert r["count"] == sum(v["count"]
                             for v in r["per_replica"].values())
    router.close()


# =========================== chaos verdicts (shared compiled program)

@pytest.fixture(scope="module")
def model_and_params():
    model = gpt_tiny()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _slo_fleet_once(model, params, specs, slo_spec):
    def factory():
        return ServeEngine(model, params, num_slots=SLOTS,
                           max_len=MAX_LEN,
                           rng=jax.random.PRNGKey(0))

    def make_request(spec):
        return Request(prompt=spec["prompt"],
                       max_new_tokens=int(spec["max_new_tokens"]),
                       uid=spec["uid"])

    replicas = [ThreadReplica(f"r{i}", factory, make_request)
                for i in range(2)]
    router = FleetRouter(replicas, log=None, slo=slo_spec, slo_window=4)
    summary = run_scenario("none", router, replicas, specs,
                           timeout_s=90)
    for r in replicas:
        r.stop(timeout_s=2.0)
    return {k: summary.get(k) for k in
            ("verdict", "completed", "lost", "slo_verdict",
             "slo_windows", "slo_breaches", "slo_worst_burn",
             "slo_worst_window")}


def test_chaos_slo_verdicts_deterministic(model_and_params):
    """Satellite 2 acceptance: an unsatisfiable SLO fails the scenario
    (every request served fine — the SLO is what failed) with the
    breached window identified; a lax SLO passes; both score dicts are
    bit-reproducible on a double run.  All-good/all-bad specs make the
    windows order-independent, so thread scheduling cannot perturb the
    score."""
    model, params = model_and_params
    specs = synthetic_specs(10, vocab_size=model.vocab_size, seed=4,
                            prompt_len=(3, 6), max_new=(3, 8))
    tight = {"ttft_ms": 1e-4, "availability": 0.99}    # unsatisfiable
    first = _slo_fleet_once(model, params, specs, tight)
    assert first["completed"] == 10 and first["lost"] == 0
    assert first["verdict"] == "fail"          # the scenario folds it in
    assert first["slo_verdict"] == "fail"
    assert first["slo_windows"] == 3           # ceil(10 / 4)
    assert first["slo_breaches"] == 3          # every window all-bad
    assert first["slo_worst_window"] == 0      # first on ties
    assert first["slo_worst_burn"] == pytest.approx(100.0, rel=1e-9)
    second = _slo_fleet_once(model, params, specs, tight)
    assert second == first                     # deterministic verdict
    lax = {"ttft_ms": 1e9, "tpot_ms": 1e9, "availability": 0.5}
    ok_first = _slo_fleet_once(model, params, specs, lax)
    assert ok_first["verdict"] == "pass"
    assert ok_first["slo_verdict"] == "pass"
    assert ok_first["slo_breaches"] == 0
    assert ok_first["slo_worst_burn"] == 0.0
    ok_second = _slo_fleet_once(model, params, specs, lax)
    assert ok_second == ok_first


# ============================== gates + reports over recorded fixtures

def _fixture_records(path):
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


def test_slo_fixtures_validate_and_announce_the_spec():
    for path in (SERVE_FIXTURE, FLEET_FIXTURE):
        records = _fixture_records(path)
        assert obs_schema.validate_stream(records) == [], path
        header = records[0]
        assert header["record"] == "run_header"
        assert header["config"].get("slo"), path
        assert any(r["record"] == "slo_window" for r in records), path
    # the fleet fixture also recorded at least one sketch rollup
    assert any(r["record"] == "fleet_rollup"
               for r in _fixture_records(FLEET_FIXTURE))


def test_ci_gate_slo_stream_passes_on_fixtures(capsys):
    ci_gate = _load_tool("ci_gate")
    assert ci_gate.main(["--slo-stream", SERVE_FIXTURE,
                         "--slo-stream", FLEET_FIXTURE]) == 0
    out = capsys.readouterr().out
    assert f"ci_gate: slo gate {SERVE_FIXTURE}: PASS" in out
    assert f"ci_gate: slo gate {FLEET_FIXTURE}: PASS" in out
    assert ci_gate.main(
        ["--slo-stream", SERVE_FIXTURE + ".missing"]) == 2


def test_ci_gate_slo_stream_fails_on_tamper(tmp_path, capsys):
    """The gate actually checks something: a summary claiming fewer
    breaches than the stream carries, and a breach record whose window
    disagrees, both fail."""
    ci_gate = _load_tool("ci_gate")
    records = _fixture_records(SERVE_FIXTURE)

    def rewrite(mutate):
        out = []
        for rec in records:
            rec = dict(rec)
            mutate(rec)
            out.append(rec)
        p = tmp_path / "tampered.jsonl"
        p.write_text("".join(json.dumps(r) + "\n" for r in out))
        return str(p)

    def hide_breaches(rec):
        if rec["record"] == "serve_summary":
            rec["slo"] = dict(rec["slo"], breaches=0, verdict="pass")

    def tear_burn(rec):
        if rec["record"] == "slo_breach":
            rec["burn_rate"] = 0.5      # contradicts its window record

    assert ci_gate.main(["--slo-stream", rewrite(hide_breaches)]) == 1
    assert "breach" in capsys.readouterr().err
    assert ci_gate.main(["--slo-stream", rewrite(tear_burn)]) == 1
    # and a sketch that lies about its percentiles is caught by the
    # sketch-vs-exact honesty bound

    def inflate_p99(rec):
        if rec["record"] == "serve_summary":
            tt = dict(rec["slo"]["ttft_ms"])
            tt["p99"] = tt["p99"] * 10 + 100.0
            rec["slo"] = dict(rec["slo"], ttft_ms=tt)

    assert ci_gate.main(["--slo-stream", rewrite(inflate_p99)]) == 1
    assert "relative-error" in capsys.readouterr().err


def test_slo_report_renders_breaches_and_verdicts(capsys):
    slo_report = _load_tool("slo_report")
    # the serve fixture was recorded with a tight spec: its compile-
    # slow first window breached, so the report fails it
    assert slo_report.main([SERVE_FIXTURE]) == 1
    out = capsys.readouterr().out
    assert "slo spec:" in out and "burn trajectory:" in out
    assert "BREACH" in out and "verdict: FAIL" in out
    # the fleet fixture's lax spec passes, rollups rendered
    assert slo_report.main([FLEET_FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "verdict: PASS" in out and "fleet rollups:" in out


def test_slo_report_torn_tail_is_not_read_as_healthy(tmp_path, capsys):
    """A stream killed right after a breaching window (no summary, no
    breach record yet) must FAIL the report — satellite 4's 'breach-
    ending streams not misread as healthy'."""
    records = _fixture_records(SERVE_FIXTURE)
    breached = next(r for r in records if r["record"] == "slo_window"
                    and r["burn_rate"] > 1.0)
    torn = [r for r in records
            if r["record"] in ("run_header", "request_complete")
            or (r["record"] == "slo_window"
                and r["window"] <= breached["window"])]
    p = tmp_path / "torn.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in torn))
    slo_report = _load_tool("slo_report")
    assert slo_report.main([str(p)]) == 1
    out = capsys.readouterr().out
    assert "NO SUMMARY" in out
    assert "BREACH" in out
    # no SLO content at all is unusable input, not a pass
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps(
        {"record": "run_header", "schema": 14, "time": 0.0,
         "run_id": "x", "num_devices": 1, "process_index": 0,
         "platform": "cpu", "config": {}}) + "\n")
    assert slo_report.main([str(empty)]) == 2


def test_telemetry_report_slo_line(tmp_path, capsys):
    telemetry_report = _load_tool("telemetry_report")
    assert telemetry_report.report(SERVE_FIXTURE) == 0
    out = capsys.readouterr().out
    assert "SLO:" in out and "breach(es)" in out
    # a breach-ending truncated stream says BREACHED, not healthy
    records = _fixture_records(SERVE_FIXTURE)
    breached = next(r for r in records if r["record"] == "slo_window"
                    and r["burn_rate"] > 1.0)
    torn = [r for r in records
            if r["record"] == "run_header"
            or (r["record"] == "slo_window"
                and r["window"] <= breached["window"])]
    p = tmp_path / "torn.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in torn))
    telemetry_report.report(str(p))
    out = capsys.readouterr().out
    assert "BREACHED" in out
