"""The trace-event stratum (obs/trace.py, tools/trace_export.py;
ISSUE 11):

- Tracer mechanics: lazy one-per-stream clock_sync, B/E/X/i emission,
  unique span ids, ph validation,
- schema v9: trace_event / clock_sync validate, malformed rejected,
  v1-v8 streams still validate unchanged,
- obs.span -> trace_event wiring (armed: X events with parent nesting;
  unarmed: stream untouched),
- trace_export: wall-clock merge of multi-process streams (clock_sync
  anchoring), Chrome metadata rows, admission flows, the xprof overlay,
  and the --check structural lint (balanced B/E, monotonic rows,
  orphans, containment, clock_sync count) wired through ci_gate,
- serve_report's per-request critical-path decomposition (components
  sum to e2e),
- supervisor-side continuity units: APEX_TRACE_ID env handoff to
  children, attempt/restart trace events gated on a --trace child.

Everything here is host-side: no model, no compile — the jax imports
are the obs package's own.  The serving/e2e acceptance rides
tests/test_serve.py (traced smoke) and tests/test_resilience.py
(cross-restart continuity).
"""

import gzip
import importlib.util
import json
import os
import sys

import pytest

from apex_example_tpu import obs
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.obs import trace as trace_lib

pytestmark = pytest.mark.trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class ListSink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


# ------------------------------------------------------ Tracer core

def test_tracer_clock_sync_lazy_and_events_validate():
    sink = ListSink()
    tr = trace_lib.Tracer(sink, trace_id="t1", run_id="r1")
    assert sink.records == []            # armed but silent until traced
    sid = tr.begin("tick", tid="engine", args={"tick": 0})
    tr.complete("admit", 1.0, 0.5, tid="engine", parent_id=sid)
    tr.instant("mark", tid="engine", parent_id=sid)
    tr.end("tick", tid="engine")
    assert [r["record"] for r in sink.records] == \
        ["clock_sync", "trace_event", "trace_event", "trace_event",
         "trace_event"]
    sync = sink.records[0]
    assert sync["trace_id"] == "t1" and sync["run_id"] == "r1"
    # one sync per stream, ever
    tr.instant("again")
    assert sum(1 for r in sink.records
               if r["record"] == "clock_sync") == 1
    for rec in sink.records:
        assert obs_schema.validate_record(rec) == [], rec
    assert tr.events == 5
    x = sink.records[2]
    assert x["ph"] == "X" and x["ts"] == 1.0 and x["dur"] == 0.5
    assert x["parent_id"] == sid
    with pytest.raises(ValueError, match="ph"):
        tr.event("Q", "bogus")
    # span ids never collide
    ids = {tr.next_id() for _ in range(100)}
    assert len(ids) == 100


def test_tracer_trace_id_from_env(monkeypatch):
    monkeypatch.setenv(trace_lib.TRACE_ID_ENV, "from-parent")
    tr = trace_lib.Tracer(ListSink())
    assert tr.trace_id == "from-parent"
    monkeypatch.delenv(trace_lib.TRACE_ID_ENV)
    assert trace_lib.Tracer(ListSink()).trace_id != "from-parent"


# ------------------------------------------------------- schema v9

def test_schema_v9_trace_records_validate():
    # the CURRENT version is pinned exactly in test_fleet (v10); here
    # only that the trace stratum's tables are still in force
    assert obs_schema.SCHEMA_VERSION >= 9
    ev = {"record": "trace_event", "ph": "X", "name": "request",
          "ts": 1.25, "dur": 0.5, "cat": "request", "tid": "req/r-1",
          "span_id": "s1", "parent_id": "s0", "trace_id": "t",
          "args": {"slot": 1}, "run_id": "r"}
    sync = {"record": "clock_sync", "time": 1e9, "ts": 12.5,
            "trace_id": "t", "run_id": "r"}
    assert obs.validate_record(ev) == []
    assert obs.validate_record(sync) == []
    # malformed still rejected: unknown field, missing required, typed
    assert obs.validate_record(dict(ev, typo=1))
    assert obs.validate_record({"record": "trace_event", "ph": "B"})
    assert obs.validate_record(dict(sync, ts="12"))


def test_schema_v1_v8_streams_still_validate():
    header = {"record": "run_header", "schema": 1, "time": 0.0,
              "run_id": "r", "num_devices": 1, "process_index": 0,
              "platform": "cpu", "config": {}}
    step = {"record": "step", "step": 1, "epoch": 0, "loss": 1.0,
            "scale": 1.0, "step_time_ms": 5.0, "items_per_sec": 10.0}
    v1 = [header, step,
          {"record": "run_summary", "steps": 1, "overflow_count": 0}]
    v5 = [dict(header, schema=5),
          {"record": "request_failed", "time": 1.0, "request_id": "r-1",
           "status": "timeout"},
          {"record": "serve_summary", "time": 2.0, "requests": 1,
           "output_tokens": 2, "tokens_per_sec": 5.0}]
    v8 = [dict(header, schema=8), step,
          {"record": "compile_event", "time": 1.0, "name": "f",
           "compile_ms": 5.0, "n_compiles": 2,
           "recompile_cause": "first divergent op: convert"},
          {"record": "run_summary", "steps": 1, "overflow_count": 0}]
    for stream in (v1, v5, v8):
        assert obs_schema.validate_stream(stream) == []


# -------------------------------------------------- span() wiring

def test_span_emits_trace_events_only_when_armed():
    sink = ListSink()
    with obs.span("outer"):
        with obs.span("inner"):
            pass
    assert sink.records == []            # unarmed: nothing anywhere
    trace_lib.set_default(trace_lib.Tracer(sink, trace_id="t"))
    try:
        with obs.span("outer") as outer:
            with obs.span("inner"):
                pass
    finally:
        trace_lib.set_default(None)
    evs = [r for r in sink.records if r["record"] == "trace_event"]
    assert [e["name"] for e in evs] == ["inner", "outer"]  # exit order
    inner, outer_ev = evs
    assert inner["parent_id"] == outer_ev["span_id"] == outer.span_id
    assert inner["ph"] == outer_ev["ph"] == "X"
    assert inner["cat"] == "span"
    # containment: the child window sits inside the parent's
    assert inner["ts"] >= outer_ev["ts"]
    assert inner["ts"] + inner["dur"] <= outer_ev["ts"] \
        + outer_ev["dur"] + 1e-6


# ----------------------------------------------------- trace_export

def _stream(path, events, sync_wall, sync_perf, header=True,
            trace_id="t"):
    """Write a synthetic traced stream: run_header, clock_sync, events."""
    recs = []
    if header:
        recs.append({"record": "run_header", "schema": 9, "time": 0.0,
                     "run_id": "r", "num_devices": 1, "process_index": 0,
                     "platform": "cpu", "config": {}, "arch": "gpt_tiny"})
    recs.append({"record": "clock_sync", "time": sync_wall,
                 "ts": sync_perf, "trace_id": trace_id})
    recs.extend(dict(e, record="trace_event", trace_id=trace_id)
                for e in events)
    with open(path, "w") as fh:
        for r in recs:
            fh.write(json.dumps(r) + "\n")
    return path


def test_trace_export_merges_streams_on_one_wall_axis(tmp_path):
    """Two streams with unrelated perf_counter origins but overlapping
    wall-clock windows land on one axis via their clock_sync anchors;
    request spans get admission flows onto the engine row."""
    export = _load_tool("trace_export")
    # stream A: perf origin ~100, wall 1000; "engine" + one request
    a = _stream(str(tmp_path / "a.jsonl"), [
        {"ph": "B", "name": "tick", "ts": 100.0, "tid": "engine",
         "span_id": "s1", "cat": "tick"},
        {"ph": "E", "name": "tick", "ts": 100.5, "tid": "engine"},
        {"ph": "X", "name": "request", "ts": 100.0, "dur": 0.4,
         "tid": "req/r-0", "span_id": "s2", "cat": "request",
         "args": {"request_id": "r-0", "status": "ok", "slot": 1}},
        {"ph": "X", "name": "queued", "ts": 100.0, "dur": 0.1,
         "tid": "req/r-0", "span_id": "s3", "parent_id": "s2",
         "cat": "request"},
        # a SHED request: root without a slot (never admitted) — its
        # queued span ends at the terminal time and must NOT grow an
        # admission flow arrow (review regression)
        {"ph": "X", "name": "request", "ts": 100.0, "dur": 0.2,
         "tid": "req/r-1", "span_id": "s4", "cat": "request",
         "args": {"request_id": "r-1", "status": "shed"}},
        {"ph": "X", "name": "queued", "ts": 100.0, "dur": 0.2,
         "tid": "req/r-1", "span_id": "s5", "parent_id": "s4",
         "cat": "request"},
    ], sync_wall=1000.0, sync_perf=100.0)
    # stream B: perf origin ~5000, wall 1000.2 (starts 0.2s later)
    b = _stream(str(tmp_path / "b.jsonl"), [
        {"ph": "i", "name": "restart", "ts": 5000.0,
         "tid": "supervisor"},
    ], sync_wall=1000.2, sync_perf=5000.0)
    out = str(tmp_path / "trace.json")
    assert export.main([a, b, "-o", out]) == 0
    doc = json.loads(open(out).read())          # valid JSON by contract
    evs = doc["traceEvents"]
    # per-stream process rows with name metadata
    names = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert any("a.jsonl" in n for n in names)
    assert any("b.jsonl" in n for n in names)
    # wall alignment: stream A starts at t=0us, B's instant at +200ms
    tick_b = next(e for e in evs if e["name"] == "tick"
                  and e["ph"] == "B")
    restart = next(e for e in evs if e["name"] == "restart")
    assert tick_b["ts"] == 0.0
    assert abs(restart["ts"] - 200000.0) < 1.0
    # X spans export microsecond durations
    req = next(e for e in evs if e["name"] == "request")
    assert req["ph"] == "X" and abs(req["dur"] - 400000.0) < 1.0
    # the admission flow binds the engine row to the ADMITTED request's
    # row — exactly one pair: the shed request gets no arrow
    assert sum(1 for e in evs if e.get("ph") == "s") == 1
    flow_s = next(e for e in evs if e.get("ph") == "s")
    flow_f = next(e for e in evs if e.get("ph") == "f")
    assert flow_s["id"] == flow_f["id"]
    assert flow_s["ts"] == flow_f["ts"] == pytest.approx(100000.0, abs=1)
    admitted_root = next(e for e in evs if e["name"] == "request"
                         and e.get("args", {}).get("slot") == 1)
    assert flow_f["tid"] == admitted_root["tid"]   # lands on r-0's row


def test_trace_export_check_catches_structural_breakage(tmp_path):
    export = _load_tool("trace_export")

    def run_check(events, **kw):
        p = _stream(str(tmp_path / "c.jsonl"), events, 1000.0, 10.0,
                    **kw)
        records = export.read_stream(p)
        return export.check_stream(records, p)

    good = [
        {"ph": "B", "name": "tick", "ts": 10.0, "tid": "engine",
         "span_id": "s1"},
        {"ph": "X", "name": "admit", "ts": 10.0, "dur": 0.1,
         "tid": "engine", "span_id": "s2", "parent_id": "s1"},
        {"ph": "E", "name": "tick", "ts": 10.5, "tid": "engine"},
    ]
    assert run_check(good) == []
    # unbalanced B
    errs = run_check(good[:2])
    assert any("never closed" in e for e in errs)
    # E without B / wrong nesting
    errs = run_check([dict(good[2], name="other")] + good[:1])
    assert any("no open B" in e for e in errs)
    # backwards B/E timestamps on one row
    errs = run_check([good[0], dict(good[2], ts=9.0)])
    assert any("backwards" in e for e in errs)
    # orphan parent_id
    errs = run_check([dict(good[1], parent_id="nope")])
    assert any("orphan parent_id" in e for e in errs)
    # child escapes its parent's window
    errs = run_check([good[0], dict(good[1], ts=11.0, dur=5.0),
                      good[2]])
    assert any("outside its parent" in e for e in errs)
    # negative X duration
    errs = run_check([dict(good[1], dur=-1.0, parent_id=None)])
    assert any("dur >= 0" in e for e in errs)
    # malformed ts / null dur on a PARENTED event must be reported,
    # never crash the containment pass (review regression: the gate
    # died with a TypeError on exactly the input it exists to catch)
    errs = run_check([good[0], {"ph": "X", "name": "x", "tid": "engine",
                                "parent_id": "s1", "dur": None},
                      good[2]])
    assert any("non-numeric ts" in e for e in errs)
    errs = run_check([good[0], dict(good[1], dur=None), good[2]])
    assert any("dur >= 0" in e for e in errs)
    # a stream with no trace at all is an error for the gate
    errs = run_check([])
    assert any("no trace_event" in e for e in errs)
    # two clock_syncs
    p = str(tmp_path / "two.jsonl")
    with open(p, "w") as fh:
        for rec in ({"record": "clock_sync", "time": 1.0, "ts": 1.0},
                    {"record": "clock_sync", "time": 2.0, "ts": 2.0},
                    {"record": "trace_event", "ph": "i", "name": "m",
                     "ts": 1.5}):
            fh.write(json.dumps(rec) + "\n")
    errs = export.check_stream(export.read_stream(p), p)
    assert any("2 clock_sync" in e for e in errs)
    # sync after the first event
    p2 = str(tmp_path / "late.jsonl")
    with open(p2, "w") as fh:
        for rec in ({"record": "trace_event", "ph": "i", "name": "m",
                     "ts": 1.5},
                    {"record": "clock_sync", "time": 1.0, "ts": 1.0}):
            fh.write(json.dumps(rec) + "\n")
    errs = export.check_stream(export.read_stream(p2), p2)
    assert any("must precede" in e for e in errs)


def test_trace_export_missing_clock_sync_is_unexportable(tmp_path):
    export = _load_tool("trace_export")
    p = str(tmp_path / "nosync.jsonl")
    with open(p, "w") as fh:
        fh.write(json.dumps({"record": "trace_event", "ph": "i",
                             "name": "m", "ts": 1.0}) + "\n")
    assert export.main([p, "-o", str(tmp_path / "o.json")]) == 2
    assert export.main([str(tmp_path / "missing.jsonl")]) == 2


def test_trace_export_xprof_overlay(tmp_path):
    """A device trace with epoch-microsecond timestamps lands on the
    same wall axis (the clock-sync pair), on its own process rows —
    shares trace_top.py's parser, gz included."""
    export = _load_tool("trace_export")
    epoch = 1.7e9                                  # a realistic wall clock
    host = _stream(str(tmp_path / "h.jsonl"), [
        {"ph": "X", "name": "step", "ts": 50.0, "dur": 1.0,
         "tid": "main", "span_id": "s1"},
    ], sync_wall=epoch, sync_perf=50.0)
    # device op 0.5s into the host span, epoch-us (the TPU convention)
    xprof = {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "name": "fusion.1", "pid": 7, "tid": 0,
         "ts": (epoch + 0.5) * 1e6, "dur": 100.0},
    ]}
    xp = str(tmp_path / "x.trace.json.gz")
    with gzip.open(xp, "wt") as fh:
        json.dump(xprof, fh)
    out = str(tmp_path / "m.json")
    assert export.main([host, "--xprof", xp, "-o", out]) == 0
    evs = json.loads(open(out).read())["traceEvents"]
    dev = next(e for e in evs if e["name"] == "fusion.1")
    assert dev["pid"] >= 1000                   # its own process block
    assert abs(dev["ts"] - 500000.0) < 1.0      # +0.5s on the shared axis


def test_ci_gate_trace_stream_gate(tmp_path, capsys):
    ci_gate = _load_tool("ci_gate")
    good = _stream(str(tmp_path / "g.jsonl"), [
        {"ph": "B", "name": "tick", "ts": 1.0, "tid": "engine"},
        {"ph": "E", "name": "tick", "ts": 2.0, "tid": "engine"},
    ], 100.0, 1.0)
    assert ci_gate.main(["--trace-stream", good]) == 0
    out = capsys.readouterr().out
    assert "trace_export --check" in out and "ci_gate: PASS" in out
    bad = _stream(str(tmp_path / "bad.jsonl"), [
        {"ph": "B", "name": "tick", "ts": 1.0, "tid": "engine"},
    ], 100.0, 1.0)
    assert ci_gate.main(["--trace-stream", bad]) == 1
    assert ci_gate.main(
        ["--trace-stream", str(tmp_path / "missing.jsonl")]) == 2


# ------------------------------------------- critical path (report)

def test_serve_report_critical_path_sums_to_e2e(tmp_path, capsys):
    report = _load_tool("serve_report")
    recs = [{"record": "run_header", "schema": 9, "time": 0.0,
             "run_id": "r", "num_devices": 1, "process_index": 0,
             "platform": "cpu", "config": {}}]
    for i, (q, p, d, extra) in enumerate(
            [(2.0, 10.0, 30.0, 0.5), (0.5, 8.0, 12.0, 0.0),
             (40.0, 9.0, 6.0, 1.5)]):
        n = 5
        recs.append({"record": "request_complete", "time": 1.0,
                     "request_id": f"r-{i}", "prompt_tokens": 4,
                     "output_tokens": n, "ttft_ms": q + p,
                     "tpot_ms": d / (n - 1), "finish_reason": "length",
                     "queue_wait_ms": q, "e2e_ms": q + p + d + extra})
    recs.append({"record": "serve_summary", "time": 2.0, "requests": 3,
                 "output_tokens": 15, "tokens_per_sec": 10.0})
    # a traced (ungated, wall-clock) submission: its handoff span rides
    # the table as its own component
    recs[1:1] = [
        {"record": "trace_event", "ph": "X", "name": "request",
         "ts": 1.0, "dur": 0.1, "tid": "req/r-0", "span_id": "s1",
         "cat": "request", "args": {"request_id": "r-0"}},
        {"record": "trace_event", "ph": "X", "name": "submit",
         "ts": 1.0, "dur": 0.007, "tid": "req/r-0", "span_id": "s2",
         "parent_id": "s1", "cat": "request"}]
    rows = report.critical_path(recs)
    assert len(rows) == 3
    assert rows[0]["handoff_ms"] == pytest.approx(7.0)
    assert "handoff_ms" not in rows[1]
    for row in rows:
        total = row["queue_ms"] + row["prefill_ms"] + row["decode_ms"] \
            + row["stall_ms"]
        assert total == pytest.approx(row["e2e_ms"], rel=0.01)
    assert rows[0]["stall_ms"] == pytest.approx(0.5)
    path = str(tmp_path / "s.jsonl")
    with open(path, "w") as fh:
        for r in recs:
            fh.write(json.dumps(r) + "\n")
    assert report.main([path]) == 0
    out = capsys.readouterr().out
    assert "critical path (share of total e2e)" in out
    # r-2's e2e (56.5) is both worst and p99; queue dominates it
    assert "worst r-2" in out and "culprit queue" in out


def test_submit_and_mature_stamp_arrival_clocks():
    """Review regressions, both clocks: (a) an UNGATED request
    "arrives" at submission — submit() re-stamps t_arrival, so the
    build->submit gap is the client's "submit" span (t_submit kept),
    never queue wait; (b) a GATED request's build->gate delay is
    deliberate staggering, not handoff — mature() re-stamps t_submit
    WITH t_arrival so no "submit" span can absorb it."""
    from apex_example_tpu.serve import Request, RequestQueue
    q = RequestQueue()
    gated = Request(prompt=[1], max_new_tokens=1, arrival_step=3,
                    t_submit=0.5)
    ungated = Request(prompt=[2], max_new_tokens=1, t_submit=0.25)
    built_at = ungated.t_arrival
    q.submit_all([gated, ungated])
    assert ungated.t_arrival > built_at          # arrives at submit()
    assert ungated.t_submit == 0.25              # wall-clock handoff kept
    assert gated.t_arrival < ungated.t_arrival   # gated: not submit-stamped
    q.mature(0)
    assert gated.t_submit == 0.5                 # gate not reached yet
    q.mature(3)
    assert gated.t_submit == gated.t_arrival     # re-stamped together


# ------------------------------------- supervisor-side continuity

def _load_supervisor():
    spec = importlib.util.spec_from_file_location(
        "apex_supervisor_trace_test",
        os.path.join(REPO, "apex_example_tpu", "resilience",
                     "supervisor.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_supervisor_propagates_trace_id_and_emits_spans(tmp_path):
    """A --trace child inherits APEX_TRACE_ID from the supervisor (one
    trace across attempts), and the supervisor's own stream carries a
    clock_sync + an X "attempt" span per child + an "i" restart marker
    — all schema-valid and structurally clean under the --check lint."""
    sup_mod = _load_supervisor()
    seen = tmp_path / "seen_ids.txt"
    marker = tmp_path / "ran_once"
    child = tmp_path / "child.py"
    child.write_text(f"""\
import os, sys
with open({str(seen)!r}, "a") as fh:
    fh.write(os.environ.get("APEX_TRACE_ID", "MISSING") + "\\n")
if os.path.exists({str(marker)!r}):
    sys.exit(0)
open({str(marker)!r}, "w").close()
sys.exit(75)
""")
    sup = sup_mod.Supervisor(
        [sys.executable, str(child), "--trace"],
        metrics_jsonl=str(tmp_path / "sup.jsonl"),
        max_restarts=2, backoff_s=0.01, sleep_fn=lambda s: None,
        log=lambda *a: None)
    assert sup._tracing
    assert sup.run() == 0
    ids = seen.read_text().splitlines()
    assert ids == [sup.trace_id] * 2             # both attempts, one trace
    recs = obs.read_jsonl(str(tmp_path / "sup.jsonl"))
    assert obs_schema.validate_stream(recs) == []
    assert sum(1 for r in recs if r["record"] == "clock_sync") == 1
    evs = [r for r in recs if r["record"] == "trace_event"]
    assert [e["name"] for e in evs] == ["attempt", "restart", "attempt"]
    attempts = [e for e in evs if e["name"] == "attempt"]
    assert [a["args"]["exit_code"] for a in attempts] == [75, 0]
    assert all(e["trace_id"] == sup.trace_id for e in evs)
    restart = evs[1]
    assert restart["ph"] == "i"
    assert restart["args"]["reason"] == "preemption"
    export = _load_tool("trace_export")
    assert export.check_stream(recs, "sup.jsonl") == []


def test_supervisor_untraced_child_emits_no_trace_records(tmp_path):
    sup_mod = _load_supervisor()
    child = tmp_path / "ok.py"
    child.write_text("import sys\nsys.exit(0)\n")
    sup = sup_mod.Supervisor(
        [sys.executable, str(child)],
        metrics_jsonl=str(tmp_path / "sup.jsonl"),
        log=lambda *a: None)
    assert not sup._tracing
    assert sup.run() == 0
    recs = obs.read_jsonl(str(tmp_path / "sup.jsonl"))
    assert not any(r["record"] in ("trace_event", "clock_sync")
                   for r in recs)
