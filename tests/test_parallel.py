"""Distributed-semantics tests on 8 real XLA CPU devices (SURVEY.md §5:
the actual psum/shard_map code path, not a mock — exceeds the reference's
two-physical-GPU test gap).

Covers: SyncBN invariant (N-shard == full-batch BN, the upstream two_gpu
test), DDP grad-averaging semantics, predivide/fp32 options, and torch
BatchNorm goldens for the single-device path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_example_tpu.parallel import (
    DDPConfig, SyncBatchNorm, allreduce_grads, convert_syncbn_model,
    make_data_mesh)
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def _bn_apply(axis_name=None, train=True):
    mod = SyncBatchNorm(use_running_average=not train, axis_name=axis_name)
    return mod


class TestSyncBatchNormLocal:
    def test_matches_torch_batchnorm_train(self):
        x = np.random.RandomState(0).randn(8, 4, 4, 3).astype(np.float32)
        mod = SyncBatchNorm(use_running_average=False)
        vars_ = mod.init(jax.random.PRNGKey(0), jnp.asarray(x))
        y, mut = mod.apply(vars_, jnp.asarray(x), mutable=["batch_stats"])

        tbn = torch.nn.BatchNorm2d(3, eps=1e-5, momentum=0.1)
        tx = torch.from_numpy(x.transpose(0, 3, 1, 2))
        ty = tbn(tx).detach().numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(y), ty, atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["mean"]),
            tbn.running_mean.numpy(), atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["var"]),
            tbn.running_var.numpy(), atol=1e-5, rtol=1e-4)

    def test_eval_uses_running_stats(self):
        x = np.random.RandomState(1).randn(4, 2, 2, 5).astype(np.float32)
        mod = SyncBatchNorm(use_running_average=True)
        vars_ = mod.init(jax.random.PRNGKey(0), jnp.asarray(x))
        y = mod.apply(vars_, jnp.asarray(x))
        # Fresh stats: mean 0, var 1 → identity up to affine (scale=1,bias=0).
        np.testing.assert_allclose(np.asarray(y),
                                   x / np.sqrt(1 + 1e-5), atol=1e-5)


class TestSyncBatchNormCrossReplica:
    def test_sharded_equals_full_batch(self, devices8):
        """The SyncBN invariant: 8-shard SyncBN == 1-device big-batch BN."""
        mesh = make_data_mesh(devices=devices8)
        n, h, w, c = 16, 4, 4, 6
        x = np.random.RandomState(2).randn(n, h, w, c).astype(np.float32)

        mod_sync = SyncBatchNorm(use_running_average=False, axis_name="data")
        mod_local = SyncBatchNorm(use_running_average=False)
        vars_ = mod_local.init(jax.random.PRNGKey(0), jnp.asarray(x))

        def shard_fn(xs):
            y, mut = mod_sync.apply(vars_, xs, mutable=["batch_stats"])
            return y, mut["batch_stats"]

        sharded = jax.jit(shard_map(
            shard_fn, mesh=mesh, in_specs=P("data"),
            out_specs=(P("data"), P())))
        y_sh, stats_sh = sharded(jnp.asarray(x))

        y_full, mut_full = mod_local.apply(vars_, jnp.asarray(x),
                                           mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_full),
                                   atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(stats_sh["mean"]),
            np.asarray(mut_full["batch_stats"]["mean"]), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(stats_sh["var"]),
            np.asarray(mut_full["batch_stats"]["var"]), atol=1e-4,
            rtol=1e-4)

    def test_backward_crosses_replicas(self, devices8):
        """Grad of per-shard loss wrt shared params must include every
        shard's contribution (psum transpose)."""
        mesh = make_data_mesh(devices=devices8)
        x = np.random.RandomState(3).randn(8, 2, 2, 3).astype(np.float32)
        mod = SyncBatchNorm(use_running_average=False, axis_name="data")
        # init outside shard_map must not touch the axis: use the local twin
        # (identical param structure).
        vars_ = SyncBatchNorm(use_running_average=False).init(
            jax.random.PRNGKey(0), jnp.asarray(x[:1]))
        params = vars_["params"]

        def shard_loss(params, xs):
            y, _ = mod.apply({"params": params}, xs,
                             mutable=["batch_stats"])
            return jnp.sum(y ** 2)

        def total_loss(params, xs):
            l = shard_loss(params, xs)
            return jax.lax.psum(l, "data")

        g = jax.jit(shard_map(
            jax.grad(total_loss), mesh=mesh,
            in_specs=(P(), P("data")), out_specs=P()))(params,
                                                       jnp.asarray(x))
        # Golden: same computation single-device (full batch, local BN).
        mod_l = SyncBatchNorm(use_running_average=False)

        def full_loss(params):
            y, _ = mod_l.apply({"params": params}, jnp.asarray(x),
                               mutable=["batch_stats"])
            return jnp.sum(y ** 2)

        g_full = jax.grad(full_loss)(params)
        for k in ("scale", "bias"):
            np.testing.assert_allclose(np.asarray(g[k]),
                                       np.asarray(g_full[k]),
                                       atol=1e-3, rtol=1e-4)


class TestDDP:
    def test_allreduce_grads_mean(self, devices8):
        mesh = make_data_mesh(devices=devices8)
        g = np.arange(8, dtype=np.float32).reshape(8, 1)

        def f(gs):
            return allreduce_grads({"w": gs}, DDPConfig(),
                                   already_reduced=False)["w"]

        out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("data"),
                                out_specs=P("data"), check_vma=False))(
            jnp.asarray(g))
        # gradient_average=True → every shard holds the mean.
        np.testing.assert_allclose(np.asarray(out),
                                   np.full((8, 1), g.mean()), rtol=1e-6)

    def test_allreduce_sum_when_average_off(self, devices8):
        mesh = make_data_mesh(devices=devices8)
        g = np.ones((8, 1), np.float32)
        cfg = DDPConfig(gradient_average=False)

        def f(gs):
            return allreduce_grads({"w": gs}, cfg,
                                   already_reduced=False)["w"]

        out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("data"),
                                out_specs=P("data"), check_vma=False))(
            jnp.asarray(g))
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 8.0))

    def test_predivide_matches_plain_average(self, devices8):
        mesh = make_data_mesh(devices=devices8)
        g = np.random.RandomState(4).randn(8, 4).astype(np.float32)

        def f(cfg):
            def inner(gs):
                return allreduce_grads({"w": gs}, cfg,
                                       already_reduced=False)["w"]
            return jax.jit(shard_map(inner, mesh=mesh, in_specs=P("data"),
                                     out_specs=P("data"), check_vma=False))(
                jnp.asarray(g))

        plain = f(DDPConfig())
        pre = f(DDPConfig(gradient_predivide_factor=8.0))
        np.testing.assert_allclose(np.asarray(plain), np.asarray(pre),
                                   rtol=1e-5, atol=1e-6)

    def test_allreduce_always_fp32_preserves_dtype(self, devices8):
        mesh = make_data_mesh(devices=devices8)
        g = jnp.ones((8, 4), jnp.bfloat16)
        cfg = DDPConfig(allreduce_always_fp32=True)

        def f(gs):
            return allreduce_grads({"w": gs}, cfg,
                                   already_reduced=False)["w"]

        out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("data"),
                                out_specs=P("data"), check_vma=False))(g)
        assert out.dtype == jnp.bfloat16


def _shard_map_unchecked(f, mesh, in_specs, out_specs):
    """shard_map without replication checking, spelled for BOTH jax
    eras: vma-typed (check_vma) and classic (check_rep) — the rig's
    0.4.37 carries only the latter."""
    try:
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


class TestDDPPrecision:
    """allreduce_always_fp32 semantics (the ISSUE 13 satellite pin) and
    the quantized-allreduce mode, on a 2-device DDP fixture."""

    def _mesh2(self, devices8):
        return make_data_mesh(devices=devices8[:2])

    def _reduce(self, mesh, cfg, g):
        def f(gs):
            return allreduce_grads({"w": gs}, cfg,
                                   already_reduced=False)["w"]
        return np.asarray(jax.jit(_shard_map_unchecked(
            f, mesh, P("data"), P("data")))(g))

    def test_allreduce_always_fp32_upcasts_before_psum(self, devices8):
        """The direct semantics pin: upcast BEFORE psum, downcast
        after.  Two fp16 shards of 40000.0 sum to 80000 — past fp16's
        65504 max — so a reduction performed in fp16 is inf by the time
        the average brings it back in range, while the fp32-upcast path
        averages to a finite 40000 and only then downcasts.  The output
        dtype stays fp16 either way (the downcast half of the
        contract)."""
        mesh = self._mesh2(devices8)
        g = jnp.full((2, 4), 40000.0, jnp.float16)
        plain = self._reduce(mesh, DDPConfig(), g)
        up = self._reduce(mesh, DDPConfig(allreduce_always_fp32=True), g)
        assert plain.dtype == np.float16 and up.dtype == np.float16
        assert not np.isfinite(plain).any()      # fp16 psum overflowed
        np.testing.assert_array_equal(
            up, np.full((2, 4), 40000.0, np.float16))

    def test_quantized_allreduce_bound_and_identities(self, devices8):
        """One quantized reduction: per-element error within the
        documented world*scale/2 bound (scale = pmax chunk max-abs /
        127; averaging divides both sides by world), the off switch
        bit-identical to the unquantized path, and composition with
        allreduce_always_fp32 exact (the quantized path already
        accumulates in f32)."""
        mesh = self._mesh2(devices8)
        chunk = 256
        g = np.random.RandomState(0).randn(2, 4096).astype(np.float32)
        exact = self._reduce(mesh, DDPConfig(), jnp.asarray(g))
        cfg = DDPConfig(quantized_allreduce=True, quant_chunk=chunk)
        quant = self._reduce(mesh, cfg, jnp.asarray(g))
        # shared scale per chunk: pmax over the 2 shards of max-abs/127
        scale = np.abs(g).reshape(2, -1, chunk).max(axis=(0, 2)) / 127.0
        err = np.abs(quant - exact).reshape(2, -1, chunk).max(axis=2)
        bound = np.broadcast_to(scale[None, :] / 2 * 1.001 + 1e-8,
                                err.shape)
        np.testing.assert_array_less(err, bound)
        assert (err > 0).any()                   # it really quantized
        off = self._reduce(mesh, DDPConfig(quantized_allreduce=False),
                           jnp.asarray(g))
        np.testing.assert_array_equal(off, exact)
        both = self._reduce(mesh, DDPConfig(
            quantized_allreduce=True, quant_chunk=chunk,
            allreduce_always_fp32=True), jnp.asarray(g))
        np.testing.assert_array_equal(both, quant)
        # grad dtype preserved through the int8 exchange
        gb = jnp.asarray(g, jnp.bfloat16)
        qb = self._reduce(mesh, cfg, gb)
        assert qb.dtype == jnp.bfloat16

    def test_quantized_allreduce_30step_lockstep_trail(self, devices8):
        """The gate the ISSUE names: 30 lockstep SGD steps on the
        2-device DDP fixture, quantized exchange vs the fp32 reduction.
        Per step the reduced-gradient error is bounded by scale/2
        (averaged), so the parameter trails stay within the summed
        per-step bounds — asserted exactly, step by step, against the
        accumulated bound rather than a vibes tolerance."""
        mesh = self._mesh2(devices8)
        chunk = 128
        rs = np.random.RandomState(7)
        w_exact = np.zeros((2, chunk), np.float32)
        w_quant = np.zeros((2, chunk), np.float32)
        budget = 0.0
        lr = 0.1
        cfg_q = DDPConfig(quantized_allreduce=True, quant_chunk=chunk)
        # ONE jitted program per config for the whole trail (the loop
        # re-invokes, never re-traces).
        mk = lambda cfg: jax.jit(_shard_map_unchecked(
            lambda gs: allreduce_grads({"w": gs}, cfg,
                                       already_reduced=False)["w"],
            mesh, P("data"), P("data")))
        red_exact, red_quant = mk(DDPConfig()), mk(cfg_q)
        for step in range(30):
            # synthetic per-shard grads: a drifting quadratic pull plus
            # shard-dependent noise (what DDP exists to average away)
            base = rs.randn(1, chunk).astype(np.float32)
            noise = rs.randn(2, chunk).astype(np.float32)
            g_exact = base + 0.3 * noise + 0.05 * w_exact
            g_quant = base + 0.3 * noise + 0.05 * w_quant
            r_exact = np.asarray(red_exact(jnp.asarray(g_exact)))
            r_quant = np.asarray(red_quant(jnp.asarray(g_quant)))
            # this step's quantization bound at the quant trail's grads
            scale = np.abs(g_quant).reshape(2, -1, chunk) \
                .max(axis=(0, 2)) / 127.0
            budget = budget * (1 + lr * 0.05) \
                + lr * (float(scale.max()) / 2 + 1e-7)
            w_exact = w_exact - lr * r_exact
            w_quant = w_quant - lr * r_quant
            assert np.abs(w_quant - w_exact).max() <= budget * 1.01, \
                f"trail diverged past the accumulated bound at {step}"
        # and the trails really are different computations
        assert np.abs(w_quant - w_exact).max() > 0


def test_convert_syncbn_model():
    from apex_example_tpu.models import resnet18
    m = resnet18(num_classes=10)
    assert m.bn_axis_name is None
    m2 = convert_syncbn_model(m)
    assert m2.bn_axis_name == "data"


def test_reducer_manual_allreduce(devices8):
    """apex.parallel.Reducer analog: manual reduction == pmean."""
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map
    from apex_example_tpu.parallel import Reducer
    mesh = Mesh(np.asarray(devices8), ("data",))
    x = jnp.arange(16.0).reshape(8, 2)

    red = Reducer()
    out = shard_map(lambda t: red.reduce({"g": t})["g"],
                    mesh=mesh, in_specs=P("data"), out_specs=P("data"))(x)
    expect = np.broadcast_to(np.asarray(x).reshape(8, 2).mean(0), (8, 2))
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)
