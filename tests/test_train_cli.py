"""train.py CLI coverage for the LM eval loops (SURVEY.md §3.5: the
reference harness's validation pass, extended to the LM archs): MLM
masked-accuracy eval, TXL perplexity eval with mems threading, and the
host-pipeline's one-shot held-out streams."""

import pytest

import train as train_mod

BASE = ["--batch-size", "8", "--seq-len", "16", "--epochs", "1",
        "--steps-per-epoch", "3", "--opt", "adam", "--opt-level", "O0",
        "--print-freq", "2", "--eval", "--eval-batches", "2"]


def test_bert_eval(capsys):
    assert train_mod.main(["--arch", "bert_tiny", "--num-devices", "1"]
                          + BASE) == 0
    out = capsys.readouterr().out
    assert "EVAL" in out and "masked_acc" in out


def test_txl_eval(capsys):
    assert train_mod.main(["--arch", "transformer_xl_tiny",
                           "--num-devices", "1"] + BASE) == 0
    out = capsys.readouterr().out
    assert "EVAL" in out and "ppl" in out


def test_bert_eval_host_pipeline(capsys):
    from apex_example_tpu import host_runtime
    if not host_runtime.available():
        pytest.skip("native runtime not buildable")
    assert train_mod.main(["--arch", "bert_tiny", "--host-pipeline",
                           "--num-devices", "1"] + BASE) == 0
    assert "masked_acc" in capsys.readouterr().out


def test_bert_eval_under_tp(devices8, capsys):
    """--eval under GSPMD TP (ADVICE r3: eval was wired through the TP path
    but never exercised — a GSPMD eval regression would ship unnoticed)."""
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    try:
        assert train_mod.main(["--arch", "bert_tiny",
                               "--tensor-parallel", "2"] + BASE) == 0
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)
    assert "masked_acc" in capsys.readouterr().out


def test_bert_eval_under_zero(devices8, capsys):
    """--eval under ZeRO-1 (sharded optimizer state; eval reads params
    only)."""
    assert train_mod.main(["--arch", "bert_tiny", "--zero"] + BASE) == 0
    assert "masked_acc" in capsys.readouterr().out


def test_bert_eval_under_pp(devices8, capsys):
    from apex_example_tpu.transformer import parallel_state
    try:
        assert train_mod.main(["--arch", "bert_tiny",
                               "--pipeline-parallel", "2",
                               "--microbatches", "2"] + BASE) == 0
    finally:
        parallel_state.set_mesh(None)
    assert "masked_acc" in capsys.readouterr().out


def test_long_seq_bumps_position_table(devices8):
    """seq_len beyond the arch's max_position default must auto-grow the
    position table (the nn.Embed gather silently clamps otherwise) — the
    long-context path's correctness depends on it, dense and CP alike."""
    from apex_example_tpu.transformer import parallel_state
    base = ["--arch", "bert_tiny", "--batch-size", "4", "--seq-len", "256",
            "--epochs", "1", "--steps-per-epoch", "2", "--opt", "adam",
            "--opt-level", "O0", "--print-freq", "1"]
    assert train_mod.main(base + ["--num-devices", "1"]) == 0
    try:
        assert train_mod.main(base + ["--context-parallel", "4"]) == 0
    finally:
        parallel_state.set_mesh(None)


@pytest.mark.parametrize("opt", ["novograd", "adagrad"])
def test_extra_fused_optimizers_from_cli(opt):
    """apex's remaining fused optimizers are harness-reachable."""
    assert train_mod.main(
        ["--arch", "resnet18", "--opt", opt, "--num-devices", "1",
         "--batch-size", "16", "--epochs", "1", "--steps-per-epoch", "2",
         "--opt-level", "O0", "--print-freq", "1"]) == 0


def test_larc_from_cli():
    """apex.parallel.LARC wraps the optimizer from the CLI (SSL recipes)."""
    assert train_mod.main(
        ["--arch", "resnet18", "--opt", "sgd", "--larc",
         "--num-devices", "1", "--batch-size", "16", "--epochs", "1",
         "--steps-per-epoch", "2", "--opt-level", "O0",
         "--print-freq", "1"]) == 0


def test_larc_zero_rejected():
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "resnet18", "--larc", "--zero",
                        "--opt", "adam"])


def test_larc_pp_rejected():
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "bert_tiny", "--pipeline-parallel", "2",
                        "--larc"])
