"""graftlint — the two-stratum static analysis pass (ISSUE 9).

- positive AND negative fixture snippets for every source rule (each
  rule must both fire and stay quiet),
- the two recorded StableHLO fixtures (bf16-clean vs seeded f32 leak)
  driving the HLO rules and the recompile-cause diff,
- baseline / suppression mechanics and the CLI exit-code contract,
- the acceptance gates: the repo itself is lint-clean at HEAD
  (``--fail-on-new`` with the checked-in EMPTY baseline exits 0), the
  jax-free contract set covers every thin client the retired runtime
  poisoned-jax guard used to spawn subprocesses for, and
  ``tools/ci_gate.py`` bundles graftlint + the recompile gate into one
  passing command.

Everything here is jax-free (the tool's own contract): no jax import,
no subprocesses, no compiles — the whole module is AST/text analysis
and must stay in the low single-digit seconds.
"""

import importlib.util
import json
import os

import pytest

from tools import graftlint
from tools.graftlint import hostsync, imports, locks, schema_rules
from tools.graftlint import hlo as hlo_rules
from tools.graftlint.base import (apply_baseline, load_baseline,
                                  tree_from_sources, write_baseline)
from tools.graftlint.cli import main as graftlint_main
from tools.graftlint.cli import run_source_lint

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HLO_DIR = os.path.join(REPO, "tests", "fixtures", "hlo")
CLEAN_MLIR = os.path.join(HLO_DIR, "bf16_clean.mlir")
LEAK_MLIR = os.path.join(HLO_DIR, "bf16_f32_leak.mlir")
INT8_CLEAN_MLIR = os.path.join(HLO_DIR, "int8_clean.mlir")
INT8_LEAK_MLIR = os.path.join(HLO_DIR, "int8_f32_leak.mlir")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------- jax-free (rule a)

_MINI_SCHEMA = 'REQUIRED = {"step": {"record": str}}\nOPTIONAL = {}\n'


def test_jax_free_rule_fires_on_transitive_reach():
    tree = tree_from_sources({
        "tools/thin.py": "import helper\n",
        "tools/helper.py": "from flax import linen\n",   # flax => jax
    })
    fs = imports.check(tree)
    assert _rules(fs) == ["jax-free"]
    # BOTH are violations: helper reaches flax directly (a flax import
    # does not opt a tool out — only a direct jax/jaxlib import does),
    # and thin reaches it transitively with the chain spelled out.
    msgs = [f.message for f in fs]
    assert any("tools/helper.py -> flax" in m for m in msgs)
    assert any("tools/thin.py -> tools/helper.py -> flax" in m
               for m in msgs)


def test_jax_free_rule_package_init_counts_as_an_edge():
    """Importing a submodule executes its package __init__: a clean
    submodule under a jax-carrying __init__ still violates."""
    tree = tree_from_sources({
        "tools/thin.py": "from pkg.sub import helper\n",
        "pkg/__init__.py": "import jax\n",
        "pkg/sub/__init__.py": "",
        "pkg/sub/helper.py": "import os\n",
    })
    fs = imports.check(tree)
    assert len(fs) == 1 and "pkg/__init__.py" in fs[0].message


def test_jax_free_rule_follows_relative_import_init_edges():
    """A RELATIVE import executes the importing package's own __init__
    chain: a jax import hiding in a subpackage __init__.py must be
    reachable from a sibling module's ``from . import x`` (review
    regression on the first cut of this rule)."""
    tree = tree_from_sources({
        "tools/pkg/__init__.py": "import jax\n",
        "tools/pkg/mod.py": "from . import helper\n",
        "tools/pkg/helper.py": "import os\n",
    })
    fs = imports.check(tree)
    assert len(fs) == 1
    assert "tools/pkg/mod.py -> tools/pkg/__init__.py -> jax" \
        in fs[0].message


def test_jax_free_rule_quiet_on_stdlib_and_guarded_imports():
    tree = tree_from_sources({
        "tools/thin.py": ("import json, os, sys\n"
                          "try:\n    import jax\n"
                          "except ImportError:\n    jax = None\n"),
        "tools/jaxy.py": "import jax\n",     # direct import: opted OUT
    })
    assert imports.check(tree) == []


def test_jax_free_rule_fallback_import_in_handler_is_a_hard_edge():
    """Only the try-BODY import is runtime-guarded; the fallback import
    in the except handler executes precisely on the jax-less host
    (review regression: `except ImportError: import flax...` must not
    be treated as soft)."""
    tree = tree_from_sources({"tools/thin.py": """
try:
    import ujson as json
except ImportError:
    import flax.serialization as json
"""})
    fs = imports.check(tree)
    assert len(fs) == 1 and "flax" in fs[0].message


def test_jax_free_contract_covers_the_retired_runtime_guard_set():
    """The static check replaces test_diag's poisoned-jax subprocess
    loop: every thin client that loop spawned must be in the verified
    contract set — a tool growing a direct jax import silently leaves
    the contract, which IS the regression this assertion catches."""
    tree = graftlint.load_tree()
    contract = set(imports.contract_modules(tree))
    for required in ("tools/metrics_lint.py", "tools/telemetry_report.py",
                     "tools/fleet_report.py", "tools/serve_report.py",
                     "tools/supervise.py", "tools/cost_report.py",
                     "tools/ci_gate.py", "tools/trace_export.py",
                     "tools/trace_top.py",
                     # ISSUE 16: the SLO sketches must merge and report
                     # on hosts that only have the JSONL (slo.py is
                     # loaded by file path by the router and fleet.py).
                     "tools/slo_report.py",
                     "apex_example_tpu/obs/slo.py",
                     "apex_example_tpu/resilience/supervisor.py",
                     "apex_example_tpu/obs/schema.py",
                     # ISSUE 12: the fleet stratum carries the same
                     # contract — the router must outlive its replicas'
                     # jax (fleet.py loads these by file path).
                     "apex_example_tpu/fleet/replica.py",
                     "apex_example_tpu/fleet/router.py",
                     "apex_example_tpu/fleet/scenarios.py"):
        assert required in contract, f"{required} left the jax-free set"
    # and graftlint must eat its own dogfood
    assert "tools/graftlint/cli.py" in contract


# ------------------------------------------- host-sync-in-step (rule b)

def test_host_sync_fires_on_fetches_of_traced_values():
    tree = tree_from_sources({"apex_example_tpu/obs/schema.py":
                              _MINI_SCHEMA,
                              "pkg/step.py": """
import jax
import numpy as np

@jax.jit
def step(state, batch):
    loss = state.loss + batch.mean()
    host = float(loss)
    per_elem = loss.item()
    arr = np.asarray(batch)
    return host, per_elem, arr
"""})
    fs = hostsync.check(tree)
    assert len(fs) == 3
    assert all(f.rule == "host-sync-in-step" for f in fs)
    assert {f.line for f in fs} == {8, 9, 10}


def test_host_sync_quiet_on_static_metadata_and_closure_config():
    """Negative space: shape/dtype metadata and factory closure config
    are host-side statics — float()/bool() on them is fine (the
    bert_pipeline ``with_aux=bool(moe)`` shape)."""
    tree = tree_from_sources({"apex_example_tpu/obs/schema.py":
                              _MINI_SCHEMA,
                              "pkg/ok.py": """
import jax

def make_train_step(model, moe, lr):
    def step(state, batch):
        width = int(batch.shape[-1])
        cfg = bool(moe)
        rate = float(lr)
        return state.apply(batch, width, cfg, rate)
    return jax.jit(step)
"""})
    assert hostsync.check(tree) == []


def test_host_sync_sees_factory_inner_functions():
    """Functions defined inside a make_*step factory run under trace
    even without a local jit call."""
    tree = tree_from_sources({"apex_example_tpu/obs/schema.py":
                              _MINI_SCHEMA,
                              "pkg/factory.py": """
def make_gpt_step(model):
    def step(state, batch):
        return int(state.loss)
    return step
"""})
    fs = hostsync.check(tree)
    assert len(fs) == 1 and fs[0].line == 4


def test_jit_in_loop_fires_and_module_level_stays_quiet():
    tree = tree_from_sources({"apex_example_tpu/obs/schema.py":
                              _MINI_SCHEMA,
                              "pkg/loop.py": """
import jax

eval_fn = jax.jit(lambda p, b: p + b)      # once per import: fine

def serve(ticks):
    for t in ticks:
        def body(x):
            return x + 1
        f = jax.jit(body)                  # fresh hash per tick
        g = jax.jit(lambda v: v * 2)       # fresh hash per tick
        f(t); g(t)
"""})
    fs = hostsync.check(tree)
    assert _rules(fs) == ["jit-in-loop"]
    assert {f.line for f in fs} == {10, 11}


# ----------------------------------------------- lock-discipline (c)

_LOCKED = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []            # guarded-by: _lock
        self.closed = False         # guarded-by: _lock

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def snapshot(self):
        with self._lock:
            return list(self._items), self.closed
"""


def test_lock_discipline_quiet_when_every_touch_holds_the_lock():
    tree = tree_from_sources({"apex_example_tpu/obs/schema.py":
                              _MINI_SCHEMA, "pkg/box.py": _LOCKED})
    assert locks.check(tree) == []


def test_lock_discipline_fires_on_unguarded_touch_and_cross_class():
    src = _LOCKED + """
    def size(self):
        return len(self._items)     # unguarded read

def poke(box):
    return box._items               # cross-class access
"""
    tree = tree_from_sources({"apex_example_tpu/obs/schema.py":
                              _MINI_SCHEMA, "pkg/box.py": src})
    fs = locks.check(tree)
    assert len(fs) == 2
    assert "Box.size touches self._items" in fs[0].message
    assert "outside its class" in fs[1].message


def test_lock_discipline_cross_class_needs_the_class_name_in_file():
    """A bare private-attr name collision in an unrelated file must not
    fire: the cross-class check requires the declaring class to be
    referenced by name in the accessing file (review precision fix)."""
    tree = tree_from_sources({
        "apex_example_tpu/obs/schema.py": _MINI_SCHEMA,
        "pkg/box.py": _LOCKED,
        "pkg/other.py": """
def close_channel(chan):
    return chan._items          # unrelated object, declaring class absent
"""})
    assert locks.check(tree) == []


def test_lock_discipline_ignore_pragma_and_init_exemption():
    src = _LOCKED + """
    def fast_size(self):
        return len(self._items)     # graftlint: ignore[lock-discipline]
"""
    tree = tree_from_sources({"apex_example_tpu/obs/schema.py":
                              _MINI_SCHEMA, "pkg/box.py": src})
    assert locks.check(tree) == []   # __init__ stores + pragma both quiet


# ---------------------------------------------- schema-emission (d)

_SCHEMA_SRC = """
REQUIRED = {
    "step": {"record": str, "loss": float},
    "run_summary": {"record": str, "steps": int},
}
OPTIONAL = {
    "step": {"lr": float, "grad_norm": float},
    "run_summary": {"aborted": bool},
}
"""


def test_schema_emission_quiet_on_valid_emitters():
    tree = tree_from_sources({
        "apex_example_tpu/obs/schema.py": _SCHEMA_SRC,
        "pkg/emit.py": """
def emit(sink, values):
    rec = {"record": "step", "loss": 0.5}
    for key in ("lr", "grad_norm"):
        if key in values:
            rec[key] = values[key]
    sink.write(rec)
    sink.write({"record": "run_summary", "steps": 3, "aborted": True})
"""})
    assert schema_rules.check(tree) == []


def test_schema_emission_fires_on_drift():
    tree = tree_from_sources({
        "apex_example_tpu/obs/schema.py": _SCHEMA_SRC,
        "pkg/emit.py": """
def emit(sink):
    rec = {"record": "step", "loss": 0.5}
    rec["undeclared"] = 1            # new field without a schema bump
    sink.write(rec)
    sink.write({"record": "run_summary"})          # missing required
    sink.write({"record": "mystery", "x": 1})      # unknown type
"""})
    msgs = [f.message for f in schema_rules.check(tree)]
    assert len(msgs) == 3
    assert any("undeclared" in m and "bump the schema" in m for m in msgs)
    assert any("never sets required field 'steps'" in m for m in msgs)
    assert any("unknown record type 'mystery'" in m for m in msgs)


def test_schema_emission_variable_rebinding_does_not_cross_contaminate():
    """Two records sharing one variable name in a function: field
    assignments after the rebinding belong to the SECOND record only
    (review regression — the fold is scoped to the binding's live
    range)."""
    tree = tree_from_sources({
        "apex_example_tpu/obs/schema.py": _SCHEMA_SRC,
        "pkg/emit.py": """
def emit(sink):
    rec = {"record": "step", "loss": 0.5}
    sink.write(rec)
    rec = {"record": "run_summary", "steps": 2}
    rec["aborted"] = True       # must not leak into the 'step' record
    sink.write(rec)
"""})
    assert schema_rules.check(tree) == []


def test_schema_emission_picks_up_v9_trace_tables():
    """ISSUE 11 regression: the REAL schema module's v9 tables reach
    the AST rule — an undeclared field on a ``trace_event`` emission
    and a brand-new emission site without a schema bump both fire
    statically, and a well-formed trace emitter stays quiet.  This
    pins 'a new field can never ship without a schema bump' for the
    trace stratum specifically, not just via runtime validation."""
    with open(os.path.join(REPO, "apex_example_tpu", "obs",
                           "schema.py")) as fh:
        real_schema = fh.read()
    tree = tree_from_sources({
        "apex_example_tpu/obs/schema.py": real_schema,
        "pkg/emit.py": """
def emit(sink, ts):
    ok = {"record": "trace_event", "ph": "X", "name": "tick", "ts": ts,
          "tid": "engine", "dur": 0.5}
    sink.write(ok)
    sink.write({"record": "clock_sync", "time": 1.0, "ts": ts})
"""})
    assert schema_rules.check(tree) == []       # valid emitters: quiet
    tree = tree_from_sources({
        "apex_example_tpu/obs/schema.py": real_schema,
        "pkg/emit.py": """
def emit(sink, ts):
    rec = {"record": "trace_event", "ph": "X", "name": "tick", "ts": ts}
    rec["wall_time"] = 1.0     # undeclared field: needs a schema bump
    sink.write(rec)
    sink.write({"record": "span_event", "ts": ts})   # new emission site
    sink.write({"record": "trace_event", "ph": "B"}) # missing name/ts
"""})
    msgs = [f.message for f in schema_rules.check(tree)]
    assert any("'trace_event' emits field 'wall_time'" in m
               and "bump the schema" in m for m in msgs)
    assert any("unknown record type 'span_event'" in m for m in msgs)
    assert any("never sets required field 'name'" in m for m in msgs)
    assert any("never sets required field 'ts'" in m for m in msgs)


def test_schema_emission_picks_up_v14_slo_tables():
    """ISSUE 16: the streaming-SLO record types reach the AST rule —
    a well-formed emitter of each new type stays quiet, and an
    undeclared field on ANY of the three fires statically (a new field
    can never ship without a schema bump, pinned per record type)."""
    with open(os.path.join(REPO, "apex_example_tpu", "obs",
                           "schema.py")) as fh:
        real_schema = fh.read()
    tree = tree_from_sources({
        "apex_example_tpu/obs/schema.py": real_schema,
        "pkg/emit.py": """
def emit(sink, t):
    sink.write({"record": "slo_window", "time": t, "window": 0,
                "requests": 16, "good": 15, "bad": 1,
                "burn_rate": 0.5})
    sink.write({"record": "slo_breach", "time": t, "window": 1,
                "burn_rate": 2.0, "requests": 16, "bad": 4})
    sink.write({"record": "fleet_rollup", "time": t, "replicas": 2,
                "count": 32})
"""})
    assert schema_rules.check(tree) == []       # valid emitters: quiet
    for rectype, literal in (
            ("slo_window", '{"record": "slo_window", "time": t, '
                           '"window": 0, "requests": 1, "good": 1, '
                           '"bad": 0, "burn_rate": 0.0}'),
            ("slo_breach", '{"record": "slo_breach", "time": t, '
                           '"window": 0, "burn_rate": 2.0, '
                           '"requests": 1, "bad": 1}'),
            ("fleet_rollup", '{"record": "fleet_rollup", "time": t, '
                             '"replicas": 1, "count": 1}')):
        tree = tree_from_sources({
            "apex_example_tpu/obs/schema.py": real_schema,
            "pkg/emit.py": f"""
def emit(sink, t):
    rec = {literal}
    rec["undeclared_{rectype}"] = 1
    sink.write(rec)
"""})
        msgs = [f.message for f in schema_rules.check(tree)]
        assert any(f"'{rectype}' emits field 'undeclared_{rectype}'"
                   in m and "bump the schema" in m for m in msgs), \
            (rectype, msgs)


def test_schema_emission_picks_up_v16_spec_fields():
    """ISSUE 18: the speculative-decoding summary fields reach the AST
    rule — a serve_summary carrying the v16 conservation triple stays
    quiet, and an undeclared spec-adjacent field fires statically (a
    new speculation counter can never ship without a schema bump)."""
    with open(os.path.join(REPO, "apex_example_tpu", "obs",
                           "schema.py")) as fh:
        real_schema = fh.read()
    valid = """
def emit(sink, t):
    rec = {"record": "serve_summary", "time": t, "requests": 8,
           "output_tokens": 126, "tokens_per_sec": 42.0}
    rec["speculate_k"] = 3
    rec["draft_kind"] = "ngram"
    rec["tokens_drafted"] = 55
    rec["tokens_accepted"] = 52
    rec["tokens_sampled"] = 74
    rec["acceptance_rate"] = 0.9455
    rec["tokens_per_tick"] = 6.0
    sink.write(rec)
"""
    tree = tree_from_sources({
        "apex_example_tpu/obs/schema.py": real_schema,
        "pkg/emit.py": valid})
    assert schema_rules.check(tree) == []       # valid emitter: quiet
    drifted = valid.replace('rec["tokens_per_tick"] = 6.0',
                            'rec["tokens_per_draft"] = 6.0')
    tree = tree_from_sources({
        "apex_example_tpu/obs/schema.py": real_schema,
        "pkg/emit.py": drifted})
    msgs = [f.message for f in schema_rules.check(tree)]
    assert any("'serve_summary' emits field 'tokens_per_draft'" in m
               and "bump the schema" in m for m in msgs), msgs


def test_schema_emission_dynamic_builders_skip_missing_check_only():
    """A ``**``-built record (bench.py shape) can't be proven complete
    statically — but its literal keys are still checked."""
    tree = tree_from_sources({
        "apex_example_tpu/obs/schema.py": _SCHEMA_SRC,
        "pkg/emit.py": """
def emit(sink, extra):
    sink.write({"record": "step", "bogus": 1, **extra})
"""})
    msgs = [f.message for f in schema_rules.check(tree)]
    assert len(msgs) == 1 and "bogus" in msgs[0]


# -------------------------------------------------- HLO stratum rules

@pytest.fixture(scope="module")
def clean_text():
    with open(CLEAN_MLIR) as fh:
        return fh.read()


@pytest.fixture(scope="module")
def leak_text():
    with open(LEAK_MLIR) as fh:
        return fh.read()


def test_upcast_leak_fixture_pair(clean_text, leak_text):
    assert hlo_rules.upcast_leak(clean_text, "bf16") == []
    fs = hlo_rules.upcast_leak(leak_text, "bf16")
    assert len(fs) == 1
    assert fs[0].rule == "hlo-upcast-leak"
    assert "dot_general" in fs[0].message and "f32" in fs[0].message
    # under an f32 policy the same program is legal
    assert hlo_rules.upcast_leak(leak_text, "f32") == []


def test_host_transfer_rule(clean_text):
    assert hlo_rules.host_transfer(clean_text) == []
    poisoned = clean_text.replace(
        "return %6 : tensor<8x8xf32>",
        '%7 = "stablehlo.outfeed"(%6, %tok) : (tensor<8x8xf32>, '
        "!stablehlo.token) -> !stablehlo.token\n    "
        "return %6 : tensor<8x8xf32>")
    fs = hlo_rules.host_transfer(poisoned)
    assert len(fs) == 1 and "outfeed" in fs[0].message
    # custom_call @Sharding only fires when unsharded is expected
    sharded = clean_text.replace(
        "%3 = stablehlo.maximum %1, %2 : tensor<8x32xbf16>",
        "%3 = stablehlo.custom_call @Sharding(%1) : "
        "(tensor<8x32xbf16>) -> tensor<8x32xbf16>")
    assert hlo_rules.host_transfer(sharded, allow_sharding=True) == []
    fs = hlo_rules.host_transfer(sharded, allow_sharding=False)
    assert len(fs) == 1 and "@Sharding" in fs[0].message


def test_recompile_cause_diff_names_divergent_op(clean_text, leak_text):
    diff = hlo_rules.diff_lowerings(clean_text, leak_text)
    assert diff is not None
    # the first structural divergence is the upcast convert feeding the
    # wide dot — naming it IS the diagnosis
    assert diff["op"] == "convert"
    assert "first divergent op: convert" in diff["summary"]
    # identical programs (modulo SSA numbering + comments) diff to None
    renumbered = clean_text.replace("%5", "%55").replace("%6", "%66") \
        .replace("// graftlint", "// renamed")
    assert hlo_rules.diff_lowerings(clean_text, renumbered) is None


def test_int8_region_fixture_pair():
    """The claimed-int8 region mode (ISSUE 13): the recorded quantized
    forward (i8 weights dequantized to bf16, scale-fused) stays quiet;
    the seeded pair — the SAME program with one dequant converted UP to
    f32 — fails on the wide dot_general; and the recompile-cause diff
    names the divergence."""
    with open(INT8_CLEAN_MLIR) as fh:
        clean = fh.read()
    with open(INT8_LEAK_MLIR) as fh:
        leak = fh.read()
    assert hlo_rules.upcast_leak(clean, "int8") == []
    fs = hlo_rules.upcast_leak(leak, "int8")
    assert len(fs) == 1 and fs[0].rule == "hlo-upcast-leak"
    assert "dot_general" in fs[0].message and "f32" in fs[0].message
    assert "int8" in fs[0].message
    # a dequant pinned in f32 is still legal under a plain f32 policy —
    # the finding is a property of the CLAIM, not the program
    assert hlo_rules.upcast_leak(leak, "f32") == []
    # the claim itself is checked: a program with no i8/f8 tensor at
    # all "quantized" nothing
    fs = hlo_rules.upcast_leak(
        clean.replace("i8", "bf16"), "int8")
    assert len(fs) == 1 and "silently skipped" in fs[0].message
    # the diff names the leak (the f32 convert feeding the wide dot)
    diff = hlo_rules.diff_lowerings(clean, leak)
    assert diff is not None and diff["op"] == "convert"


def test_int8_cli_policy(capsys):
    assert graftlint_main(["--hlo", INT8_CLEAN_MLIR,
                           "--policy", "int8"]) == 0
    assert graftlint_main(["--hlo", INT8_LEAK_MLIR,
                           "--policy", "int8"]) == 1
    out = capsys.readouterr().out
    assert "hlo-upcast-leak" in out


def test_hlo_cli_exit_codes(capsys):
    assert graftlint_main(["--hlo", CLEAN_MLIR]) == 0
    assert graftlint_main(["--hlo", LEAK_MLIR]) == 1
    assert graftlint_main(["--hlo", LEAK_MLIR, "--policy", "f32"]) == 0
    assert graftlint_main(["--hlo-diff", CLEAN_MLIR, LEAK_MLIR]) == 1
    assert graftlint_main(["--hlo-diff", CLEAN_MLIR, CLEAN_MLIR]) == 0
    out = capsys.readouterr().out
    assert "hlo-upcast-leak" in out
    assert "first divergent op" in out


# ------------------------------------------ baseline + CLI mechanics

def test_baseline_roundtrip_and_fail_on_new(tmp_path):
    tree = tree_from_sources({"apex_example_tpu/obs/schema.py":
                              _MINI_SCHEMA,
                              "pkg/bad.py": """
import jax

@jax.jit
def step(state):
    return float(state.loss)
"""})
    findings = []
    for rule in (hostsync.check,):
        findings += rule(tree)
    assert len(findings) == 1
    path = str(tmp_path / "baseline.json")
    write_baseline(path, findings)
    baseline = load_baseline(path)
    assert len(baseline) == 1 and baseline[0].startswith(
        "host-sync-in-step::pkg/bad.py::")
    # identity is line-free: the same finding on a shifted line matches
    apply_baseline(findings, baseline)
    assert all(f.baselined for f in findings)


def test_repo_is_lint_clean_at_head(capsys):
    """The acceptance bar: the checked-in baseline is EMPTY and the
    whole source stratum exits 0 — every violation the rules found when
    they landed (the watchdog stall-counter race, the RequestQueue
    deadline fast-path read) was fixed in this PR."""
    baseline_path = os.path.join(REPO, "tools", "graftlint",
                                 "baseline.json")
    assert load_baseline(baseline_path) == []      # shipped empty
    assert graftlint_main(["--fail-on-new"]) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out


def test_repo_json_output_parses(capsys):
    assert graftlint_main(["--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["findings"] == [] and data["failed"] is False


def test_run_source_lint_reports_parse_errors():
    tree = tree_from_sources({"apex_example_tpu/obs/schema.py":
                              _MINI_SCHEMA,
                              "pkg/broken.py": "def broken(:\n"})
    fs = run_source_lint(tree)
    assert [f.rule for f in fs] == ["parse-error"]


# ------------------------------------------------- ci_gate (satellite)

def test_ci_gate_bundles_both_gates(tmp_path, capsys):
    """One CI command: graftlint --fail-on-new + cost_report
    --fail-on-recompile.  A recompiling stream must fail the bundle and
    surface the schema-v8 recompile_cause diagnosis."""
    ci_gate = _load_tool("ci_gate")
    clean = tmp_path / "clean.jsonl"
    clean.write_text(json.dumps(
        {"record": "compile_event", "time": 1.0, "name": "train_step",
         "compile_ms": 10.0, "n_compiles": 1}) + "\n")
    assert ci_gate.main(["--stream", str(clean)]) == 0
    out = capsys.readouterr().out
    assert "graftlint --fail-on-new: PASS" in out
    assert "ci_gate: PASS" in out

    recompiled = tmp_path / "re.jsonl"
    with open(recompiled, "w") as fh:
        for n in (1, 2):
            rec = {"record": "compile_event", "time": float(n),
                   "name": "train_step", "compile_ms": 10.0,
                   "n_compiles": n}
            if n == 2:
                rec["recompile_cause"] = "first divergent op: convert"
            fh.write(json.dumps(rec) + "\n")
    assert ci_gate.main(["--stream", str(recompiled)]) == 1
    out = capsys.readouterr().out
    assert "FAIL" in out
    assert "first divergent op: convert" in out    # diagnosis rendered

    assert ci_gate.main(["--stream", str(tmp_path / "missing.jsonl")]) \
        == 2
    # usage errors stay 2 end-to-end (not collapsed into gate-failure 1)
    assert ci_gate.main(["--baseline",
                         str(tmp_path / "no_such_baseline.json")]) == 2


def test_schema_v8_recompile_cause_validates():
    """Thin-client schema check without importing the package: load
    obs/schema.py by file path (the metrics_lint pattern)."""
    spec = importlib.util.spec_from_file_location(
        "schema_under_test",
        os.path.join(REPO, "apex_example_tpu", "obs", "schema.py"))
    schema = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(schema)
    assert schema.SCHEMA_VERSION >= 9   # v8's tables are a subset since
    rec = {"record": "compile_event", "time": 1.0, "name": "f",
           "compile_ms": 5.0, "n_compiles": 2,
           "recompile_cause": "first divergent op: convert"}
    assert schema.validate_record(rec) == []
    assert schema.validate_record({**rec, "recompile_cause": 3})
