"""Block-paged KV cache (serve/slots.py; ISSUE 8).

- BlockAllocator unit coverage: deterministic alloc/free order,
  refcounts, the chain-keyed prefix index (full-block walk + partial
  overlap), immutability/COW bookkeeping, LRU reuse of zero-ref cached
  blocks, deterministic out-of-blocks.
- BlockPool budgets: worst-case reservation at admission, can_admit
  gating while a slot is free but blocks are not, eviction returning
  both blocks and reservation (no compiled step involved — the pool's
  construction is an abstract init trace).
- Engine-level acceptance: shared-prefix and chunked-prefill greedy
  outputs token-identical to one-shot generate(), COW actually firing
  with refcounted sharing, the zero-output-budget rejection satellite,
  and block-budget head-of-line queueing keeping FIFO order.

Engine tests ride the session's SLOTS=4 / MAX_LEN=32 / block-size-8
geometry, so the ONE paged decode program test_serve.py already
compiles serves here too (suite-budget constraint: no new compiles).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_example_tpu.models.gpt import generate, gpt_tiny
from apex_example_tpu.serve import (BlockAllocator, BlockPool, Request,
                                    ServeEngine, synthetic_requests)

pytestmark = pytest.mark.serve

SLOTS, MAX_LEN, BS = 4, 32, 8


@pytest.fixture(scope="module")
def model_and_params():
    model = gpt_tiny()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _run(model, params, requests, rng_seed=0, **kw):
    eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                      rng=jax.random.PRNGKey(rng_seed), **kw)
    eng.queue.submit_all(requests)
    eng.queue.close()
    eng.run(max_steps=2000)
    return eng


def _ref_tokens(model, params, prompt, n):
    P = len(prompt)
    ref = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_len=MAX_LEN)
    return np.asarray(ref)[0, P:P + n].tolist()


# ============================ allocator =============================

def test_allocator_alloc_free_deterministic():
    a = BlockAllocator(4, 8)
    assert a.available() == 4 and a.blocks_in_use == 0
    got = [a.alloc() for _ in range(4)]
    assert got == [0, 1, 2, 3]               # deterministic pop order
    assert a.available() == 0 and a.blocks_in_use == 4
    with pytest.raises(RuntimeError, match="out of KV blocks"):
        a.alloc()
    a.unref(2)
    assert a.available() == 1
    assert a.alloc() == 2                    # unindexed free: LIFO reuse
    with pytest.raises(RuntimeError, match="unref of free"):
        a.unref(2)
        a.unref(2)
    with pytest.raises(ValueError, match="num_blocks"):
        BlockAllocator(0, 8)
    with pytest.raises(ValueError, match="block_size"):
        BlockAllocator(4, 0)


def test_allocator_refcount_sharing():
    a = BlockAllocator(4, 4)
    b0 = a.alloc()
    assert a.refcount[b0] == 1 and not a.immutable(b0)
    key = a.register_full(None, (1, 2, 3, 4), b0)
    assert a.immutable(b0)
    a.ref(b0)                                # second slot maps it
    assert a.refcount[b0] == 2
    a.unref(b0)
    a.unref(b0)
    # zero refs + indexed: parks in the reusable cache, still matchable
    assert a.available() == 4
    shared, bids, keys = a.match_prefix([1, 2, 3, 4, 9])
    assert shared == 4 and bids == [b0] and keys == [key]


def test_allocator_prefix_chain_and_partial_overlap():
    a = BlockAllocator(8, 4)
    # chain: block A = tokens 0..3, block B = 4..7 (child of A)
    ba, bb = a.alloc(), a.alloc()
    ka = a.register_full(None, (10, 11, 12, 13), ba)
    a.register_full(ka, (14, 15, 16, 17), bb)
    # exact 2-block walk, capped one short of the full prompt
    shared, bids, _ = a.match_prefix([10, 11, 12, 13, 14, 15, 16, 17])
    assert shared == 7 and bids == [ba, bb]
    # full chain + divergent tail: only the matching prefix is shared
    shared, bids, _ = a.match_prefix([10, 11, 12, 13, 99, 15])
    assert shared == 4 and bids == [ba]
    # partial overlap INTO an indexed block (the COW case): 2 tokens of
    # B match, so B is mapped read-only for positions 4-5
    shared, bids, _ = a.match_prefix([10, 11, 12, 13, 14, 15, 99])
    assert shared == 6 and bids == [ba, bb]
    # chain keys encode the whole prefix: same content under a
    # different parent must NOT match
    bc = a.alloc()
    a.register_full(None, (14, 15, 16, 17), bc)
    shared, bids, _ = a.match_prefix([14, 15, 16, 17, 1])
    assert shared == 4 and bids == [bc]      # root chain, not A's child
    # no match at all
    assert a.match_prefix([1, 2, 3])[0] == 0


def test_allocator_lru_reuse_eviction():
    a = BlockAllocator(2, 2)
    b0, b1 = a.alloc(), a.alloc()
    k0 = a.register_full(None, (1, 2), b0)
    a.register_full(None, (3, 4), b1)
    a.unref(b0)                              # parked first -> LRU oldest
    a.unref(b1)
    assert a.available() == 2
    # allocation under pressure evicts the LRU reusable block (b0) and
    # deregisters its index entry; b1's stays matchable
    got = a.alloc()
    assert got == b0
    assert a.match_prefix([1, 2, 9])[0] == 0          # k0 evicted
    assert a.match_prefix([3, 4, 9])[0] == 2          # b1 still cached
    assert k0 not in a._index


def test_allocator_duplicate_chain_keeps_first():
    a = BlockAllocator(4, 2)
    b0, b1 = a.alloc(), a.alloc()
    a.register_full(None, (5, 6), b0)
    a.register_full(None, (5, 6), b1)        # same chain, parallel slot
    shared, bids, _ = a.match_prefix([5, 6, 7])
    assert bids == [b0]                      # first registration wins
    assert a.immutable(b1)                   # duplicate still immutable
    a.unref(b1)
    assert a.available() == 3                # unindexed: plain free


# ============================ pool budgets ==========================

def test_pool_reservation_and_can_admit(model_and_params):
    """Worst-case block budgets gate admission even with a slot free,
    and eviction returns blocks + unspent reservation."""
    model, _ = model_and_params
    pool = BlockPool(model, num_slots=2, max_len=16, block_size=8,
                     num_blocks=2)
    # r1 needs ceil((3+13)/8) = 2 blocks -> the whole arena
    r1 = Request(prompt=[1, 2, 3], max_new_tokens=16)
    r2 = Request(prompt=[4, 5, 6], max_new_tokens=16)
    assert pool.blocks_needed(r1) == 2 and pool.fits(r1)
    assert pool.can_admit(r1)
    idx = pool.admit(r1, step=0)
    assert pool.free_count == 1              # a slot IS free...
    assert not pool.can_admit(r2)            # ...but no block budget
    assert pool.blocks_committed() == 2
    pool.evict(idx)
    assert pool.can_admit(r2)                # budget released
    assert pool.blocks_committed() == 0
    # a request that can NEVER fit is rejected up front, not queued
    huge = Request(prompt=[1] * 15, max_new_tokens=1)   # 2 blocks, fits
    assert pool.fits(huge)
    pool2 = BlockPool(model, num_slots=1, max_len=16, block_size=8,
                      num_blocks=1)
    assert not pool2.fits(huge)              # needs 2 > arena's 1
    full = Request(prompt=[1] * 16, max_new_tokens=4)
    assert pool.max_new_for(full) == 0 and not pool.fits(full)


def test_pool_stage_commit_cow(model_and_params):
    """stage_writes maps/COWs exactly the tick's span; commit_writes
    registers blocks as they fill; a second slot sharing the chain
    triggers COW at its first divergent write."""
    model, _ = model_and_params
    pool = BlockPool(model, num_slots=2, max_len=16, block_size=8)
    ra = Request(prompt=list(range(100, 110)), max_new_tokens=6)  # 10+6
    ia = pool.admit(ra, step=0)
    assert pool.slots[ia].reserved == 2
    assert pool.stage_writes(ia, 8) == (-1, -1)        # fresh block 0
    pool.commit_writes(ia, 8)                          # block 0 full
    assert pool.slots[ia].block_keys[0] is not None    # registered
    assert pool.alloc.immutable(int(pool.table[ia, 0]))
    assert pool.stage_writes(ia, 2) == (-1, -1)        # fresh block 1
    pool.commit_writes(ia, 2)
    assert pool.slots[ia].reserved == 0
    # rb shares ra's full block 0 (8 of its 10 prompt tokens)...
    rb = Request(prompt=list(range(100, 110)), max_new_tokens=6)
    ib = pool.admit(rb, step=1)
    slot_b = pool.slots[ib]
    assert slot_b.shared_len == 8 and slot_b.cursor == 8
    assert int(pool.table[ib, 0]) == int(pool.table[ia, 0])
    assert pool.alloc.refcount[int(pool.table[ia, 0])] == 2
    assert pool.prefix_hit_rate() == 8 / 20
    # ...and rb's first write lands in a FRESH block 1, no COW (ra's
    # block 1 is mutable/private, not indexed, so it never matched)
    src, dst = pool.stage_writes(ib, 2)
    assert (src, dst) == (-1, -1)
    assert int(pool.table[ib, 1]) != int(pool.table[ia, 1])
    pool.commit_writes(ib, 2)
    # now force the COW case: evict ra (its block 1 stays mutable ->
    # freed; block 0 parks reusable), fill a slot whose prompt overlaps
    # partway into a REGISTERED block
    pool.evict(ia)
    pool.evict(ib)
    rc = Request(prompt=list(range(100, 112)), max_new_tokens=2)  # 12+2
    ic = pool.admit(rc, step=2)
    slot_c = pool.slots[ic]
    assert slot_c.shared_len == 8            # full block 0 only
    cows_before = pool.cow_copies
    src, dst = pool.stage_writes(ic, 4)
    assert (src, dst) == (-1, -1) and pool.cow_copies == cows_before
    pool.commit_writes(ic, 4)                # block 1 (12 tokens) not full
    pool.evict(ic)
    # rd overlaps 4 tokens into rc's... rc's block 1 never filled, so
    # build the COW against a filled chain: re-admit rc's twin and run
    # it to fill block 1, then share partially into it
    re_ = Request(prompt=list(range(100, 112)), max_new_tokens=6)  # 12+6
    ie = pool.admit(re_, step=3)
    assert pool.slots[ie].cursor == 8        # rode block 0 again
    pool.stage_writes(ie, 4)                 # remaining prompt chunk
    pool.commit_writes(ie, 4)                # cursor 12
    for g in range(4):                       # decode through 16, engine
        pool.slots[ie].tokens.append(200 + g)  # order: append after
        pool.stage_writes(ie, 1)               # the PREVIOUS commit
        pool.commit_writes(ie, 1)
    assert pool.slots[ie].cursor == 16
    assert pool.slots[ie].block_keys[1] is not None  # block 1 full
    rf = Request(prompt=list(range(100, 111)), max_new_tokens=4)  # 11+4
    if_ = pool.admit(rf, step=4)
    assert pool.slots[if_].shared_len == 10  # 8 + 2-token overlap
    assert pool.alloc.refcount[int(pool.table[ie, 1])] == 2
    src, dst = pool.stage_writes(if_, 1)     # first divergent write
    assert src == int(pool.table[ie, 1]) and dst >= 0
    assert pool.cow_copies == cows_before + 1
    assert int(pool.table[if_, 1]) == dst    # remapped to the copy
    assert pool.alloc.refcount[src] == 1     # back to ie alone


# ====================== engine-level acceptance =====================

def test_shared_prefix_token_identity_and_cow(model_and_params):
    """The gold standard under prefix sharing: a --shared-prefix-style
    workload (20-token common system prompt: two full shared blocks
    PLUS a 4-token overlap into the third) stays token-identical to
    one-shot generate() per request, while the pool actually shares
    (hit rate > 0, refcounted blocks) and copy-on-writes at the first
    divergent token inside the partially-shared block."""
    model, params = model_and_params
    reqs = synthetic_requests(6, vocab_size=model.vocab_size, seed=7,
                              prompt_len=(3, 6), max_new=(4, 8),
                              stagger=3, shared_prefix=20)
    assert all(r.prompt[:20] == reqs[0].prompt[:20] for r in reqs)
    eng = _run(model, params, reqs)
    assert eng.counts["ok"] == 6
    for c in eng.completions:
        assert c.tokens == _ref_tokens(model, params,
                                       list(c.request.prompt),
                                       len(c.tokens)), c.request.uid
    # the 20-token prefix rides 2 full shared blocks per later arrival
    assert eng.pool.prefix_hit_rate() > 0.4
    assert eng.pool.cow_copies >= 1          # divergence inside block 2
    s = eng.summary_record()
    assert s["prefix_hit_rate"] == round(eng.pool.prefix_hit_rate(), 4)
    assert s["cow_copies"] == eng.pool.cow_copies
    # sharing packs the arena: waste stays under the acceptance bar
    # even with every request carrying a 16-token system prompt
    assert s["kv_waste_pct"] <= 40.0


def test_chunked_prefill_token_identity_and_speed(model_and_params):
    """A prompt spanning multiple blocks prefills at up to block_size
    tokens per tick through the same compiled step: outputs stay
    token-identical to generate(), and TTFT-in-ticks collapses from
    n_prompt to ceil(n_prompt / block_size)."""
    model, params = model_and_params
    prompt = [int(t) for t in
              np.random.RandomState(11).randint(0, model.vocab_size, 20)]
    req = Request(prompt=prompt, max_new_tokens=8)
    eng = _run(model, params, [req])
    comp = eng.completions[0]
    assert comp.status == "ok" and len(comp.tokens) == 8
    assert comp.tokens == _ref_tokens(model, params, prompt, 8)
    # 3 prefill ticks (8+8+4 tokens; the first token arrives with the
    # prompt-crossing chunk) + 7 more decode ticks
    assert eng.step_count == 10
    # mixed with short requests: chunked prefill must not perturb a
    # concurrently decoding slot's stream
    short = Request(prompt=[5, 9, 13], max_new_tokens=10)
    long_ = Request(prompt=prompt, max_new_tokens=6, arrival_step=2)
    eng2 = _run(model, params, [short, long_])
    assert eng2.counts["ok"] == 2
    for c in eng2.completions:
        assert c.tokens == _ref_tokens(model, params,
                                       list(c.request.prompt),
                                       len(c.tokens)), c.request.uid


def test_admission_rejects_zero_output_budget(model_and_params,
                                              tmp_path):
    """The ISSUE 8 satellite bugfix: a request whose prompt fills the
    cache (max_new_for == 0) used to occupy a slot and 'complete' with
    zero tokens; now it terminates at admission with first-class
    status 'rejected' (request_failed record, summary count,
    availability debit) and never touches a slot."""
    from apex_example_tpu import obs
    from apex_example_tpu.obs import schema as obs_schema
    model, params = model_and_params
    path = str(tmp_path / "rej.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    emitter = obs.TelemetryEmitter(sink)
    emitter.run_header(config={}, arch="gpt_tiny")
    full = Request(prompt=list(range(MAX_LEN)), max_new_tokens=4)
    okr = Request(prompt=[1, 2, 3], max_new_tokens=4)
    eng = _run(model, params, [full, okr], sink=sink,
               run_id=emitter.run_id)
    sink.write(eng.summary_record())
    sink.close()
    assert eng.counts["rejected"] == 1 and eng.counts["ok"] == 1
    comp = next(c for c in eng.completions if c.request is full)
    assert comp.status == "rejected" and comp.slot == -1
    assert comp.tokens == [] and comp.ttft_s is None
    recs = obs.read_jsonl(path)
    assert obs_schema.validate_stream(recs) == []
    failed = next(r for r in recs if r["record"] == "request_failed")
    assert failed["status"] == "rejected"
    assert failed["request_id"] == full.uid
    summary = recs[-1]
    assert summary["rejected"] == 1 and summary["completed"] == 1
    assert summary["availability"] == 0.5


def test_block_budget_queueing_is_fifo(model_and_params):
    """Out-of-blocks at admission resolves as deterministic
    head-of-line queueing: with a 12-block arena, three hogs book the
    whole arena (4 blocks each) while a SLOT still sits free — the
    tiny head request waits at the queue front (the later arrival does
    not jump it), admits as soon as an eviction frees its budget, and
    every request completes token-identically.  (The default arena is
    dense-capacity sized, where a free slot always implies free
    blocks; shrinking it is the only way to exercise this path — the
    one extra decode-step compile in the suite, ~tiny-GPT sized.)"""
    model, params = model_and_params
    hogs = [Request(prompt=[i + 1] * 8, max_new_tokens=24)
            for i in range(3)]                    # 4 blocks each -> 12
    tiny = Request(prompt=[60, 61], max_new_tokens=2)     # 1 block
    late = Request(prompt=[70, 71, 72], max_new_tokens=2,
                   arrival_step=1)                # behind tiny in FIFO
    eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                      num_blocks=12, rng=jax.random.PRNGKey(0))
    eng.queue.submit_all(hogs + [tiny, late])
    eng.queue.close()
    eng.step()
    # hogs admitted and fully booked; tiny is BLOCK-gated though a
    # slot is free, and holds the line for late (FIFO preserved)
    assert sorted(c.request.uid for c in eng.completions) == []
    assert len(eng.pool.live) == 3 and eng.pool.free_count == 1
    assert eng.pool.blocks_committed() == 12
    assert eng.queue.pending() == 2
    comps = eng.run(max_steps=2000)
    assert eng.counts["ok"] == 5
    by = {c.request.uid: c for c in comps}
    first_evict = min(by[h.uid].finished_step for h in hogs)
    assert by[tiny.uid].admitted_step >= first_evict
    assert by[late.uid].admitted_step >= by[tiny.uid].admitted_step
    for c in comps:
        assert c.tokens == _ref_tokens(model, params,
                                       list(c.request.prompt),
                                       len(c.tokens)), c.request.uid


def test_loadgen_shared_prefix():
    reqs = synthetic_requests(4, vocab_size=100, seed=3, stagger=2,
                              shared_prefix=6, prompt_len=(2, 4))
    head = reqs[0].prompt[:6]
    assert len(head) == 6
    for r in reqs:
        assert list(r.prompt[:6]) == list(head)
        assert 8 <= len(r.prompt) <= 10          # 6 + sampled 2..4
    # deterministic under the seed, including the prefix draw
    again = synthetic_requests(4, vocab_size=100, seed=3, stagger=2,
                               shared_prefix=6, prompt_len=(2, 4))
    assert [r.prompt for r in reqs] == [r.prompt for r in again]
    with pytest.raises(ValueError, match="shared_prefix"):
        synthetic_requests(2, vocab_size=100, shared_prefix=-1)


def test_queue_push_front_preserves_fifo():
    from apex_example_tpu.serve import RequestQueue
    q = RequestQueue()
    a = Request(prompt=[1], max_new_tokens=1)
    b = Request(prompt=[2], max_new_tokens=1)
    q.submit_all([a, b])
    q.close()                                # engine hand-back still works
    got = q.pop(0)
    assert got is a
    q.push_front(got)
    assert q.pop(0) is a and q.pop(0) is b
