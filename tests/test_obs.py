"""Observability subsystem coverage: meter math (utils/meters.py), the
obs/ registry + JSONL schema round-trip, spans, profiler windows, and the
tier-1 telemetry smoke test the ISSUE acceptance bar names — a 10-step C1
run with --metrics-jsonl validated by tools/metrics_lint.py."""

import importlib.util
import json
import os
import time

import jax
import jax.numpy as jnp
import pytest

import train as train_mod
from apex_example_tpu import obs
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.utils.meters import AverageMeter, Throughput

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------- meters

def test_average_meter_math():
    m = AverageMeter("loss")
    m.update(2.0)
    m.update(4.0, n=3)
    assert m.val == 4.0
    assert m.count == 4
    assert m.avg == pytest.approx((2.0 + 3 * 4.0) / 4)
    m.reset()
    assert (m.val, m.sum, m.count, m.avg) == (0.0, 0.0, 0, 0.0)


def test_throughput_zero_warmup_counts_from_first_step():
    """warmup_steps=0 used to never set the start timestamp (seen_steps
    starts at 1) and report 0.0 forever."""
    thr = Throughput(warmup_steps=0)
    thr.step(100)
    time.sleep(0.01)
    thr.step(100)
    assert thr.items == 200
    assert thr.rate > 0.0


def test_throughput_warmup_skips_items():
    thr = Throughput(warmup_steps=2)
    thr.step(100)
    assert thr.rate == 0.0          # still warming up
    thr.step(100)
    assert thr.items == 0           # clock starts at end of step 2
    time.sleep(0.01)
    thr.step(100)
    assert thr.items == 100
    assert thr.rate > 0.0


def test_throughput_warmup_longer_than_run():
    thr = Throughput(warmup_steps=5)
    for _ in range(3):
        thr.step(10)
    assert thr.rate == 0.0          # never reached steady state — no crash


# -------------------------------------------------------------- registry

def test_registry_instruments():
    reg = obs.MetricsRegistry()
    reg.counter("steps").inc()
    reg.counter("steps").inc(4)
    reg.gauge("loss").set(2.5)
    for v in [1.0, 2.0, 3.0, 4.0]:
        reg.histogram("t").observe(v)
    snap = reg.snapshot()
    assert snap["steps"] == 5
    assert snap["loss"] == 2.5
    assert snap["t"]["count"] == 4
    assert snap["t"]["mean"] == pytest.approx(2.5)
    assert snap["t"]["min"] == 1.0 and snap["t"]["max"] == 4.0


def test_registry_type_conflict_rejected():
    reg = obs.MetricsRegistry()
    reg.counter("x")
    with pytest.raises(ValueError):
        reg.gauge("x")


def test_counter_rejects_negative():
    with pytest.raises(ValueError):
        obs.MetricsRegistry().counter("c").inc(-1)


# ------------------------------------------------------- sink and schema

def test_jsonl_sink_round_trip(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with obs.JsonlSink(path, rank=0) as sink:
        assert sink.write({"record": "bench", "metric": "m", "value": 1.5,
                           "unit": "x/s"})
    [rec] = obs.read_jsonl(path)
    assert rec == {"record": "bench", "metric": "m", "value": 1.5,
                   "unit": "x/s"}
    assert obs.validate_record(rec) == []


def test_jsonl_sink_rank_awareness(tmp_path):
    path = str(tmp_path / "m.jsonl")
    quiet = obs.JsonlSink(path, rank=1)           # default: rank 0 only
    assert not quiet.write({"record": "bench"})
    assert not os.path.exists(path)
    loud = obs.JsonlSink(path, rank=1, all_ranks=True)
    assert loud.write({"record": "bench", "metric": "m", "value": 1.0,
                       "unit": "u"})
    loud.close()
    assert os.path.exists(path + ".rank1")        # per-host file, no clash


def test_schema_rejects_bad_records():
    assert obs.validate_record({"record": "nope"})
    assert obs.validate_record({"record": "step"})        # missing fields
    good = {"record": "step", "step": 1, "epoch": 0, "loss": 1.0,
            "scale": 1.0, "step_time_ms": 5.0, "items_per_sec": 10.0}
    assert obs.validate_record(good) == []
    assert obs.validate_record({**good, "typo_field": 1})  # unknown field
    assert obs.validate_record({**good, "loss": "high"})   # wrong type


def test_schema_stream_invariants():
    header = {"record": "run_header", "schema": 1, "time": 0.0,
              "run_id": "a", "num_devices": 1, "process_index": 0,
              "platform": "cpu", "config": {}}
    step = {"record": "step", "step": 1, "epoch": 0, "loss": 1.0,
            "scale": 1.0, "step_time_ms": 5.0, "items_per_sec": 10.0}
    assert obs_schema.validate_stream([header, step]) == []
    # header not first, and duplicated
    assert obs_schema.validate_stream([step, header, header])


# ----------------------------------------------------------------- spans

def test_spans_nest_and_record():
    reg = obs.MetricsRegistry()
    with obs.span("outer", registry=reg) as outer:
        with obs.span("inner", registry=reg) as inner:
            time.sleep(0.005)
    assert outer.children == [inner]
    assert inner.dur_ms >= 5.0
    assert outer.dur_ms >= inner.dur_ms
    snap = reg.snapshot()
    assert snap["span.outer"]["count"] == 1
    assert snap["span.outer.inner"]["count"] == 1   # dotted nesting path
    assert obs.current_span() is None               # stack unwound


def test_device_span_traces():
    """device_span is jax.named_scope — must be usable inside jit."""
    @jax.jit
    def f(x):
        with obs.device_span("fwd_bwd"):
            return x * 2
    assert float(f(jnp.float32(3.0))) == 6.0


# ------------------------------------------------------ profiler windows

def test_parse_window():
    assert obs.parse_window("2:5") == (2, 5)
    assert obs.parse_window("7:7") == (7, 7)
    for bad in ("5", "0:3", "4:2", "a:b", "1:2:3"):
        with pytest.raises(ValueError):
            obs.parse_window(bad)


def test_prof_and_window_mutually_exclusive():
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "resnet18", "--prof",
                        "--profile-window", "1:2"])


def test_bad_window_rejected_at_cli():
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "resnet18", "--profile-window", "3:1"])


# ----------------------------------------------------------- rank_print

def test_rank_print_is_print_on_rank0(capsys):
    obs.rank_print("hello", 42, sep="|")
    assert capsys.readouterr().out == "hello|42\n"


# ------------------------------------------------------------- telemetry

def test_emitter_records_and_lints(tmp_path):
    path = str(tmp_path / "t.jsonl")
    emitter = obs.TelemetryEmitter(obs.JsonlSink(path, rank=0))
    emitter.run_header(config={"arch": "x", "steps": 3}, argv=["--x"])
    for i in range(3):
        t0 = time.perf_counter()
        metrics = {"loss": jnp.float32(1.0 + i), "scale": jnp.float32(8.0),
                   "grads_finite": jnp.float32(0.0 if i == 1 else 1.0),
                   "grad_norm": jnp.float32(0.5)}
        emitter.on_step(global_step=i + 1, epoch=0, metrics=metrics,
                        items=64, t_start=t0)
    emitter.close()
    records = obs.read_jsonl(path)
    assert [r["record"] for r in records] == \
        ["run_header"] + ["step"] * 3 + ["run_summary"]
    assert records[2]["overflow_count"] == 1      # the i==1 overflow step
    assert records[-1]["overflow_count"] == 1
    assert "first_step_ms" in records[-1]
    lint = _load_tool("metrics_lint")
    code, errors = lint.lint(path, require=["grad_norm"], steps=3)
    assert code == 0, errors


# --------------------------------------- tier-1 CLI smoke (ISSUE gate)

C1_ARGS = ["--arch", "resnet18", "--dataset", "cifar10", "--opt-level",
           "O0", "--epochs", "1", "--steps-per-epoch", "10",
           "--batch-size", "16", "--num-devices", "1", "--print-freq", "5"]


def test_c1_metrics_jsonl_schema_valid(tmp_path, capsys):
    """The acceptance bar: a 10-step C1 CPU run with --metrics-jsonl emits
    one schema-valid step record per step (loss, scale, step_time_ms,
    items_per_sec, grad_norm) plus a run header, verified by
    tools/metrics_lint.py — and the default stdout meters stay intact."""
    path = str(tmp_path / "c1.jsonl")
    assert train_mod.main(C1_ARGS + ["--metrics-jsonl", path]) == 0
    out = capsys.readouterr().out
    assert "epoch 0 step 10/10" in out            # stdout contract intact

    lint = _load_tool("metrics_lint")
    code, errors = lint.lint(
        path, steps=10,
        require=["loss", "scale", "step_time_ms", "items_per_sec",
                 "grad_norm"])
    assert code == 0, errors
    assert lint.main([path, "--steps", "10", "--require", "grad_norm"]) == 0

    records = obs.read_jsonl(path)
    header = records[0]
    assert header["record"] == "run_header"
    assert header["config"]["arch"] == "resnet18"
    steps = [r for r in records if r["record"] == "step"]
    assert [r["step"] for r in steps] == list(range(1, 11))
    # report tool runs over the same file
    report = _load_tool("telemetry_report")
    assert report.main([path]) == 0


def test_profile_window_cli(tmp_path, monkeypatch):
    """--profile-window N:M captures a trace for just that window."""
    import apex_example_tpu.obs.profiler as prof_mod
    logdir = str(tmp_path / "trace")
    monkeypatch.setattr(prof_mod, "DEFAULT_TRACE_DIR", logdir)
    args = ["--arch", "resnet18", "--dataset", "cifar10", "--opt-level",
            "O0", "--epochs", "1", "--steps-per-epoch", "4",
            "--batch-size", "8", "--num-devices", "1", "--print-freq", "4",
            "--profile-window", "2:3"]
    assert train_mod.main(args) == 0
    assert os.path.isdir(logdir) and os.listdir(logdir)


def test_bench_emit_writes_schema_valid_record(tmp_path, capsys, monkeypatch):
    """bench._emit mirrors its stdout JSON line into the sink as a 'bench'
    record (vs_baseline null on stdout, omitted in the sink)."""
    import bench as bench_mod
    path = str(tmp_path / "b.jsonl")
    monkeypatch.setattr(bench_mod, "_SINK", obs.JsonlSink(path, rank=0))
    bench_mod._emit("m", 123.45, "img/s", None)
    bench_mod._SINK.close()
    line = capsys.readouterr().out.strip()
    assert json.loads(line) == {"metric": "m", "value": 123.5,
                                "unit": "img/s", "vs_baseline": None}
    [rec] = obs.read_jsonl(path)
    assert rec["record"] == "bench" and "vs_baseline" not in rec
    assert obs.validate_record(rec) == []
