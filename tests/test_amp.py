"""AMP policy + loss scaler unit tests (reference pyramid: tests/L0/run_amp;
SURVEY.md §5 — opt-level property semantics, overflow/skip/growth schedule,
checkpoint round-trip of scaler state)."""

import jax
import jax.numpy as jnp
import pytest

from apex_example_tpu import amp


class TestPolicyTable:
    def test_o0_is_fp32_noop(self):
        p = amp.get_policy("O0")
        assert p.param_dtype == jnp.float32
        assert p.compute_dtype == jnp.float32
        assert not p.master_weights
        assert p.static_scale == 1.0

    def test_o1_boundary_casts(self):
        p = amp.get_policy("O1")
        assert p.param_dtype == jnp.float32
        assert p.compute_dtype == jnp.bfloat16
        assert p.bn_dtype == jnp.float32
        assert p.cast_at_call_sites

    def test_o2_master_weights_bn_fp32(self):
        p = amp.get_policy("O2")
        assert p.compute_dtype == jnp.bfloat16
        assert p.bn_dtype == jnp.float32
        assert p.master_weights
        # bf16: static scaling by default (fp32-equal exponent range).
        assert not p.uses_dynamic_scaling

    def test_o2_fp16_is_dynamic(self):
        p = amp.get_policy("O2", half_dtype=jnp.float16)
        assert p.uses_dynamic_scaling

    def test_o3_pure_half(self):
        p = amp.get_policy("O3")
        assert p.param_dtype == jnp.bfloat16
        assert p.bn_dtype == jnp.bfloat16

    def test_overrides(self):
        p = amp.get_policy("O2", loss_scale=128.0)
        assert p.static_scale == 128.0
        p = amp.get_policy("O2", loss_scale="dynamic")
        assert p.uses_dynamic_scaling
        p = amp.get_policy("O3", keep_batchnorm_fp32=True)
        assert p.bn_dtype == jnp.float32

    def test_bad_level_raises(self):
        with pytest.raises(ValueError):
            amp.get_policy("O4")


class TestScaler:
    def _dyn(self, **kw):
        p = amp.get_policy("O2", loss_scale="dynamic")
        return amp.make_scaler(p, **kw)

    def test_scale_unscale_roundtrip(self):
        s = self._dyn(init_scale=2.0 ** 8)
        loss = jnp.asarray(3.0)
        scaled = amp.scale_loss(loss, s)
        assert scaled == 3.0 * 256.0
        grads = {"w": jnp.full((4,), 256.0)}
        un, finite = amp.unscale_grads(grads, s)
        assert bool(finite)
        assert jnp.allclose(un["w"], 1.0)

    def test_overflow_backoff_and_growth(self):
        s = self._dyn(init_scale=2.0 ** 16, growth_interval=3)
        grads = {"w": jnp.array([jnp.inf, 1.0])}
        _, finite = amp.unscale_grads(grads, s)
        assert not bool(finite)
        s2 = amp.update_scaler(s, finite)
        assert float(s2.scale) == 2.0 ** 15      # ×0.5 backoff
        assert int(s2.growth_counter) == 0
        # 3 clean steps → ×2 growth.
        clean = jnp.asarray(True)
        for _ in range(3):
            s2 = amp.update_scaler(s2, clean)
        assert float(s2.scale) == 2.0 ** 16
        assert int(s2.growth_counter) == 0

    def test_static_scaler_ignores_updates(self):
        p = amp.get_policy("O2")          # static
        s = amp.make_scaler(p)
        s2 = amp.update_scaler(s, jnp.asarray(False))
        assert float(s2.scale) == float(s.scale)

    def test_nan_detected(self):
        s = self._dyn()
        _, finite = amp.unscale_grads({"w": jnp.array([jnp.nan])}, s)
        assert not bool(finite)

    def test_state_dict_roundtrip(self):
        s = self._dyn(init_scale=4096.0)
        s = amp.update_scaler(s, jnp.asarray(True))
        d = amp.state_dict(s)
        fresh = self._dyn()
        restored = amp.load_state_dict(fresh, d)
        assert float(restored.scale) == 4096.0
        assert int(restored.growth_counter) == 1

    def test_update_traced_in_jit(self):
        s = self._dyn(growth_interval=2)

        @jax.jit
        def f(scaler, flag):
            return amp.update_scaler(scaler, flag)

        s2 = f(s, jnp.asarray(False))
        assert float(s2.scale) == float(s.scale) * 0.5

    def test_select_tree_skip_step(self):
        old = {"w": jnp.zeros(3)}
        new = {"w": jnp.ones(3)}
        kept = amp.select_tree(jnp.asarray(False), new, old)
        assert jnp.allclose(kept["w"], 0.0)
        taken = amp.select_tree(jnp.asarray(True), new, old)
        assert jnp.allclose(taken["w"], 1.0)


def test_initialize_frontend():
    policy, scaler = amp.initialize(opt_level="O2", loss_scale="dynamic",
                                    init_scale=1024.0)
    assert policy.opt_level == "O2"
    assert scaler.dynamic
    assert float(scaler.scale) == 1024.0


class TestOpClassification:
    """The O1 engine: white/blacklist tables → per-boundary dtypes
    (reference: apex/amp/lists + wrap.py; SURVEY.md §3.1).  These tests pin
    the BEHAVIORAL differences between O1, O2 and O3."""

    def test_module_dtypes_table(self):
        o1 = amp.module_dtypes(amp.get_policy("O1"))
        o2 = amp.module_dtypes(amp.get_policy("O2"))
        o3 = amp.module_dtypes(amp.get_policy("O3"))
        # whitelist (conv/dense): half under all of O1/O2/O3
        assert o1.compute == o2.compute == o3.compute == jnp.bfloat16
        # blacklist (batch_norm): O1 runs it WHOLLY fp32 (I/O included);
        # O2 keeps only the stats fp32; O3 is pure half.
        assert o1.bn_io == jnp.float32
        assert o2.bn_io == jnp.bfloat16
        assert o3.bn_io == jnp.bfloat16
        assert o2.bn_stats == jnp.float32
        assert o3.bn_stats == jnp.bfloat16
        # blacklist (softmax): fp32 under O1/O2, half under O3.
        assert o1.softmax == jnp.float32
        assert o2.softmax == jnp.float32
        assert o3.softmax == jnp.bfloat16

    def test_op_dtype_only_active_under_o1(self):
        o1, o2 = amp.get_policy("O1"), amp.get_policy("O2")
        assert amp.op_dtype(o1, "conv") == jnp.bfloat16
        assert amp.op_dtype(o1, "softmax") == jnp.float32
        assert amp.op_dtype(o2, "conv") is None   # O2 casts at model build
        # promote: widest participating dtype
        assert amp.op_dtype(o1, "add", jnp.bfloat16, jnp.float32) \
            == jnp.float32

    def test_cast_args(self):
        o1 = amp.get_policy("O1")
        x = jnp.ones((4,), jnp.float32)
        assert amp.cast_args(o1, "dense", x).dtype == jnp.bfloat16
        a, b = amp.cast_args(o1, "add", x.astype(jnp.bfloat16), x)
        assert a.dtype == b.dtype == jnp.float32

    def test_register_functions_move_ops(self):
        from apex_example_tpu.amp import lists
        o1 = amp.get_policy("O1")
        assert amp.op_dtype(o1, "softmax") == jnp.float32
        amp.register_half_function("softmax")
        try:
            assert amp.op_dtype(o1, "softmax") == jnp.bfloat16
        finally:
            amp.register_float_function("softmax")
        assert amp.op_dtype(o1, "softmax") == jnp.float32
        assert "softmax" in lists.FP32_FUNCS

    def test_o1_vs_o2_bn_io_in_model(self):
        """A blacklisted op (batch_norm) runs fp32 under O1 but half under
        O2/O3 in an actual model forward (capture_intermediates)."""
        from apex_example_tpu.models.resnet import BasicBlock, ResNet
        x = jnp.zeros((2, 8, 8, 3), jnp.float32)
        outs = {}
        for lvl in ("O1", "O2", "O3"):
            md = amp.module_dtypes(amp.get_policy(lvl))
            m = ResNet(stage_sizes=[1], block_cls=BasicBlock, num_classes=4,
                       num_filters=8, small_stem=True, dtype=md.compute,
                       param_dtype=md.param, bn_dtype=md.bn_stats,
                       bn_io_dtype=md.bn_io)
            v = m.init(jax.random.PRNGKey(0), x, train=False)
            _, inter = m.apply(v, x, train=False,
                               capture_intermediates=True)
            outs[lvl] = inter["intermediates"]["bn_init"]["__call__"][0]
        assert outs["O1"].dtype == jnp.float32
        assert outs["O2"].dtype == jnp.bfloat16
        assert outs["O3"].dtype == jnp.bfloat16


class TestMultiLoss:
    """num_losses > 1: one independent scaler per loss (reference:
    amp.initialize(num_losses=N) + scale_loss(..., loss_id=i); upstream
    exercises this in L0/run_amp/test_multiple_models_optimizers_losses)."""

    def test_initialize_returns_tuple(self):
        policy, scalers = amp.initialize("O2", loss_scale="dynamic",
                                         num_losses=3)
        assert isinstance(scalers, tuple) and len(scalers) == 3
        assert all(s.dynamic for s in scalers)

    def test_overflow_isolated_per_loss(self):
        _, scalers = amp.initialize("O2", loss_scale="dynamic", num_losses=2)
        s0 = float(scalers[0].scale)
        good = {"w": jnp.ones((4,))}
        bad = {"w": jnp.array([1.0, jnp.inf, 1.0, 1.0])}

        @jax.jit
        def step(scalers):
            _, f0 = amp.unscale_grads(good, scalers, loss_id=0)
            scalers = amp.update_scaler(scalers, f0, loss_id=0)
            _, f1 = amp.unscale_grads(bad, scalers, loss_id=1)
            scalers = amp.update_scaler(scalers, f1, loss_id=1)
            return scalers

        scalers = step(scalers)
        assert float(scalers[0].scale) == s0          # clean loss: unchanged
        assert float(scalers[1].scale) == s0 * 0.5    # overflowed: backoff
        assert int(scalers[0].growth_counter) == 1
        assert int(scalers[1].growth_counter) == 0

    def test_scale_loss_uses_loss_id(self):
        _, scalers = amp.initialize("O2", loss_scale="dynamic", num_losses=2)
        scalers = (scalers[0].replace(scale=jnp.asarray(4.0, jnp.float32)),
                   scalers[1])
        assert float(amp.scale_loss(jnp.asarray(1.0), scalers,
                                    loss_id=0)) == 4.0
        assert float(amp.scale_loss(jnp.asarray(1.0), scalers,
                                    loss_id=1)) == 2.0 ** 16

    def test_state_dict_roundtrip(self):
        _, scalers = amp.initialize("O2", loss_scale="dynamic", num_losses=2)
        scalers = amp.update_scaler(scalers, jnp.asarray(False), loss_id=1)
        d = amp.state_dict(scalers)
        _, fresh = amp.initialize("O2", loss_scale="dynamic", num_losses=2)
        restored = amp.load_state_dict(fresh, d)
        assert float(restored[1].scale) == float(scalers[1].scale)
        assert float(restored[0].scale) == float(scalers[0].scale)


def test_disable_casts_context():
    """amp.handle.disable_casts analog: inside the context the O1 engine
    answers fp32 for every op class; outside, whitelist ops go half."""
    policy, _ = amp.initialize("O1")
    assert amp.op_dtype(policy, "dense") == policy.compute_dtype
    with amp.disable_casts():
        assert amp.op_dtype(policy, "dense") == jnp.float32
        x = jnp.ones((2, 2), jnp.float32)
        assert amp.cast_args(policy, "dense", x).dtype == jnp.float32
    assert amp.op_dtype(policy, "dense") == policy.compute_dtype
