"""GPT decoder-only causal LM (models/gpt.py; train.py --arch gpt_*).

GPT is a beyond-reference extension (the reference family's causal LM is
Transformer-XL via segment recurrence): the model itself is the composition
demo for the framework's parallelisms, so the tests pin (a) causality —
the property the arch is named for, (b) trajectory parity of the TP and CP
forms against the dense model, (c) the CLI surface.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_example_tpu import amp
from apex_example_tpu.data import lm_batch
from apex_example_tpu.engine import (create_train_state, make_train_step)
from apex_example_tpu.models.gpt import gpt_tiny
from apex_example_tpu.optim import FusedSGD
from apex_example_tpu.transformer import parallel_state
from apex_example_tpu.workloads import lm_loss

BATCH, SEQ = 8, 16


def _batch(i, vocab, batch=BATCH, seq=SEQ):
    toks = lm_batch(jnp.asarray(i, jnp.int32), batch_size=batch,
                    seq_len=seq, vocab_size=vocab, seed=0)
    return toks[:, :-1], toks[:, 1:]


def test_causality():
    """Logits at position t must be independent of every token > t — the
    defining property of the decoder-only arch (einsum path)."""
    model = gpt_tiny()
    V = model.vocab_size
    x, _ = _batch(0, V)
    params = model.init(jax.random.PRNGKey(0), x[:1])["params"]
    logits = model.apply({"params": params}, x, train=False)
    t = SEQ // 2
    x2 = x.at[:, t + 1:].set((x[:, t + 1:] + 7) % V)  # perturb the future
    logits2 = model.apply({"params": params}, x2, train=False)
    np.testing.assert_allclose(np.asarray(logits[:, :t + 1]),
                               np.asarray(logits2[:, :t + 1]),
                               rtol=1e-6, atol=1e-6)
    # sanity: the perturbation DID change later positions
    assert not np.allclose(np.asarray(logits[:, t + 1:]),
                           np.asarray(logits2[:, t + 1:]), atol=1e-3)


def test_flash_matches_einsum():
    """fused_attention=True (kernel/reference fallback) == einsum path for
    the causal mask."""
    dense = gpt_tiny(fused_attention=False)
    flash = gpt_tiny(fused_attention=True)
    V = dense.vocab_size
    x, _ = _batch(0, V)
    params = dense.init(jax.random.PRNGKey(0), x[:1])["params"]
    a = dense.apply({"params": params}, x, train=False)
    b = flash.apply({"params": params}, x, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)


def test_gpt_tp_matches_dense(devices8):
    """30 lockstep TP train steps on a (data=2, model=4) mesh == dense."""
    from apex_example_tpu.engine import (create_gspmd_train_state,
                                         make_gspmd_train_step)
    from apex_example_tpu.ops import _config as ops_config
    mesh = parallel_state.initialize_model_parallel(tensor_parallel=4,
                                                    devices=devices8)
    ops_config.set_force_xla(True)
    try:
        policy, scaler = amp.initialize("O0")
        dense = gpt_tiny()
        tp_model = gpt_tiny(tensor_parallel=True)
        V = dense.vocab_size
        opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
        sample = _batch(0, V)[0][:1]
        state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                     sample, policy, scaler)
        step_d = jax.jit(make_train_step(dense, opt(), policy,
                                         loss_fn=lm_loss,
                                         compute_accuracy=False))
        state_t, shardings = create_gspmd_train_state(
            jax.random.PRNGKey(0), mesh, tp_model, opt(), sample, policy,
            scaler)
        state_t = state_t.replace(
            params=jax.device_put(state_d.params, shardings.params))
        step_t = make_gspmd_train_step(mesh, tp_model, opt(), policy,
                                       shardings, loss_fn=lm_loss,
                                       compute_accuracy=False, donate=False)
        for i in range(30):
            b = _batch(i, V)
            state_d, m_d = step_d(state_d, b)
            state_t, m_t = step_t(state_t, b)
            np.testing.assert_allclose(float(m_d["loss"]),
                                       float(m_t["loss"]), rtol=3e-5 * (1 + i / 3))
        for (ka, a), (_, b2) in zip(
                jax.tree_util.tree_leaves_with_path(state_d.params),
                jax.tree_util.tree_leaves_with_path(state_t.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                       rtol=1e-3, atol=3e-5,
                                       err_msg=str(ka))
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


@pytest.mark.parametrize("mode", ["ring", "zigzag", "ulysses"])
def test_gpt_cp_matches_dense(devices8, mode):
    """30 lockstep CP train steps on a (data=2, context=4) mesh == dense for
    EVERY attention program: "ring" pins the causal chunk skipping and
    global position-count normalization; "zigzag" additionally composes
    the factory's zigzag_shard pre-pass, the model's zigzag position ids,
    and ring_attention_zigzag's four-pair chunk algebra; "ulysses" pins
    the all-to-all head-sharding exchange (full sequence per device)."""
    from apex_example_tpu.workloads import make_gpt_cp_train_step
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("data", "context"))
    policy, scaler = amp.initialize("O0")
    dense = gpt_tiny()
    cp_model = gpt_tiny(context_parallel=True, cp_mode=mode)
    V = dense.vocab_size
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
    sample = _batch(0, V)[0][:1]
    state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                 sample, policy, scaler)
    step_d = jax.jit(make_train_step(dense, opt(), policy, loss_fn=lm_loss,
                                     compute_accuracy=False))
    state_c = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                 sample, policy, scaler)
    step_c = make_gpt_cp_train_step(mesh, cp_model, opt(), policy,
                                    donate=False, mode=mode)
    for i in range(30):
        b = _batch(i, V)
        state_d, m_d = step_d(state_d, b)
        state_c, m_c = step_c(state_c, b)
        np.testing.assert_allclose(float(m_d["loss"]), float(m_c["loss"]),
                                   rtol=3e-5 * (1 + i / 3))
    for (ka, a), (_, b2) in zip(
            jax.tree_util.tree_leaves_with_path(state_d.params),
            jax.tree_util.tree_leaves_with_path(state_c.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=1e-3, atol=3e-5, err_msg=str(ka))


@pytest.mark.parametrize("sched", ["ring", "1f1b"])
def test_gpt_pp_matches_dense(devices8, sched):
    """30 lockstep pipeline-parallel GPT train steps == dense — the GPT head
    cell (final LN + tied decoder) and the all-ones-weights normalization
    (== next-token mean) inside the schedule are the parts worth pinning."""
    from apex_example_tpu.engine import TrainState
    from apex_example_tpu.transformer.bert_pipeline import (
        bert_pp_state_shardings, make_bert_pp_train_step, pack_params,
        pack_params_1f1b, unpack_params, unpack_params_1f1b)
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("pipe", "data"))
    policy, scaler = amp.initialize("O0")
    model = gpt_tiny()
    V = model.vocab_size
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
    sample = _batch(0, V)[0][:1]
    state_d = create_train_state(jax.random.PRNGKey(0), model, opt(),
                                 sample, policy, scaler)
    step_d = jax.jit(make_train_step(model, opt(), policy, loss_fn=lm_loss,
                                     compute_accuracy=False))
    zopt = opt()
    if sched == "ring":
        packed = pack_params(state_d.params, model.num_layers)
        unp = lambda p: unpack_params(p, model.num_layers)
    else:
        packed = pack_params_1f1b(state_d.params, model.num_layers, 2, 1)
        unp = lambda p: unpack_params_1f1b(p, model.num_layers, 2, 1)
    state_p = TrainState(step=jnp.zeros((), jnp.int32), params=packed,
                         batch_stats={}, opt_state=zopt.init(packed),
                         scaler=state_d.scaler)
    state_p = jax.device_put(
        state_p, bert_pp_state_shardings(mesh, state_p, zopt))
    step_p = make_bert_pp_train_step(mesh, model, zopt, policy,
                                     microbatches=2, donate=False,
                                     schedule=sched)
    for i in range(30):
        b = _batch(i, V)
        state_d, m_d = step_d(state_d, b)
        state_p, m_p = step_p(state_p, b)
        np.testing.assert_allclose(float(m_d["loss"]), float(m_p["loss"]),
                                   rtol=3e-5 * (1 + i / 3))
    key = lambda kv: str(kv[0])
    for (ka, a), (_, b2) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(state_d.params),
                   key=key),
            sorted(jax.tree_util.tree_leaves_with_path(unp(state_p.params)),
                   key=key)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=1e-3, atol=3e-5, err_msg=str(ka))


def test_train_py_cli_gpt_pp(devices8, capsys):
    """GPT rides the pipeline from the CLI (ring + eval via unpack)."""
    import train as train_mod
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "gpt_tiny", "--pipeline-parallel", "2",
            "--microbatches", "2", "--batch-size", str(BATCH),
            "--seq-len", str(SEQ), "--epochs", "1", "--steps-per-epoch",
            "2", "--opt", "adam", "--lr", "1e-3", "--opt-level", "O0",
            "--print-freq", "1", "--eval", "--eval-batches", "2"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        parallel_state.set_mesh(None)
    assert "ppl" in capsys.readouterr().out


def test_train_py_cli_gpt(devices8, capsys):
    """DDP + eval ppl from the CLI."""
    import train as train_mod
    argv = ["--arch", "gpt_tiny", "--batch-size", "16", "--seq-len", "16",
            "--epochs", "1", "--steps-per-epoch", "3", "--opt", "adam",
            "--lr", "1e-3", "--opt-level", "O0", "--print-freq", "1",
            "--eval", "--eval-batches", "2"]
    assert train_mod.main(argv) == 0
    assert "ppl" in capsys.readouterr().out


def test_train_py_cli_gpt_moe(devices8, capsys):
    """MoE GPT: switch-MoE FFNs with the lm objective, EP over 'data'."""
    import train as train_mod
    argv = ["--arch", "gpt_tiny", "--moe-experts", "8",
            "--batch-size", "16", "--seq-len", "16", "--epochs", "1",
            "--steps-per-epoch", "3", "--opt", "adam", "--lr", "1e-3",
            "--opt-level", "O0", "--print-freq", "1",
            "--eval", "--eval-batches", "2"]
    assert train_mod.main(argv) == 0
    assert "ppl" in capsys.readouterr().out


def test_generate_greedy_matches_full_forward():
    """KV-cache greedy decode must equal the argmax chain of full forward
    passes on the growing sequence — exact (fp32): the cached-prefix
    attention adds only zero-contribution masked slots, so any deviation
    is a cache/position bug, not numerics."""
    from apex_example_tpu.models.gpt import generate
    model = gpt_tiny()
    V = model.vocab_size
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, V, (2, 3)), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), prompt)["params"]
    out = generate(model, params, prompt, max_len=10)
    seq = np.array(prompt)
    for _ in range(7):
        logits = model.apply({"params": params},
                             jnp.asarray(seq, jnp.int32), train=False)
        nxt = np.argmax(np.asarray(logits)[:, -1], -1)[:, None]
        seq = np.concatenate([seq, nxt], axis=1)
    np.testing.assert_array_equal(np.array(out), seq)


def test_generate_sampling():
    """temperature > 0: deterministic under a fixed rng, prompt preserved,
    tokens in-vocab; rng required."""
    from apex_example_tpu.models.gpt import generate
    model = gpt_tiny()
    V = model.vocab_size
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, V, (2, 3)), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), prompt)["params"]
    s1 = generate(model, params, prompt, max_len=8, temperature=0.8,
                  rng=jax.random.PRNGKey(7))
    s2 = generate(model, params, prompt, max_len=8, temperature=0.8,
                  rng=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.array(s1), np.array(s2))
    a = np.array(s1)
    assert (a[:, :3] == np.array(prompt)).all()
    assert (a >= 0).all() and (a < V).all()
    with pytest.raises(ValueError, match="rng"):
        generate(model, params, prompt, max_len=8, temperature=0.8)


def test_generate_one_compiled_program_across_sampling_configs():
    """Regression (ISSUE 3 satellite): temperature used to be part of
    _decode_loop's lru_cache key — every distinct temperature recompiled
    the scan.  It now rides as a runtime scalar (with top_k): two
    temperatures, one cache entry."""
    from apex_example_tpu.models.gpt import _decode_loop, generate
    model = gpt_tiny()
    V = model.vocab_size
    prompt = jnp.asarray(
        np.random.RandomState(2).randint(0, V, (2, 3)), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), prompt)["params"]
    before = _decode_loop.cache_info().currsize
    # max_len=11 is unique to this test, so the delta below is exact.
    g = generate(model, params, prompt, max_len=11)
    s1 = generate(model, params, prompt, max_len=11, temperature=0.8,
                  rng=jax.random.PRNGKey(7))
    generate(model, params, prompt, max_len=11, temperature=0.3,
             rng=jax.random.PRNGKey(7), top_k=5)
    assert _decode_loop.cache_info().currsize - before == 1
    # the shared program still distinguishes the configs
    assert not np.array_equal(np.array(g), np.array(s1))
    # top_k=1 collapses to greedy at any temperature
    k1 = generate(model, params, prompt, max_len=11, temperature=1.5,
                  rng=jax.random.PRNGKey(9), top_k=1)
    np.testing.assert_array_equal(np.array(k1), np.array(g))
    with pytest.raises(ValueError, match="top_k"):
        generate(model, params, prompt, max_len=11, top_k=-1)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_gpt_cp_tp_train_matches_dense(devices8, mode):
    """GPT CP x TP: the causal CP attention program over 'context' with
    GSPMD TP attention on the still-automatic 'model' axis — trajectory
    matches dense and the params keep their model-axis sharding (mirror
    of the BERT CP x TP test; the ops-config XLA pin follows the
    train.py path).  "ulysses" additionally pins the manual context-axis
    head all_to_all composing with the auto model-axis head sharding."""
    from apex_example_tpu.engine import gspmd_state_shardings
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    from apex_example_tpu.workloads import make_gpt_cp_train_step
    mesh = parallel_state.initialize_model_parallel(
        tensor_parallel=2, context_parallel=2, devices=devices8)
    ops_config.set_force_xla(True)
    try:
        policy, scaler = amp.initialize("O0")
        dense = gpt_tiny()
        tp_model = gpt_tiny(tensor_parallel=True)
        cp_tp_model = gpt_tiny(tensor_parallel=True, context_parallel=True,
                               cp_mode=mode)
        V = dense.vocab_size
        opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
        sample = _batch(0, V)[0][:1]
        state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                     sample, policy, scaler)
        step_d = jax.jit(make_train_step(dense, opt(), policy,
                                         loss_fn=lm_loss,
                                         compute_accuracy=False))
        state_c = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                     sample, policy, scaler)
        sh = gspmd_state_shardings(mesh, tp_model, opt(), sample, policy)
        state_c = jax.device_put(state_c, sh)
        step_c = make_gpt_cp_train_step(mesh, cp_tp_model, opt(), policy,
                                        donate=False, state_shardings=sh,
                                        mode=mode)
        for i in range(30):
            b = _batch(i, V)
            state_d, m_d = step_d(state_d, b)
            state_c, m_c = step_c(state_c, b)
            np.testing.assert_allclose(float(m_d["loss"]),
                                       float(m_c["loss"]), rtol=3e-5 * (1 + i / 3))
        for a, b in zip(jax.tree_util.tree_leaves(state_d.params),
                        jax.tree_util.tree_leaves(state_c.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=3e-5)
        qk = state_c.params["layer_0"]["attention"]["query"]["kernel"]
        assert qk.addressable_shards[0].data.shape == (64, 32), \
            "query kernel lost its model-axis sharding"
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


def test_train_py_cli_gpt_cp_zigzag(devices8, capsys):
    """Load-balanced causal ring from the CLI."""
    import train as train_mod
    argv = ["--arch", "gpt_tiny", "--context-parallel", "4",
            "--cp-mode", "zigzag",
            "--batch-size", "16", "--seq-len", "16", "--epochs", "1",
            "--steps-per-epoch", "2", "--opt", "adam", "--lr", "1e-3",
            "--opt-level", "O0", "--print-freq", "1",
            "--eval", "--eval-batches", "2"]
    assert train_mod.main(argv) == 0
    assert "ppl" in capsys.readouterr().out


def test_train_py_gpt_rejections():
    import train as train_mod
    base = ["--arch", "gpt_tiny", "--batch-size", "16", "--seq-len", "16",
            "--epochs", "1", "--steps-per-epoch", "1"]
    with pytest.raises(SystemExit):   # non-ring modes need CP
        train_mod.main(base + ["--cp-mode", "zigzag"])
    with pytest.raises(SystemExit):   # zigzag balances the CAUSAL mask
        train_mod.main(["--arch", "bert_tiny", "--context-parallel", "4",
                        "--cp-mode", "zigzag", "--batch-size", "16",
                        "--seq-len", "16", "--epochs", "1",
                        "--steps-per-epoch", "1"])
    with pytest.raises(SystemExit):   # MoE x PP is ring-schedule only and
        train_mod.main(base + ["--moe-experts", "4",    # pairwise (no TP)
                               "--pipeline-parallel", "2",
                               "--tensor-parallel", "2",
                               "--microbatches", "2"])
    with pytest.raises(SystemExit):   # TXL's recurrence spans all layers
        train_mod.main(["--arch", "transformer_xl_tiny",
                        "--pipeline-parallel", "2", "--batch-size", "16",
                        "--seq-len", "16", "--epochs", "1",
                        "--steps-per-epoch", "1"])


def test_generate_tp_matches_dense(devices8):
    """TP-composed generation (VERDICT r4 item 7): greedy decode of the
    tensor_parallel model on a (data=2, model=4) mesh — KV caches sharded
    over heads on the 'model' axis via the layers' constraint points —
    must produce exactly the dense single-device generate's tokens (greedy
    argmax is invariant to the TP reduction order at these magnitudes; any
    mismatch is a sharding/cache bug)."""
    from apex_example_tpu.models.gpt import generate
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    from apex_example_tpu.transformer.tensor_parallel.layers import (
        param_partition_specs)
    from flax.core import meta
    from jax.sharding import NamedSharding

    mesh = parallel_state.initialize_model_parallel(tensor_parallel=4,
                                                    devices=devices8)
    try:
        dense = gpt_tiny()
        tp_model = gpt_tiny(tensor_parallel=True)
        V = dense.vocab_size
        prompt = jnp.asarray(
            np.random.RandomState(3).randint(0, V, (2, 3)), jnp.int32)
        params = dense.init(jax.random.PRNGKey(1), prompt)["params"]
        ref = generate(dense, params, prompt, max_len=10)

        # Same param tree; placed per the TP layers' partition metadata.
        abs_vars = jax.eval_shape(
            lambda r: tp_model.init(r, prompt), jax.random.PRNGKey(1))
        specs = param_partition_specs(abs_vars)["params"]
        tp_params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda v: not isinstance(v, dict))
        out = generate(tp_model, tp_params, prompt, max_len=10)
        np.testing.assert_array_equal(np.array(out), np.array(ref))
        # a head-sharded param really is distributed under the mesh
        q = tp_params["layer_0"]["attention"]["query"]["kernel"]
        assert q.addressable_shards[0].data.shape[1] == q.shape[1] // 4
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


def test_generate_tp_sampling_runs(devices8):
    """Sampled TP decode: same rng => same tokens, prompt preserved."""
    from apex_example_tpu.models.gpt import generate
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state

    mesh = parallel_state.initialize_model_parallel(tensor_parallel=4,
                                                    devices=devices8)
    try:
        model = gpt_tiny(tensor_parallel=True)
        V = model.vocab_size
        prompt = jnp.asarray(
            np.random.RandomState(5).randint(0, V, (2, 3)), jnp.int32)
        params = model.init(jax.random.PRNGKey(1), prompt)["params"]
        s1 = generate(model, params, prompt, max_len=8, temperature=0.7,
                      rng=jax.random.PRNGKey(11))
        s2 = generate(model, params, prompt, max_len=8, temperature=0.7,
                      rng=jax.random.PRNGKey(11))
        np.testing.assert_array_equal(np.array(s1), np.array(s2))
        assert (np.array(s1)[:, :3] == np.array(prompt)).all()
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


def test_generate_rejects_sp_moe_cp():
    """decode guards: SP (length-1 sequence cannot partition), MoE, CP all
    rejected with a clean ValueError, not a deep GSPMD trace error."""
    from apex_example_tpu.models.gpt import generate
    V = 256
    prompt = jnp.zeros((1, 2), jnp.int32)
    for kw in ({"tensor_parallel": True, "sequence_parallel": True},
               {"moe_experts": 4},
               {"context_parallel": True}):
        model = gpt_tiny(**kw)
        with pytest.raises(ValueError, match="decode"):
            generate(model, {}, prompt, max_len=6)
