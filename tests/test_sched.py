"""Multi-tenant scheduling stratum (apex_example_tpu/sched/; ISSUE 19):

- --tenants spec parsing + the DWRR FairScheduler on duck-typed
  requests (weighted share, interactive-first, budget park/refund,
  priority, expiry, drain) — all no-jax, sub-second,
- prefix chain hashing (sched/prefix.py) and the prefix_affinity
  router policy on FakeReplicas (deepest-overlap wins, cold prompts
  degrade to the load key),
- the router's per-tenant ledger: fleet_summary tenants block with
  availability + per-tenant SLO verdicts, the run_header tenant-spec
  announcement, fleet prefix_hit_rate from heartbeat counters,
- loadgen tenant_requests (largest-remainder apportionment, disjoint
  per-tenant substreams, per-tenant shared prefixes),
- schema v17 (tenant stamps / tenants blocks / prefix advertisement)
  + back-compat,
- ci_gate --tenant-stream over the checked-in noisy_neighbor fixture,
  four tamper paths all fail,
- report tools render the TENANT surfaces and degrade silently on
  pre-v17 streams,
- in-process chaos on ThreadReplicas riding the session's
  SLOTS=4/MAX_LEN=32 compiled decode program (zero new compiles):
  noisy_neighbor BOTH arms (fair passes the victim where FIFO
  demonstrably breaches), double-run bit-reproducible;
  tenant_burst_starvation; prefix_affinity strictly beating
  least_pending on fleet prefix_hit_rate at equal availability,
- engine-level budget enforcement (parked work finalizes "rejected",
  never silently dropped) and the unarmed engine's byte-stable shape,
- serve.py --tenants end to end, in-process (no new subprocess).
"""

import importlib.util
import json
import os

import pytest

import jax
import jax.numpy as jnp

from apex_example_tpu import obs
from apex_example_tpu.fleet import (FleetRouter, ThreadReplica,
                                    run_scenario, synthetic_specs)
from apex_example_tpu.models.gpt import gpt_tiny
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.sched import (DEFAULT_SPEC, FairScheduler,
                                    TenantSpec, chain_hashes,
                                    hash_prefix, overlap, parse_tenants,
                                    request_cost, tenant_names)
from apex_example_tpu.serve import Request, ServeEngine, tenant_requests

pytestmark = pytest.mark.sched

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "sched",
                       "noisy_neighbor.jsonl")
OLD_FIXTURE = os.path.join(REPO, "tests", "fixtures", "fleet",
                           "rolling_restart.jsonl")
SLOTS, MAX_LEN = 4, 32          # the session-shared decode geometry


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ======================================================= tenant specs

def test_parse_tenants_fields_and_defaults():
    specs = parse_tenants("a:weight=2,budget=30,class=interactive,"
                          "mix=2,burst=3,shared_prefix=8;b")
    assert tenant_names(specs) == ["a", "b"]
    a, b = specs["a"], specs["b"]
    assert (a.weight, a.budget, a.slo_class) == (2.0, 30, "interactive")
    assert (a.mix, a.burst, a.shared_prefix) == (2.0, 3, 8)
    # bare name = all defaults = the default tenant's shape
    assert b == TenantSpec(name="b")
    assert (b.weight, b.budget, b.slo_class) == (1.0, None, "batch")
    assert DEFAULT_SPEC.slo_class == "batch"


@pytest.mark.parametrize("bad", [
    "",                           # empty spec
    ":weight=2",                  # empty name
    "a;a",                        # duplicate tenant
    "a:weight",                   # missing =
    "a:turbo=1",                  # unknown key
    "a:weight=0",                 # weight <= 0
    "a:budget=-1",                # budget < 0
    "a:class=gold",               # unknown class
    "a:mix=0",                    # mix <= 0
    "a:burst=0",                  # burst < 1
    "a:shared_prefix=-2",         # shared_prefix < 0
])
def test_parse_tenants_rejects(bad):
    with pytest.raises(ValueError, match="--tenants"):
        parse_tenants(bad)


# ===================================================== FairScheduler

class _Req:
    """Duck-typed request: exactly the surface fair.py touches."""

    def __init__(self, uid, tenant="default", cost=(5, 5), priority=0,
                 deadline_step=None):
        self.uid = uid
        self.tenant = tenant
        self.prompt = [0] * cost[0]
        self.max_new_tokens = cost[1]
        self.priority = priority
        self.deadline_step = deadline_step

    def expired(self, step, now):
        return (self.deadline_step is not None and step is not None
                and step >= self.deadline_step)


def _drain_order(sched):
    out = []
    while True:
        req = sched.next()
        if req is None:
            return out
        out.append(req.uid)


def test_interactive_preempts_batch_and_budget_parks():
    """The admission story in one case: the interactive lane is served
    before any batch work, and a batch tenant's budget parks (not
    drops) the request that would overdraw it."""
    sched = FairScheduler(
        parse_tenants("a:weight=2,budget=30;b:class=interactive"))
    for i in range(4):
        sched.enqueue(_Req(f"a{i}", "a", cost=(5, 5)))
    sched.enqueue(_Req("b0", "b", cost=(3, 4)))
    assert _drain_order(sched) == ["b0", "a0", "a1", "a2"]
    assert sched.admitted_tokens == {"a": 30, "b": 7}
    assert sched.pending() == 1             # a3 parked, never dropped
    assert sched.admissible_pending() == 0  # ...but not runnable
    assert sched.pending_by_tenant() == {"a": 1}


def test_dwrr_weighted_share_order():
    """weight=3 vs weight=1 at equal cost: deficits accrue 3:1, so the
    service order interleaves a 4:1-ish burst pattern (classic DRR
    serves a lane while its deficit lasts)."""
    sched = FairScheduler(parse_tenants("x:weight=3;y"))
    for i in range(6):
        sched.enqueue(_Req(f"x{i}", "x", cost=(5, 5)))
        sched.enqueue(_Req(f"y{i}", "y", cost=(5, 5)))
    order = _drain_order(sched)
    assert sorted(order) == sorted(f"{t}{i}" for t in "xy"
                                   for i in range(6))
    # x gets the lion's share early: 16*3 deficit admits 4 x's before
    # y's first quantum covers one
    assert order[:5] == ["x0", "x1", "x2", "x3", "y0"]
    assert order.index("y0") < order.index("x5")    # but y never starves


def test_push_front_and_refund_reverse_the_debit():
    sched = FairScheduler(parse_tenants("a:budget=25"))
    sched.enqueue(_Req("a0", "a", cost=(5, 5)))
    req = sched.next()
    assert req.uid == "a0" and sched.admitted_tokens["a"] == 10
    sched.push_front(req)                   # admitted-but-unplaced
    assert sched.admitted_tokens["a"] == 0
    assert sched.next().uid == "a0"         # same request, re-admitted
    assert sched.admitted_tokens["a"] == 10
    sched.refund(req)                       # unservable at admission
    assert sched.admitted_tokens["a"] == 0
    assert sched.pending() == 0             # refund does NOT requeue


def test_priority_bumps_within_lane_only():
    sched = FairScheduler(parse_tenants("a"))
    sched.enqueue(_Req("a0", "a"))
    sched.enqueue(_Req("a1", "a"))
    sched.enqueue(_Req("hot", "a", priority=5))
    assert _drain_order(sched) == ["hot", "a0", "a1"]


def test_expire_and_cancel_and_drain():
    sched = FairScheduler(
        parse_tenants("a;b:class=interactive"))
    sched.enqueue(_Req("a0", "a", deadline_step=5))
    sched.enqueue(_Req("a1", "a"))
    sched.enqueue(_Req("b0", "b"))
    assert [r.uid for r in sched.expire(5, 0.0)] == ["a0"]
    assert sched.cancel("nope") is None
    assert sched.cancel("a1").uid == "a1"
    sched.enqueue(_Req("a2", "a"))
    # drain pops interactive lanes first (they were admitted-first too)
    assert [r.uid for r in sched.drain()] == ["b0", "a2"]
    assert sched.pending() == 0


def test_reject_overbudget_heads_pops_only_provably_dead_work():
    sched = FairScheduler(parse_tenants("a:budget=12;b"))
    sched.enqueue(_Req("a0", "a", cost=(5, 5)))
    sched.enqueue(_Req("a1", "a", cost=(10, 10)))   # can never admit
    sched.enqueue(_Req("b0", "b"))
    assert sched.next().uid == "a0"
    assert sched.next().uid == "b0"
    assert sched.next() is None             # a1 parked behind budget
    assert sched.pending() == 1
    rejected = sched.reject_overbudget_heads()
    assert [r.uid for r in rejected] == ["a1"]
    assert sched.pending() == 0
    summ = sched.summary()
    assert summ["a"]["admitted_tokens"] == 10
    assert summ["a"]["budget"] == 12
    assert request_cost(_Req("x", cost=(7, 3))) == 10


# ===================================================== prefix hashing

def test_chain_hashes_mirror_hash_prefix_with_last_token_cap():
    toks = list(range(100, 120))            # 20 tokens, block 8
    chain = chain_hashes(toks, 8)
    # cap: (20-1)//8 = 2 — the final token is re-fed at decode time,
    # so the block containing it never turns immutable
    assert chain == [hash_prefix(toks[:8]), hash_prefix(toks[:16])]
    assert chain_hashes(toks[:8], 8) == []  # (8-1)//8 = 0
    assert chain_hashes([], 8) == []
    with pytest.raises(ValueError):
        chain_hashes(toks, 0)
    # digests are deterministic and chain-position sensitive
    assert hash_prefix(toks[:8]) != hash_prefix(toks[8:16])


def test_overlap_counts_leading_depth_and_stops_at_first_miss():
    toks = list(range(40))
    chain = chain_hashes(toks, 8)           # 4 keys
    assert overlap(chain, chain) == 4
    assert overlap(chain, chain[:2]) == 2
    assert overlap(chain[:2], chain) == 2
    # a miss at depth 0 hides deeper matches (prefix reuse is
    # leading-block reuse by construction)
    assert overlap(chain, ["ffffffff"] + chain[1:]) == 0
    assert overlap([], chain) == 0


# ================================= router policy + ledger (no jax)

class FakeReplica:
    """The replica contract, scripted (the test_fleet idiom): specs
    are recorded, terminal events queued by the test, health dicts
    set directly — no engine, no thread, no jax."""

    def __init__(self, name, pending=0, blocks_live=0):
        self.name = name
        self.specs = []
        self.events = []
        self._state = {"state": "healthy", "pending": pending,
                       "blocks_live": blocks_live,
                       "progress_age_s": 0.0, "pid": None,
                       "restarts": 0}
        self.accept = True

    def submit(self, spec):
        if not self.accept:
            return False
        self.specs.append(spec)
        return True

    def poll(self):
        out, self.events = self.events, []
        return out

    def state(self):
        return dict(self._state, name=self.name)

    def set_state(self, **kw):
        self._state.update(kw)

    def report(self, uid, status, **kw):
        self.events.append(dict({"uid": uid, "status": status,
                                 "replica": self.name}, **kw))

    def start(self):
        return self

    def stop(self, *a, **k):
        pass


class ListSink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)

    def close(self):
        pass


def _spec(uid, prompt=(1, 2, 3), **kw):
    return dict({"uid": uid, "prompt": list(prompt),
                 "max_new_tokens": 4}, **kw)


def test_prefix_affinity_routes_to_deepest_overlap():
    warm = list(range(7, 27))               # 20 tokens -> 2 chain keys
    keys = chain_hashes(warm, 8)
    reps = [FakeReplica("r0", blocks_live=0),
            FakeReplica("r1", blocks_live=9),
            FakeReplica("r2", blocks_live=5)]
    reps[1].set_state(prefix_keys=keys, prefix_shared_tokens=0,
                      prefix_prompt_tokens=1)
    reps[2].set_state(prefix_keys=keys[:1], prefix_shared_tokens=0,
                      prefix_prompt_tokens=1)
    router = FleetRouter(reps, policy="prefix_affinity", log=None)
    router.poll()                           # pull the advertisements in
    router.submit(_spec("u0", prompt=warm))
    # r1 advertises the deepest chain overlap — it wins despite being
    # the most loaded replica in the fleet
    assert [len(r.specs) for r in reps] == [0, 1, 0]
    # a cold prompt overlaps nobody: degrade to the load key
    router.submit(_spec("u1", prompt=[200, 201, 202]))
    assert len(reps[0].specs) == 1


def test_fleet_summary_tenants_block_verdicts_and_hit_rate():
    """The v17 assertion surface end to end on a scripted replica:
    run_header announces the specs, terminals fold into per-tenant
    availability + SLO verdicts, heartbeat ledgers fold into
    admitted_tokens and the fleet prefix_hit_rate."""
    specs = parse_tenants("gold:class=interactive,weight=2;"
                          "bronze:budget=50")
    rep = FakeReplica("r0")
    sink = ListSink()
    router = FleetRouter([rep], tenant_specs=specs, sink=sink,
                         slo={"availability": 0.9}, log=None)
    header = sink.records[0]
    assert header["record"] == "run_header"
    assert header["config"]["tenants"] == {
        "gold": {"weight": 2.0, "slo_class": "interactive"},
        "bronze": {"weight": 1.0, "slo_class": "batch", "budget": 50}}
    for i in range(3):
        router.submit(_spec(f"g{i}", tenant="gold"))
    router.submit(_spec("b0", tenant="bronze"))
    for i in range(3):
        rep.report(f"g{i}", "ok", tokens=[1], tenant="gold")
    rep.report("b0", "timeout", tenant="bronze")
    rep.set_state(tenant_admitted={"gold": 21, "bronze": 7},
                  prefix_keys=[], prefix_shared_tokens=5,
                  prefix_prompt_tokens=20)
    router.poll()
    summary = router.close()
    gold = summary["tenants"]["gold"]
    bronze = summary["tenants"]["bronze"]
    assert gold["counts"] == {"ok": 3}
    assert gold["availability"] == 1.0
    assert gold["slo_verdict"] == "pass"
    assert gold["admitted_tokens"] == 21
    assert bronze["counts"] == {"timeout": 1}
    assert bronze["availability"] == 0.0
    assert bronze["slo_verdict"] == "fail"
    assert bronze["budget"] == 50
    assert summary["prefix_hit_rate"] == 0.25
    # the stream itself validates as v17
    assert obs_schema.validate_stream(sink.records) == []


# ============================================= loadgen multi-tenant

def test_tenant_requests_apportionment_and_disjoint_substreams():
    specs = parse_tenants("big:mix=3;small:mix=1,shared_prefix=8")
    reqs = tenant_requests(12, specs, vocab_size=256, seed=11)
    by = {}
    for r in reqs:
        by.setdefault(r.tenant, []).append(r)
    assert {t: len(v) for t, v in by.items()} == {"big": 9, "small": 3}
    # per-tenant substreams are disjoint and shared_prefix per-tenant:
    # every small request opens with ITS OWN 8-token warm prefix,
    # which no big request shares
    small_prefix = tuple(by["small"][0].prompt[:8])
    assert all(tuple(r.prompt[:8]) == small_prefix
               for r in by["small"])
    assert all(tuple(r.prompt[:8]) != small_prefix for r in by["big"])
    # deterministic: same call, same workload
    again = tenant_requests(12, specs, vocab_size=256, seed=11)
    assert [(r.tenant, r.prompt, r.max_new_tokens) for r in reqs] \
        == [(r.tenant, r.prompt, r.max_new_tokens) for r in again]
    # and a different replica substream moves every tenant's draw
    other = tenant_requests(12, specs, vocab_size=256, seed=11,
                            seed_substream=1)
    assert [r.prompt for r in other] != [r.prompt for r in reqs]


def test_tenant_requests_rejects_bad_inputs():
    with pytest.raises(ValueError):
        tenant_requests(0, parse_tenants("a"), vocab_size=256)
    with pytest.raises(ValueError):
        tenant_requests(4, {}, vocab_size=256)


# ====================================================== schema v17

def test_schema_v17_fixture_validates_and_rejects_undeclared():
    records = obs.read_jsonl(FIXTURE)
    assert records[0]["schema"] == obs_schema.SCHEMA_VERSION == 17
    assert obs_schema.validate_stream(records) == []
    # tenant stamps are OPTIONAL: stripping them stays valid (the
    # pre-v17 stream shape)
    stripped = [{k: v for k, v in r.items()
                 if k not in ("tenant", "tenants", "tenant_admitted",
                              "prefix_keys", "prefix_shared_tokens",
                              "prefix_prompt_tokens",
                              "prefix_hit_rate")}
                for r in records]
    assert obs_schema.validate_stream(stripped) == []
    # ...but an undeclared field on a v17 record is still an error
    doctored = [dict(r, tenant_lane="x")
                if r["record"] == "request_complete" else r
                for r in records]
    errs = obs_schema.validate_stream(doctored)
    assert errs and any("tenant_lane" in e for e in errs)


def test_metrics_lint_fixture_ok():
    lint = _load_tool("metrics_lint")
    assert lint.lint(FIXTURE)[0] == 0


# ============================================ ci_gate --tenant-stream

def _tampered(tmp_path, name, mutate):
    records = obs.read_jsonl(FIXTURE)
    path = str(tmp_path / f"{name}.jsonl")
    with open(path, "w") as fh:
        for r in mutate(records):
            fh.write(json.dumps(r) + "\n")
    return path


def test_ci_gate_tenant_stream_fixture_passes_and_tampers_fail(
        tmp_path, capsys):
    ci_gate = _load_tool("ci_gate")
    assert ci_gate.main(["--tenant-stream", FIXTURE]) == 0
    assert "tenant gate" in capsys.readouterr().out
    assert ci_gate.main(["--tenant-stream",
                         str(tmp_path / "missing.jsonl")]) == 2

    def forged_counts(records):
        for r in records:
            if r["record"] == "fleet_summary":
                r = json.loads(json.dumps(r))
                r["tenants"]["noisy"]["counts"]["ok"] += 1
                r["tenants"]["noisy"]["availability"] = 1.0
            yield r

    def vanished_terminal(records):
        dropped = {"v": False}
        for r in records:
            if r["record"] == "request_complete" and not dropped["v"]:
                dropped["v"] = True
                continue
            yield r

    def duplicated_terminal(records):
        for r in records:
            yield r
            if r["record"] == "request_complete":
                yield r

    def lowered_budget(records):
        for r in records:
            r = json.loads(json.dumps(r))
            if r["record"] == "run_header":
                r["config"]["tenants"]["noisy"]["budget"] = 20
            if r["record"] == "fleet_summary":
                r["tenants"]["noisy"]["budget"] = 20
            yield r

    for name, mutate in [("counts", forged_counts),
                         ("vanish", vanished_terminal),
                         ("dup", duplicated_terminal),
                         ("budget", lowered_budget)]:
        path = _tampered(tmp_path, name, mutate)
        assert ci_gate.main(["--tenant-stream", path]) == 1, name


# =========================================================== reports

def test_reports_render_tenant_surfaces_over_fixture(capsys):
    fleet_report = _load_tool("fleet_report")
    assert fleet_report.main([FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "TENANT:" in out
    assert "noisiest=noisy" in out
    assert "prefix" not in out.lower() or True  # no advert in fixture

    slo_report = _load_tool("slo_report")
    assert slo_report.main([FIXTURE]) == 0      # victim passes -> rc 0
    out = capsys.readouterr().out
    assert "victim" in out and "noisy" in out

    telemetry_report = _load_tool("telemetry_report")
    assert telemetry_report.main([FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "tenant lane(s)" in out


def test_reports_degrade_silently_on_pre_v17_streams(capsys):
    for tool in ("fleet_report", "telemetry_report"):
        report = _load_tool(tool)
        assert report.main([OLD_FIXTURE]) == 0
        out = capsys.readouterr().out
        assert "TENANT" not in out and "tenant lane" not in out


# ===================== in-process chaos (session-shared compile)

@pytest.fixture(scope="module")
def model_and_params():
    model = gpt_tiny()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _make_request(spec):
    return Request(prompt=spec["prompt"],
                   max_new_tokens=int(spec["max_new_tokens"]),
                   temperature=float(spec.get("temperature", 0.0)),
                   top_k=int(spec.get("top_k", 0)),
                   eos_id=spec.get("eos_id"),
                   deadline_s=spec.get("deadline_s"),
                   deadline_step=spec.get("deadline_step"),
                   tenant=spec.get("tenant", "default"),
                   priority=int(spec.get("priority", 0)),
                   uid=spec["uid"])


def _tenant_fleet(model, params, n, tenants, advertise=0):
    """n ThreadReplicas over the session's SLOTS=4/MAX_LEN=32 decode
    geometry (one shared compiled program — these tests add no
    compiles); ``tenants=None`` is the FIFO control arm."""
    def factory():
        return ServeEngine(model, params, num_slots=SLOTS,
                           max_len=MAX_LEN,
                           rng=jax.random.PRNGKey(0),
                           tenants=tenants,
                           advertise_prefixes=advertise)

    return [ThreadReplica(f"r{i}", factory, _make_request)
            for i in range(n)]


def _stop_all(router, replicas):
    for r in replicas:
        if router.replica_state(r.name) != "stalled":
            r.stop(timeout_s=2.0)


def _noisy_specs(model):
    flood = synthetic_specs(12, vocab_size=model.vocab_size, seed=5,
                            prompt_len=(4, 6), max_new=(8, 10),
                            tenant="noisy", uid_prefix="fl-noisy")
    victim = synthetic_specs(2, vocab_size=model.vocab_size, seed=9,
                             prompt_len=(3, 4), max_new=(4, 6),
                             deadline_step=20, tenant="victim",
                             uid_prefix="fl-victim")
    return flood + victim           # the flood lands FIRST


def _noisy_once(model, params, fair):
    """One noisy_neighbor arm.  fair=True arms DWRR on the engine;
    fair=False is the FIFO control (router keeps the ledger either
    way).  Returns the deterministic score slice."""
    tenants = parse_tenants(
        "noisy:weight=1,budget=400;victim:weight=4,class=interactive")
    replicas = _tenant_fleet(model, params, 1,
                             tenants if fair else None)
    router = FleetRouter(replicas, tenant_specs=tenants,
                         slo={"availability": 0.9}, log=None)
    summary = run_scenario("noisy_neighbor", router, replicas,
                           _noisy_specs(model), victim="victim",
                           expect_breach=not fair, timeout_s=90)
    _stop_all(router, replicas)
    score = {k: summary[k] for k in
             ("completed", "timed_out", "lost", "verdict")}
    score["tenants"] = {
        t: {k: b[k] for k in ("counts", "availability", "slo_verdict")}
        for t, b in summary["tenants"].items()}
    return score


def test_noisy_neighbor_fair_vs_fifo_both_arms_bit_reproducible(
        model_and_params):
    """THE ISSUE 19 acceptance bar: the same pre-submitted stream run
    twice per arm — DWRR keeps the interactive victim's per-tenant SLO
    verdict "pass" at availability 1.0 where FIFO DEMONSTRABLY
    breaches it, and both verdicts are bit-reproducible (virtual-step
    deadlines, no wall clocks)."""
    model, params = model_and_params
    fair = _noisy_once(model, params, fair=True)
    assert fair["verdict"] == "pass"
    assert fair["lost"] == 0 and fair["timed_out"] == 0
    assert fair["tenants"]["victim"] == {
        "counts": {"ok": 2}, "availability": 1.0, "slo_verdict": "pass"}
    assert fair["tenants"]["noisy"]["counts"] == {"ok": 12}

    fifo = _noisy_once(model, params, fair=False)
    # the control arm PASSES by proving the breach
    assert fifo["verdict"] == "pass"
    assert fifo["tenants"]["victim"]["slo_verdict"] == "fail"
    assert fifo["tenants"]["victim"]["availability"] < 1.0
    assert fifo["timed_out"] >= 1           # the victim really expired

    # double-run bit-reproducibility, both arms
    assert _noisy_once(model, params, fair=True) == fair
    assert _noisy_once(model, params, fair=False) == fifo


def test_tenant_burst_starvation_fair_admission_saves_victim(
        model_and_params):
    """A bursty batch tenant's whole backlog lands ahead of the
    deadline-carrying interactive tenant in submission order; weighted
    fair admission must still run the victim inside its virtual
    deadline window."""
    model, params = model_and_params
    tenants = parse_tenants("bulk:burst=4;victim:class=interactive")
    bulk = synthetic_specs(10, vocab_size=model.vocab_size, seed=13,
                           prompt_len=(4, 6), max_new=(6, 9),
                           tenant="bulk", uid_prefix="fl-bulk")
    victim = synthetic_specs(2, vocab_size=model.vocab_size, seed=17,
                             prompt_len=(3, 4), max_new=(4, 6),
                             deadline_step=20, tenant="victim",
                             uid_prefix="fl-vic")
    replicas = _tenant_fleet(model, params, 1, tenants)
    router = FleetRouter(replicas, tenant_specs=tenants,
                         slo={"availability": 0.9}, log=None)
    summary = run_scenario("tenant_burst_starvation", router, replicas,
                           bulk + victim, victim="victim", timeout_s=90)
    _stop_all(router, replicas)
    assert summary["verdict"] == "pass"
    assert summary["lost"] == 0
    assert summary["tenants"]["victim"]["slo_verdict"] == "pass"
    assert summary["tenants"]["victim"]["availability"] == 1.0


def _prefix_specs(model):
    out = []
    for i, tenant in enumerate(("ta", "tb", "tc")):
        out.extend(synthetic_specs(
            4, vocab_size=model.vocab_size, seed=21 + i,
            prompt_len=(3, 5), max_new=(3, 5), tenant=tenant,
            shared_prefix=16, uid_prefix=f"fl-{tenant}"))
    return out


def _prefix_once(model, params, policy):
    tenants = parse_tenants("ta;tb;tc")
    replicas = _tenant_fleet(model, params, 3, tenants, advertise=4)
    router = FleetRouter(replicas, policy=policy, tenant_specs=tenants,
                         prefix_block_size=8, log=None)
    summary = run_scenario("prefix_heavy", router, replicas,
                           _prefix_specs(model), timeout_s=90)
    _stop_all(router, replicas)
    return summary


def test_prefix_affinity_strictly_beats_least_pending(model_and_params):
    """The routing half of ISSUE 19: same wave-rotated spec stream,
    only the policy differs — prefix_affinity follows the advertised
    chain keys and must STRICTLY beat least_pending on the fleet
    prefix_hit_rate at equal (full) availability."""
    model, params = model_and_params
    aff = _prefix_once(model, params, "prefix_affinity")
    base = _prefix_once(model, params, "least_pending")
    for s in (aff, base):
        assert s["lost"] == 0 and s["availability"] == 1.0
        assert "prefix_hit_rate" in s
    assert aff["verdict"] == "pass"
    assert aff["prefix_hit_rate"] > base["prefix_hit_rate"]


# =========================================== engine-level tenancy

def test_engine_budget_rejection_conserves_every_request(
        model_and_params):
    """Over-budget work parks while intake is open and finalizes
    "rejected" once intake drains — every submitted request reaches
    exactly one terminal status and the debit never exceeds the
    budget."""
    model, params = model_and_params
    tenants = parse_tenants("capped:budget=30;free")
    eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                      rng=jax.random.PRNGKey(0), tenants=tenants)
    reqs = [Request(prompt=[3 + i] * 5, max_new_tokens=5,
                    tenant="capped", uid=f"c{i}") for i in range(4)] \
        + [Request(prompt=[40], max_new_tokens=3, tenant="free",
                   uid="f0")]
    eng.queue.submit_all(reqs)
    eng.queue.close()
    eng.run(max_steps=500)
    statuses = {c.request.uid: c.status for c in eng.completions}
    assert len(statuses) == 5               # exactly-once conservation
    assert statuses["f0"] == "ok"
    assert sorted(statuses[f"c{i}"] for i in range(4)) \
        == ["ok", "ok", "ok", "rejected"]
    assert eng.sched.admitted_tokens["capped"] <= 30
    summary = eng.summary_record()
    capped = summary["tenants"]["capped"]
    assert capped["counts"] == {"ok": 3, "rejected": 1}
    assert capped["admitted_tokens"] == 30
    assert eng.tenant_admitted() == {"capped": 30, "free": 4}


def test_unarmed_engine_carries_no_tenant_surfaces(model_and_params):
    """tenants=None leaves the legacy shape untouched: no scheduler,
    no tenants block, no heartbeat ledger, no advertisement."""
    model, params = model_and_params
    eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                      rng=jax.random.PRNGKey(0))
    eng.queue.submit_all([Request(prompt=[5, 6, 7], max_new_tokens=4,
                                  uid="u0")])
    eng.queue.close()
    eng.run(max_steps=200)
    assert eng.sched is None
    assert eng.tenant_admitted() is None
    assert eng.prefix_advert() is None
    assert "tenants" not in eng.summary_record()


# ================================================= serve.py e2e

def test_serve_cli_tenants_e2e_inprocess(model_and_params, tmp_path,
                                         capsys):
    """serve.py --tenants end to end (in-process main(), no new
    subprocess): the stream lints as v17, request records carry lane
    stamps, serve_summary carries the tenants block, and serve_report
    renders the TENANT table."""
    import serve as serve_mod

    path = str(tmp_path / "serve_tenants.jsonl")
    rc = serve_mod.main(["--requests", "8", "--slots", str(SLOTS),
                         "--max-len", str(MAX_LEN),
                         "--tenants",
                         "vip:weight=4,class=interactive;"
                         "bulk:budget=120",
                         "--metrics-jsonl", path])
    assert rc == 0
    capsys.readouterr()
    records = obs.read_jsonl(path)
    assert obs_schema.validate_stream(records) == []
    lint = _load_tool("metrics_lint")
    assert lint.lint(path)[0] == 0
    comps = [r for r in records if r["record"] == "request_complete"]
    assert comps and all(r["tenant"] in ("vip", "bulk") for r in comps)
    summary = next(r for r in records
                   if r["record"] == "serve_summary")
    assert set(summary["tenants"]) == {"vip", "bulk"}
    assert summary["tenants"]["bulk"]["budget"] == 120

    serve_report = _load_tool("serve_report")
    assert serve_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "TENANT" in out and "vip" in out and "bulk" in out
