"""Fleet stratum (apex_example_tpu/fleet/, fleet.py; ISSUE 12):

- router core on tiny no-jax fake replicas: policy selection,
  requeue-on-drain exactly-once, circuit-break/half-open, deadline-
  aware retry, backlog admission — all sub-second, no compiles,
- schema v10 (route / replica_state / fleet_summary, restart
  classification) + v1-v9 back-compat,
- the loadgen substream satellite (disjoint-yet-deterministic
  per-replica workloads),
- supervisor restart classification (two tiny no-jax subprocess
  children, the test_trace pattern),
- in-process chaos on ThreadReplicas riding the session's
  SLOTS=4/MAX_LEN=32 compiled decode program (zero new compiles):
  fleet-wide token identity vs one-shot generate(), deterministic
  crash_storm scores, straggler stall-rescue, thread-mode rolling
  restart,
- ci_gate --fleet-stream + fleet_report serve-fleet mode over the
  checked-in rolling_restart scenario stream,
- THE one new subprocess e2e: rolling restart over 2 supervised
  serve.py replicas — zero lost requests, availability 1.0, one
  trace_id, merged trace --check clean.
"""

import importlib.util
import json
import os
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_example_tpu import obs
from apex_example_tpu.fleet import (FleetRouter, ThreadReplica,
                                    run_scenario, synthetic_specs)
from apex_example_tpu.models.gpt import generate, gpt_tiny
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.resilience.faults import SERVE_KINDS, FaultPlan
from apex_example_tpu.serve import (Request, ServeEngine, substream,
                                    synthetic_requests)

pytestmark = pytest.mark.fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "fleet",
                       "rolling_restart.jsonl")
SLOTS, MAX_LEN = 4, 32          # the session-shared decode geometry


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_supervisor():
    spec = importlib.util.spec_from_file_location(
        "apex_supervisor_fleet_test",
        os.path.join(REPO, "apex_example_tpu", "resilience",
                     "supervisor.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ================================================== no-jax router core

class FakeReplica:
    """The replica contract, scripted: dispatched specs are recorded,
    terminal events are queued by the test and handed to the next
    poll().  No engine, no thread, no jax — the router-core tests run
    sub-second."""

    def __init__(self, name, pending=0, blocks_live=0):
        self.name = name
        self.specs = []
        self.events = []
        self._state = {"state": "healthy", "pending": pending,
                       "blocks_live": blocks_live,
                       "progress_age_s": 0.0, "pid": None,
                       "restarts": 0}
        self.accept = True

    def submit(self, spec):
        if not self.accept:
            return False
        self.specs.append(spec)
        return True

    def poll(self):
        out, self.events = self.events, []
        return out

    def state(self):
        return dict(self._state, name=self.name)

    def set_state(self, **kw):
        self._state.update(kw)

    def report(self, uid, status, **kw):
        self.events.append(dict({"uid": uid, "status": status,
                                 "replica": self.name}, **kw))

    def start(self):
        return self

    def stop(self, *a, **k):
        pass


class ListSink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)

    def close(self):
        pass


def _spec(uid, **kw):
    return dict({"uid": uid, "prompt": [1, 2, 3], "max_new_tokens": 4},
                **kw)


def test_policy_round_robin_cycles():
    reps = [FakeReplica(f"r{i}") for i in range(3)]
    router = FleetRouter(reps, policy="round_robin", log=None)
    for i in range(6):
        router.submit(_spec(f"u{i}"))
    assert [len(r.specs) for r in reps] == [2, 2, 2]
    assert [s["uid"] for s in reps[0].specs] == ["u0", "u3"]
    assert [s["uid"] for s in reps[1].specs] == ["u1", "u4"]


def test_policy_least_pending_and_least_kv_use_tailed_gauges():
    reps = [FakeReplica("r0", pending=5, blocks_live=9),
            FakeReplica("r1", pending=0, blocks_live=4),
            FakeReplica("r2", pending=2, blocks_live=0)]
    router = FleetRouter(reps, policy="least_pending", log=None)
    router.poll()                       # pull the health gauges in
    router.submit(_spec("u0"))
    assert [len(r.specs) for r in reps] == [0, 1, 0]

    router2 = FleetRouter(reps, policy="least_kv", log=None)
    router2.poll()
    router2.submit(_spec("k0"))
    assert len(reps[2].specs) == 1      # fewest live KV blocks wins


def test_policy_least_kv_prefers_dtype_accurate_bytes():
    """v12 (ISSUE 14): two replicas holding the SAME block count but
    different arena precisions — least_kv keys on the dtype-accurate
    ``kv_bytes_live`` gauge a sharded/quantized replica heartbeats, so
    the int8 replica (fewer real bytes, more headroom) wins; the block
    count alone could not tell them apart."""
    reps = [FakeReplica("bf16", blocks_live=8),
            FakeReplica("int8", blocks_live=8)]
    reps[0].set_state(kv_bytes_live=8 * 8 * 512)
    reps[1].set_state(kv_bytes_live=8 * 8 * 264)
    router = FleetRouter(reps, policy="least_kv", log=None)
    router.poll()
    router.submit(_spec("q0"))
    assert len(reps[1].specs) == 1 and not reps[0].specs


def test_proc_replica_passes_mesh_flags_through(tmp_path):
    """ISSUE 14 satellite: a ProcReplica built with sharding serve_args
    spawns a supervised child whose argv carries them verbatim — the
    supervisor wrapper must not eat --mesh/--role flags."""
    from apex_example_tpu.fleet.replica import ProcReplica
    rep = ProcReplica("r0", str(tmp_path), REPO,
                      serve_args=["--mesh", "1,2", "--slots", "2"])
    argv = rep.argv()
    assert argv[argv.index("--mesh") + 1] == "1,2"
    assert argv.index("--mesh") > argv.index("--")   # on the CHILD side


def test_requeue_on_drain_exactly_once(tmp_path):
    a, b = FakeReplica("a"), FakeReplica("b")
    sink = ListSink()
    router = FleetRouter([a, b], sink=sink, log=None)
    router.submit(_spec("u1"))
    assert len(a.specs) == 1
    a.report("u1", "drained")
    router.poll()
    # handed to the sibling, exactly once
    assert [s["uid"] for s in b.specs] == ["u1"]
    a.report("u1", "drained")           # duplicate drain report
    router.poll()
    assert len(b.specs) == 1            # NOT re-dispatched
    b.report("u1", "ok", tokens=[7])
    router.poll()
    assert router.done()
    summary = router.close()
    assert summary["completed"] == 1
    assert summary["drained_requeued"] == 1
    assert summary["duplicates"] == 1
    assert summary["lost"] == 0
    assert summary["availability"] == 1.0
    reasons = [r["reason"] for r in sink.records
               if r["record"] == "route"]
    assert reasons == ["dispatch", "requeue_drain"]
    requeue = [r for r in sink.records if r["record"] == "route"][1]
    assert requeue["replica"] == "b" and requeue["from_replica"] == "a"


def test_circuit_breaker_opens_half_opens_and_closes():
    a, b = FakeReplica("a"), FakeReplica("b")
    router = FleetRouter([a, b], breaker_backoff_s=0.05, log=None)
    router.submit(_spec("u1"))
    assert len(a.specs) == 1
    # a crashes holding u1: breaker opens, u1 retries onto b
    a.set_state(state="crashed")
    a.report("u1", "lost")
    router.poll()
    assert router._replicas["a"].breaker == "open"
    assert [s["uid"] for s in b.specs] == ["u1"]
    # while open (and still crashed), everything routes around a
    router.submit(_spec("u2"))
    assert len(a.specs) == 1 and len(b.specs) == 2
    b.report("u1", "ok")
    b.report("u2", "ok")
    router.poll()
    # a comes back; after the backoff the NEXT dispatch is the single
    # half-open probe — and a second request routes around the probe
    a.set_state(state="healthy")
    time.sleep(0.06)
    router.poll()
    router.submit(_spec("u3"))
    router.submit(_spec("u4"))
    assert router._replicas["a"].breaker == "half_open"
    assert [s["uid"] for s in a.specs][-1] == "u3"   # the probe
    assert [s["uid"] for s in b.specs][-1] == "u4"   # routed around
    a.report("u3", "ok")
    b.report("u4", "ok")
    router.poll()
    assert router._replicas["a"].breaker == "closed"
    assert router._replicas["a"].fail_streak == 0
    summary = router.close()
    assert summary["completed"] == 4 and summary["lost"] == 0


def test_probe_loss_does_not_charge_the_request_retry_budget():
    """Regression guard (the PR-16 straggler flake): a half-open probe
    that goes down WITH its target replica was the ROUTER's gamble —
    re-opening the breaker is the whole verdict, and the probed uid
    keeps its retry budget.  Without the probe_loss rule a permanently
    wedged replica (hang drill: never crashes, eats every probe for
    stall_after_s) burns the same request's max_retries through
    repeated probes until the router kills it "failed"."""
    a, b = FakeReplica("a"), FakeReplica("b")
    # max_retries=0: ANY charged loss is instantly terminal — the
    # sharpest possible detector for an unwanted charge.
    router = FleetRouter([a, b], max_retries=0,
                         breaker_backoff_s=0.01, log=None)
    # open a's breaker without involving any request
    a.set_state(state="crashed")
    router.poll()
    assert router._replicas["a"].breaker == "open"
    a.set_state(state="healthy")
    time.sleep(0.02)
    router.poll()
    # the next dispatch is the half-open probe — and the probe target
    # wedges again, surfacing the probed uid as lost
    router.submit(_spec("u1"))
    assert [s["uid"] for s in a.specs] == ["u1"]    # u1 IS the probe
    a.set_state(state="crashed")
    a.report("u1", "lost")
    router.poll()
    # probe loss: breaker re-opens, u1 re-routes UNCHARGED (with
    # max_retries=0 any charge would have killed it "failed" here)
    assert router._replicas["a"].breaker == "open"
    assert [s["uid"] for s in b.specs] == ["u1"]
    b.report("u1", "ok")
    router.poll()
    assert router.results["u1"]["status"] == "ok"
    # a plain (non-probe) loss still charges: u2 dies on its first loss
    router.submit(_spec("u2"))
    b.report("u2", "lost")
    router.poll()
    assert router.results["u2"]["status"] == "failed"
    summary = router.close()
    assert summary["completed"] == 1 and summary["failed"] == 1
    assert summary["lost"] == 0
    assert summary["retries"] == 0      # the probe bounce never counted


def test_deadline_aware_retry_and_budget():
    a = FakeReplica("a")
    router = FleetRouter([a], max_retries=1, log=None)
    # expired deadline: lost resolves as timeout, never re-dispatched
    router.submit(_spec("u1", deadline_s=0.01))
    time.sleep(0.02)
    a.report("u1", "lost")
    router.poll()
    assert router.results["u1"]["status"] == "timeout"
    assert len(a.specs) == 1
    # no deadline: retried up to max_retries, then fails first-class
    router.submit(_spec("u2"))
    a.report("u2", "lost")
    router.poll()
    assert [s["uid"] for s in a.specs] == ["u1", "u2", "u2"]
    a.report("u2", "lost")
    router.poll()
    assert router.results["u2"]["status"] == "failed"
    assert len(a.specs) == 3            # budget exhausted, no 4th try
    summary = router.close()
    assert summary["timed_out"] == 1 and summary["failed"] == 1
    assert summary["retries"] == 1 and summary["lost"] == 0
    assert summary["availability"] == 0.0


def test_late_report_from_released_booking_keeps_inflight_accounting():
    """Review regression (ISSUE 12): a late terminal report from a
    replica whose booking was already released (rescue/retry) must not
    decrement that replica's LIVE inflight count — while the one
    replica still holding a live booking for an already-done uid is
    released exactly when its own report arrives."""
    a, b = FakeReplica("a"), FakeReplica("b")
    router = FleetRouter([a, b], breaker_backoff_s=0.01, log=None)
    # u1 -> a; a loses it; retried to b; b completes it; a then gets a
    # NEW request — and only afterwards late-reports u1.
    router.submit(_spec("u1"))
    a.report("u1", "lost")
    router.poll()
    b.report("u1", "ok")
    router.poll()
    router.submit(_spec("u2"))          # rr -> b, then next to a
    router.submit(_spec("u3"))
    holder = "a" if any(s["uid"] == "u3" for s in a.specs) else "b"
    live_before = router._replicas[holder].inflight
    a.report("u1", "ok")                # late report: booking long gone
    router.poll()
    assert router._replicas[holder].inflight == live_before
    assert router._duplicates == 1

    # the inverse: u5 -> a, a loses it, retried to b — then the
    # ABANDONED copy on a completes first.  a's report wins the uid;
    # b's live booking is released by b's own (now duplicate) report.
    router.submit(_spec("u5"))
    src5 = "a" if any(s["uid"] == "u5" for s in a.specs) else "b"
    other = "b" if src5 == "a" else "a"
    [r for r in (a, b) if r.name == src5][0].report("u5", "lost")
    router.poll()                       # retried onto `other`
    [r for r in (a, b) if r.name == src5][0].report("u5", "ok")
    router.poll()
    assert router.results["u5"]["status"] == "ok"
    assert router._replicas[other].inflight >= 1    # still booked
    [r for r in (a, b) if r.name == other][0].report("u5", "ok")
    router.poll()
    assert router._replicas[other].inflight == \
        sum(1 for e in router._inflight.values()
            if e["replica"] == other)   # booking released exactly once


def test_outbox_replay_skips_drained_occurrences_not_uids(tmp_path):
    """Review regression (ISSUE 12): a 'drained' outbox line consumed
    ONE inbox occurrence — the uid itself must stay servable, or a
    drain-requeue routed back to the same replica (single-survivor
    fleet) is silently lost after the restart."""
    import serve as serve_mod

    path = str(tmp_path / "outbox.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({"uid": "u-ok", "status": "ok",
                             "tokens": []}) + "\n")
        fh.write(json.dumps({"uid": "u-drained", "status": "drained"})
                 + "\n")
        fh.write(json.dumps({"uid": "u-double", "status": "drained"})
                 + "\n")
        fh.write(json.dumps({"uid": "u-double", "status": "drained"})
                 + "\n")
    box = serve_mod._Outbox(path)
    assert box.should_skip("u-ok") and box.should_skip("u-ok")
    # one drain = skip exactly one occurrence, then serve
    assert box.should_skip("u-drained")
    assert not box.should_skip("u-drained")
    # two drains = skip exactly two
    assert box.should_skip("u-double")
    assert box.should_skip("u-double")
    assert not box.should_skip("u-double")
    assert not box.should_skip("u-new")
    box.close()


def test_fleet_report_does_not_misread_replica_child_stream(tmp_path):
    """Review regression (ISSUE 12): a serve.py replica child's OWN
    metrics stream carries replica_state heartbeats but is not a
    router stream — it must fall through to the rank path, not error
    as a 'truncated router stream'."""
    report = _load_tool("fleet_report")
    path = str(tmp_path / "child.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps(
            {"record": "replica_state", "time": 1.0, "replica": "r0",
             "state": "serving", "tick": 3, "pending": 0,
             "blocks_live": 2, "pid": 42}) + "\n")
    assert report.load_fleet_records(path) is None

    # ...while a ROUTER stream truncated before its first dispatch
    # still self-identifies (header platform) and gets the truncation
    # diagnostic instead of a nonsensical rank report
    trunc = str(tmp_path / "trunc.jsonl")
    with open(trunc, "w") as fh:
        fh.write(json.dumps(
            {"record": "run_header", "schema": 10, "time": 1.0,
             "run_id": "x", "num_devices": 0, "process_index": 0,
             "platform": "fleet-router", "config": {}}) + "\n")
    assert report.load_fleet_records(trunc) is not None
    assert report.main([trunc]) == 2    # truncated, not a rank stream


def test_backlog_parks_until_capacity_returns():
    a = FakeReplica("a")
    a.set_state(state="stopped")
    sink = ListSink()
    router = FleetRouter([a], sink=sink, log=None)
    router.poll()                       # pull the down state in
    router.submit(_spec("u1"))
    assert a.specs == [] and not router.done()
    router.poll()
    assert a.specs == []                # still parked
    a.set_state(state="healthy")
    router.poll()
    assert [s["uid"] for s in a.specs] == ["u1"]
    route = [r for r in sink.records if r["record"] == "route"][0]
    assert route["reason"] == "backlog"
    a.report("u1", "ok")
    router.poll()
    assert router.done()


def test_router_stream_validates_and_traces(tmp_path, monkeypatch):
    monkeypatch.delenv("APEX_TRACE_ID", raising=False)
    path = str(tmp_path / "fleet.jsonl")
    a, b = FakeReplica("a"), FakeReplica("b")
    router = FleetRouter([a, b], metrics_jsonl=path, trace=True,
                         log=None)
    try:
        router.submit(_spec("u1"))
        a.report("u1", "drained")
        router.poll()
        b.report("u1", "ok")
        router.poll()
        router.scenario, router.verdict = "none", "pass"
        router.close()
    finally:
        monkeypatch.delenv("APEX_TRACE_ID", raising=False)
    records = obs.read_jsonl(path)
    assert obs_schema.validate_stream(records) == []
    kinds = [r["record"] for r in records]
    assert kinds[0] == "run_header"
    assert kinds[-1] == "fleet_summary"
    assert "route" in kinds and "replica_state" in kinds
    # the router's trace side: one clock_sync before the first event,
    # structurally clean under the exporter's lint
    assert sum(1 for k in kinds if k == "clock_sync") == 1
    export = _load_tool("trace_export")
    assert export.check_stream(records, "fleet.jsonl") == []
    ids = {r["trace_id"] for r in records
           if r["record"] in ("trace_event", "clock_sync")}
    assert ids == {router.trace_id}


# ========================================================= schema v10

def test_schema_v10_fleet_records_validate():
    recs = [
        {"record": "route", "time": 1.0, "request_id": "u1",
         "replica": "r0", "policy": "round_robin", "attempt": 0,
         "reason": "dispatch", "run_id": "x"},
        {"record": "route", "time": 1.0, "request_id": "u1",
         "replica": "r1", "reason": "requeue_drain",
         "from_replica": "r0"},
        {"record": "replica_state", "time": 1.0, "replica": "r0",
         "state": "serving", "tick": 3, "pending": 2, "blocks_live": 5,
         "pid": 123, "run_id": "x"},
        {"record": "replica_state", "time": 1.0, "replica": "r0",
         "state": "restarting", "exit_code": 75,
         "classification": "preempted"},
        {"record": "fleet_summary", "time": 1.0, "replicas": 2,
         "requests": 16, "availability": 1.0, "policy": "least_kv",
         "scenario": "rolling_restart", "verdict": "pass",
         "completed": 16, "failed": 0, "timed_out": 0, "shed": 0,
         "cancelled": 0, "rejected": 0, "drained_requeued": 2,
         "retries": 0, "duplicates": 0, "lost": 0,
         "per_replica": {"r0": {"ok": 8}}, "routing": {"skew": 1.0},
         "duration_s": 20.0, "run_id": "x"},
        {"record": "restart", "time": 1.0, "attempt": 0,
         "exit_code": 75, "reason": "preemption",
         "classification": "preempted", "backoff_s": 0.0},
    ]
    for rec in recs:
        assert obs_schema.validate_record(rec) == [], rec
    assert obs_schema.SCHEMA_VERSION >= 10   # v10 tables are a floor
    # malformed: unknown field, missing required, wrong type
    assert obs_schema.validate_record(
        {"record": "route", "time": 1.0, "request_id": "u",
         "replica": "r", "oops": 1}) != []
    assert obs_schema.validate_record(
        {"record": "replica_state", "time": 1.0, "replica": "r"}) != []
    assert obs_schema.validate_record(
        {"record": "fleet_summary", "time": 1.0, "replicas": 2,
         "requests": 1, "availability": "high"}) != []


def test_schema_v1_v9_streams_still_validate():
    old = [
        {"record": "step", "step": 1, "epoch": 0, "loss": 1.0,
         "scale": 1.0, "step_time_ms": 9.0, "items_per_sec": 10.0},
        {"record": "crash_dump", "time": 1.0, "reason": "sigterm"},
        {"record": "request_complete", "time": 1.0, "request_id": "r",
         "prompt_tokens": 3, "output_tokens": 4, "ttft_ms": 1.0,
         "tpot_ms": 1.0, "finish_reason": "length"},
        {"record": "preemption", "time": 1.0, "signal": "SIGTERM",
         "step": 5},
        {"record": "restart", "time": 1.0, "attempt": 0,
         "exit_code": 75, "reason": "preemption"},   # v4: no classification
        {"record": "request_failed", "time": 1.0, "request_id": "r",
         "status": "timeout"},
        {"record": "serve_drain", "time": 1.0, "signal": "SIGTERM"},
        {"record": "compile_event", "time": 1.0, "name": "f",
         "compile_ms": 2.0, "recompile_cause": "dot shape"},
        {"record": "cost_model", "time": 1.0, "name": "f",
         "flops": None},
        {"record": "trace_event", "ph": "X", "name": "tick", "ts": 0.5,
         "dur": 0.1, "tid": "engine", "trace_id": "t"},
        {"record": "clock_sync", "time": 1.0, "ts": 0.4,
         "trace_id": "t"},
    ]
    for rec in old:
        assert obs_schema.validate_record(rec) == [], rec


# ============================================== loadgen substream (sat)

def test_loadgen_substream_disjoint_and_deterministic():
    """Two replicas sharing a base seed used to serve IDENTICAL prompt
    sets; substream(i) derivation makes them disjoint while each stays
    reproducible."""
    assert substream(0, 0) != 0         # index 0 is not the identity
    assert substream(7, 3) == substream(7, 3)
    assert substream(7, 3) != substream(7, 4)
    assert substream(8, 3) != substream(7, 3)
    with pytest.raises(ValueError):
        substream(0, -1)

    def prompts(sub):
        reqs = synthetic_requests(12, vocab_size=256, seed=42,
                                  seed_substream=sub)
        return [tuple(r.prompt) for r in reqs]

    base = prompts(None)
    r0a, r0b, r1 = prompts(0), prompts(0), prompts(1)
    assert r0a == r0b                   # deterministic per index
    assert not set(r0a) & set(r1)       # disjoint across replicas
    assert r0a != base                  # substreamed != raw seed
    # regression: the pre-fix behavior (same seed, no substream) is
    # exactly the identical-prompt-sets bug
    assert prompts(None) == base


# ================================== supervisor classification (satellite)

def test_supervisor_restart_classification(tmp_path):
    """The v10 satellite: restart records say HOW the child died
    (preempted/crashed/stall_killed) so fleet tooling never re-parses
    child streams.  Two tiny no-jax children, the test_trace pattern."""
    sup_mod = _load_supervisor()

    def run_child(first_exit):
        marker = tmp_path / f"ran{first_exit}"
        child = tmp_path / f"c{first_exit}.py"
        child.write_text(
            f"import os, sys\n"
            f"if os.path.exists({str(marker)!r}): sys.exit(0)\n"
            f"open({str(marker)!r}, 'w').close()\n"
            f"sys.exit({first_exit})\n")
        stream = tmp_path / f"sup{first_exit}.jsonl"
        sup = sup_mod.Supervisor(
            [sys.executable, str(child)], metrics_jsonl=str(stream),
            max_restarts=2, backoff_s=0.01, sleep_fn=lambda s: None,
            log=lambda *a: None)
        assert sup.run() == 0
        recs = obs.read_jsonl(str(stream))
        assert obs_schema.validate_stream(recs) == []
        return [r for r in recs if r["record"] == "restart"]

    preempted = run_child(75)
    assert len(preempted) == 1
    assert preempted[0]["classification"] == "preempted"
    assert preempted[0]["reason"] == "preemption"
    crashed = run_child(3)
    assert len(crashed) == 1
    assert crashed[0]["classification"] == "crashed"
    assert sup_mod.SCHEMA == obs_schema.SCHEMA_VERSION >= 10


# ==================================== in-process chaos (shared compile)

@pytest.fixture(scope="module")
def model_and_params():
    model = gpt_tiny()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def _thread_fleet(model, params, n, faults=None):
    """n ThreadReplicas over the session's SLOTS=4/MAX_LEN=32 decode
    geometry — the engines share ONE compiled program (the step cache
    keys on the module-clone config), so these tests add no compiles."""
    def factory():
        return ServeEngine(model, params, num_slots=SLOTS,
                           max_len=MAX_LEN,
                           rng=jax.random.PRNGKey(0))

    def make_request(spec):
        return Request(prompt=spec["prompt"],
                       max_new_tokens=int(spec["max_new_tokens"]),
                       temperature=float(spec.get("temperature", 0.0)),
                       top_k=int(spec.get("top_k", 0)),
                       eos_id=spec.get("eos_id"),
                       deadline_s=spec.get("deadline_s"),
                       uid=spec["uid"])

    return [ThreadReplica(f"r{i}", factory, make_request,
                          fault=(faults or {}).get(f"r{i}"))
            for i in range(n)]


def _stop_all(router, replicas):
    # Short join: a replica abandoned mid-hang (the straggler drill)
    # never exits its sleep — its daemon thread is simply left behind.
    for r in replicas:
        if router.replica_state(r.name) != "stalled":
            r.stop(timeout_s=2.0)


def test_fleet_token_identity_across_replicas(model_and_params):
    """Routing must not change WHAT gets served: every greedy request
    completes on some replica with tokens identical to one-shot
    generate() — the serve smoke's contract, now fleet-wide."""
    model, params = model_and_params
    replicas = _thread_fleet(model, params, 2)
    router = FleetRouter(replicas, policy="round_robin", log=None)
    specs = synthetic_specs(10, vocab_size=model.vocab_size, seed=3,
                            prompt_len=(3, 8), max_new=(3, 10))
    summary = run_scenario("none", router, replicas, specs,
                           timeout_s=90)
    _stop_all(router, replicas)
    assert summary["verdict"] == "pass"
    assert summary["completed"] == 10 and summary["lost"] == 0
    # both replicas actually served (the routing-balance stats agree)
    assert all(v > 0 for v in
               summary["routing"]["dispatches"].values())
    for spec in specs:
        ev = router.results[spec["uid"]]
        assert ev["status"] == "ok"
        P = len(spec["prompt"])
        n = len(ev["tokens"])
        assert n == min(spec["max_new_tokens"], MAX_LEN - P)
        ref = generate(model, params,
                       jnp.asarray([spec["prompt"]], jnp.int32),
                       max_len=MAX_LEN)
        np.testing.assert_array_equal(
            np.asarray(ref)[0, P:P + n],
            np.asarray(ev["tokens"], np.int32), err_msg=spec["uid"])


def _storm_once(model, params, specs):
    # tick 3: early enough that r0 still holds live slots when it dies
    # (a crash after the last harvest loses nothing and proves nothing)
    faults = {"r0": FaultPlan("crash", 3, kinds=SERVE_KINDS)}
    replicas = _thread_fleet(model, params, 3, faults)
    router = FleetRouter(replicas, breaker_backoff_s=0.1, log=None)
    summary = run_scenario("crash_storm", router, replicas, specs,
                           crashed_names=["r0"], timeout_s=90)
    _stop_all(router, replicas)
    score = {k: summary[k] for k in
             ("completed", "failed", "timed_out", "retries", "lost",
              "availability", "verdict")}
    score["r0_lost"] = summary["per_replica"]["r0"].get("lost", 0)
    return score


def test_crash_storm_inprocess_deterministic_score(model_and_params):
    """crash@tick on pre-submitted queues: which requests the crash
    takes down is a pure function of the workload (ThreadReplica ticks
    only when work exists), so the scenario SCORE is bit-reproducible
    — run it twice and compare."""
    model, params = model_and_params
    specs = synthetic_specs(12, vocab_size=model.vocab_size, seed=4,
                            prompt_len=(3, 6), max_new=(3, 8))
    first = _storm_once(model, params, specs)
    assert first["verdict"] == "pass"
    assert first["completed"] == 12 and first["lost"] == 0
    assert first["retries"] >= 1        # the crash actually cost work
    assert first["r0_lost"] >= 1
    second = _storm_once(model, params, specs)
    assert second == first              # deterministic chaos score


def test_crash_storm_fails_when_the_crash_never_fires(model_and_params):
    """Review regression (ISSUE 12): a drill armed past the workload's
    last tick never fires — the scenario must FAIL its
    every_crash_observed check rather than score a storm that never
    happened."""
    model, params = model_and_params
    faults = {"r0": FaultPlan("crash", 10_000, kinds=SERVE_KINDS)}
    replicas = _thread_fleet(model, params, 2, faults)
    router = FleetRouter(replicas, log=None)
    specs = synthetic_specs(6, vocab_size=model.vocab_size, seed=7,
                            prompt_len=(3, 5), max_new=(3, 5))
    summary = run_scenario("crash_storm", router, replicas, specs,
                           crashed_names=["r0"], timeout_s=60)
    _stop_all(router, replicas)
    assert summary["completed"] == 6 and summary["lost"] == 0
    assert summary["verdict"] == "fail"     # the chaos never happened


def test_straggler_inprocess_stall_rescue(model_and_params):
    """A hung replica (hang drill: the silent-wedge shape) never
    crashes; the router's stall detector must open its breaker and
    rescue its requests onto siblings — availability stays 1.0."""
    model, params = model_and_params
    # Warm the shared decode-step program BEFORE arming the stall
    # clock: a cold jit compile (seconds on this rig) freezes the
    # healthy siblings' first tick past any sane stall_after_s, so a
    # fresh-process run (`pytest -k straggler`) would false-trip them
    # and charge rescues before the hang drill even fires.
    warm = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                       rng=jax.random.PRNGKey(0))
    warm.queue.submit_all([Request(prompt=[1, 2, 3],
                                   max_new_tokens=2, uid="warm")])
    warm.queue.close()
    warm.run(max_steps=50)
    faults = {"r0": FaultPlan("hang", 3, kinds=SERVE_KINDS)}
    replicas = _thread_fleet(model, params, 3, faults)
    sink = ListSink()
    # Regression guard (PR-16 acceptance flake).  Two margins at once:
    # (a) stall_after_s must stay well above the worst-case tick gap
    # of a HEALTHY loaded sibling — at 0.4s a single-core rig under
    # full-suite contention can stretch a healthy replica's jitted
    # tick past the threshold, falsely breakering it and charging a
    # retry to every uid it holds.  The genuinely hung replica is
    # detected at ANY threshold (its progress age grows without
    # bound), so widening only removes false positives.  (b) The wide
    # threshold also keeps the half-open PROBE path hot: r0 never
    # crashes, so after each rescue its breaker half-opens and a live
    # uid probes the wedge, parking there for a full stall_after_s per
    # cycle.  Probe losses must not charge the probed uid's retry
    # budget (router probe_loss rule) or this scenario dies "failed"
    # nondeterministically — exactly the flake this pins.  Keep 2.0s.
    router = FleetRouter(replicas, stall_after_s=2.0,
                         breaker_backoff_s=0.1, sink=sink, log=None)
    specs = synthetic_specs(12, vocab_size=model.vocab_size, seed=5,
                            prompt_len=(3, 6), max_new=(3, 8))
    summary = run_scenario("straggler", router, replicas, specs,
                           straggler_name="r0", timeout_s=90)
    assert summary["verdict"] == "pass"     # incl. the stall_detected check
    assert summary["completed"] == 12 and summary["lost"] == 0
    assert summary["retries"] >= 1      # rescued off the straggler
    # the transition was recorded (the state legitimately reverts once
    # the rescue empties the straggler's inflight set — an idle replica
    # that is not progressing is not stalled)
    assert any(r["record"] == "replica_state" and r["replica"] == "r0"
               and r["state"] == "stalled" for r in sink.records)
    # the rescue is the deadline-aware retry path, not a drain
    assert summary["drained_requeued"] == 0
    for r in replicas[1:]:
        r.stop(timeout_s=2.0)           # r0's thread is hung: abandoned


def test_rolling_restart_inprocess(model_and_params):
    """Thread-transport rolling restart: interrupt() drains the engine
    (queued requests requeue to the sibling) and rebuilds it — zero
    lost, availability 1.0, both replicas restarted."""
    model, params = model_and_params
    replicas = _thread_fleet(model, params, 2)
    router = FleetRouter(replicas, log=None)
    specs = synthetic_specs(16, vocab_size=model.vocab_size, seed=6,
                            prompt_len=(3, 6), max_new=(4, 8))
    summary = run_scenario("rolling_restart", router, replicas, specs,
                           timeout_s=90, settle_timeout_s=30)
    _stop_all(router, replicas)
    assert summary["verdict"] == "pass"
    assert summary["completed"] == 16 and summary["lost"] == 0
    assert summary["availability"] == 1.0
    assert all(r.restarts == 1 for r in replicas)


# ===================================== disagg fleet chaos (ISSUE 15)


def test_router_spool_stale_sweep_reroutes_through_prefill():
    """The one crash window the lease cannot redeliver: a decode
    worker acked its claim (spool file gone) then died before any
    terminal reached its outbox — nothing will ever report the uid.
    With spool_timeout_s armed the router presumes it lost and
    re-routes it through a prefill replica from scratch."""
    pre = FakeReplica("p0")
    pre.role = "prefill"
    dec = FakeReplica("d0")
    dec.role = "decode"
    router = FleetRouter([pre, dec], spool_timeout_s=0.05, log=None)
    router.submit(_spec("u1"))
    assert [s["uid"] for s in pre.specs] == ["u1"]   # never to decode
    pre.report("u1", "handoff")
    router.poll()
    assert not router.done()                # parked on the spool
    time.sleep(0.08)
    router.poll()                           # stale sweep fires
    assert [s["uid"] for s in pre.specs] == ["u1", "u1"]  # re-prefilled
    pre.report("u1", "ok", tokens=[1])
    router.poll()
    assert router.done()
    summary = router.summary_record()
    assert summary["lost"] == 0 and summary["retries"] == 1
    assert summary["handoffs"] == 1 and summary["in_spool"] == 0


def test_thread_replica_rejects_inert_handoff_drills(model_and_params):
    """A drill the transport/drive loop can never express must be a
    construction error, not a silently-clean chaos run."""
    model, params = model_and_params

    def factory():
        return ServeEngine(model, params, num_slots=SLOTS,
                           max_len=MAX_LEN, role="decode")

    for kind in ("handoff_dup", "handoff_torn", "sentinel_lost"):
        with pytest.raises(ValueError, match="cannot express"):
            ThreadReplica("d0", factory, role="decode",
                          transport_factory=lambda: None,
                          fault=FaultPlan(kind, 1, kinds=SERVE_KINDS))
    with pytest.raises(ValueError, match="cannot express"):
        ThreadReplica("p0", factory, lambda s: s, role="prefill",
                      fault=FaultPlan("handoff_crash_preack", 1,
                                      kinds=SERVE_KINDS))


def _disagg_thread_fleet(model, params, spool, lease_s=0.3,
                         crash_decode=None, crash_prefill_tick=None):
    """1 prefill + 2 decode ThreadReplicas over one leased FileTransport
    spool — every engine rides the session's compiled programs (the
    [4, 8] prefill step shared with test_serve, the [4, 1] decode step
    shared with test_disagg): zero new compiles."""
    from apex_example_tpu.serve import FileTransport

    def make_request(spec):
        return Request(prompt=spec["prompt"],
                       max_new_tokens=int(spec["max_new_tokens"]),
                       temperature=float(spec.get("temperature", 0.0)),
                       top_k=int(spec.get("top_k", 0)),
                       eos_id=spec.get("eos_id"),
                       deadline_s=spec.get("deadline_s"),
                       uid=spec["uid"])

    def prefill_factory():
        tx = FileTransport(spool, worker="p0.tx")
        return ServeEngine(model, params, num_slots=SLOTS,
                           max_len=MAX_LEN, rng=jax.random.PRNGKey(0),
                           role="prefill", handoff_sink=tx.send)

    def decode_factory():
        return ServeEngine(model, params, num_slots=SLOTS,
                           max_len=MAX_LEN, rng=jax.random.PRNGKey(0),
                           role="decode")

    pre_fault = FaultPlan("crash", crash_prefill_tick,
                          kinds=SERVE_KINDS) if crash_prefill_tick \
        else None
    replicas = [ThreadReplica("p0", prefill_factory, make_request,
                              fault=pre_fault, role="prefill")]
    for name in ("d0", "d1"):
        fault = FaultPlan("handoff_crash_preack", 1,
                          kinds=SERVE_KINDS) \
            if name == crash_decode else None

        def tx_factory(worker=name):
            return FileTransport(spool, worker=worker, lease_s=lease_s)

        replicas.append(ThreadReplica(name, decode_factory, fault=fault,
                                      role="decode",
                                      transport_factory=tx_factory))
    return replicas


def _midspool_once(model, params, specs, spool):
    replicas = _disagg_thread_fleet(model, params, spool,
                                    crash_decode="d0")
    router = FleetRouter(replicas, log=None)
    summary = run_scenario("decode_crash_midspool", router, replicas,
                           specs, crashed_name="d0", timeout_s=90)
    results = dict(router.results)
    for r in replicas:
        r.stop(timeout_s=5.0)
    # The INVARIANT score: everything here is a pure function of the
    # workload (which uids exist, that they all complete, that nothing
    # leaks) — handoff_redelivered is deliberately excluded: HOW MANY
    # claims the dead worker held when it died depends on claim-race
    # timing, only that the peer finished them does not.
    score = {k: summary[k] for k in
             ("completed", "failed", "timed_out", "lost",
              "availability", "verdict", "requests", "handoffs",
              "in_spool", "prefill_replicas", "decode_replicas")}
    return score, summary, results


def test_disagg_fleet_decode_crash_midspool_deterministic(
        model_and_params, tmp_path):
    """THE ISSUE 15 chaos acceptance: a 1-prefill + 2-decode fleet;
    decode worker d0 dies in the ack-crash window holding claimed-but-
    unacked handoffs; nobody restarts it — the PEER reclaims the
    expired leases and finishes the redelivered handoffs.  Zero lost,
    exactly-once per uid, redelivery really happened, survivors'
    outputs token-identical to generate(), and the invariant score is
    bit-identical across two runs."""
    model, params = model_and_params
    specs = synthetic_specs(10, vocab_size=model.vocab_size, seed=8,
                            prompt_len=(3, 8), max_new=(3, 8))
    first, summary, results = _midspool_once(
        model, params, specs, str(tmp_path / "spool_a"))
    assert first["verdict"] == "pass"
    assert first["completed"] == 10 and first["lost"] == 0
    assert first["availability"] == 1.0
    assert first["handoffs"] == 10 and first["in_spool"] == 0
    assert first["prefill_replicas"] == 1
    assert first["decode_replicas"] == 2
    assert summary["handoff_redelivered"] >= 1   # the peer did work
    # every uid exactly once, token-identical to one-shot generate()
    assert len(results) == 10
    for spec in specs:
        ev = results[spec["uid"]]
        assert ev["status"] == "ok", (spec["uid"], ev)
        P = len(spec["prompt"])
        n = len(ev["tokens"])
        ref = generate(model, params,
                       jnp.asarray([spec["prompt"]], jnp.int32),
                       max_len=MAX_LEN)
        np.testing.assert_array_equal(
            np.asarray(ref)[0, P:P + n],
            np.asarray(ev["tokens"], np.int32), err_msg=spec["uid"])
    second, _, _ = _midspool_once(model, params, specs,
                                  str(tmp_path / "spool_b"))
    assert second == first              # deterministic chaos score


def test_disagg_fleet_prefill_crash(model_and_params, tmp_path):
    """The prefill role dies mid-serve: requests it held come back
    lost and re-route once the scenario restarts it; requests already
    on the spool keep decoding untouched — zero lost, spool drained."""
    model, params = model_and_params
    spool = str(tmp_path / "spool")
    # tick 1: the first admitted wave hands off within its first tick
    # (one-chunk prompts sample their first token in the same tick),
    # so a later crash would find an empty queue and prove nothing —
    # crash while 6 of 10 requests are still queued behind the slots.
    replicas = _disagg_thread_fleet(model, params, spool,
                                    crash_prefill_tick=1)
    router = FleetRouter(replicas, breaker_backoff_s=0.1, log=None)
    specs = synthetic_specs(10, vocab_size=model.vocab_size, seed=9,
                            prompt_len=(3, 8), max_new=(3, 8))
    summary = run_scenario("prefill_crash", router, replicas, specs,
                           crashed_name="p0", timeout_s=90)
    for r in replicas:
        r.stop(timeout_s=5.0)
    assert summary["verdict"] == "pass"
    assert summary["completed"] == 10 and summary["lost"] == 0
    assert summary["availability"] == 1.0
    assert summary["retries"] >= 1          # the crash really cost work
    assert summary["handoffs"] >= 10        # every uid crossed the spool
    assert summary["in_spool"] == 0
    assert replicas[0].restarts == 1


def test_proc_replica_disagg_argv(tmp_path):
    """Role plumbing for supervised children: a decode ProcReplica
    spawns serve.py with NO --inbox (the spool is its intake), the
    role/spool flags, and the drill-stripping drop flag; submit()
    always refuses on it."""
    from apex_example_tpu.fleet.replica import ProcReplica
    spool = str(tmp_path / "spool")
    dec = ProcReplica("d0", str(tmp_path), REPO, role="decode",
                      spool_dir=spool)
    argv = dec.argv()
    sup_side = argv[:argv.index("--")]
    child = argv[argv.index("--") + 1:]
    assert "--inbox" not in child
    assert child[child.index("--role") + 1] == "decode"
    assert child[child.index("--handoff-dir") + 1] == spool
    assert "--outbox" in child
    assert "--drop-flag-on-restart=--inject-fault" in sup_side
    assert dec.submit({"uid": "x"}) is False
    assert dec.role == "decode"
    pre = ProcReplica("p0", str(tmp_path), REPO, role="prefill",
                      spool_dir=spool)
    child = pre.argv()[pre.argv().index("--") + 1:]
    assert "--inbox" in child
    assert child[child.index("--role") + 1] == "prefill"
    with pytest.raises(ValueError, match="spool_dir"):
        ProcReplica("x0", str(tmp_path), REPO, role="decode")


# ================================= tools over the checked-in scenario

def test_ci_gate_fleet_stream_over_checked_in_scenario(tmp_path,
                                                       capsys):
    ci_gate = _load_tool("ci_gate")
    # ONE full-command run (this is the CI surface: graftlint + fleet
    # gate); the failure variants exercise the gate function directly —
    # re-linting the whole tree per variant would buy nothing.
    assert ci_gate.main(["--fleet-stream", FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "fleet gate" in out and "PASS" in out
    assert ci_gate.main(["--fleet-stream",
                         str(tmp_path / "missing.jsonl")]) == 2

    # doctored streams fail loudly: lost requests / low availability /
    # failed verdict / no summary
    records = obs.read_jsonl(FIXTURE)
    summ = next(r for r in records if r["record"] == "fleet_summary")

    def doctored(**kw):
        path = str(tmp_path / f"bad{len(kw)}{list(kw)[0]}.jsonl")
        with open(path, "w") as fh:
            for r in records:
                r2 = dict(r, **kw) if r["record"] == "fleet_summary" \
                    else r
                fh.write(json.dumps(r2) + "\n")
        return path

    assert ci_gate._fleet_gate(FIXTURE, 1.0) == 0
    assert ci_gate._fleet_gate(doctored(lost=2), 1.0) == 1
    assert ci_gate._fleet_gate(doctored(availability=0.5), 1.0) == 1
    assert ci_gate._fleet_gate(doctored(verdict="fail"), 1.0) == 1
    assert ci_gate._fleet_gate(FIXTURE, summ["availability"]) == 0
    no_summary = str(tmp_path / "nosummary.jsonl")
    with open(no_summary, "w") as fh:
        for r in records:
            if r["record"] != "fleet_summary":
                fh.write(json.dumps(r) + "\n")
    assert ci_gate._fleet_gate(no_summary, 1.0) == 1


def test_fleet_report_serve_fleet_mode(tmp_path, capsys):
    """The fleet_report satellite: per-replica availability table,
    routing-balance skew, scenario verdict line — auto-detected from
    the v10 records, still jax-free (the graftlint contract covers
    it)."""
    report = _load_tool("fleet_report")
    assert report.main([FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "serve fleet:" in out
    assert "scenario rolling_restart" in out
    assert "replica" in out and "avail" in out
    assert "r0" in out and "r1" in out
    assert "routing balance" in out
    assert "scenario verdict: PASS" in out

    # lost requests flip the exit code
    records = obs.read_jsonl(FIXTURE)
    bad = str(tmp_path / "lost.jsonl")
    with open(bad, "w") as fh:
        for r in records:
            r2 = dict(r, lost=3, availability=0.8, verdict="fail") \
                if r["record"] == "fleet_summary" else r
            fh.write(json.dumps(r2) + "\n")
    assert report.main([bad]) == 1
    out = capsys.readouterr().out
    assert "LOST REQUESTS" in out

    # the checked-in stream also validates and the TRAIN mode is
    # untouched (a rank stream without fleet records takes the old path)
    assert obs_schema.validate_stream(records) == []
    lint = _load_tool("metrics_lint")
    assert lint.lint(FIXTURE)[0] == 0


def test_telemetry_report_fleet_line(capsys):
    report = _load_tool("telemetry_report")
    assert report.main([FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "FLEET:" in out and "availability" in out


def test_replica_mode_steps_cap_reports_stranded(tmp_path, capsys):
    """Review regression (ISSUE 12): a --steps-capped replica that runs
    out of ticks with inbox requests still queued/mid-decode must exit
    nonzero with the stranded warning — not 0 with silent loss (the
    router would wait out its timeout on those uids)."""
    import serve as serve_mod

    inbox = str(tmp_path / "inbox.jsonl")
    with open(inbox, "w") as fh:
        for i in range(6):
            fh.write(json.dumps({"uid": f"s{i}", "prompt": [1 + i, 2, 3],
                                 "max_new_tokens": 8}) + "\n")
        # no close sentinel: the queue stays open at the cap
    rc = serve_mod.main(["--inbox", inbox,
                         "--outbox", str(tmp_path / "outbox.jsonl"),
                         "--replica-id", "rX", "--slots", str(SLOTS),
                         "--max-len", str(MAX_LEN), "--steps", "3"])
    assert rc == 1
    err = capsys.readouterr().err
    assert "unfinished at the --steps cap" in err


def test_fleet_cli_thread_smoke(tmp_path, capsys):
    """fleet.py --transport thread end to end: the CLI builds N
    in-process replicas over ONE shared compiled program (the session's
    SLOTS=4/MAX_LEN=32 geometry), routes, scores, exits 0 on a passing
    verdict, and leaves a lintable v10 stream."""
    import fleet as fleet_cli

    path = str(tmp_path / "fleet.jsonl")
    rc = fleet_cli.main(["--transport", "thread", "--replicas", "2",
                         "--requests", "6", "--slots", str(SLOTS),
                         "--max-len", str(MAX_LEN),
                         "--metrics-jsonl", path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "verdict=pass" in out
    records = obs.read_jsonl(path)
    assert obs_schema.validate_stream(records) == []
    summary = records[-1]
    assert summary["record"] == "fleet_summary"
    assert summary["completed"] == 6 and summary["lost"] == 0
    lint = _load_tool("metrics_lint")
    assert lint.lint(path)[0] == 0


# ============================================ THE subprocess scenario

def test_rolling_restart_supervised_e2e(tmp_path):
    """The ISSUE 12 acceptance bar: 2 supervised serve.py subprocess
    replicas under burst load, SIGTERM'd in turn by the scenario —
    every submitted uid reaches exactly one non-drained terminal
    status (zero lost), fleet availability 1.0, ONE trace_id across
    router + children + supervisors, and the merged 7-stream export is
    trace_export --check clean.  The suite's one new subprocess e2e."""
    import fleet as fleet_cli

    fleet_jsonl = str(tmp_path / "fleet.jsonl")
    workdir = str(tmp_path / "work")
    argv = ["--replicas", "2", "--transport", "proc",
            "--scenario", "rolling_restart", "--requests", "16",
            "--slots", "2", "--max-len", "16",
            "--metrics-jsonl", fleet_jsonl, "--workdir", workdir,
            "--trace", "--timeout", "150"]
    try:
        rc = fleet_cli.main(argv)
    finally:
        os.environ.pop("APEX_TRACE_ID", None)   # the router exports it
    assert rc == 0

    records = obs.read_jsonl(fleet_jsonl)
    assert obs_schema.validate_stream(records) == []
    summary = records[-1]
    assert summary["record"] == "fleet_summary"
    assert summary["scenario"] == "rolling_restart"
    assert summary["verdict"] == "pass"
    assert summary["availability"] == 1.0
    assert summary["lost"] == 0
    assert summary["requests"] == 16

    # zero lost at the uid level: every uid exactly ONE non-drained
    # terminal across the whole fleet (outboxes are append-only and
    # survive the restarts, so this audits all attempts at once)
    terminal = {}
    for name in ("r0", "r1"):
        with open(os.path.join(workdir, name, "outbox.jsonl")) as fh:
            for line in fh:
                ev = json.loads(line)
                if ev.get("status") != "drained":
                    terminal[ev["uid"]] = terminal.get(ev["uid"], 0) + 1
    assert len(terminal) == 16
    assert set(terminal.values()) == {1}

    # both replicas were actually restarted (supervisor streams carry
    # the v10 classification: a drain is a preemption, not a crash)
    for name in ("r0", "r1"):
        sup = obs.read_jsonl(os.path.join(workdir, name, "sup.jsonl"))
        restarts = [r for r in sup if r["record"] == "restart"]
        assert len(restarts) == 1
        assert restarts[0]["exit_code"] == 75
        assert restarts[0]["classification"] == "preempted"
        att0 = obs.read_jsonl(
            os.path.join(workdir, name, "serve.jsonl"))
        assert any(r["record"] == "serve_drain" for r in att0)
        beats = [r for r in att0 if r["record"] == "replica_state"]
        assert beats and all(r["replica"] == name for r in beats)

    # ONE trace across router + 2 children x 2 attempts + 2 supervisors,
    # and the merged export passes the structural lint
    streams = [fleet_jsonl]
    for name in ("r0", "r1"):
        streams += [os.path.join(workdir, name, "serve.jsonl"),
                    os.path.join(workdir, name, "serve.jsonl.attempt1"),
                    os.path.join(workdir, name, "sup.jsonl")]
    assert all(os.path.exists(s) for s in streams)
    ids = set()
    for s in streams:
        for r in obs.read_jsonl(s):
            if r["record"] in ("trace_event", "clock_sync") \
                    and "trace_id" in r:
                ids.add(r["trace_id"])
    assert len(ids) == 1, ids
    export = _load_tool("trace_export")
    assert export.main(["--check"] + streams) == 0
    merged = str(tmp_path / "merged.json")
    assert export.main(streams + ["-o", merged]) == 0
    names = {e["name"] for e in
             json.load(open(merged))["traceEvents"]}
    assert {"route", "interrupt", "drain", "attempt",
            "scenario:rolling_restart"} <= names

    # and the recorded stream passes the CI fleet gate + fleet_report
    ci_gate = _load_tool("ci_gate")
    assert ci_gate.main(["--fleet-stream", fleet_jsonl]) == 0
    report = _load_tool("fleet_report")
    assert report.main([fleet_jsonl]) == 0


def test_disagg_proc_decode_crash_e2e(tmp_path, capsys):
    """THE ISSUE 15 subprocess chaos e2e: a 1-prefill + 2-decode
    supervised serve.py fleet over one leased spool; decode child r1
    crashes in the ack-crash window at its first admit
    (handoff_crash_preack@1), its supervisor restarts it with the
    drill STRIPPED (the drop-flag satellite, live), its adopted claims
    redeliver, and the scenario scores verdict pass — zero lost,
    exactly one non-drained terminal per uid across the decode
    outboxes, fleet gate + report green with the DISAGG line."""
    import fleet as fleet_cli

    fleet_jsonl = str(tmp_path / "fleet.jsonl")
    workdir = str(tmp_path / "work")
    # Regression guard (the PR-16 acceptance flake): this e2e proves
    # LEASE redelivery, not the stale sweep — at the derived
    # spool_timeout (max(4*lease, 5) = 5s here) a loaded single-core
    # rig can park honest spool dwell past the threshold (the
    # restarted decode child pays python+jax startup plus a recompile
    # before its first claim), the sweep re-routes the uids through
    # prefill a second time, and handoffs lands at 20 != 10.  The
    # sweep path has its own dedicated unit test
    # (test_router_spool_stale_sweep_reroutes_through_prefill); here
    # it is pushed far out of the hot path.
    argv = ["--replicas", "3", "--decode-replicas", "2",
            "--transport", "proc",
            "--scenario", "decode_crash_midspool",
            "--requests", "10", "--slots", "2", "--max-len", "16",
            "--handoff-lease", "1.0", "--spool-timeout", "120",
            "--metrics-jsonl", fleet_jsonl, "--workdir", workdir,
            "--timeout", "150"]
    rc = fleet_cli.main(argv)
    assert rc == 0

    records = obs.read_jsonl(fleet_jsonl)
    assert obs_schema.validate_stream(records) == []
    summary = records[-1]
    assert summary["record"] == "fleet_summary"
    assert summary["scenario"] == "decode_crash_midspool"
    assert summary["verdict"] == "pass"
    assert summary["lost"] == 0 and summary["availability"] == 1.0
    assert summary["prefill_replicas"] == 1
    assert summary["decode_replicas"] == 2
    assert summary["handoffs"] == 10 and summary["in_spool"] == 0
    assert summary["handoff_redelivered"] >= 1

    # the crashed decode child was classified + restarted, and the
    # restart attempt's argv lost the drill (otherwise it would
    # re-fire on the replayed claim set and flap until the budget ran
    # out)
    sup = obs.read_jsonl(os.path.join(workdir, "r1", "sup.jsonl"))
    restarts = [r for r in sup if r["record"] == "restart"]
    assert restarts and restarts[0]["classification"] == "crashed"
    assert len(restarts) == 1               # the stripped drill stayed dead

    # exactly-once at the uid level across the decode outboxes
    terminal = {}
    for name in ("r1", "r2"):
        path = os.path.join(workdir, name, "outbox.jsonl")
        if os.path.exists(path):
            with open(path) as fh:
                for line in fh:
                    ev = json.loads(line)
                    if ev.get("status") != "drained":
                        terminal[ev["uid"]] = \
                            terminal.get(ev["uid"], 0) + 1
    assert len(terminal) == 10
    assert set(terminal.values()) == {1}

    ci_gate = _load_tool("ci_gate")
    assert ci_gate.main(["--fleet-stream", fleet_jsonl]) == 0
    report = _load_tool("fleet_report")
    capsys.readouterr()
    assert report.main([fleet_jsonl]) == 0
    out = capsys.readouterr().out
    assert "DISAGG: 1 prefill + 2 decode" in out
