"""Context-parallel BERT training (workloads.make_bert_cp_train_step;
train.py --context-parallel): ring attention over a ('data', 'context')
mesh driving the full MLM train step — the long-context training path (no
reference analog; SURVEY.md §3.2 CP row).

The CP model's param tree is identical to the dense one (the ring branch
reuses the same query/key/value/output projections), so tests initialize
via the dense twin and pin trajectory equality.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from apex_example_tpu import amp
from apex_example_tpu.data import mlm_batch
from apex_example_tpu.engine import create_train_state, make_train_step
from apex_example_tpu.models.bert import bert_tiny
from apex_example_tpu.optim import FusedAdam, FusedSGD
from apex_example_tpu.workloads import make_bert_cp_train_step, mlm_loss

B, L = 4, 32      # context=4 -> local seq 8


def _batch(i, vocab):
    ids, lab, w = mlm_batch(jnp.asarray(i, jnp.int32), batch_size=B,
                            seq_len=L, vocab_size=vocab,
                            mask_token_id=vocab - 1, seed=0)
    return ids, (lab, w)


def test_cp_train_matches_dense(devices8):
    """30-step LOCKSTEP run on a (data=2, context=4) mesh vs dense
    single-device (VERDICT r3 item 7: 3 steps was a smoke test, not a
    trajectory): the ring attention, the shard-offset position embeddings,
    and the globally normalized MLM loss must agree at every step, with
    tolerances that only absorb fp32 reduction-order noise (growing
    mildly as the trajectories compound)."""
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("data", "context"))
    policy, scaler = amp.initialize("O0")
    dense = bert_tiny()
    cp_model = bert_tiny(context_parallel=True)
    V = dense.vocab_size
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)

    sample = _batch(0, V)[0][:1]
    state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                 sample, policy, scaler)
    step_d = jax.jit(make_train_step(dense, opt(), policy, loss_fn=mlm_loss,
                                     compute_accuracy=False))
    state_c = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                 sample, policy, scaler)
    step_c = make_bert_cp_train_step(mesh, cp_model, opt(), policy,
                                     donate=False)
    for i in range(30):
        b = _batch(i, V)
        state_d, m_d = step_d(state_d, b)
        state_c, m_c = step_c(state_c, b)
        np.testing.assert_allclose(
            float(m_d["loss"]), float(m_c["loss"]),
            rtol=3e-5 * (1 + i / 3),
            err_msg=f"loss diverged at step {i}")
    for a, b in zip(jax.tree_util.tree_leaves(state_d.params),
                    jax.tree_util.tree_leaves(state_c.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=3e-5)


def test_cp_ulysses_train_matches_dense(devices8):
    """BERT CP with the all-to-all (Ulysses) attention program == dense:
    full sequence per device on H/N head shards, exact attention — the
    bidirectional counterpart of the GPT ulysses test."""
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("data", "context"))
    policy, scaler = amp.initialize("O0")
    dense = bert_tiny()
    cp_model = bert_tiny(context_parallel=True, cp_mode="ulysses")
    V = dense.vocab_size
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
    sample = _batch(0, V)[0][:1]
    state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                 sample, policy, scaler)
    step_d = jax.jit(make_train_step(dense, opt(), policy,
                                     loss_fn=mlm_loss,
                                     compute_accuracy=False))
    state_c = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                 sample, policy, scaler)
    step_c = make_bert_cp_train_step(mesh, cp_model, opt(), policy,
                                     donate=False)
    for i in range(30):
        b = _batch(i, V)
        state_d, m_d = step_d(state_d, b)
        state_c, m_c = step_c(state_c, b)
        np.testing.assert_allclose(float(m_d["loss"]), float(m_c["loss"]),
                                   rtol=3e-5 * (1 + i / 3))
    for a, b in zip(jax.tree_util.tree_leaves(state_d.params),
                    jax.tree_util.tree_leaves(state_c.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=3e-5)


def test_cp_eval_matches_dense(devices8):
    """Sequence-sharded eval (workloads.make_bert_cp_eval_step) returns the
    dense eval's loss AND masked accuracy on the same params — the ring
    forward and the psum-normalized metrics are exact restatements."""
    from apex_example_tpu.workloads import (make_bert_cp_eval_step,
                                            make_bert_eval_step)
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("data", "context"))
    policy, scaler = amp.initialize("O0")
    dense = bert_tiny()
    cp_model = bert_tiny(context_parallel=True)
    V = dense.vocab_size
    state = create_train_state(jax.random.PRNGKey(0), dense,
                               FusedAdam(lr=1e-3), _batch(0, V)[0][:1],
                               policy, scaler)
    ev_d = jax.jit(make_bert_eval_step(dense))
    ev_c = make_bert_cp_eval_step(mesh, cp_model)
    for i in range(2):
        b = _batch(100 + i, V)
        md, mc = ev_d(state.params, b), ev_c(state.params, b)
        np.testing.assert_allclose(float(md["loss"]), float(mc["loss"]),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(md["masked_acc"]),
                                   float(mc["masked_acc"]), rtol=1e-5)


def test_cp_grad_accum_matches_dense(devices8):
    """--grad-accum under CP: K local microbatches with per-microbatch
    psum-normalized losses equal dense K-microbatch accumulation on the
    SAME example grouping.  CP's microbatch m holds each data-shard's m-th
    local slice (examples {m, local+m, ...}) while the dense engine takes
    contiguous blocks, so the dense side gets the batch permuted into CP's
    grouping — grad accumulation is a mean over microbatch losses, which
    depends on the grouping whenever per-example masked counts differ."""
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("data", "context"))
    policy, scaler = amp.initialize("O0")
    dense = bert_tiny()
    cp_model = bert_tiny(context_parallel=True)
    V = dense.vocab_size
    K, data = 2, 2
    local = B // data
    perm = np.array([s * local + m * (local // K) + j
                     for m in range(K) for s in range(data)
                     for j in range(local // K)])
    opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
    sample = _batch(0, V)[0][:1]
    state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                 sample, policy, scaler)
    step_d = jax.jit(make_train_step(dense, opt(), policy, loss_fn=mlm_loss,
                                     compute_accuracy=False, grad_accum=K))
    state_c = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                 sample, policy, scaler)
    step_c = make_bert_cp_train_step(mesh, cp_model, opt(), policy,
                                     donate=False, grad_accum=K)
    for i in range(10):
        ids, (lab, w) = _batch(i, V)
        state_d, m_d = step_d(state_d, (ids[perm], (lab[perm], w[perm])))
        state_c, m_c = step_c(state_c, (ids, (lab, w)))
        np.testing.assert_allclose(float(m_d["loss"]), float(m_c["loss"]),
                                   rtol=3e-5)
    for a, b in zip(jax.tree_util.tree_leaves(state_d.params),
                    jax.tree_util.tree_leaves(state_c.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_cp_o2_bf16_trains(devices8):
    mesh = Mesh(np.asarray(devices8).reshape(2, 4), ("data", "context"))
    policy, scaler = amp.initialize("O2")
    md = amp.module_dtypes(policy)
    kw = dict(dtype=md.compute, param_dtype=md.param, ln_dtype=md.ln_io,
              softmax_dtype=md.softmax)
    dense = bert_tiny(**kw)
    cp_model = bert_tiny(context_parallel=True, **kw)
    V = dense.vocab_size
    opt = FusedAdam(lr=3e-3)
    state = create_train_state(jax.random.PRNGKey(0), dense, opt,
                               _batch(0, V)[0][:1], policy, scaler)
    step = make_bert_cp_train_step(mesh, cp_model, opt, policy,
                                   donate=False)
    # Overfit ONE batch: per-step losses on fresh random batches are too
    # noisy at this tiny scale for a monotonicity check.
    b = _batch(0, V)
    losses = []
    for _ in range(6):
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < 0.7 * losses[0], losses


def test_cp_tp_train_matches_dense(devices8):
    """CP×TP composition: ring attention over 'context' with the GSPMD TP
    layers on a still-automatic 'model' axis (the same partially-manual
    shard_map form as TP×PP) — trajectory matches dense and the params
    keep their model-axis sharding across steps (the step pins its output
    shardings; without that the compiler may hand updated params back
    replicated)."""
    from apex_example_tpu.engine import gspmd_state_shardings
    from apex_example_tpu.transformer import parallel_state
    mesh = parallel_state.initialize_model_parallel(
        tensor_parallel=2, context_parallel=2, devices=devices8)
    try:
        policy, scaler = amp.initialize("O0")
        dense = bert_tiny()
        tp_model = bert_tiny(tensor_parallel=True)
        cp_tp_model = bert_tiny(tensor_parallel=True, context_parallel=True)
        V = dense.vocab_size
        opt = lambda: FusedSGD(lr=0.05, momentum=0.9)
        sample = _batch(0, V)[0][:1]
        state_d = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                     sample, policy, scaler)
        step_d = jax.jit(make_train_step(dense, opt(), policy,
                                         loss_fn=mlm_loss,
                                         compute_accuracy=False))
        # Dense init (the TP twin's VocabParallelEmbedding has a different
        # initializer), placed into the TP metadata shardings.
        state_c = create_train_state(jax.random.PRNGKey(0), dense, opt(),
                                     sample, policy, scaler)
        sh = gspmd_state_shardings(mesh, tp_model, opt(), sample, policy)
        state_c = jax.device_put(state_c, sh)
        step_c = make_bert_cp_train_step(mesh, cp_tp_model, opt(), policy,
                                         donate=False, state_shardings=sh)
        for i in range(30):
            b = _batch(i, V)
            state_d, m_d = step_d(state_d, b)
            state_c, m_c = step_c(state_c, b)
            np.testing.assert_allclose(float(m_d["loss"]),
                                       float(m_c["loss"]), rtol=3e-5 * (1 + i / 3))
        for a, b in zip(jax.tree_util.tree_leaves(state_d.params),
                        jax.tree_util.tree_leaves(state_c.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=3e-5)
        qk = state_c.params["layer_0"]["attention"]["query"]["kernel"]
        assert qk.addressable_shards[0].data.shape == (64, 32), \
            "query kernel lost its model-axis sharding"
    finally:
        parallel_state.set_mesh(None)


def test_train_py_cli_cp_tp(tmp_path, devices8, capsys):
    """--context-parallel 2 --tensor-parallel 2 trains, evals
    (sequence-sharded ring eval on the TP model), accumulates gradients,
    checkpoints, and resumes (the tp>1 template is gspmd-placed, so the
    direct-restore branch must land the shards back where the step expects
    them)."""
    import train as train_mod
    from apex_example_tpu.ops import _config as ops_config
    from apex_example_tpu.transformer import parallel_state
    ck = str(tmp_path / "ck")
    base = ["--arch", "bert_tiny", "--context-parallel", "2",
            "--tensor-parallel", "2", "--batch-size", str(B),
            "--seq-len", str(L), "--steps-per-epoch", "2",
            "--opt", "adam", "--opt-level", "O0", "--print-freq", "1",
            "--grad-accum", "2", "--eval", "--eval-batches", "2"]
    try:
        assert train_mod.main(base + ["--epochs", "1",
                                      "--checkpoint-dir", ck]) == 0
        assert "masked_acc" in capsys.readouterr().out
        assert train_mod.main(base + ["--epochs", "2",
                                      "--resume", ck]) == 0
        assert "resumed from step 2" in capsys.readouterr().out
    finally:
        ops_config.set_force_xla(False)
        parallel_state.set_mesh(None)


def test_cp_model_rejects_mask():
    m = bert_tiny(context_parallel=True)
    ids = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError):
        jax.eval_shape(lambda: m.init(jax.random.PRNGKey(0), ids,
                                      attention_mask=jnp.ones((1, 8))))


def test_train_py_cli_context_parallel(devices8):
    import train as train_mod
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "bert_tiny", "--context-parallel", "4",
            "--batch-size", str(B), "--seq-len", str(L), "--epochs", "1",
            "--steps-per-epoch", "3", "--opt", "adam", "--opt-level", "O0",
            "--print-freq", "1"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        parallel_state.set_mesh(None)


def test_train_py_cli_cp_eval_and_grad_accum(devices8, capsys):
    """--eval and --grad-accum now compose with --context-parallel
    (VERDICT r3 item 6): the eval pass runs sequence-sharded on the ring."""
    import train as train_mod
    from apex_example_tpu.transformer import parallel_state
    argv = ["--arch", "bert_tiny", "--context-parallel", "4",
            "--batch-size", str(B), "--seq-len", str(L), "--epochs", "1",
            "--steps-per-epoch", "2", "--opt", "adam", "--opt-level", "O0",
            "--print-freq", "1", "--grad-accum", "2",
            "--eval", "--eval-batches", "2"]
    try:
        assert train_mod.main(argv) == 0
    finally:
        parallel_state.set_mesh(None)
    assert "masked_acc" in capsys.readouterr().out


def test_train_py_cp_rejections():
    import train as train_mod
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "resnet18", "--context-parallel", "2"])
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "transformer_xl_tiny",
                        "--context-parallel", "2"])
    with pytest.raises(SystemExit):
        # (CP x PP composes since round 5; the ZeRO x CP x TP triple
        # does not)
        train_mod.main(["--arch", "bert_tiny", "--context-parallel", "2",
                        "--tensor-parallel", "2", "--zero"])
    with pytest.raises(SystemExit):
        # SP's sequence sharding conflicts with the context axis.
        train_mod.main(["--arch", "bert_tiny", "--context-parallel", "2",
                        "--tensor-parallel", "2", "--sequence-parallel"])
    with pytest.raises(SystemExit):
        train_mod.main(["--arch", "bert_tiny", "--context-parallel", "3",
                        "--seq-len", "16"])
    with pytest.raises(SystemExit):
        # O3's half-softmax contract: rejected at the CLI (the model-level
        # ValueError would otherwise only fire at trace time).
        train_mod.main(["--arch", "bert_tiny", "--context-parallel", "2",
                        "--opt-level", "O3"])
