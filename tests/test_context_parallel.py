"""Context parallelism tests: ring + Ulysses attention vs plain attention.

8 logical CPU devices shard the sequence dim; both parallel forms must agree
with single-device attention to float tolerance, values and gradients
(the same golden-parity pattern as the TP tests; SURVEY.md §5).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_example_tpu.parallel import (CONTEXT_AXIS, plain_attention,
                                       ring_attention, ulysses_attention)


@pytest.fixture()
def ctx_mesh(devices8):
    return Mesh(np.asarray(devices8), (CONTEXT_AXIS,))


def _qkv(seed, b=2, s=32, h=8, d=16):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d), jnp.float32) * 0.5
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_plain(ctx_mesh, causal):
    q, k, v = _qkv(0)

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=causal),
        mesh=ctx_mesh,
        in_specs=P(None, CONTEXT_AXIS, None, None),
        out_specs=P(None, CONTEXT_AXIS, None, None))
    out = ring(q, k, v)
    ref = plain_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_grads_match(ctx_mesh):
    q, k, v = _qkv(1, s=16)

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=True),
        mesh=ctx_mesh,
        in_specs=P(None, CONTEXT_AXIS, None, None),
        out_specs=P(None, CONTEXT_AXIS, None, None))

    def loss_ring(args):
        return jnp.sum(ring(*args) ** 2)

    def loss_ref(args):
        return jnp.sum(plain_attention(*args, causal=True) ** 2)

    g = jax.grad(loss_ring)((q, k, v))
    g_ref = jax.grad(loss_ref)((q, k, v))
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_plain(ctx_mesh, causal):
    q, k, v = _qkv(2)

    uly = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, causal=causal),
        mesh=ctx_mesh,
        in_specs=P(None, CONTEXT_AXIS, None, None),
        out_specs=P(None, CONTEXT_AXIS, None, None))
    out = uly(q, k, v)
    ref = plain_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_attention_grads_match(ctx_mesh):
    q, k, v = _qkv(3, s=16)

    uly = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, causal=False),
        mesh=ctx_mesh,
        in_specs=P(None, CONTEXT_AXIS, None, None),
        out_specs=P(None, CONTEXT_AXIS, None, None))

    g = jax.grad(lambda a: jnp.sum(uly(*a) ** 2))((q, k, v))
    g_ref = jax.grad(
        lambda a: jnp.sum(plain_attention(*a) ** 2))((q, k, v))
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_ring_attention_long_sequence_memory_shape(ctx_mesh):
    """The point of the ring: per-device logits are [s, s] blocks, never
    [S, S].  Smoke a longer sequence through to prove the sharded path
    compiles and matches."""
    q, k, v = _qkv(4, b=1, s=256, h=2, d=8)
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=True),
        mesh=ctx_mesh,
        in_specs=P(None, CONTEXT_AXIS, None, None),
        out_specs=P(None, CONTEXT_AXIS, None, None))
    out = jax.jit(ring)(q, k, v)
    ref = plain_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_initialize_model_parallel_4d_topology(devices8):
    """The 4-D (pipe, data, context, model) reshape and its divisibility
    guard (reference-parity entry point; SURVEY.md §3.2)."""
    from apex_example_tpu.transformer import parallel_state as ps

    mesh = ps.initialize_model_parallel(
        tensor_parallel=2, pipeline_parallel=2, context_parallel=2,
        devices=devices8)
    try:
        assert dict(mesh.shape) == {"pipe": 2, "data": 1, "context": 2,
                                    "model": 2}
        assert ps.get_tensor_model_parallel_world_size() == 2
        assert ps.get_context_parallel_world_size() == 2
        assert ps.get_pipeline_model_parallel_world_size() == 2
        assert ps.get_data_parallel_world_size() == 1
        # TP innermost: the first TP group is the first two devices in order.
        arr = np.asarray(mesh.devices).reshape(-1)
        assert list(arr[:2]) == list(devices8[:2])

        with pytest.raises(ValueError, match="not divisible"):
            ps.initialize_model_parallel(tensor_parallel=3,
                                         devices=devices8)
    finally:
        ps.set_mesh(None)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_vs_inline_fold(ctx_mesh, causal):
    """The two ring implementations (per-chunk flash kernel + lse combine
    vs the self-contained inline online-softmax fold) agree."""
    q, k, v = _qkv(4)
    run = lambda flash: shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=causal,
                                       use_flash=flash),
        mesh=ctx_mesh,
        in_specs=P(None, CONTEXT_AXIS, None, None),
        out_specs=P(None, CONTEXT_AXIS, None, None))(q, k, v)
    np.testing.assert_allclose(np.asarray(run(True)),
                               np.asarray(run(False)),
                               rtol=1e-5, atol=1e-5)


def test_ring_flash_grads_match_plain(ctx_mesh):
    q, k, v = _qkv(5, s=16)
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=True, use_flash=True),
        mesh=ctx_mesh,
        in_specs=P(None, CONTEXT_AXIS, None, None),
        out_specs=P(None, CONTEXT_AXIS, None, None))
    g = jax.grad(lambda a: jnp.sum(ring(*a) ** 2))((q, k, v))
    gr = jax.grad(lambda a: jnp.sum(
        plain_attention(*a, causal=True) ** 2))((q, k, v))
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_ulysses_with_flash_inner(ctx_mesh):
    from apex_example_tpu.ops.attention import flash_attention
    q, k, v = _qkv(6)
    out = shard_map(
        lambda q, k, v: ulysses_attention(
            q, k, v, inner=lambda a, b, c: flash_attention(a, b, c)),
        mesh=ctx_mesh,
        in_specs=P(None, CONTEXT_AXIS, None, None),
        out_specs=P(None, CONTEXT_AXIS, None, None))(q, k, v)
    ref = plain_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


class TestZigzag:
    """Load-balanced causal ring: zigzag layout round-trip + equivalence
    with single-device causal attention, values and gradients."""

    def test_shard_roundtrip(self):
        from apex_example_tpu.parallel import zigzag_shard, zigzag_unshard
        x = jnp.arange(32.0).reshape(1, 32, 1, 1)
        z = zigzag_shard(x, n=4)
        np.testing.assert_array_equal(np.asarray(zigzag_unshard(z, n=4)),
                                      np.asarray(x))
        # device 0's shard = chunks 0 and 7 of the 8-chunk split
        np.testing.assert_array_equal(
            np.asarray(z[0, :8, 0, 0]),
            np.r_[np.arange(0.0, 4), np.arange(28.0, 32)])

    def test_matches_plain_causal(self, ctx_mesh):
        from apex_example_tpu.parallel import (ring_attention_zigzag,
                                               zigzag_shard, zigzag_unshard)
        q, k, v = _qkv(7)
        zq, zk, zv = (zigzag_shard(t, n=8) for t in (q, k, v))
        run = shard_map(
            lambda q, k, v: ring_attention_zigzag(q, k, v),
            mesh=ctx_mesh,
            in_specs=P(None, CONTEXT_AXIS, None, None),
            out_specs=P(None, CONTEXT_AXIS, None, None))
        out = zigzag_unshard(run(zq, zk, zv), n=8)
        ref = plain_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_plain_causal(self, ctx_mesh):
        from apex_example_tpu.parallel import (ring_attention_zigzag,
                                               zigzag_shard, zigzag_unshard)
        q, k, v = _qkv(8, s=16)
        run = shard_map(
            lambda q, k, v: ring_attention_zigzag(q, k, v),
            mesh=ctx_mesh,
            in_specs=P(None, CONTEXT_AXIS, None, None),
            out_specs=P(None, CONTEXT_AXIS, None, None))

        def loss_zz(args):
            zq, zk, zv = (zigzag_shard(t, n=8) for t in args)
            out = zigzag_unshard(run(zq, zk, zv), n=8)
            return jnp.sum(out ** 2)

        g = jax.grad(loss_zz)((q, k, v))
        gr = jax.grad(lambda a: jnp.sum(
            plain_attention(*a, causal=True) ** 2))((q, k, v))
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
