"""Flash-attention kernel tests: Pallas (interpret) vs XLA reference vs a
plain-softmax golden, forward and VJP.

Mirrors the reference's fused-attention testing obligation (apex contrib fmha
ships its own test_fmha.py comparing against a python softmax — SURVEY.md
§2.1 contrib row): the kernel must agree with naive attention in both values
and gradients, across causal/bias/dtype variants.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_example_tpu.ops import _config
from apex_example_tpu.ops.attention import (attention_reference,
                                            flash_attention)


def _inputs(b=2, sq=256, sk=256, h=2, d=64, dtype=jnp.float32, seed=0,
            bias=False):
    ks = jax.random.split(jax.random.key(seed), 4)
    q = jax.random.normal(ks[0], (b, sq, h, d), dtype)
    k = jax.random.normal(ks[1], (b, sk, h, d), dtype)
    v = jax.random.normal(ks[2], (b, sk, h, d), dtype)
    bias_arr = None
    if bias:
        # Key-padding style: mask the tail quarter of keys in batch row 0.
        keep = jnp.ones((b, sk), jnp.float32)
        keep = keep.at[0, 3 * sk // 4:].set(0.0)
        bias_arr = jnp.where(keep > 0, 0.0, -1e9).astype(jnp.float32)
    return q, k, v, bias_arr


def _golden(q, k, v, bias, causal):
    """Independent plain-softmax attention in fp64-ish fp32, no shared code
    with the op's reference path beyond jnp."""
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / np.sqrt(q.shape[-1])
    if bias is not None:
        s = s + bias[:, None, None, :]
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        s = jnp.where(np.tril(np.ones((sq, sk), bool), k=sk - sq), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vf)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("bias", [False, True])
def test_forward_matches_golden(causal, bias):
    q, k, v, b = _inputs(bias=bias)
    out = flash_attention(q, k, v, b, causal)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_golden(q, k, v, b, causal)),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_kernel_vs_reference_bf16(causal):
    q, k, v, _ = _inputs(dtype=jnp.bfloat16, seed=1)
    out = flash_attention(q, k, v, None, causal)
    ref = attention_reference(q, k, v, None, causal)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=2e-2, rtol=2e-2)


def test_rectangular_and_multiblock():
    # sq != sk and both > one 256-block: exercises the full grid walk.
    q, k, v, _ = _inputs(sq=256, sk=512, seed=2)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_golden(q, k, v, None, False)),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("bias", [False, True])
def test_grads_match_golden(causal, bias):
    q, k, v, b = _inputs(sq=128, sk=128, h=1, seed=3, bias=bias)
    dout = jax.random.normal(jax.random.key(9), q.shape, q.dtype)

    def loss(fn):
        def f(q, k, v):
            return jnp.vdot(fn(q, k, v, b, causal).astype(jnp.float32), dout)
        return f

    gk = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(loss(lambda *a: _golden(*a).astype(q.dtype)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, bx, name in zip(gk, gg, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bx),
                                   atol=5e-5, rtol=5e-5, err_msg=f"d{name}")


def test_grads_multiblock_causal():
    q, k, v, _ = _inputs(sq=256, sk=256, seed=4)

    def f(fn, *args):
        return jnp.sum(jnp.square(fn(*args, None, True)))

    gk = jax.grad(lambda *a: f(flash_attention, *a), argnums=(0, 1, 2))(
        q, k, v)
    gr = jax.grad(lambda *a: f(attention_reference, *a), argnums=(0, 1, 2))(
        q, k, v)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_fallback_path_small_seq():
    # S=64 doesn't tile to 128 — must silently use the XLA reference.
    q, k, v, _ = _inputs(sq=64, sk=64)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_golden(q, k, v, None, False)),
                               atol=2e-5, rtol=2e-5)
    g = jax.grad(lambda q: jnp.sum(jnp.square(flash_attention(q, k, v))))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_bias_grad_is_zero():
    q, k, v, b = _inputs(bias=True)
    db = jax.grad(
        lambda b: jnp.sum(flash_attention(q, k, v, b)))(b)
    np.testing.assert_array_equal(np.asarray(db), 0.0)


def test_head_dim_padding():
    # d=96 exercises the pad-to-128 path (zeros must not change results).
    q, k, v, _ = _inputs(d=96, seed=5)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_golden(q, k, v, None, False)),
                               atol=2e-5, rtol=2e-5)


def test_xla_reference_when_interpret_off():
    saved = _config.INTERPRET
    _config.INTERPRET = False      # on CPU this selects the XLA reference
    try:
        q, k, v, _ = _inputs()
        out = flash_attention(q, k, v)
    finally:
        _config.INTERPRET = saved
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_golden(q, k, v, None, False)),
                               atol=2e-5, rtol=2e-5)


def test_bert_fused_vs_naive_model_parity():
    """Same params through the fused-attention and naive BERT paths."""
    from apex_example_tpu.models.bert import bert_tiny
    ids = jax.random.randint(jax.random.key(0), (2, 128), 0, 255)
    mask = jnp.ones((2, 128), jnp.int32).at[0, 100:].set(0)
    naive = bert_tiny()
    fused = bert_tiny(fused_attention=True)
    params = naive.init(jax.random.key(1), ids, mask)
    out_n = naive.apply(params, ids, mask)
    out_f = fused.apply(params, ids, mask)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_n),
                               atol=5e-4, rtol=5e-4)
    # Gradients agree too (the custom-VJP path end-to-end in a real model).
    def loss(m, p):
        return jnp.mean(jnp.square(m.apply(p, ids, mask)))
    gn = jax.grad(lambda p: loss(naive, p))(params)
    gf = jax.grad(lambda p: loss(fused, p))(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-3), gn, gf)


def test_rectangular_causal_bottom_right():
    """Causal masking for Sq != Sk follows the bottom-right (prefix-cache)
    convention in kernel and reference alike."""
    q, k, v, _ = _inputs(sq=128, sk=256, seed=6)
    out = flash_attention(q, k, v, None, True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_golden(q, k, v, None, True)),
                               atol=2e-5, rtol=2e-5)
    gk = jax.grad(lambda k: jnp.sum(jnp.square(
        flash_attention(q, k, v, None, True))))(k)
    gr = jax.grad(lambda k: jnp.sum(jnp.square(
        attention_reference(q, k, v, None, True))))(k)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                               atol=1e-4, rtol=1e-4)


def test_causal_rejects_more_queries_than_keys():
    q, k, v, _ = _inputs(sq=256, sk=128, seed=7)
    with pytest.raises(ValueError, match="Sq <= Sk"):
        flash_attention(q, k, v, None, True)


class TestWithLse:
    """flash_attention_with_lse: the composable (ring/blockwise) form — lse
    values match logsumexp of the true scores, and the lse COTANGENT is
    honored (the combine's weights differentiate through it)."""

    def test_lse_matches_golden(self):
        from apex_example_tpu.ops.attention import flash_attention_with_lse
        q, k, v, _ = _inputs(seed=8)
        out, lse = flash_attention_with_lse(q, k, v)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_golden(q, k, v, None, False)),
                                   atol=2e-5, rtol=2e-5)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
        np.testing.assert_allclose(
            np.asarray(lse),
            np.asarray(jax.scipy.special.logsumexp(s, axis=-1)),
            atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_lse_cotangent(self, causal):
        """Loss uses BOTH outputs; grads must match autodiff of an
        independent (out, lse) computation."""
        from apex_example_tpu.ops.attention import flash_attention_with_lse
        q, k, v, _ = _inputs(sq=128, sk=128, h=1, seed=9)

        def golden_pair(q, k, v):
            qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
            s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / np.sqrt(q.shape[-1])
            if causal:
                sq = q.shape[1]
                s = jnp.where(np.tril(np.ones((sq, sq), bool)), s, -1e30)
            lse = jax.scipy.special.logsumexp(s, axis=-1)
            out = jnp.einsum("bhqk,bkhd->bqhd",
                             jnp.exp(s - lse[..., None]), vf)
            return out, lse

        def loss(fn):
            def f(q, k, v):
                o, lse = fn(q, k, v)
                return (jnp.sum(jnp.square(o.astype(jnp.float32)))
                        + jnp.sum(jnp.sin(lse)))
            return f

        gk = jax.grad(loss(lambda q, k, v: flash_attention_with_lse(
            q, k, v, None, causal)), argnums=(0, 1, 2))(q, k, v)
        gg = jax.grad(loss(golden_pair), argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gk, gg, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4,
                                       err_msg=f"d{name}")
