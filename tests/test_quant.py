"""Quantization stratum (apex_example_tpu/quant/; ISSUE 13).

- Pure-numpy round-trip coverage (NO compile cost): int8 and fp8
  quantize/dequantize against the documented error bounds
  (quant/core.py — <= scale/2 unclipped, <= scale at the clipped
  extreme, scales stored NARROWER than f32).
- Weight-tree quantization: the AMP op tables decide eligibility
  (kernels/embeddings quantize; layernorm scale/bias, biases and the
  fp32 lm head bias stay high-precision), dequantize_tree restores
  structure/dtype, per-channel error bounded.
- The serving acceptance bar: int8-weight + int8-KV greedy outputs on
  the tiny-GPT fixture >= 95% token match vs the full-precision
  generate() reference with the first divergence reported; ONE
  compiled decode program with quantization armed (compile_events
  gate); kv_bytes_committed <= 55% of the bf16-equivalent bytes.
- COW-under-quantization regression: diverging a shared int8 block
  copies its SCALES with the payload (shared-prefix outputs stay
  identical to solo quantized runs of the same prompts).
- The jax-free tool surface: ci_gate --quant-stream over the
  checked-in quantized-smoke fixture, serve_report's QUANT line,
  schema-v11 quant_event validation + v1-v10 back-compat.

Engine tests share ONE quantized engine geometry (the session's
SLOTS=4 / MAX_LEN=32 / block-size-8) through a module-scoped fixture,
so the quantized decode program — the suite's one deliberate new
compile — is built exactly once.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_example_tpu import obs
from apex_example_tpu.amp import lists as amp_lists
from apex_example_tpu.amp.policy import get_quant_policy
from apex_example_tpu.models.gpt import generate, gpt_tiny
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.quant import core as qcore
from apex_example_tpu.quant import kv as qkv
from apex_example_tpu.quant import weights as qweights
from apex_example_tpu.serve import Request, ServeEngine, synthetic_requests

pytestmark = pytest.mark.quant

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
QUANT_FIXTURE = os.path.join(REPO, "tests", "fixtures", "quant",
                             "quant_smoke.jsonl")
SLOTS, MAX_LEN, BS = 4, 32, 8


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ===================== pure-numpy numerics (no compile) ==============

def test_int8_roundtrip_error_bound():
    """|x - dq| <= stored_scale/2 for unclipped values, <= stored_scale
    at the clipped extreme — rounding happens against the STORED
    (possibly narrowed) scale, so the bound holds exactly even when the
    scale lost mantissa bits on the way to bf16."""
    x = np.random.RandomState(0).randn(64, 32).astype(np.float32) * 3.0
    for scale_dtype in (jnp.float32, jnp.bfloat16):
        scale = qcore.abs_max_scale(x, axis=1).astype(scale_dtype)
        q = qcore.quantize_int8(jnp.asarray(x), scale)
        assert q.dtype == jnp.int8
        dq = np.asarray(qcore.dequantize(q, scale))
        s = np.asarray(scale, np.float32)
        err = np.abs(x - dq)
        assert (err <= s * 1.0 + 1e-7).all()          # clipped extreme
        interior = np.abs(x) < np.abs(x).max(axis=1, keepdims=True)
        assert (err[interior.nonzero()]
                <= (np.broadcast_to(s, x.shape)[interior.nonzero()] / 2
                    + 1e-7)).all()


def test_int8_all_zero_slice_is_finite():
    x = jnp.zeros((4, 8))
    scale = qcore.abs_max_scale(x, axis=1)
    dq = np.asarray(qcore.dequantize(qcore.quantize_int8(x, scale),
                                     scale))
    assert np.array_equal(dq, np.zeros((4, 8), np.float32))


def test_fp8_roundtrip_error_bound():
    """e4m3 carries 3 mantissa bits: error <= |x|/16 relative plus half
    a subnormal step (scale * 2^-10) absolute.  Native float8_e4m3fn on
    this rig; the emulated e4m3 grid covers the normal range."""
    x = np.random.RandomState(1).randn(256).astype(np.float32)
    scale = qcore.abs_max_scale(x, qmax=qcore.FP8_QMAX)
    q, emulated = qcore.quantize_fp8(jnp.asarray(x), scale)
    dq = np.asarray(qcore.dequantize(q, scale))
    s = float(np.asarray(scale).reshape(()))
    bound = np.abs(x) / 16.0 + s * 2.0 ** -9
    assert (np.abs(x - dq) <= bound + 1e-9).all()
    if qcore.fp8_dtype() is not None:
        assert not emulated and q.dtype == qcore.fp8_dtype()
    # the emulation grid itself: 3-bit mantissa snapping on normals
    # (1.0625 sits mid-step and rounds half-to-even back to 1.0)
    em = np.asarray(qcore._round_e4m3(jnp.asarray(
        [1.0, 1.0625, 1.09, 2.5, -3.1, 448.0], jnp.float32)))
    np.testing.assert_allclose(
        em, [1.0, 1.0, 1.125, 2.5, -3.0, 448.0], rtol=0, atol=0)


def test_quant_policy_and_lists():
    """The AMP engine hosts the eligibility rules: MXU weight classes
    quantize, the FP32 sensitivity set always wins, registration
    mutates the same tables the O1 lists do."""
    assert amp_lists.quant_classify("dense") == "quant"
    assert amp_lists.quant_classify("embedding") == "quant"
    assert amp_lists.quant_classify("layer_norm") == "keep"
    assert amp_lists.quant_classify("softmax") == "keep"
    assert amp_lists.quant_classify("unknown_op") == "keep"
    amp_lists.register_quant_function("my_custom_mm")
    try:
        assert amp_lists.quant_classify("my_custom_mm") == "quant"
    finally:
        amp_lists.INT8_FUNCS.discard("my_custom_mm")
    p = get_quant_policy("int8", kv_int8=True)
    assert p.weight_dtype_name == "int8" and p.any_armed
    p8 = get_quant_policy("fp8")
    assert p8.weight_dtype_name in ("float8_e4m3", "fp8_e4m3_emulated")
    assert get_quant_policy("none").weight_dtype_name == "float32"
    assert not get_quant_policy("none").any_armed
    with pytest.raises(ValueError, match="none|int8|fp8"):
        get_quant_policy("int4")


def test_weight_tree_classification_and_roundtrip():
    """Kernels/embeddings quantize per-channel; norm scale/bias, biases
    and lm_bias keep their dtype/identity; dequantize_tree restores
    structure with a bounded per-channel error."""
    model = gpt_tiny()
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    qtree, stats = qweights.quantize_params(params, "int8")
    # every kernel and embedding leaf quantized, nothing else
    flat = jax.tree_util.tree_flatten_with_path(
        qtree, is_leaf=qweights.is_quantized_leaf)[0]
    for path, leaf in flat:
        name = path[-1].key
        if name in ("kernel", "embedding"):
            assert qweights.is_quantized_leaf(leaf), path
            assert leaf["qvalue"].dtype == jnp.int8
        else:
            assert not qweights.is_quantized_leaf(leaf), path
    assert stats["tensors"] > 0 and stats["kept"] > 0
    assert stats["bytes_after"] < stats["bytes_before"] / 3
    assert 0 < stats["scale_min"] <= stats["scale_max"]
    deq = qweights.dequantize_tree(qtree)
    ref_flat = jax.tree_util.tree_flatten_with_path(params)[0]
    deq_flat = jax.tree_util.tree_flatten_with_path(deq)[0]
    assert [p for p, _ in ref_flat] == [p for p, _ in deq_flat]
    for (path, a), (_, b) in zip(ref_flat, deq_flat):
        assert a.shape == b.shape and a.dtype == b.dtype, path
        name = path[-1].key
        if name in ("kernel", "embedding"):
            amax = np.abs(np.asarray(a)).max()
            assert np.abs(np.asarray(a) - np.asarray(b)).max() \
                <= amax / 127 + 1e-6, path
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=str(path))
    # fp8 mode rides the same tree shape
    q8, s8 = qweights.quantize_params(params, "fp8")
    assert s8["tensors"] == stats["tensors"]
    d8 = qweights.dequantize_tree(q8)
    for (path, a), (_, b) in zip(
            ref_flat, jax.tree_util.tree_flatten_with_path(d8)[0]):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=0.14, atol=1e-4,
                                   err_msg=str(path))
    with pytest.raises(ValueError, match="int8"):
        qweights.quantize_params(params, "int4")


def test_kv_write_gather_roundtrip():
    """quantize_write/dequantize_gather: per-token scales over the
    [H, D] vector, bf16 scale storage, bound <= scale."""
    x = np.random.RandomState(2).randn(4, 8, 4, 16).astype(np.float32)
    q, scale = qkv.quantize_write(jnp.asarray(x))
    assert q.shape == x.shape and q.dtype == jnp.int8
    assert scale.shape == (4, 8) and scale.dtype == jnp.bfloat16
    dq = np.asarray(qkv.dequantize_gather(q, scale, jnp.float32))
    s = np.asarray(scale, np.float32)[..., None, None]
    assert (np.abs(x - dq) <= np.broadcast_to(s, x.shape) + 1e-6).all()


# ==================== serving acceptance (one compile) ===============

@pytest.fixture(scope="module")
def model_and_params():
    model = gpt_tiny()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def qparams(model_and_params):
    _, params = model_and_params
    qtree, _ = qweights.quantize_params(params, "int8")
    return qtree


def _quant_engine(model, qtree, requests, sink=None, run_id=None,
                  registry=None):
    """Every engine here shares ONE module config (int8 KV + int8
    weights at the session geometry), so _slot_step's lru_cache hands
    all of them the same compiled program — the suite's single
    deliberate new compile."""
    eng = ServeEngine(model, qtree, num_slots=SLOTS, max_len=MAX_LEN,
                      rng=jax.random.PRNGKey(0), sink=sink,
                      run_id=run_id, registry=registry,
                      kv_quant=True, weight_quant="int8")
    eng.queue.submit_all(requests)
    eng.queue.close()
    eng.run(max_steps=2000)
    return eng


def test_quantized_serve_token_match_and_bytes(model_and_params,
                                               qparams, tmp_path,
                                               compile_events, capsys):
    """The ISSUE 13 acceptance bar, one run: >= 95% positional token
    match vs the full-precision generate() reference (first divergence
    reported), ONE compile_event with quantization armed (+ the actual
    CI gate command), dtype-accurate committed bytes <= 55% of the
    bf16-equivalent, v11 stream validity, and the serve_report QUANT
    line."""
    from apex_example_tpu.obs import costmodel
    model, params = model_and_params
    path = str(tmp_path / "quant_serve.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    emitter = obs.TelemetryEmitter(sink)
    emitter.run_header(config={"slots": SLOTS, "max_len": MAX_LEN},
                       arch="gpt_tiny")
    costmodel.set_default(obs.CostModel(
        sink=sink, registry=emitter.registry, run_id=emitter.run_id))
    try:
        reqs = synthetic_requests(8, vocab_size=model.vocab_size,
                                  seed=3, prompt_len=(3, 8),
                                  max_new=(4, 10), stagger=2)
        eng = _quant_engine(model, qparams, reqs, sink=sink,
                            run_id=emitter.run_id,
                            registry=emitter.registry)
    finally:
        costmodel.set_default(None)
    summary = eng.summary_record()
    sink.write(summary)
    sink.close()
    assert eng.counts["ok"] == 8

    # (a) token match vs the full-precision one-shot reference,
    # positional, with the first divergence named in the failure.
    match = total = 0
    first_div = None
    for c in sorted(eng.completions, key=lambda c: c.request.uid):
        P = len(c.request.prompt)
        ref = np.asarray(generate(
            model, params,
            jnp.asarray([list(c.request.prompt)], jnp.int32),
            max_len=MAX_LEN))[0, P:P + len(c.tokens)]
        eq = ref == np.asarray(c.tokens, np.int32)
        match += int(eq.sum())
        total += len(eq)
        if not eq.all() and first_div is None:
            first_div = (c.request.uid, int(np.argmin(eq)))
    assert total > 20
    assert match / total >= 0.95, (
        f"int8 serve matched {match}/{total} tokens "
        f"({match / total:.3f} < 0.95); first divergence at "
        f"(request, step) {first_div}")

    # (b) compile-once with quantization armed: the quantized program
    # is ONE new compile, checked through the counter AND the CI gate.
    records = obs.read_jsonl(path)
    assert obs_schema.validate_stream(records) == []
    assert compile_events(records) == {"serve_decode_step": 1}
    assert compile_events.gate(path) == 0
    cm = next(r for r in records if r["record"] == "cost_model")
    assert cm["flops"] > 0 and cm["bytes_accessed"] > 0

    # (c) dtype-accurate bytes: per-token cost = int8 payload + bf16
    # block scales; committed <= 55% of the bf16-equivalent workload.
    per = summary["kv_bytes_per_token"]
    bf16 = summary["kv_bytes_per_token_bf16"]
    assert summary["kv_dtype"] == "int8"
    assert summary["weight_dtype"] == "int8"
    assert per == 2 * model.num_layers * (model.hidden_size + 2)
    assert bf16 == 2 * model.num_layers * model.hidden_size * 2
    assert bf16 / per >= 1.9
    committed = summary["kv_bytes_committed"]["max"]
    assert committed <= 0.55 * (committed / per * bf16)
    assert eng.pool.kv_bytes_reserved() \
        == eng.pool.num_blocks * BS * per

    # (d) the QUANT report line renders from the v11 fields, jax-free.
    report = _load_tool("serve_report")
    assert report.main([path]) == 0
    out = capsys.readouterr().out
    assert "QUANT: weights=int8  kv=int8" in out
    assert "compression 1.9" in out


def test_quantized_cow_copies_scales(model_and_params, qparams):
    """The COW-under-quantization regression: diverging a shared int8
    block must copy its SCALE rows with the payload.  Shared-prefix
    requests (two full shared blocks + a partial overlap -> a real COW)
    produce exactly the tokens the same prompts produce in solo
    quantized runs — if scales were not copied, the COW'd block would
    dequantize under a fresh block's zero scales and the streams would
    diverge immediately."""
    model, _ = model_and_params
    reqs = synthetic_requests(6, vocab_size=model.vocab_size, seed=7,
                              prompt_len=(3, 6), max_new=(4, 8),
                              stagger=3, shared_prefix=20)
    eng = _quant_engine(model, qparams, reqs)
    assert eng.counts["ok"] == 6
    assert eng.pool.cow_copies >= 1          # the drill actually fired
    assert eng.pool.prefix_hit_rate() > 0.4
    solo_tokens = {}
    for c in eng.completions:
        solo = _quant_engine(
            model, qparams,
            [Request(prompt=list(c.request.prompt),
                     max_new_tokens=c.request.max_new_tokens)])
        solo_tokens[c.request.uid] = solo.completions[0].tokens
        assert c.tokens == solo_tokens[c.request.uid], (
            f"{c.request.uid}: shared-prefix quantized stream diverged "
            "from the solo quantized run — COW dropped the scales")


def test_quant_disabled_path_untouched(model_and_params):
    """The fp32-scale path (quantization off) keeps its identity
    contract: summary reports the full-precision dtypes and the arena
    allocates no scale leaves (kv_bytes_per_token is the v7 value)."""
    model, params = model_and_params
    eng = ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                      rng=jax.random.PRNGKey(0))
    s = eng.summary_record()
    assert s["kv_dtype"] == "float32"
    assert s["weight_dtype"] == "float32"
    per_v7 = 2 * model.num_layers * model.hidden_size * 4
    assert s["kv_bytes_per_token"] == per_v7
    assert s["kv_bytes_per_token_bf16"] == per_v7 // 2
    with pytest.raises(ValueError, match="weight_quant"):
        ServeEngine(model, params, num_slots=SLOTS, max_len=MAX_LEN,
                    weight_quant="int4")


def test_kv_quant_requires_slot_decode():
    model = gpt_tiny(decode=True, kv_quant=True)
    with pytest.raises(ValueError, match="slot_decode"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))


# ===================== jax-free tool surface =========================

def test_ci_gate_quant_stream_fixture(capsys):
    """The tier-1 quant gate: the checked-in quantized-smoke stream
    passes `ci_gate --quant-stream` (v11 validation + exactly-one
    serve_summary + the 1.9x compression floor), and tampering the
    committed bytes above the floor fails it."""
    ci_gate = _load_tool("ci_gate")
    assert ci_gate.main(["--quant-stream", QUANT_FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "quant gate" in out and "PASS" in out


def test_ci_gate_quant_stream_rejects_regression(tmp_path, capsys):
    records = [json.loads(ln) for ln in open(QUANT_FIXTURE)
               if ln.strip()]
    ci_gate = _load_tool("ci_gate")

    def run_tampered(mutate):
        recs = [json.loads(json.dumps(r)) for r in records]
        mutate(recs)
        p = str(tmp_path / "tampered.jsonl")
        with open(p, "w") as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")
        rc = ci_gate.main(["--quant-stream", p])
        capsys.readouterr()
        return rc

    def summ(recs):
        return next(r for r in recs
                    if r["record"] == "serve_summary")

    # committed bytes ballooned past the bf16-equivalent/1.9 floor
    def fat(recs):
        s = summ(recs)
        s["kv_bytes_per_token"] = s["kv_bytes_per_token_bf16"]
    assert run_tampered(fat) == 1
    # quantization silently off
    def off(recs):
        summ(recs)["kv_dtype"] = "float32"
    assert run_tampered(off) == 1
    # missing the quant_event announcement
    def silent(recs):
        recs[:] = [r for r in recs if r["record"] != "quant_event"]
    assert run_tampered(silent) == 1
    # duplicated summary
    def dup(recs):
        recs.append(summ(recs))
    assert run_tampered(dup) == 1


def test_schema_v11_quant_records_validate():
    assert obs_schema.SCHEMA_VERSION >= 11   # v11 tables are a floor
    good = [
        {"record": "quant_event", "time": 1.0, "kind": "weights",
         "dtype": "int8", "tensors": 14, "kept": 25,
         "bytes_before": 368128, "bytes_after": 102912,
         "scale_min": 0.001, "scale_max": 0.004, "emulated": False,
         "run_id": "r1"},
        {"record": "quant_event", "time": 1.0, "kind": "kv",
         "dtype": "int8", "block_size": 8, "scale_dtype": "bfloat16"},
    ]
    for rec in good:
        assert obs_schema.validate_record(rec) == [], rec
    # unknown field, missing required, wrong type
    assert obs_schema.validate_record(
        {"record": "quant_event", "time": 1.0, "kind": "kv",
         "dtype": "int8", "zstd": True})
    assert obs_schema.validate_record(
        {"record": "quant_event", "time": 1.0, "kind": "kv"})
    assert obs_schema.validate_record(
        {"record": "quant_event", "time": 1.0, "kind": 3,
         "dtype": "int8"})
    # v11 serve_summary fields validate; pre-v11 summaries still do
    v11 = {"record": "serve_summary", "time": 1.0, "requests": 1,
           "output_tokens": 4, "tokens_per_sec": 1.0,
           "kv_dtype": "int8", "weight_dtype": "int8",
           "kv_bytes_per_token": 264, "kv_bytes_per_token_bf16": 512}
    assert obs_schema.validate_record(v11) == []
    v10 = {"record": "serve_summary", "time": 1.0, "requests": 1,
           "output_tokens": 4, "tokens_per_sec": 1.0}
    assert obs_schema.validate_record(v10) == []
