"""Resilience-runtime coverage (apex_example_tpu/resilience/,
tools/supervise.py; ISSUE 4):

- schema v4 records (preemption / restart / resume, run_summary
  restart_count) + v1-v3 back-compat,
- FaultPlan parse / fire-once / NaN batch poisoning,
- PreemptionHandler flag semantics and the flight-recorder SIGTERM
  handover (release_signal),
- CheckpointManager host-state sidecar round-trip + pruning,
- jax-free Supervisor units: --resume rewrite, metrics rotation,
  preemption restart, crash backoff, restart budget,
- the acceptance loop, in-process: sigterm fault -> grace save -> exit
  75 -> resume -> loss trail bit-identical to the uninterrupted run,
- the acceptance loop, end-to-end: the same drill under
  tools/supervise.py with real train.py children,
- crash-fault forensics (flight recorder still crash_dumps), nan-fault
  overflow provenance, image-path --save-every-steps + grace.

Subprocess tests carry the ``resilience`` marker (pytest.ini);
everything here rides tier-1.
"""

import importlib.util
import json
import math
import os
import signal
import sys
import time

import jax.numpy as jnp
import pytest

import train as train_mod
from apex_example_tpu import obs
from apex_example_tpu.obs import schema as obs_schema
from apex_example_tpu.resilience import (EX_TEMPFAIL, FaultInjected,
                                         FaultPlan, PreemptionHandler)
from apex_example_tpu.utils.checkpoint import CheckpointManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_supervisor():
    """By file path, exactly as tools/supervise.py does — the package
    import would be a different (jax-carrying) code path."""
    spec = importlib.util.spec_from_file_location(
        "apex_supervisor_under_test",
        os.path.join(REPO, "apex_example_tpu", "resilience",
                     "supervisor.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _header(rank=0):
    return {"record": "run_header", "schema": obs_schema.SCHEMA_VERSION,
            "time": 0.0, "run_id": "r", "num_devices": 1,
            "process_index": rank, "platform": "cpu", "config": {}}


def _step(i, loss=1.0):
    return {"record": "step", "step": i, "epoch": 0, "loss": loss,
            "scale": 1.0, "step_time_ms": 10.0, "items_per_sec": 100.0}


def _losses(path):
    return {r["step"]: r["loss"] for r in obs.read_jsonl(path)
            if r["record"] == "step"}


def _args(steps):
    """The shared tiny-LM config (C4-shaped, single device) all the
    loop-level tests train under — identical config => comparable loss
    trails."""
    return ["--arch", "bert_tiny", "--batch-size", "8", "--seq-len", "16",
            "--epochs", "1", "--steps-per-epoch", str(steps),
            "--opt", "adam", "--opt-level", "O0", "--num-devices", "1",
            "--print-freq", str(steps)]


# ------------------------------------------------------- schema v4

def test_schema_v4_resilience_records_validate():
    pre = {"record": "preemption", "time": 1.0, "signal": "SIGTERM",
           "step": 3, "run_id": "r", "checkpoint_step": 3, "saved": True}
    restart = {"record": "restart", "time": 1.0, "attempt": 0,
               "exit_code": 75, "reason": "preemption", "backoff_s": 0.0,
               "last_step": 3, "checkpoint_step": 3, "run_id": "r"}
    resume = {"record": "resume", "time": 1.0, "attempt": 1,
              "checkpoint_step": 3, "resume_dir": "/ck", "run_id": "r"}
    summary = {"record": "run_summary", "steps": 6, "overflow_count": 0,
               "restart_count": 1, "exit_code": 0}
    for rec in (pre, restart, resume, summary):
        assert obs.validate_record(rec) == [], rec["record"]
    assert obs_schema.validate_stream(
        [_header(), _step(1), pre, summary]) == []
    # supervisor-stream shape: no step records at all
    assert obs_schema.validate_stream(
        [_header(), restart, resume, summary]) == []


def test_schema_v1_v3_streams_still_validate():
    """v4 is a strict superset: pre-PR streams keep validating."""
    v1 = [dict(_header(), schema=1), _step(1),
          {"record": "run_summary", "steps": 1, "overflow_count": 0}]
    v2 = [dict(_header(), schema=2), _step(1),
          {"record": "crash_dump", "time": 1.0, "reason": "signal:SIGTERM"},
          {"record": "run_summary", "steps": 1, "overflow_count": 0,
           "aborted": True, "abort_reason": "signal:SIGTERM"}]
    v3 = [dict(_header(), schema=3),
          {"record": "request_complete", "time": 1.0, "request_id": "r-0",
           "prompt_tokens": 4, "output_tokens": 6, "ttft_ms": 10.0,
           "tpot_ms": 1.5, "finish_reason": "length"},
          {"record": "serve_summary", "time": 2.0, "requests": 1,
           "output_tokens": 6, "tokens_per_sec": 50.0}]
    for stream in (v1, v2, v3):
        assert obs_schema.validate_stream(stream) == []


def test_schema_v4_rejects_malformed():
    assert obs.validate_record({"record": "preemption", "time": 1.0,
                                "step": 3})              # missing signal
    assert obs.validate_record({"record": "restart", "time": 1.0,
                                "attempt": "0", "exit_code": 75,
                                "reason": "crash"})      # str attempt
    assert obs.validate_record({"record": "resume", "time": 1.0,
                                "attempt": 1, "typo": 1})  # unknown field


# ------------------------------------------------------ fault plans

def test_fault_plan_parse_and_rejections():
    fp = FaultPlan.parse("sigterm@12")
    assert (fp.kind, fp.step) == ("sigterm", 12)
    for bad in ("sigterm", "bogus@3", "crash@0", "crash@x", "@3",
                "crash@"):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)


def test_fault_plan_crash_fires_once_at_exact_step():
    fp = FaultPlan("crash", 2)
    fp.maybe_fire(1)                               # not yet
    with pytest.raises(FaultInjected, match="injected crash at step 2"):
        fp.maybe_fire(2)
    fp.maybe_fire(2)                               # fired: no-op
    resumed_past = FaultPlan("crash", 2)
    resumed_past.maybe_fire(3)                     # == only: never fires
    assert not resumed_past.fired


def test_fault_plan_sigterm_and_hang_mechanisms(monkeypatch):
    kills = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: kills.append((pid,
                                                                   sig)))
    FaultPlan("sigterm", 1).maybe_fire(1)
    assert kills == [(os.getpid(), signal.SIGTERM)]
    naps = []
    monkeypatch.setattr(time, "sleep", naps.append)
    FaultPlan("hang", 1, hang_s=123.0).maybe_fire(1)
    assert naps == [123.0]


def test_fault_plan_serve_kinds():
    """slot_fail is a SERVE-only kind: serve.py's parse accepts it,
    train.py's default parse keeps rejecting it; due()/take() is the
    caller-handled one-shot (nan token degeneration, slot_fail) — >=
    semantics, because a slot-level fault scheduled on a tick that
    cannot express it must fire at the next one that can."""
    from apex_example_tpu.resilience.faults import SERVE_KINDS
    fp = FaultPlan.parse("slot_fail@4", kinds=SERVE_KINDS)
    assert (fp.kind, fp.step) == ("slot_fail", 4)
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse("slot_fail@4")            # training kinds
    assert not fp.due(3)
    assert fp.due(4) and fp.due(5)                # >= until consumed
    fp.take()
    assert not fp.due(5)                          # consumed: once only
    fp.maybe_fire(4)                              # not its mechanism: noop


def test_fault_plan_nan_poisons_only_float_leaves():
    fp = FaultPlan("nan", 3)
    batch = (jnp.ones((2, 2)), jnp.zeros((2,), jnp.int32))
    assert fp.maybe_poison(2, batch) is batch      # wrong step: untouched
    x, y = fp.maybe_poison(3, batch)
    assert bool(jnp.isnan(x).all())
    assert y.dtype == jnp.int32 and int(y.sum()) == 0
    assert fp.fired
    with pytest.raises(FaultInjected, match="no floating-point leaf"):
        FaultPlan("nan", 1).maybe_poison(1, (jnp.zeros((2,), jnp.int32),))


# ------------------------------------------------ preemption handler

def test_preemption_handler_flag_and_restore():
    prev_term = signal.getsignal(signal.SIGTERM)
    prev_usr1 = signal.getsignal(signal.SIGUSR1)
    h = PreemptionHandler()
    h.install()
    assert h.installed and not h.preempted
    os.kill(os.getpid(), signal.SIGUSR1)
    for _ in range(200):
        if h.preempted:
            break
        time.sleep(0.005)
    assert h.preempted and h.signal_name == "SIGUSR1"
    os.kill(os.getpid(), signal.SIGUSR1)           # repeat: ignored
    time.sleep(0.01)
    assert h.signal_name == "SIGUSR1"
    h.close()
    assert signal.getsignal(signal.SIGTERM) == prev_term
    assert signal.getsignal(signal.SIGUSR1) == prev_usr1


def test_preemption_takes_over_flight_recorder(tmp_path):
    """The handover: SIGTERM under --preempt-grace sets the flag instead
    of crash-dumping, and close ORDER does not matter (release_signal
    removes the recorder's claim at install time)."""
    path = str(tmp_path / "f.jsonl")
    sink = obs.JsonlSink(path, rank=0)
    recorder = obs.FlightRecorder(sink=sink)
    prev_term = signal.getsignal(signal.SIGTERM)
    recorder.install()
    h = PreemptionHandler(signals=(signal.SIGTERM,), recorder=recorder)
    h.install()
    os.kill(os.getpid(), signal.SIGTERM)
    for _ in range(200):
        if h.preempted:
            break
        time.sleep(0.005)
    assert h.preempted and h.signal_name == "SIGTERM"
    assert not recorder._dumped                    # no crash forensics
    recorder.close()                               # recorder first...
    assert signal.getsignal(signal.SIGTERM) == h._on_signal  # ...ours holds
    h.close()
    assert signal.getsignal(signal.SIGTERM) == prev_term
    assert not os.path.exists(path)                # nothing ever written


# ------------------------------------------- host-state sidecar

def test_host_state_sidecar_roundtrip_and_prune(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
    for step in (1, 2, 3, 4):
        mgr.save_host_state(step, {"step": step, "data_index": step})
    assert sorted(mgr.host_state_steps()) == [3, 4]    # retention window
    assert mgr.load_host_state(4) == {"step": 4, "data_index": 4}
    assert mgr.load_host_state(1) is None              # pruned
    assert mgr.load_host_state(99) is None
    mgr.close()


# ------------------------------------------------- supervisor units

def _child_script(tmp_path, name, body):
    path = tmp_path / name
    path.write_text(body)
    return str(path)


def test_supervisor_checkpoint_and_tail_helpers(tmp_path):
    sup = _load_supervisor()
    assert sup.latest_checkpoint_step(None) is None
    assert sup.latest_checkpoint_step(str(tmp_path / "missing")) is None
    ck = tmp_path / "ck"
    (ck / "3").mkdir(parents=True)
    (ck / "12").mkdir()
    (ck / "notastep").mkdir()
    (ck / "7").write_text("a file, not a step dir")
    (ck / "host_state-12.json").write_text("{}")
    assert sup.latest_checkpoint_step(str(ck)) == 12

    stream = tmp_path / "m.jsonl"
    with open(stream, "w") as fh:
        fh.write(json.dumps(_header()) + "\n")
        fh.write(json.dumps(_step(4)) + "\n")
        fh.write(json.dumps(_step(5)) + "\n")
        fh.write('{"record":"step","step":6')       # torn final line
    assert sup.tail_last_step(str(stream)) == 5
    assert sup.tail_last_step(str(tmp_path / "missing.jsonl")) is None

    assert sup._set_flag(["a", "--resume", "old"], "--resume", "ck") == \
        ["a", "--resume", "ck"]
    assert sup._set_flag(["a", "--resume=old"], "--resume", "ck") == \
        ["a", "--resume=ck"]
    assert sup._set_flag(["a"], "--resume", "ck") == ["a", "--resume", "ck"]


def test_supervisor_preemption_restart_then_success(tmp_path):
    """Exit 75 once -> one prompt restart with --resume rewritten and the
    child metrics rotated; schema-valid supervisor stream throughout."""
    sup_mod = _load_supervisor()
    marker = tmp_path / "ran_once"
    argv_log = tmp_path / "argvs.txt"
    child = _child_script(tmp_path, "child.py", f"""\
import os, sys
with open({str(argv_log)!r}, "a") as fh:
    fh.write(" ".join(sys.argv[1:]) + "\\n")
if os.path.exists({str(marker)!r}):
    sys.exit(0)
open({str(marker)!r}, "w").close()
sys.exit(75)
""")
    (tmp_path / "ck" / "5").mkdir(parents=True)        # pre-existing ckpt
    sleeps = []
    sup = sup_mod.Supervisor(
        [sys.executable, child, "--metrics-jsonl",
         str(tmp_path / "c.jsonl")],
        checkpoint_dir=str(tmp_path / "ck"),
        metrics_jsonl=str(tmp_path / "sup.jsonl"),
        max_restarts=2, backoff_s=0.01, sleep_fn=sleeps.append,
        log=lambda *a: None)
    assert sup.run() == 0
    launches = argv_log.read_text().splitlines()
    assert len(launches) == 2
    # attempt 0 already resumes the pre-existing checkpoint
    assert f"--resume {tmp_path / 'ck'}" in launches[0]
    assert ".attempt1" not in launches[0]
    assert ".attempt1" in launches[1]                  # rotated metrics
    assert sleeps == []                                # preemption: prompt
    recs = obs.read_jsonl(str(tmp_path / "sup.jsonl"))
    assert obs_schema.validate_stream(recs) == []
    assert [r["record"] for r in recs] == \
        ["run_header", "resume", "restart", "resume", "run_summary"]
    restart = recs[2]
    assert restart["exit_code"] == 75
    assert restart["reason"] == "preemption"
    assert restart["attempt"] == 0
    assert recs[3]["attempt"] == 1 and recs[3]["checkpoint_step"] == 5
    assert recs[-1]["restart_count"] == 1 and recs[-1]["exit_code"] == 0


def test_supervisor_crash_backoff_and_budget(tmp_path):
    """Crash exits restart with exponential backoff until the budget is
    spent; the supervisor then surfaces the child's status."""
    sup_mod = _load_supervisor()
    child = _child_script(tmp_path, "crasher.py", "import sys\nsys.exit(3)\n")
    sleeps = []
    sup = sup_mod.Supervisor(
        [sys.executable, child],
        metrics_jsonl=str(tmp_path / "sup.jsonl"),
        max_restarts=2, backoff_s=0.5, backoff_max_s=10.0,
        sleep_fn=sleeps.append, log=lambda *a: None)
    assert sup.run() == 3
    assert sleeps == [0.5, 1.0]                        # 0.5 * 2^k
    recs = obs.read_jsonl(str(tmp_path / "sup.jsonl"))
    assert obs_schema.validate_stream(recs) == []
    restarts = [r for r in recs if r["record"] == "restart"]
    assert len(restarts) == 2
    assert all(r["reason"] == "crash" and r["exit_code"] == 3
               for r in restarts)
    assert not any(r["record"] == "resume" for r in recs)  # no ckpt dir
    assert recs[-1]["restart_count"] == 2 and recs[-1]["exit_code"] == 3


def test_supervisor_relaunch_continues_attempt_numbering(tmp_path):
    """A relaunched supervisor must not let its attempt-0 child truncate
    a previous incarnation's streams: numbering continues past existing
    PATH/PATH.attempt* files.  An explicit --child-metrics stays the
    tail target regardless of rotation."""
    sup_mod = _load_supervisor()
    base = tmp_path / "c.jsonl"
    base.write_text(json.dumps(_step(7)) + "\n")       # predecessor's
    (tmp_path / "c.jsonl.attempt1").write_text("old forensics\n")
    child = _child_script(tmp_path, "ok.py", "import sys\nsys.exit(0)\n")
    sup = sup_mod.Supervisor(
        [sys.executable, child, "--metrics-jsonl", str(base)],
        metrics_jsonl=str(tmp_path / "sup.jsonl"),
        max_restarts=1, sleep_fn=lambda s: None, log=lambda *a: None)
    assert sup.run() == 0
    assert sup._attempt_offset == 2
    assert sup._flag_path(0) == str(base) + ".attempt2"
    assert base.read_text() != ""                      # not truncated
    assert (tmp_path / "c.jsonl.attempt1").read_text() == "old forensics\n"
    # explicit tail wins over the rotated flag path
    sup2 = sup_mod.Supervisor(
        [sys.executable, child, "--metrics-jsonl", str(base)],
        child_metrics=str(tmp_path / "real.jsonl"),
        log=lambda *a: None)
    assert sup2._metrics_path(3) == str(tmp_path / "real.jsonl")


def test_supervisor_tail_only_child_metrics_not_injected(tmp_path):
    """--child-metrics names a file to TAIL; when the child's own argv
    has no --metrics-jsonl (e.g. a wrapper that rejects unknown flags),
    restart attempts must not inject one — and tailing sticks to the
    un-rotated path."""
    sup_mod = _load_supervisor()
    marker = tmp_path / "ran_once"
    argv_log = tmp_path / "argvs.txt"
    child = _child_script(tmp_path, "wrapper.py", f"""\
import os, sys
assert "--metrics-jsonl" not in " ".join(sys.argv), sys.argv
with open({str(argv_log)!r}, "a") as fh:
    fh.write(" ".join(sys.argv[1:]) + "\\n")
if os.path.exists({str(marker)!r}):
    sys.exit(0)
open({str(marker)!r}, "w").close()
sys.exit(75)
""")
    sup = sup_mod.Supervisor(
        [sys.executable, child],
        child_metrics=str(tmp_path / "external.jsonl"),
        metrics_jsonl=str(tmp_path / "sup.jsonl"),
        max_restarts=2, sleep_fn=lambda s: None, log=lambda *a: None)
    assert not sup._child_owns_metrics
    assert sup.run() == 0                       # wrapper never saw the flag
    assert len(argv_log.read_text().splitlines()) == 2
    assert sup._metrics_path(1) == str(tmp_path / "external.jsonl")


def test_supervisor_no_resume_and_drop_flags(tmp_path):
    """Serving-child generalization: resume=False never injects
    --resume even with a checkpoint present, and drop_flags_on_restart
    strips a one-shot drill flag from restart attempts (attempt 0 keeps
    it — the drill must fire once)."""
    sup_mod = _load_supervisor()
    assert sup_mod._strip_flag(
        ["a", "--inject-fault", "sigterm@4", "b"], "--inject-fault") \
        == ["a", "b"]
    assert sup_mod._strip_flag(
        ["a", "--inject-fault=crash@2"], "--inject-fault") == ["a"]
    assert sup_mod._strip_flag(["a"], "--inject-fault") == ["a"]
    # a store_true flag must not swallow the following argument
    assert sup_mod._strip_flag(
        ["--no-drain", "--metrics-jsonl", "out.jsonl"], "--no-drain") \
        == ["--metrics-jsonl", "out.jsonl"]
    assert sup_mod._strip_flag(["x", "--no-drain"], "--no-drain") == ["x"]
    (tmp_path / "ck" / "5").mkdir(parents=True)
    sup = sup_mod.Supervisor(
        ["child", "--inject-fault", "sigterm@4"],
        checkpoint_dir=str(tmp_path / "ck"),
        resume=False, drop_flags_on_restart=["--inject-fault"],
        log=lambda *a: None)
    sup._attempt_offset = 0
    argv0 = sup._launch_argv(0)
    argv1 = sup._launch_argv(1)
    assert "--resume" not in argv0 and "--resume" not in argv1
    assert "--inject-fault" in argv0                 # attempt 0: fires
    assert "--inject-fault" not in argv1             # restarts: stripped
    # default resume path still rewrites (the training contract)
    sup2 = sup_mod.Supervisor(["child"],
                              checkpoint_dir=str(tmp_path / "ck"),
                              log=lambda *a: None)
    sup2._attempt_offset = 0
    assert "--resume" in sup2._launch_argv(0)


# ------------------------------------------------- CLI flag guards

def test_resilience_cli_guards():
    for extra in (["--inject-fault", "bogus@3"],
                  ["--inject-fault", "crash"],
                  ["--save-every-steps", "-1"],
                  ["--save-every-steps", "2"]):       # no --checkpoint-dir
        with pytest.raises(SystemExit):
            train_mod.main(["--arch", "resnet18"] + extra)


# --------------------------------- the acceptance loop, in-process

@pytest.fixture(scope="module")
def baseline(tmp_path_factory):
    """Uninterrupted 6-step run under the shared config: the loss-trail
    oracle for the equivalence tests — and the clean-run acceptance
    check (grace armed, zero resilience records emitted)."""
    path = str(tmp_path_factory.mktemp("resilience_base") / "a.jsonl")
    rc = train_mod.main(_args(6) + ["--metrics-jsonl", path,
                                    "--preempt-grace"])
    assert rc == 0
    records = obs.read_jsonl(path)
    kinds = [r["record"] for r in records]
    assert not any(k in ("preemption", "restart", "resume")
                   for k in kinds)                     # clean run: silent
    summary = records[-1]
    assert summary["record"] == "run_summary" and "aborted" not in summary
    losses = _losses(path)
    assert sorted(losses) == [1, 2, 3, 4, 5, 6]
    return losses


def test_nan_fault_poisons_grads(tmp_path):
    """nan-kind drills poison the step's float batch leaves through the
    CLI: the loss goes NaN at exactly the chosen step (the overflow-
    provenance drill).  (The --save-every-steps wiring on the IMAGE loop
    rides test_diag's existing resnet diagnostics run — no second resnet
    compile here; the LM-loop wiring is line-identical and e2e-covered.)
    """
    path = str(tmp_path / "n.jsonl")
    rc = train_mod.main(_args(2) + ["--metrics-jsonl", path,
                                    "--preempt-grace",
                                    "--inject-fault", "nan@2"])
    assert rc == 0                                     # drill, not crash
    steps = [r for r in obs.read_jsonl(path) if r["record"] == "step"]
    assert len(steps) == 2
    assert not math.isnan(steps[0]["loss"])
    assert math.isnan(steps[1]["loss"])                # poisoned step 2


def test_crash_fault_flight_recorder_forensics(tmp_path):
    """crash-kind drills still reach the flight recorder: crash_dump with
    the injected traceback + aborted summary (the 'forensics' leg)."""
    path = str(tmp_path / "c.jsonl")
    with pytest.raises(FaultInjected):
        train_mod.main(_args(2) + ["--metrics-jsonl", path,
                                   "--flight-recorder",
                                   "--inject-fault", "crash@2"])
    recs = obs.read_jsonl(path)
    assert obs_schema.validate_stream(recs) == []
    crash = next(r for r in recs if r["record"] == "crash_dump")
    assert crash["reason"] == "exception:FaultInjected"
    assert "injected crash at step 2" in crash["traceback"]
    summary = recs[-1]
    assert summary["aborted"] is True
    assert summary["abort_reason"] == "exception:FaultInjected"
    assert len([r for r in recs if r["record"] == "step"]) == 2
    lint = _load_tool("metrics_lint")
    assert lint.lint(path, require_summary=True)[0] == 0


# ----------------------------------- end-to-end under the supervisor

@pytest.mark.resilience
def test_supervised_sigterm_e2e(tmp_path, baseline, capsys):
    """The acceptance bar, end-to-end: --inject-fault sigterm@3 under
    tools/supervise.py yields a preemption record (no crash_dump, an
    un-aborted summary) + exit 75 + exactly one restart, the grace save
    leaves a checkpoint + host-state sidecar at step 3, the resumed
    attempt continues mid-epoch, and the spliced loss trail is
    bit-identical to the uninterrupted run (covers AMP scaler state,
    opt_state, and data-stream position)."""
    # Children inherit the suite's XLA_FLAGS (8-logical-device client):
    # the XLA CPU client's device count perturbs low-bit float reduction
    # order, and the splice assertion below is BIT-exact against the
    # in-process baseline — the environments must match.
    ck = str(tmp_path / "ck")
    sup_path = str(tmp_path / "sup.jsonl")
    child_metrics = str(tmp_path / "child.jsonl")
    child = [sys.executable, os.path.join(REPO, "train.py")] + _args(6) + [
        "--metrics-jsonl", child_metrics, "--preempt-grace",
        "--flight-recorder", "--checkpoint-dir", ck,
        "--inject-fault", "sigterm@3"]
    supervise = _load_tool("supervise")
    rc = supervise.main(["--metrics-jsonl", sup_path,
                         "--max-restarts", "2", "--backoff", "0.1",
                         "--"] + child)
    assert rc == 0

    sup_recs = obs.read_jsonl(sup_path)
    assert obs_schema.validate_stream(sup_recs) == []
    assert [r["record"] for r in sup_recs] == \
        ["run_header", "restart", "resume", "run_summary"]
    restart = sup_recs[1]
    assert restart["exit_code"] == EX_TEMPFAIL == 75   # the wire contract
    assert restart["reason"] == "preemption"
    assert restart["last_step"] == 3 and restart["checkpoint_step"] == 3
    resume = sup_recs[2]
    assert resume["attempt"] == 1 and resume["checkpoint_step"] == 3
    summary = sup_recs[-1]
    assert summary["restart_count"] == 1 and summary["exit_code"] == 0
    assert summary["steps"] == 6

    att0 = obs.read_jsonl(child_metrics)
    assert obs_schema.validate_stream(att0) == []
    assert "crash_dump" not in [r["record"] for r in att0]  # grace path
    pre = next(r for r in att0 if r["record"] == "preemption")
    assert pre["signal"] == "SIGTERM" and pre["step"] == 3
    assert pre["saved"] is True and pre["checkpoint_step"] == 3
    assert att0[-1]["record"] == "run_summary"
    assert "aborted" not in att0[-1]                   # resumable != broken
    att1 = obs.read_jsonl(child_metrics + ".attempt1")
    assert att1[-1]["record"] == "run_summary"
    assert sorted(_losses(child_metrics + ".attempt1")) == [4, 5, 6]
    trail = {**_losses(child_metrics),
             **_losses(child_metrics + ".attempt1")}
    assert trail == baseline                           # bit-identical

    mgr = CheckpointManager(ck)                        # the grace save
    hs = mgr.load_host_state(3)
    assert hs["step_in_epoch"] == 3 and hs["data_index"] == 3
    assert "python_random" in hs
    mgr.close()
    lint = _load_tool("metrics_lint")
    assert lint.lint(child_metrics, steps=3, require_summary=True)[0] == 0
    report = _load_tool("telemetry_report")
    assert report.main([child_metrics]) == 0
    assert report.main([sup_path]) == 0
    rep = capsys.readouterr().out
    assert "PREEMPTED RUN (graceful): SIGTERM at step 3" in rep
    assert "restarts: 1" in rep


@pytest.mark.resilience
def test_supervised_serve_drain_e2e(tmp_path):
    """The serving acceptance bar, end-to-end (ISSUE 5): a SIGTERM'd
    serve.py subprocess admits no new requests, resolves every in-flight
    request, emits serve_drain + an un-aborted serve_summary and exits
    75 (EX_TEMPFAIL); tools/supervise.py treats that as prompt-restart
    (--no-resume, --drop-flag-on-restart stripping the one-shot drill),
    rotates the serve metrics stream, and the restarted attempt serves
    to completion.

    The child runs with --trace (ISSUE 11, same subprocess pair): the
    APEX_TRACE_ID env handoff makes BOTH attempt streams and the
    supervisor's own stream carry ONE trace_id, and the merged
    trace_export timeline renders the drain + restart spans — a
    supervised SIGTERM -> drain -> restart is one continuous story."""
    child_metrics = str(tmp_path / "serve.jsonl")
    sup_path = str(tmp_path / "sup.jsonl")
    child = [sys.executable, os.path.join(REPO, "serve.py"),
             "--requests", "6", "--slots", "2", "--max-len", "16",
             "--prompt-len", "3:5", "--max-new", "3:6", "--stagger", "2",
             "--seed", "7", "--metrics-jsonl", child_metrics, "--trace",
             "--inject-fault", "sigterm@4"]
    supervise = _load_tool("supervise")
    rc = supervise.main(["--metrics-jsonl", sup_path,
                         "--max-restarts", "2", "--backoff", "0.1",
                         "--no-resume",
                         "--drop-flag-on-restart=--inject-fault",
                         "--"] + child)
    assert rc == 0

    sup_recs = obs.read_jsonl(sup_path)
    assert obs_schema.validate_stream(sup_recs) == []
    # no checkpoints, no resumes — just one drain-restart (the trace
    # stratum rides alongside: clock_sync + attempt/restart spans)
    assert [r["record"] for r in sup_recs
            if r["record"] not in ("trace_event", "clock_sync")] == \
        ["run_header", "restart", "run_summary"]
    restart = next(r for r in sup_recs if r["record"] == "restart")
    assert restart["exit_code"] == EX_TEMPFAIL == 75   # the wire contract
    assert restart["reason"] == "preemption"
    assert sup_recs[-1]["restart_count"] == 1
    assert sup_recs[-1]["exit_code"] == 0

    att0 = obs.read_jsonl(child_metrics)               # the drained attempt
    assert obs_schema.validate_stream(att0) == []
    kinds0 = [r["record"] for r in att0]
    assert "crash_dump" not in kinds0                  # grace, not crash
    drain = next(r for r in att0 if r["record"] == "serve_drain")
    assert drain["signal"] == "SIGTERM"
    assert drain["in_flight"] == drain["completed"] + drain["evicted"]
    assert drain["requeued"] >= 1
    summ0 = att0[-1]
    assert summ0["record"] == "serve_summary"
    assert "aborted" not in summ0                      # resumable != broken
    assert summ0["drained"] == drain["requeued"]
    # every request resolved with an explicit status, none admitted
    # after the drain began
    assert summ0["requests"] == 6
    assert summ0["completed"] + summ0["timed_out"] + summ0["drained"] == 6
    assert all(r.get("admitted_step", -1) <= drain["step"]
               for r in att0 if r["record"] == "request_complete")

    att1 = obs.read_jsonl(child_metrics + ".attempt1")  # rotated stream
    assert obs_schema.validate_stream(att1) == []
    kinds1 = [r["record"] for r in att1]
    assert "serve_drain" not in kinds1                 # drill was stripped
    summ1 = att1[-1]
    assert summ1["record"] == "serve_summary"
    assert summ1["completed"] == 6 and summ1["availability"] == 1.0

    lint = _load_tool("metrics_lint")
    assert lint.lint(child_metrics)[0] == 0
    assert lint.lint(child_metrics + ".attempt1")[0] == 0

    # --- cross-restart trace continuity (ISSUE 11) ---------------
    # one trace_id across the drained attempt, the restarted attempt
    # AND the supervisor's own stream (the APEX_TRACE_ID handoff)
    streams = [att0, att1, sup_recs]
    ids = {r["trace_id"] for recs in streams for r in recs
           if r["record"] in ("trace_event", "clock_sync")
           and "trace_id" in r}
    assert len(ids) == 1, ids
    # each stream carries its own clock_sync anchor
    assert all(sum(1 for r in recs if r["record"] == "clock_sync") == 1
               for recs in streams)
    # attempt 0 traced the drain; the supervisor traced the restart
    names0 = [r["name"] for r in att0 if r["record"] == "trace_event"]
    assert "drain" in names0
    sup_names = [r["name"] for r in sup_recs
                 if r["record"] == "trace_event"]
    assert sup_names == ["attempt", "restart", "attempt"]
    # the merged export is ONE structurally-clean timeline holding
    # the drain span and the restart marker
    export = _load_tool("trace_export")
    paths = [child_metrics, child_metrics + ".attempt1", sup_path]
    assert export.main(["--check"] + paths) == 0
    merged = str(tmp_path / "merged.json")
    assert export.main(paths + ["-o", merged]) == 0
    evs = json.load(open(merged))["traceEvents"]
    names = {e["name"] for e in evs}
    assert "drain" in names and "restart" in names and "attempt" in names
    assert len({e["pid"] for e in evs
                if e.get("ph") not in ("M",)}) == 3   # 3 process rows
